"""Recompile-hazard linter: a lightweight AST pass over driver code.

The jaxpr auditor sees what a program TRACED to; this pass catches hazards
that live in the Python around the trace and may never show up in a single
tracing — values that leak host round-trips or silent retraces:

- ``DAL101 block-until-ready-in-library``: ``.block_until_ready()`` /
  ``jax.block_until_ready()`` in library code serializes the async dispatch
  stream. Legitimate uses (honest phase timing in the per-round drivers)
  carry an inline waiver.
- ``DAL102 host-cast-in-traced-code``: ``float()``/``int()``/``bool()`` on a
  value inside a jit-decorated function is a trace-time ConcretizationError
  at best, a silently-baked constant at worst.
- ``DAL103 mutable-closure-in-jit``: a jitted function closing over an
  enclosing-scope name that is rebound (re-assigned/augmented) — the trace
  bakes whichever value was live, and later mutations silently don't apply
  (or force a retrace via static-arg changes).
- ``DAL104 dict-ordered-static-arg``: ``tuple(d.items())``/``list(d.items())``
  hash by insertion order; two equal configs built in different orders then
  miss the jit cache and recompile. Use ``sorted(d.items())``.

Waivers: append ``# audit: ok`` (any rule) or ``# audit: ok[DAL101]`` (one
rule) to the offending line — any line of a multi-line call works. For
DAL103 (whose finding anchors to the jitted function itself) put the waiver
on the ``def`` line or a decorator line; waivers inside the body are
deliberately ignored, so one comment can't blanket a whole function.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from distributed_active_learning_tpu.analysis.report import Finding

LINT_RULES: Dict[str, Tuple[str, str]] = {
    "DAL101": ("warn", "block_until_ready in library code serializes dispatch"),
    "DAL102": ("error", "float()/int()/bool() on a traced value inside jit"),
    "DAL103": ("warn", "jitted function closes over a mutated enclosing name"),
    "DAL104": ("warn", "tuple(dict.items()) hashes by insertion order"),
}

_WAIVER_RE = re.compile(r"#\s*audit:\s*ok(?:\[(?P<rules>[A-Z0-9,\s]+)\])?")


def _waivers(source: str) -> Dict[int, Optional[Set[str]]]:
    """Line number -> waived rule ids (None = all rules waived)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _WAIVER_RE.search(line)
        if m:
            rules = m.group("rules")
            out[i] = (
                None if rules is None
                else {r.strip() for r in rules.split(",") if r.strip()}
            )
    return out


def _is_jit_decorator(node: ast.expr) -> bool:
    """Matches @jax.jit, @jit, @jax.jit(...), @functools.partial(jax.jit, ...)."""

    def names(expr: ast.expr) -> str:
        if isinstance(expr, ast.Attribute):
            return f"{names(expr.value)}.{expr.attr}"
        if isinstance(expr, ast.Name):
            return expr.id
        return ""

    if names(node) in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fn = names(node.func)
        if fn in ("jax.jit", "jit"):
            return True
        if fn in ("functools.partial", "partial") and node.args:
            return names(node.args[0]) in ("jax.jit", "jit")
    return False


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound in ONE function's own scope (params + assignments +
    imports + nested def/class names), not descending into nested scopes."""
    bound: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
        for arg in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            + ([a.vararg] if a.vararg else []) + ([a.kwarg] if a.kwarg else [])
        ):
            bound.add(arg.arg)

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                bound.add(child.name)
                continue  # nested scope: its bindings are its own
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Name) and isinstance(child.ctx, (ast.Store, ast.Del)):
                bound.add(child.id)
            if isinstance(child, (ast.Import, ast.ImportFrom)):
                for alias in child.names:
                    bound.add((alias.asname or alias.name).split(".")[0])
            walk(child)

    walk(fn)
    return bound


def _rebound_names(fn: ast.AST) -> Set[str]:
    """Names bound MORE than once (or augmented / loop-bound) in one
    function's own scope — the mutation half of DAL103."""
    counts: Dict[str, int] = {}

    def bump(name: str, n: int = 1):
        counts[name] = counts.get(name, 0) + n

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.AugAssign) and isinstance(child.target, ast.Name):
                bump(child.target.id, 2)  # augmenting is inherently a rebind
            elif isinstance(child, (ast.For, ast.AsyncFor)):
                for t in ast.walk(child.target):
                    if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
                        bump(t.id, 2)  # loop vars rebind per iteration
            elif isinstance(child, ast.Name) and isinstance(child.ctx, ast.Store):
                bump(child.id)
            walk(child)

    walk(fn)
    return {name for name, n in counts.items() if n > 1}


def _loaded_names(fn: ast.AST) -> Set[str]:
    """Names LOADED anywhere inside a function, nested scopes included
    (a nested def's closure reads count against the jitted boundary)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.add(node.id)
    return out


def _dotted(expr: ast.expr) -> str:
    if isinstance(expr, ast.Attribute):
        return f"{_dotted(expr.value)}.{expr.attr}"
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


class _Linter(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.waivers = _waivers(source)
        self.findings: List[Finding] = []
        self._fn_stack: List[ast.AST] = []   # enclosing FunctionDefs
        self._jit_depth = 0                  # inside a jit-decorated def?

    def _waived(self, rule: str, lines) -> bool:
        for line in lines:
            waived = self.waivers.get(line)
            if line in self.waivers and (waived is None or rule in waived):
                return True
        return False

    def _emit(self, rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        # A waiver anywhere on the node's own line span counts: a multi-line
        # call's `# audit: ok[...]` naturally lands on its closing line, not
        # its first. Function nodes (DAL103) check only their header — the
        # decorators and the `def` line — so a waiver inside the body can't
        # silently blanket the whole function.
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lines = [d.lineno for d in node.decorator_list] + [node.lineno]
        else:
            lines = range(line, getattr(node, "end_lineno", line) + 1)
        if self._waived(rule, lines):
            return
        severity, _ = LINT_RULES[rule]
        self.findings.append(
            Finding(
                rule=rule,
                severity=severity,
                program=self.relpath,
                location=f"{self.relpath}:{line}",
                message=message,
            )
        )

    # -- function scopes ----------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_fn(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_fn(node)

    def _visit_fn(self, node):
        jitted = any(_is_jit_decorator(d) for d in node.decorator_list)
        if jitted:
            self._check_mutable_closure(node)
        self._fn_stack.append(node)
        self._jit_depth += int(jitted)
        self.generic_visit(node)
        self._jit_depth -= int(jitted)
        self._fn_stack.pop()

    def _check_mutable_closure(self, fn: ast.AST):
        """DAL103: free names of a jitted def that some enclosing FUNCTION
        scope both binds and rebinds."""
        free = _loaded_names(fn) - _bound_names(fn)
        for enclosing in reversed(self._fn_stack):
            bound = _bound_names(enclosing)
            rebound = _rebound_names(enclosing)
            for name in sorted(free & bound & rebound):
                self._emit(
                    "DAL103", fn,
                    f"jitted `{getattr(fn, 'name', '<fn>')}` closes over "
                    f"`{name}`, which is rebound in the enclosing scope — the "
                    "trace bakes whichever value was live at first call",
                )
            free -= bound  # resolved at this level; stop attributing upward

    # -- calls --------------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        fn = node.func
        # DAL101: obj.block_until_ready() or jax.block_until_ready(x)
        if isinstance(fn, ast.Attribute) and fn.attr == "block_until_ready":
            self._emit(
                "DAL101", node,
                "block_until_ready in library code serializes the dispatch "
                "stream; time at the driver boundary or waive with "
                "`# audit: ok[DAL101]` where the sync is the point",
            )
        # DAL102: float()/int()/bool() under a jit-decorated function
        if (
            self._jit_depth > 0
            and isinstance(fn, ast.Name)
            and fn.id in ("float", "int", "bool")
            and node.args
        ):
            self._emit(
                "DAL102", node,
                f"{fn.id}() inside a jit-traced function concretizes a "
                "traced value (ConcretizationTypeError at best, a baked "
                "constant at worst)",
            )
        # DAL104: tuple(d.items()) / list(d.items())
        if (
            isinstance(fn, ast.Name)
            and fn.id in ("tuple", "list")
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Call)
            and isinstance(node.args[0].func, ast.Attribute)
            and node.args[0].func.attr == "items"
        ):
            self._emit(
                "DAL104", node,
                f"{fn.id}(...items()) preserves dict insertion order; as a "
                "jit static arg two equal configs can hash differently and "
                "recompile — use sorted(...items())",
            )
        self.generic_visit(node)


def lint_file(path: str, relpath: Optional[str] = None) -> List[Finding]:
    rel = relpath or os.path.basename(path)
    with open(path) as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                rule="lint-parse-failure",
                severity="error",
                program=rel,
                location=f"{rel}:{e.lineno or 0}",
                message=str(e),
            )
        ]
    linter = _Linter(rel, source)
    linter.visit(tree)
    return linter.findings


def default_lint_targets(root: Optional[str] = None) -> List[str]:
    """The driver surfaces the recompile hazards live in: ``runtime/`` and
    ``strategies/`` of the installed package."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = []
    for sub in ("runtime", "strategies"):
        d = os.path.join(root, sub)
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".py"):
                targets.append(os.path.join(d, fn))
    return targets


def lint_paths(paths: Sequence[str], root: Optional[str] = None) -> List[Finding]:
    if root is None and paths:
        root = os.path.commonpath([os.path.dirname(os.path.abspath(p)) for p in paths])
    findings: List[Finding] = []
    for p in paths:
        rel = os.path.relpath(p, root) if root else os.path.basename(p)
        findings.extend(lint_file(p, rel))
    return findings


def iter_rule_table() -> Iterator[Tuple[str, str, str]]:
    for rule_id, (severity, desc) in LINT_RULES.items():
        yield rule_id, severity, desc
