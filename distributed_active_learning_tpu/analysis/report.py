"""Structured findings: the auditor's and linter's shared output layer.

Every rule — jaxpr-level (analysis/rules.py) or AST-level (analysis/lint.py)
— yields :class:`Finding` records; a :class:`Report` aggregates them with the
list of programs that were actually examined (an audit that silently traced
nothing must not read as "clean"). Two renderings: ``to_json`` for machines
(the CI gate, ``python -m ...analysis --json``) and ``render_table`` for
humans, both fed by the same records so they can never disagree.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

#: Severity ladder, least to most severe. ``max_severity``/gating compare by
#: index in this tuple, so adding a level means inserting it in rank order.
SEVERITIES = ("info", "warn", "error")


def severity_rank(severity: str) -> int:
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        raise ValueError(
            f"unknown severity {severity!r}; one of {SEVERITIES}"
        ) from None


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``program`` names the traced program for jaxpr rules (e.g.
    ``chunk/uncertainty/cpu``) or the relative file path for lint rules;
    ``location`` is the op path inside the jaxpr (``scan/pjit/...``) or
    ``file:line`` for lint.
    """

    rule: str
    severity: str
    program: str
    location: str
    message: str

    def asdict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return (
            f"[{self.severity}] {self.rule} @ {self.program}"
            f" ({self.location}): {self.message}"
        )


@dataclasses.dataclass
class Report:
    """All findings from one audit run plus what was examined."""

    findings: List[Finding] = dataclasses.field(default_factory=list)
    programs: List[str] = dataclasses.field(default_factory=list)
    skipped: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: Per-program accounting the rules computed on the way to their
    #: verdicts (today: collective_bytes / collective_sites for programs
    #: with any collective traffic) — numbers, not judgments, so a reviewer
    #: can see HOW FAR under the gate a program sits.
    stats: Dict[str, dict] = dataclasses.field(default_factory=dict)

    def extend(self, findings: Sequence[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def max_severity(self) -> Optional[str]:
        if not self.findings:
            return None
        return max(self.findings, key=lambda f: severity_rank(f.severity)).severity

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def at_or_above(self, severity: str) -> List[Finding]:
        floor = severity_rank(severity)
        return [f for f in self.findings if severity_rank(f.severity) >= floor]

    def gate(self, fail_on: str = "error") -> bool:
        """True when the report should FAIL a gate at ``fail_on`` severity."""
        return bool(self.at_or_above(fail_on))

    def to_json(self, indent: Optional[int] = None) -> str:
        payload = {
            "schema": 1,
            "programs_audited": list(self.programs),
            "programs_skipped": dict(self.skipped),
            "counts": self.counts(),
            "max_severity": self.max_severity,
            "findings": [f.asdict() for f in self.findings],
            "program_stats": dict(self.stats),
        }
        return json.dumps(payload, indent=indent)

    def render_table(self) -> str:
        lines = [
            f"audited {len(self.programs)} program(s)"
            + (f", skipped {len(self.skipped)}" if self.skipped else "")
        ]
        for name, why in sorted(self.skipped.items()):
            lines.append(f"  skipped {name}: {why}")
        if not self.findings:
            lines.append("no findings")
            return "\n".join(lines)
        rows = [
            (f.severity, f.rule, f.program, f.location, f.message)
            for f in sorted(
                self.findings,
                key=lambda f: (-severity_rank(f.severity), f.rule, f.program),
            )
        ]
        header = ("severity", "rule", "program", "location", "message")
        widths = [
            max(len(header[i]), *(len(str(r[i])) for r in rows))
            for i in range(4)
        ]
        fmt = lambda r: "  ".join(  # noqa: E731 - tiny local formatter
            [str(r[i]).ljust(widths[i]) for i in range(4)] + [str(r[4])]
        )
        lines.append(fmt(header))
        lines.append(fmt(tuple("-" * w for w in widths) + ("-" * 7,)))
        lines.extend(fmt(r) for r in rows)
        c = self.counts()
        lines.append(
            "totals: " + "  ".join(f"{s}={c[s]}" for s in SEVERITIES if c[s])
        )
        return "\n".join(lines)
