"""Static program auditing: jaxpr invariant checks + recompile-hazard lint.

The deep-stack PRs (scan fusion, donation, padded reveals, pipelined
dispatch, vmapped sweeps) built a fast path whose performance rests on
invariants nothing verified — a stray host callback, a dropped donation, or
a weak-type leak costs exactly the perf the bench trajectory tracks (the r04
MFU regression was found only by re-benching). This package verifies them
statically, at PR time:

- :mod:`analysis.programs` rebuilds every fused program the drivers launch
  (strategy x {chunk, sweep, neural_chunk} x {cpu, mesh4x2}) over abstract
  inputs;
- :mod:`analysis.auditor` traces each one and applies the jaxpr rule
  registry (:mod:`analysis.rules`);
- :mod:`analysis.lint` AST-scans ``runtime/`` and ``strategies/`` for
  host-sync and retrace hazards the trace can't see;
- :mod:`analysis.report` renders both as JSON (the CI gate) or a table;
- :mod:`analysis.roofline` prices each program with XLA's own cost model
  (``compiled.cost_analysis()``) and joins measured seconds into roofline
  attribution — achieved FLOP/s, bandwidth, MFU, bound verdict (surfaced as
  ``--costs``, the ``bench.py --mode round`` roofline section, and
  ``run.py --roofline``).

Entry points: ``python -m distributed_active_learning_tpu.analysis``,
``run.py --audit``, ``bench.py --audit``.
"""

from distributed_active_learning_tpu.analysis.report import (  # noqa: F401
    Finding,
    Report,
    SEVERITIES,
    severity_rank,
)
from distributed_active_learning_tpu.analysis.auditor import (  # noqa: F401
    AuditUnit,
    audit_unit,
    run_audit,
)
from distributed_active_learning_tpu.analysis.programs import (  # noqa: F401
    ProgramSpec,
    SkipProgram,
    build_registry,
    specs_for_experiment,
)
from distributed_active_learning_tpu.analysis.lint import (  # noqa: F401
    default_lint_targets,
    lint_paths,
)
from distributed_active_learning_tpu.analysis.roofline import (  # noqa: F401
    attribute,
    cost_table,
    peak_bandwidth,
    peak_flops,
    program_cost,
)
