"""The program auditor: trace registered programs abstractly, apply rules.

Tracing (``jax.jit(...).trace(*abstract_args)``) runs the Python of a program
once with ShapeDtypeStruct inputs and yields the full ClosedJaxpr plus output
avals — no compilation, no device execution, seconds per program even for the
scan-fused chunks. The auditor walks that jaxpr (and, for donation checks,
the lowered MLIR's aliasing metadata) against the rule registry
(analysis/rules.py) and reports structured findings (analysis/report.py).

This is the static half of the invariant story; the runtime half (veto
counts, recompile detection) rides the JSONL telemetry
(``runtime/telemetry.py`` launch / ``launch_veto`` events).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax

from distributed_active_learning_tpu.analysis import rules as rules_lib
from distributed_active_learning_tpu.analysis.report import Finding, Report


@dataclasses.dataclass
class AuditUnit:
    """One auditable program: a jitted callable plus its abstract inputs and
    the invariants the builder promised (what the rules check against).

    ``carry_in_argnums``/``carry_out_index`` name the launch-to-launch carry:
    which top-level argument positions hold the carried state and which
    top-level output position returns it (the chunk drivers thread out[0]
    back into the state argument). ``None`` disables the carry rules.
    """

    name: str
    fn: Any
    args: Tuple[Any, ...]
    allows_callbacks: bool = False
    expect_donation: bool = False
    with_metrics: bool = False
    carry_in_argnums: Optional[Tuple[int, ...]] = None
    carry_out_index: Optional[int] = None
    # Quantized forest storage this program was built with ("bf16"/"int8");
    # None = unquantized. The quantized-leaf-upcast rule fires on it.
    quantize: Optional[str] = None
    # Pool scale of this program's audit shapes: any aval dim >= pool_rows
    # is "pool-sized" to the sharding rules (replicated-pool-operand /
    # pool-scale-collective). None disables them — single-device programs
    # have no sharding contract to audit.
    pool_rows: Optional[int] = None
    # Per-launch collective traffic ceiling in bytes (scan trip counts
    # multiplied in). None derives the default: N x the largest input
    # operand — a program whose collectives move more than a few pools'
    # worth of data per launch is the r4-style bandwidth cliff regardless
    # of which primitive moved it.
    collective_bytes_budget: Optional[float] = None
    # Megakernel tile parameters ({n_trees, max_depth, n_rows, features,
    # window, quantize}) for programs that wrap the pallas round kernel;
    # the memory planner's VMEM estimator prices them. None = no pallas
    # tile claim (gemm/gather paths).
    pallas_tiles: Optional[dict] = None


class TracedUnit:
    """An :class:`AuditUnit` traced once, with everything rules consume
    computed lazily and cached (several rules share the eqn walk; only the
    donation rule needs the lowering)."""

    def __init__(self, unit: AuditUnit):
        self.unit = unit
        self.name = unit.name
        self.allows_callbacks = unit.allows_callbacks
        self.expect_donation = unit.expect_donation
        self.with_metrics = unit.with_metrics
        self.quantize = unit.quantize
        self.pool_rows = unit.pool_rows
        self.pallas_tiles = unit.pallas_tiles
        self.collective_bytes_budget = unit.collective_bytes_budget
        self._traced = unit.fn.trace(*unit.args)
        self._eqn_sites = None
        self._avals = None
        self._lowered_text = None
        self._lowered_tried = False

    @property
    def jaxpr(self):
        return self._traced.jaxpr

    @property
    def eqn_sites(self):
        if self._eqn_sites is None:
            self._eqn_sites = list(rules_lib.iter_eqns(self.jaxpr.jaxpr))
        return self._eqn_sites

    @property
    def avals(self):
        if self._avals is None:
            self._avals = list(rules_lib.iter_avals(self.jaxpr.jaxpr))
        return self._avals

    @property
    def out_avals(self):
        return list(self.jaxpr.out_avals)

    @property
    def out_tree_repr(self) -> str:
        return str(jax.tree_util.tree_structure(self._traced.out_info))

    @property
    def lowered_text(self) -> Optional[str]:
        if not self._lowered_tried:
            self._lowered_tried = True
            try:
                self._lowered_text = self._traced.lower().as_text()
            except Exception:
                self._lowered_text = None
        return self._lowered_text

    # -- carry aval bookkeeping ---------------------------------------------

    def _flat_arg_ranges(self) -> List[Tuple[int, int]]:
        """Flat-aval index range of each top-level positional argument (the
        jaxpr's invars are the flattened args in order)."""
        ranges = []
        offset = 0
        for a in self.unit.args:
            n = len(jax.tree_util.tree_leaves(a))
            ranges.append((offset, offset + n))
            offset += n
        return ranges

    @property
    def carry_in_avals(self):
        if self.unit.carry_in_argnums is None:
            return None
        in_avals = self.jaxpr.in_avals
        out = []
        ranges = self._flat_arg_ranges()
        for argnum in self.unit.carry_in_argnums:
            lo, hi = ranges[argnum]
            out.extend(in_avals[lo:hi])
        return out

    @property
    def carry_out_avals(self):
        if self.unit.carry_out_index is None:
            return None
        out_info = self._traced.out_info
        # top-level output position -> flat range, same arithmetic as args
        ranges = []
        offset = 0
        for part in out_info:
            n = len(jax.tree_util.tree_leaves(part))
            ranges.append((offset, offset + n))
            offset += n
        lo, hi = ranges[self.unit.carry_out_index]
        return self.jaxpr.out_avals[lo:hi]


def audit_unit(
    unit: AuditUnit,
    rules: Optional[Sequence[rules_lib.Rule]] = None,
    stats: Optional[dict] = None,
) -> List[Finding]:
    """Trace one program and run every rule over it. A program that fails to
    TRACE is itself an error finding — an untraceable registered program
    means the audit surface regressed, not that the program is clean.

    ``stats`` (optional dict) receives the program's accounting the rules
    compute as a side effect — today the per-launch collective traffic
    (``collective_bytes``, ``collective_sites``) — so reports can carry the
    numbers, not just the verdicts."""
    try:
        traced = TracedUnit(unit)
    except Exception as e:  # noqa: BLE001 - converted into a finding
        return [
            Finding(
                rule="trace-failure",
                severity="error",
                program=unit.name,
                location="<trace>",
                message=f"{type(e).__name__}: {e}",
            )
        ]
    findings: List[Finding] = []
    for rule in rules or rules_lib.default_rules():
        findings.extend(rule.check(traced))
    if stats is not None:
        traffic = rules_lib.collective_traffic(traced)
        stats["collective_bytes"] = float(sum(b for _, b in traffic))
        stats["collective_sites"] = len(traffic)
    return findings


def run_audit(
    specs,
    rules: Optional[Sequence[rules_lib.Rule]] = None,
) -> Report:
    """Audit a list of :class:`~analysis.programs.ProgramSpec`; returns the
    aggregate :class:`Report`. Specs whose builder declines (e.g. a mesh
    variant without enough devices) land in ``report.skipped`` with the
    builder's reason rather than vanishing."""
    from distributed_active_learning_tpu.analysis.programs import SkipProgram

    report = Report()
    for spec in specs:
        try:
            unit = spec.build()
        except SkipProgram as skip:
            report.skipped[spec.name] = str(skip)
            continue
        except Exception as e:  # noqa: BLE001 - a broken builder is a finding
            report.programs.append(spec.name)
            report.findings.append(
                Finding(
                    rule="build-failure",
                    severity="error",
                    program=spec.name,
                    location="<build>",
                    message=f"{type(e).__name__}: {e}",
                )
            )
            continue
        report.programs.append(spec.name)
        stats: dict = {}
        report.extend(audit_unit(unit, rules=rules, stats=stats))
        if stats.get("collective_sites"):
            report.stats[spec.name] = stats
    return report
