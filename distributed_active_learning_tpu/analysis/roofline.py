"""Static per-program cost accounting + roofline attribution.

The bench trajectory regressed from 2.1M scores/s at 14% MFU (BENCH_r03) to
431k at 2.9% (BENCH_r04) and nothing in either artifact could say WHY: we
measured seconds per phase but never compared them to what the program
*should* cost. This module closes that gap with two halves:

- **Static cost**: XLA's own cost model, pulled from a compiled executable
  (``compiled.cost_analysis()`` — flops and bytes accessed). Any jitted
  program lowers and compiles from the same abstract inputs the PR-6 program
  registry (analysis/programs.py) already builds, so :func:`cost_table` can
  price the whole registered-program matrix without running anything.

- **Attribution**: :func:`attribute` joins a program's static cost with its
  MEASURED device seconds and the chip's peak FLOP/s + HBM bandwidth tables
  to report achieved FLOP/s, achieved bytes/s, MFU, bandwidth utilization,
  and a compute-vs-bandwidth-bound roofline verdict — so a bench artifact
  names the bottleneck instead of just the number.

Consumers: ``bench.py --mode round`` emits a per-phase ``roofline`` section
(fit / score / round / chunk programs), ``run.py --roofline`` folds the same
attribution into the JSONL metrics stream as ``roofline`` events, and
``python -m distributed_active_learning_tpu.analysis --costs`` prints the
static table for the registry.

Caveat worth keeping in mind: ``cost_analysis`` is the compiler's ESTIMATE
(post-fusion flops and a bytes-touched model, not an HBM traffic trace), and
the AOT ``lower().compile()`` path does not share the jit cache — pricing a
program pays one extra compile. Both halves therefore run strictly OUTSIDE
timed regions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

#: Per-chip bf16 peak FLOP/s by jax device_kind prefix (public spec sheets).
#: bench.py's scoring MFU divides by these; matching prefixes, not equality,
#: because device_kind strings carry revision suffixes on some runtimes.
PEAK_BF16_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

#: Per-chip HBM bandwidth in bytes/s (public spec sheets). The roofline's
#: other axis: a program whose arithmetic intensity sits below the chip's
#: machine balance (peak flops / peak bandwidth) cannot reach peak MFU no
#: matter how good the kernel is — the verdict names that case
#: ``bandwidth-bound`` so an MFU drop is read against the right ceiling.
PEAK_HBM_BYTES_PER_SEC = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1200e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}

_GiB = float(1 << 30)
_MiB = float(1 << 20)

#: Per-device HBM CAPACITY in bytes (public spec sheets; per jax device —
#: one TensorCore on v2/v3, one megacore chip from v4 on). The static memory
#: planner (analysis/memory.py) gates every registered program's peak
#: footprint against these, so an r05-style OOM death becomes a named
#: pre-flight finding instead of rc 124 with no artifact. The "cpu" entry is
#: the CI/smoke stand-in: host RAM is not the scarce resource there, so the
#: budget is a generous fixed slab that only a genuinely runaway program
#: (or a deliberately tiny test table) can exceed.
HBM_BYTES_PER_DEVICE = {
    "TPU v2": 8 * _GiB,
    "TPU v3": 16 * _GiB,
    "TPU v4": 32 * _GiB,
    "TPU v5 lite": 16 * _GiB,
    "TPU v5e": 16 * _GiB,
    "TPU v5": 95 * _GiB,
    "TPU v5p": 95 * _GiB,
    "TPU v6 lite": 32 * _GiB,
    "TPU v6e": 32 * _GiB,
    "cpu": 4 * _GiB,
}

#: Per-core VMEM capacity in bytes (~16 MiB on every shipped TPU core; see
#: the pallas guide's memory hierarchy table). The planner's megakernel
#: VMEM estimator prices the kernel's resident tile set against this. The
#: "cpu" entry keeps the SAME 16 MiB: CPU runs never touch VMEM, but the
#: megakernel's tile shapes are placement-independent, so pricing them
#: against the TPU budget on the CPU rig catches an over-tiled kernel
#: BEFORE the multi-hour TPU launch — exactly the pre-flight point.
VMEM_BYTES_PER_CORE = {
    "TPU v2": 16 * _MiB,
    "TPU v3": 16 * _MiB,
    "TPU v4": 16 * _MiB,
    "TPU v5 lite": 16 * _MiB,
    "TPU v5e": 16 * _MiB,
    "TPU v5": 16 * _MiB,
    "TPU v5p": 16 * _MiB,
    "TPU v6 lite": 16 * _MiB,
    "TPU v6e": 16 * _MiB,
    "cpu": 16 * _MiB,
}


def _lookup(table: Dict[str, float], kind: str) -> Optional[float]:
    for name, peak in table.items():
        if kind.startswith(name):
            return peak
    return None


def device_kind() -> str:
    import jax

    return jax.devices()[0].device_kind


def peak_flops(kind: Optional[str] = None) -> Tuple[Optional[float], str]:
    """(bf16 peak FLOP/s, device_kind) for this chip; (None, kind) when the
    chip has no table entry (CPU, unknown accelerators)."""
    kind = device_kind() if kind is None else kind
    return _lookup(PEAK_BF16_FLOPS, kind), kind


def peak_bandwidth(kind: Optional[str] = None) -> Tuple[Optional[float], str]:
    """(HBM peak bytes/s, device_kind), None off the table like peak_flops."""
    kind = device_kind() if kind is None else kind
    return _lookup(PEAK_HBM_BYTES_PER_SEC, kind), kind


def hbm_capacity(kind: Optional[str] = None) -> Tuple[Optional[float], str]:
    """(HBM capacity bytes, device_kind) for this chip — the static memory
    planner's per-device budget. Unknown accelerators return None (the
    planner then reports footprints without gating them)."""
    kind = device_kind() if kind is None else kind
    cap = _lookup(HBM_BYTES_PER_DEVICE, kind)
    if cap is None and kind.lower().startswith("cpu"):
        cap = HBM_BYTES_PER_DEVICE["cpu"]
    return cap, kind


def vmem_capacity(kind: Optional[str] = None) -> Tuple[Optional[float], str]:
    """(VMEM capacity bytes, device_kind), keyed like :func:`hbm_capacity`."""
    kind = device_kind() if kind is None else kind
    cap = _lookup(VMEM_BYTES_PER_CORE, kind)
    if cap is None and kind.lower().startswith("cpu"):
        cap = VMEM_BYTES_PER_CORE["cpu"]
    return cap, kind


# ---------------------------------------------------------------------------
# static cost extraction
# ---------------------------------------------------------------------------


def compiled_cost(compiled) -> Dict[str, Optional[float]]:
    """Normalize ``compiled.cost_analysis()`` into ``{flops, bytes_accessed}``.

    jax has returned both shapes over time: a list with one properties dict
    per partition (0.4.x) and a bare dict (newer). Multi-partition programs
    sum. Keys the backend doesn't report come back None, never 0 — a zero
    would read as "free program" in downstream ratios.
    """
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {"flops": None, "bytes_accessed": None}
    parts = ca if isinstance(ca, (list, tuple)) else [ca]
    out: Dict[str, Optional[float]] = {"flops": None, "bytes_accessed": None}
    for key, name in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
        vals = [
            float(p[key])
            for p in parts
            if isinstance(p, dict) and isinstance(p.get(key), (int, float))
        ]
        if vals:
            out[name] = sum(vals)
    return out


def _cost_from_compiled(compiled) -> Dict[str, Optional[float]]:
    """``{flops, bytes_accessed, flops_per_byte}`` from an already-compiled
    executable — the one derivation :func:`program_cost` and
    :func:`cost_table` share (the table also reads memory stats off the
    same executable, so it must not pay a second compile)."""
    cost = compiled_cost(compiled)
    flops, nbytes = cost["flops"], cost["bytes_accessed"]
    cost["flops_per_byte"] = (
        round(flops / nbytes, 4) if flops and nbytes else None
    )
    return cost


def program_cost(fn, *args) -> Dict[str, Optional[float]]:
    """Static cost of one jitted program at these (abstract or concrete)
    argument shapes: ``{flops, bytes_accessed, flops_per_byte}``.

    Pays one AOT compile (``fn.lower(*args).compile()`` does not share the
    jit dispatch cache) — call it outside timed regions. Raises on programs
    that fail to lower/compile; :func:`cost_table` converts that into a
    per-program error entry instead.
    """
    return _cost_from_compiled(fn.lower(*args).compile())


def cost_table(specs) -> Dict[str, Dict[str, Any]]:
    """Price every registry program (analysis/programs.py ProgramSpecs).

    Returns ``{program name: {flops, bytes_accessed, flops_per_byte,
    peak_hbm_bytes}}`` — the memory planner's peak footprint rides the SAME
    compiled executable the cost model reads, so one ``--costs`` invocation
    prices flops, bytes, and footprint per program without a second compile.
    Builders that decline (SkipProgram: mesh variants without devices) get
    ``{"skipped": reason}`` and build/compile failures ``{"error": ...}`` —
    the table never silently drops a registered program.
    """
    from distributed_active_learning_tpu.analysis import memory as memory_lib
    from distributed_active_learning_tpu.analysis.programs import SkipProgram

    table: Dict[str, Dict[str, Any]] = {}
    for spec in specs:
        try:
            unit = spec.build()
            compiled = unit.fn.lower(*unit.args).compile()
            cost = _cost_from_compiled(compiled)
            cost["peak_hbm_bytes"] = memory_lib.compiled_memory(compiled)[
                "peak_hbm_bytes"
            ]
            table[spec.name] = cost
        except SkipProgram as skip:
            table[spec.name] = {"skipped": str(skip)}
        except Exception as e:  # noqa: BLE001 — per-program, keep pricing
            table[spec.name] = {"error": f"{type(e).__name__}: {e}"}
    return table


# ---------------------------------------------------------------------------
# attribution: join static cost with measured seconds
# ---------------------------------------------------------------------------


def roofline_verdict(
    mfu: Optional[float],
    bw_util: Optional[float],
    flops_per_byte: Optional[float],
    machine_balance: Optional[float],
) -> str:
    """Name the binding resource.

    Preferred evidence is MEASURED: whichever utilization (MFU vs bandwidth)
    is higher is the wall the program is closer to. Without peaks (CPU, an
    untabled chip) fall back to the STATIC comparison — arithmetic intensity
    vs machine balance — and say so in the verdict, since a static verdict
    cannot see a badly-scheduled kernel. ``indeterminate`` only when neither
    side has data.
    """
    if mfu is not None and bw_util is not None:
        return "compute-bound" if mfu >= bw_util else "bandwidth-bound"
    if flops_per_byte is not None and machine_balance is not None:
        side = "compute" if flops_per_byte >= machine_balance else "bandwidth"
        return f"{side}-bound(static)"
    if flops_per_byte is not None:
        # Cost known but the chip has no peak table (CPU smoke runs): the
        # verdict is honest about WHY it cannot rule, not just absent.
        return "indeterminate:no-peak-table"
    return "indeterminate"


def attribute(
    cost: Dict[str, Optional[float]],
    seconds: Optional[float],
    *,
    peak_flops_per_sec: Optional[float] = None,
    peak_bytes_per_sec: Optional[float] = None,
    n_devices: int = 1,
) -> Dict[str, Any]:
    """Join one program's static cost with its measured device seconds.

    ``peak_*`` default to this chip's tables (times ``n_devices`` for mesh
    programs, matching bench.py's aggregate-MFU convention). Returns a flat
    JSON-ready dict: the static keys pass through, plus ``seconds``,
    ``achieved_gflops_per_sec``, ``achieved_gbytes_per_sec``, ``mfu``,
    ``bandwidth_util``, and ``bound``.
    """
    if peak_flops_per_sec is None:
        peak_flops_per_sec, _ = peak_flops()
    if peak_bytes_per_sec is None:
        peak_bytes_per_sec, _ = peak_bandwidth()
    if peak_flops_per_sec is not None:
        peak_flops_per_sec *= max(n_devices, 1)
    if peak_bytes_per_sec is not None:
        peak_bytes_per_sec *= max(n_devices, 1)
    flops, nbytes = cost.get("flops"), cost.get("bytes_accessed")
    achieved_f = flops / seconds if flops and seconds else None
    achieved_b = nbytes / seconds if nbytes and seconds else None
    mfu = (
        achieved_f / peak_flops_per_sec
        if achieved_f is not None and peak_flops_per_sec
        else None
    )
    bw_util = (
        achieved_b / peak_bytes_per_sec
        if achieved_b is not None and peak_bytes_per_sec
        else None
    )
    balance = (
        peak_flops_per_sec / peak_bytes_per_sec
        if peak_flops_per_sec and peak_bytes_per_sec
        else None
    )
    return {
        "flops": flops,
        "bytes_accessed": nbytes,
        "flops_per_byte": cost.get("flops_per_byte"),
        "seconds": round(seconds, 6) if seconds is not None else None,
        "achieved_gflops_per_sec": (
            round(achieved_f / 1e9, 3) if achieved_f is not None else None
        ),
        "achieved_gbytes_per_sec": (
            round(achieved_b / 1e9, 3) if achieved_b is not None else None
        ),
        "mfu": round(mfu, 5) if mfu is not None else None,
        "bandwidth_util": round(bw_util, 5) if bw_util is not None else None,
        "bound": roofline_verdict(
            mfu, bw_util, cost.get("flops_per_byte"), balance
        ),
    }


def render_cost_table(table: Dict[str, Dict[str, Any]]) -> str:
    """Human table for ``--costs``: one row per program, sorted by name."""
    header = ("program", "flops", "bytes", "flops/byte", "peak_hbm")
    rows = []
    for name in sorted(table):
        entry = table[name]
        if "skipped" in entry:
            rows.append((name, "(skipped)", entry["skipped"][:40], "", ""))
            continue
        if "error" in entry:
            rows.append((name, "(error)", entry["error"][:40], "", ""))
            continue

        def _fmt(v):
            return f"{v:,.0f}" if isinstance(v, (int, float)) else "?"

        peak = entry.get("peak_hbm_bytes")
        rows.append(
            (
                name,
                _fmt(entry.get("flops")),
                _fmt(entry.get("bytes_accessed")),
                str(entry.get("flops_per_byte") or "?"),
                f"{peak / (1 << 20):.2f} MiB"
                if isinstance(peak, (int, float)) else "?",
            )
        )
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows else len(header[i])
        for i in range(len(header))
    ]

    def _row(cols):
        return "  ".join(str(c).ljust(w) for c, w in zip(cols, widths))

    return "\n".join(
        [_row(header), _row(["-" * w for w in widths])] + [_row(r) for r in rows]
    )
