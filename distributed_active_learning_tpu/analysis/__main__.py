"""CLI for the static program auditor.

    python -m distributed_active_learning_tpu.analysis [--json] \
        [--kinds chunk,sweep] [--strategies uncertainty,density] \
        [--placements cpu,mesh4x2] [--fail-on error|warn|info]

Exit code 0 when no finding reaches the ``--fail-on`` threshold, 1 otherwise
— the tier-1 ``analysis`` CI job gates on exactly this. ``--rules`` prints
the live rule table (jaxpr + lint registries).
"""

from __future__ import annotations

import os
import sys

# Route the audit onto an 8-virtual-device CPU platform. `python -m
# pkg.analysis` imports the parent package (and therefore jax) BEFORE this
# module runs, and jax latches JAX_PLATFORMS from the environment at import
# time — so the env-var route is already too late here. The config route is
# not: platform and XLA_FLAGS are only consumed at FIRST BACKEND USE, which
# hasn't happened yet (the package imports never touch devices). Mirrors
# tests/conftest.py, which faces the same pre-imported-jax constraint.
import jax  # noqa: E402

if "JAX_PLATFORMS" not in os.environ:  # an explicit platform wins
    jax.config.update("jax_platforms", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
try:
    # jax >= 0.5 spelling; on 0.4.x the XLA_FLAGS route above carries it
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import argparse  # noqa: E402


def _csv(value):
    return [v.strip() for v in value.split(",") if v.strip()] if value else None


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="distributed_active_learning_tpu.analysis",
        description="jaxpr-level invariant audit + recompile-hazard lint",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable report")
    ap.add_argument(
        "--strategies", type=_csv, default=None,
        help="comma-separated strategy names (default: all registered)",
    )
    ap.add_argument(
        "--kinds", type=_csv, default=None,
        help="comma-separated program kinds: chunk,sweep,neural_chunk,serve",
    )
    ap.add_argument(
        "--placements", type=_csv, default=None,
        help="comma-separated placements: cpu,mesh4x2",
    )
    ap.add_argument(
        "--fail-on", choices=["info", "warn", "error"], default="error",
        help="exit 1 when any finding is at or above this severity "
        "(default error)",
    )
    ap.add_argument(
        "--no-lint", action="store_true",
        help="skip the AST recompile-hazard pass (jaxpr audit only)",
    )
    ap.add_argument(
        "--no-audit", action="store_true",
        help="skip the jaxpr audit (lint only; no jax tracing)",
    )
    ap.add_argument(
        "--costs", action="store_true",
        help="price each registered program with XLA's cost model "
        "(analysis/roofline.py: lower+compile, then compiled.cost_analysis) "
        "and print the static per-program cost table — flops, bytes "
        "accessed, arithmetic intensity. Unlike the audit this COMPILES "
        "every selected program; filter with --kinds/--strategies for a "
        "quick look",
    )
    ap.add_argument(
        "--memory", action="store_true",
        help="static memory planner (analysis/memory.py): AOT-compile each "
        "selected program, normalize compiled.memory_analysis() into peak "
        "HBM (args + temps + outputs + generated code - donation credit), "
        "estimate the "
        "pallas megakernel's VMEM tile set, and gate both against the "
        "device budget (or --budget-table). Error findings "
        "(hbm-over-budget / vmem-over-budget) fail the gate like audit "
        "findings do. Like --costs this COMPILES every selected program; "
        "filter with --kinds/--strategies for a quick look",
    )
    ap.add_argument(
        "--budget-table", default=None, metavar="PATH",
        help="JSON memory budget overriding the per-chip tables: "
        '{"hbm_bytes": N, "vmem_bytes": N} (either may be null to disable '
        "that axis; optional \"source\" labels findings). The tier-1 "
        "analysis job passes the committed CPU table",
    )
    ap.add_argument(
        "--list", action="store_true", help="list auditable programs and exit"
    )
    ap.add_argument(
        "--rules", action="store_true", help="print the rule table and exit"
    )
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from distributed_active_learning_tpu.analysis import lint as lint_lib
    from distributed_active_learning_tpu.analysis import rules as rules_lib
    from distributed_active_learning_tpu.analysis.auditor import run_audit
    from distributed_active_learning_tpu.analysis.programs import build_registry
    from distributed_active_learning_tpu.analysis.report import Report

    if args.rules:
        from distributed_active_learning_tpu.analysis.memory import MEMORY_RULES

        print("jaxpr rules:")
        for rule in rules_lib.default_rules():
            print(f"  {rule.id:28s} [{rule.severity}] {rule.description}")
        print("lint rules:")
        for rule_id, severity, desc in lint_lib.iter_rule_table():
            print(f"  {rule_id:28s} [{severity}] {desc}")
        print("memory rules:")
        for rule_id, (severity, desc) in MEMORY_RULES.items():
            print(f"  {rule_id:28s} [{severity}] {desc}")
        return 0

    specs = build_registry(
        strategies=args.strategies,
        kinds=args.kinds,
        placements=args.placements,
    )
    if args.list:
        for spec in specs:
            print(spec.name)
        return 0

    if args.costs:
        import json

        from distributed_active_learning_tpu.analysis.roofline import (
            cost_table,
            render_cost_table,
        )

        table = cost_table(specs)
        if args.json:
            print(json.dumps({"schema": 1, "costs": table}))
        else:
            print(render_cost_table(table))
        return 0

    if args.memory:
        import json

        from distributed_active_learning_tpu.analysis import memory as memory_lib

        budget = (
            memory_lib.load_budget_table(args.budget_table)
            if args.budget_table
            else memory_lib.device_budget()
        )
        table, findings = memory_lib.memory_table(specs, budget)
        section = memory_lib.memory_section(table, findings, budget)
        if args.json:
            print(json.dumps({"schema": 1, "memory": section}))
        else:
            print(memory_lib.render_memory_table(table, budget))
            for f in findings:
                print(str(f))
        gating = Report(findings=list(findings))
        return 1 if gating.gate(args.fail_on) else 0

    if args.no_audit:
        report = Report()
    else:
        report = run_audit(specs)
    if not args.no_lint:
        report.extend(lint_lib.lint_paths(lint_lib.default_lint_targets()))

    if args.json:
        print(report.to_json())
    else:
        print(report.render_table())
    return 1 if report.gate(args.fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
