"""Jaxpr-level invariant rules for the program auditor.

Each rule inspects ONE traced program (an :class:`~analysis.auditor.TracedUnit`
— the ClosedJaxpr, the output avals/structure, the lowered MLIR text) and
yields :class:`~analysis.report.Finding` records. Rules are registered in
``RULES`` by id; ``python -m distributed_active_learning_tpu.analysis --rules``
prints the registry as the living rule table.

The invariants these encode are exactly the ones the PR-2..PR-5 fast path
depends on but nothing verified statically until now:

- the fused scan must not hide host callbacks or device transfers (each one
  serializes every scan step on a launch boundary);
- declared buffer donation must actually alias (a donated-but-copied carry
  silently doubles HBM traffic on pool-scale states);
- no f64/weak-type avals may leak into programs or their boundary outputs
  (a weak output rebound as the next launch's input retriggers compilation);
- shard_map'd forest ops must not smuggle in unexpected gathers;
- the metrics contract (``with_metrics=True`` => RoundMetrics in the ys) must
  hold, or fused runs silently lose their per-round observability.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax

from distributed_active_learning_tpu.analysis.report import Finding

core = jax.core  # 0.4.x: ClosedJaxpr/Jaxpr both live here

# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

#: Host-callback primitives: their presence in a fused program means every
#: scan step funnels through the host runtime (the exact overhead the chunked
#: driver exists to remove). ``--stream-rounds`` opts into debug_callback.
CALLBACK_PRIMITIVES = frozenset({"pure_callback", "debug_callback", "io_callback"})

#: Collectives allowed inside a shard_map region: psum is the sharded vote /
#: bookkeeping reduction (parallel/kernels.py, collectives.py), ppermute the
#: ring schedule (ops/ring_attention.py), axis_index free. all_gather /
#: all_to_all rematerialize a full axis per shard — the r4-style silent
#: bandwidth cliff this rule exists to catch.
SHARD_MAP_ALLOWED_COLLECTIVES = frozenset({"psum", "ppermute", "axis_index", "pmin", "pmax"})
SHARD_MAP_FLAGGED_COLLECTIVES = frozenset({"all_gather", "all_to_all"})

_64BIT_DTYPES = frozenset({"float64", "complex128", "int64", "uint64"})


@dataclasses.dataclass(frozen=True)
class EqnSite:
    """One equation plus where it sits: the primitive path from the program
    root (e.g. ``scan/pjit/scan``), whether a shard_map encloses it, and the
    product of enclosing scan trip counts (an eqn inside a length-3 round
    scan EXECUTES three times per launch — byte accounting that ignores the
    multiplier undercounts collective traffic by the round count)."""

    eqn: object
    path: Tuple[str, ...]
    in_shard_map: bool
    trip_multiplier: int = 1

    @property
    def location(self) -> str:
        loc = "/".join(self.path) or "<top>"
        src = _source_of(self.eqn)
        return f"{loc}: {src}" if src else loc


def _source_of(eqn) -> Optional[str]:
    try:
        from jax._src import source_info_util

        src = source_info_util.summarize(eqn.source_info)
        return src or None
    except Exception:
        return None


def _sub_jaxprs(eqn) -> List[core.Jaxpr]:
    subs: List[core.Jaxpr] = []
    for v in eqn.params.values():
        if isinstance(v, core.ClosedJaxpr):
            subs.append(v.jaxpr)
        elif isinstance(v, core.Jaxpr):
            subs.append(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, core.ClosedJaxpr):
                    subs.append(item.jaxpr)
                elif isinstance(item, core.Jaxpr):
                    subs.append(item)
    return subs


def iter_eqns(jaxpr: core.Jaxpr) -> Iterator[EqnSite]:
    """Depth-first walk over every equation, including those inside scan /
    cond / pjit / shard_map / custom_* sub-jaxprs. ``trip_multiplier``
    accumulates scan ``length`` params down the walk (cond branches and
    while bodies count as 1 — a static walk cannot bound them tighter)."""

    def walk(jx: core.Jaxpr, path: Tuple[str, ...], in_sm: bool, trips: int):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            yield EqnSite(
                eqn=eqn, path=path, in_shard_map=in_sm, trip_multiplier=trips
            )
            inner_sm = in_sm or name == "shard_map"
            inner_trips = trips
            if name == "scan":
                length = eqn.params.get("length")
                if isinstance(length, int) and length > 0:
                    inner_trips = trips * length
            for sub in _sub_jaxprs(eqn):
                yield from walk(sub, path + (name,), inner_sm, inner_trips)

    yield from walk(jaxpr, (), False, 1)


def iter_avals(jaxpr: core.Jaxpr) -> Iterator[Tuple[str, object]]:
    """Every aval in the program: boundary vars, closure constants, and each
    equation's outputs, labeled with where it was seen."""
    for v in jaxpr.invars:
        yield "<input>", v.aval
    for v in jaxpr.constvars:
        # captured closure constants — a stray np.float64 scalar enters here,
        # not through the declared inputs
        yield "<const>", v.aval
    for site in iter_eqns(jaxpr):
        for v in site.eqn.outvars:
            if hasattr(v, "aval"):
                yield site.location, v.aval


def _aval_str(aval) -> str:
    try:
        return aval.str_short()
    except Exception:
        return str(aval)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    description: str
    check: Callable  # (TracedUnit) -> Iterator[Finding]


RULES: Dict[str, Rule] = {}


def register_rule(rule_id: str, severity: str, description: str):
    def deco(fn):
        RULES[rule_id] = Rule(
            id=rule_id, severity=severity, description=description, check=fn
        )
        return fn

    return deco


def _finding(rule_id: str, unit, location: str, message: str) -> Finding:
    rule = RULES[rule_id]
    return Finding(
        rule=rule_id,
        severity=rule.severity,
        program=unit.name,
        location=location,
        message=message,
    )


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

@register_rule(
    "host-callback-in-fast-path",
    "error",
    "no pure_callback/debug_callback/io_callback inside a fused program "
    "unless the program opted into round streaming (--stream-rounds)",
)
def _rule_host_callback(unit) -> Iterator[Finding]:
    if unit.allows_callbacks:
        return
    for site in unit.eqn_sites:
        if site.eqn.primitive.name in CALLBACK_PRIMITIVES:
            yield _finding(
                "host-callback-in-fast-path",
                unit,
                site.location,
                f"{site.eqn.primitive.name} rides the traced fast path; every "
                "scan step now funnels through the host callback runtime",
            )


@register_rule(
    "device-transfer-in-fast-path",
    "error",
    "no device_put with a concrete destination inside a fused program "
    "(alias-semantics puts with no target device are benign)",
)
def _rule_device_transfer(unit) -> Iterator[Finding]:
    for site in unit.eqn_sites:
        if site.eqn.primitive.name != "device_put":
            continue
        devices = site.eqn.params.get("devices", ())
        if any(d is not None for d in devices):
            yield _finding(
                "device-transfer-in-fast-path",
                unit,
                site.location,
                f"device_put with explicit destination {devices} inside the "
                "traced program forces a placement/transfer per execution",
            )


@register_rule(
    "f64-aval",
    "error",
    "no 64-bit (f64/c128/i64/u64) avals anywhere in the program — an x64 "
    "leak doubles bandwidth on the whole downstream chain",
)
def _rule_f64(unit) -> Iterator[Finding]:
    seen = set()
    for where, aval in unit.avals:
        dtype = getattr(aval, "dtype", None)
        if dtype is not None and str(dtype) in _64BIT_DTYPES:
            key = (where, str(dtype))
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                "f64-aval", unit, where,
                f"64-bit aval {_aval_str(aval)} in the traced program",
            )


@register_rule(
    "weak-type-output",
    "error",
    "program outputs must not be weakly typed: a weak output rebound as the "
    "next launch's input has a different aval and retriggers compilation",
)
def _rule_weak_output(unit) -> Iterator[Finding]:
    for i, aval in enumerate(unit.out_avals):
        if getattr(aval, "weak_type", False):
            yield _finding(
                "weak-type-output", unit, f"output[{i}]",
                f"weakly-typed output {_aval_str(aval)}; rebinding it as an "
                "input changes the aval and recompiles",
            )


@register_rule(
    "carry-aval-drift",
    "error",
    "the carried state's output avals must equal its input avals exactly "
    "(shape, dtype, weak type) so launch N+1 reuses launch N's executable",
)
def _rule_carry_drift(unit) -> Iterator[Finding]:
    if unit.carry_in_avals is None or unit.carry_out_avals is None:
        return
    ins, outs = unit.carry_in_avals, unit.carry_out_avals
    if len(ins) != len(outs):
        yield _finding(
            "carry-aval-drift", unit, "<carry>",
            f"carry leaf count changed across the launch: {len(ins)} in, "
            f"{len(outs)} out",
        )
        return
    for i, (a_in, a_out) in enumerate(zip(ins, outs)):
        same = (
            getattr(a_in, "shape", None) == getattr(a_out, "shape", None)
            and getattr(a_in, "dtype", None) == getattr(a_out, "dtype", None)
            and getattr(a_in, "weak_type", False) == getattr(a_out, "weak_type", False)
        )
        if not same:
            yield _finding(
                "carry-aval-drift", unit, f"carry leaf [{i}]",
                f"carry aval drifts across the launch: {_aval_str(a_in)} in "
                f"vs {_aval_str(a_out)} out — the next dispatch recompiles",
            )


@register_rule(
    "donation-not-aliased",
    "error",
    "a program built with donate_argnums must actually alias its donated "
    "buffers to outputs (cross-checked against the lowering's "
    "tf.aliasing_output / jax.buffer_donor metadata)",
)
def _rule_donation(unit) -> Iterator[Finding]:
    if not unit.expect_donation:
        return
    text = unit.lowered_text
    if text is None:
        yield _finding(
            "donation-not-aliased", unit, "<lowering>",
            "program expects donation but could not be lowered to check "
            "aliasing metadata",
        )
        return
    # Two valid spellings of a live donation in the lowering: a resolved
    # input-output alias (tf.aliasing_output — single-device programs, where
    # jax matches avals itself) or a deferred donation handed to the
    # compiler (jax.buffer_donor — sharded programs, where output shardings
    # are GSPMD's to decide). A donated-but-UNUSABLE buffer gets NEITHER
    # (jax strips it with the "donated buffers were not usable" warning) —
    # that silent drop is the regression this rule exists to catch.
    aliased = len(re.findall(r"tf\.aliasing_output", text))
    donors = len(re.findall(r"jax\.buffer_donor", text))
    if aliased == 0 and donors == 0:
        yield _finding(
            "donation-not-aliased", unit, "<lowering>",
            "donation declared but no donated input survives to the "
            "lowering (no tf.aliasing_output, no jax.buffer_donor) — the "
            "carried state is copied every launch",
        )


@register_rule(
    "collective-in-shard-map",
    "error",
    "no all_gather/all_to_all inside shard_map'd forest ops (psum/ppermute "
    "are the sanctioned collectives); a gather rematerializes a full mesh "
    "axis per shard. Units that declare pool_rows narrow the lint to "
    "pool-sized operands/results: the rebalance epoch's WINDOW-sized "
    "all_to_all row exchange is sanctioned there, priced by the bytes "
    "budget instead",
)
def _rule_shard_map_collectives(unit) -> Iterator[Finding]:
    pool_rows = getattr(unit, "pool_rows", None)
    for site in unit.eqn_sites:
        if not site.in_shard_map:
            continue
        name = site.eqn.primitive.name
        if name not in SHARD_MAP_FLAGGED_COLLECTIVES:
            continue
        if pool_rows:
            # pool-aware units (serve/pod programs): a bounded window
            # exchange is the rebalance contract — only a pool-scale
            # gather/exchange is the bandwidth cliff this rule names.
            # (Outputs count too: all_gather's cliff is its RESULT.)
            avals = [
                getattr(v, "aval", None)
                for v in list(site.eqn.invars) + list(site.eqn.outvars)
            ]
            if not any(
                a is not None and _has_pool_dim(a, pool_rows) for a in avals
            ):
                continue
        yield _finding(
            "collective-in-shard-map", unit, site.location,
            f"{name} inside a shard_map region rematerializes the "
            "sharded axis on every shard",
        )


# ---------------------------------------------------------------------------
# sharding / collective invariants (the pod-sharding contract)
# ---------------------------------------------------------------------------

#: Primitives that move bytes across a mesh axis. The byte accounting prices
#: every one of them (per-shard operand bytes x scan trips); the pool-scale
#: rule only fires on those whose operands carry a pool-sized dim.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
})


def _aval_nbytes(aval) -> Optional[float]:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    n = 1.0
    for s in shape:
        if not isinstance(s, int):
            return None  # dynamic dims: unpriceable statically
        n *= s
    try:
        return n * dtype.itemsize
    except Exception:
        return None


def _has_pool_dim(aval, pool_rows: int) -> bool:
    shape = getattr(aval, "shape", ())
    return any(isinstance(s, int) and s >= pool_rows for s in shape)


def collective_traffic(unit) -> List[Tuple[object, float]]:
    """Every collective site inside a shard_map region with its per-launch
    byte cost: per-shard operand bytes x the enclosing scan trip count.
    The per-SHARD number is deliberate — it is what crosses each link."""
    out = []
    for site in unit.eqn_sites:
        if not site.in_shard_map:
            continue
        if site.eqn.primitive.name not in COLLECTIVE_PRIMITIVES:
            continue
        nbytes = 0.0
        for v in site.eqn.invars:
            b = _aval_nbytes(getattr(v, "aval", None))
            if b:
                nbytes += b
        out.append((site, nbytes * site.trip_multiplier))
    return out


#: Derived collective budget: this many times the largest input operand.
#: Sanctioned traffic (vote psums, ring ppermutes, bookkeeping reductions)
#: sits orders of magnitude below it; an all-gathered pool axis (shards x
#: pool bytes x rounds) blows straight through.
COLLECTIVE_BUDGET_FACTOR = 16


@register_rule(
    "replicated-pool-operand",
    "error",
    "a pool-sized operand must not enter a shard_map fully replicated "
    "(empty in_names): every device then holds — and streams — the whole "
    "pool, which is exactly the footprint pod-sharding exists to remove",
)
def _rule_replicated_pool(unit) -> Iterator[Finding]:
    pool_rows = getattr(unit, "pool_rows", None)
    if not pool_rows:
        return
    for site in unit.eqn_sites:
        if site.eqn.primitive.name != "shard_map":
            continue
        in_names = site.eqn.params.get("in_names", ())
        for v, names in zip(site.eqn.invars, in_names):
            aval = getattr(v, "aval", None)
            if aval is None or not _has_pool_dim(aval, pool_rows):
                continue
            if not names:  # {} = no dim sharded over any mesh axis
                yield _finding(
                    "replicated-pool-operand", unit, site.location,
                    f"pool-sized operand {_aval_str(aval)} enters shard_map "
                    "fully replicated (empty in_names) — every shard "
                    "materializes the whole pool",
                )


@register_rule(
    "pool-scale-collective",
    "error",
    "no collective may move a pool-sized array across the mesh (a "
    "per-shard operand carrying a pool-scale dim means the sharding "
    "failed to divide the pool before the collective ran)",
)
def _rule_pool_scale_collective(unit) -> Iterator[Finding]:
    pool_rows = getattr(unit, "pool_rows", None)
    if not pool_rows:
        return
    for site in unit.eqn_sites:
        if not site.in_shard_map:
            continue
        name = site.eqn.primitive.name
        if name not in COLLECTIVE_PRIMITIVES:
            continue
        for v in site.eqn.invars:
            aval = getattr(v, "aval", None)
            if aval is not None and _has_pool_dim(aval, pool_rows):
                yield _finding(
                    "pool-scale-collective", unit, site.location,
                    f"{name} moves a pool-scale operand {_aval_str(aval)} "
                    "across the mesh — per-shard traffic proportional to "
                    "the FULL pool, not the shard",
                )
                break


@register_rule(
    "collective-bytes-over-budget",
    "error",
    "a program's accounted collective traffic (per-shard operand bytes x "
    "scan trips, summed over every collective in its shard_map regions) "
    "must stay under its budget — default 16x the largest input operand; "
    "the exactness contract the ring-exchange selection merge inherits",
)
def _rule_collective_bytes(unit) -> Iterator[Finding]:
    traffic = collective_traffic(unit)
    if not traffic:
        return
    total = sum(b for _, b in traffic)
    budget = getattr(unit, "collective_bytes_budget", None)
    if budget is None:
        largest = max(
            (b for b in (
                _aval_nbytes(a) for a in unit.jaxpr.in_avals
            ) if b),
            default=None,
        )
        if largest is None:
            return
        budget = COLLECTIVE_BUDGET_FACTOR * largest
    if total <= budget:
        return
    worst_site, worst_bytes = max(traffic, key=lambda t: t[1])
    yield _finding(
        "collective-bytes-over-budget", unit, worst_site.location,
        f"collective traffic {total:,.0f} B/launch exceeds the budget "
        f"{budget:,.0f} B ({len(traffic)} collective site(s); worst: "
        f"{worst_site.eqn.primitive.name} at {worst_bytes:,.0f} B incl. "
        f"x{worst_site.trip_multiplier} scan trips)",
    )


@register_rule(
    "metrics-missing",
    "error",
    "a program built with with_metrics=True must return the RoundMetrics "
    "pytree in its ys (fused runs otherwise lose per-round observability "
    "silently)",
)
def _rule_metrics(unit) -> Iterator[Finding]:
    if not unit.with_metrics:
        return
    if "RoundMetrics" not in unit.out_tree_repr:
        yield _finding(
            "metrics-missing", unit, "<outputs>",
            "with_metrics=True but no RoundMetrics node in the output tree",
        )


@register_rule(
    "quantized-leaf-upcast",
    "error",
    "a program built with quantized forest storage (ForestConfig.quantize) "
    "must keep the narrow representation live: the storage dtype present, an "
    "in-program dequantization convert present, and (int8) the rank-<=2 leaf "
    "tensor reaching the streaming eval eqns — a silent f32 upcast between "
    "fit and eval forfeits the 2-4x bandwidth headroom without failing any "
    "numeric test",
)
def _rule_quantized_upcast(unit) -> Iterator[Finding]:
    mode = getattr(unit, "quantize", None)
    if mode not in ("bf16", "int8"):
        return
    narrow = "int8" if mode == "int8" else "bfloat16"
    # (1) storage exists at all: if quantize_forest stopped being applied the
    # whole program is silently f32 again.
    if not any(
        str(getattr(aval, "dtype", "")) == narrow for _, aval in unit.avals
    ):
        yield _finding(
            "quantized-leaf-upcast", unit, "<avals>",
            f"quantize={mode!r} declared but no {narrow} aval exists anywhere "
            "in the traced program — the storage was never narrowed",
        )
        return
    # (2) the point-of-use dequant: some narrow -> f32 convert must exist
    # (models.forest.dequantize_leaf_values inside the eval bodies).
    has_dequant = any(
        site.eqn.primitive.name == "convert_element_type"
        and site.eqn.invars
        and hasattr(site.eqn.invars[0], "aval")
        and str(site.eqn.invars[0].aval.dtype) == narrow
        and str(site.eqn.params.get("new_dtype")) == "float32"
        for site in unit.eqn_sites
    )
    if not has_dequant:
        yield _finding(
            "quantized-leaf-upcast", unit, "<eqns>",
            f"no {narrow} -> float32 convert in the program: the quantized "
            "leaves are never dequantized at the point of use (either the "
            "eval reads them raw — wrong numerics — or a cached f32 copy is "
            "being streamed instead)",
        )
    if mode != "int8":
        # bf16 mode has no sharper static signature: bf16 operands are
        # legitimate all over the eval kernels (x tiles, path matrices), so
        # presence + dequant is the checkable invariant.
        return
    # (3) int8 only: the leaf-stat tensor (rank <= 2; the pallas path matrix
    # is the only other int8 operand and rides rank 3) must be an INPUT of a
    # streaming eval eqn — pallas_call, or a scan nested under the chunk's
    # outer scan (the lax.map tile stream). An upcast between fit and eval
    # hands those eqns f32 leaves instead.
    for site in unit.eqn_sites:
        name = site.eqn.primitive.name
        in_stream = name == "pallas_call" or (
            name == "scan" and site.path.count("scan") >= 1
        )
        if not in_stream:
            continue
        for v in site.eqn.invars:
            aval = getattr(v, "aval", None)
            if (
                aval is not None
                and str(getattr(aval, "dtype", "")) == "int8"
                and len(getattr(aval, "shape", ())) <= 2
            ):
                return
    yield _finding(
        "quantized-leaf-upcast", unit, "<eqns>",
        "int8 leaf stats never reach a streaming eval eqn (pallas_call or "
        "nested scan) as an input — the stored forest was upcast to f32 "
        "between fit and eval, forfeiting the bandwidth headroom",
    )


def default_rules() -> List[Rule]:
    return list(RULES.values())
