"""Command-line experiment driver.

The reference is launched as ``spark-submit uncertainty_sampling.py`` with all
parameters hardcoded per file (SURVEY.md §5.6); this CLI is the replacement:

    python -m distributed_active_learning_tpu.run \
        --dataset checkerboard4x4 --strategy uncertainty --window 10 \
        --rounds 40 --out results/distUS_w10.txt

``--strategy random`` reproduces the control arm (``random_sampling.py``),
``--strategy density`` the information-density run (``density_weighting.py``),
``--strategy lal`` the LAL learner (``classes/active_learner.py``); results are
written in the reference's log format for curve-for-curve comparison.
"""

from __future__ import annotations

import argparse
import json
import sys

from distributed_active_learning_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ForestConfig,
    MeshConfig,
    StrategyConfig,
)


# The paper's strategy abbreviations (PAPER.md §0 results matrix) accepted
# anywhere a strategy is named on the CLI: "us" is uncertainty sampling.
_STRATEGY_ALIASES = {"us": "uncertainty"}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="distributed_active_learning_tpu.run",
        description="TPU-native pool-based active learning",
    )
    ap.add_argument("--dataset", default="checkerboard2x2")
    ap.add_argument(
        "--datasets", default=None, metavar="A,B,...",
        help="comma-separated dataset list: with --sweep-seeds/--strategies "
        "this adds a batched dataset axis to the grid launch (pools padded "
        "to a common slab width, one compile shared across cells; "
        "runtime/sweep.py run_grid). Overrides --dataset",
    )
    ap.add_argument("--data-path", default=None, help="path for file-backed datasets")
    ap.add_argument("--n-samples", type=int, default=None, help="subsample the pool")
    ap.add_argument("--strategy", default="uncertainty")
    ap.add_argument(
        "--strategies", default=None, metavar="A,B,...",
        help="comma-separated strategy list: run the whole strategies x "
        "seeds (x datasets) grid as ONE pipelined launch stream — cells "
        "grouped by scoring family, one top-k per group, masked merge "
        "(runtime/sweep.py run_grid). Combine with --sweep-seeds N and "
        "--datasets; per-cell records are bit-identical to the serial "
        "S x E loop. Overrides --strategy; needs --fit device for the "
        "batched path (host fit falls back to serial cells)",
    )
    ap.add_argument("--window", type=int, default=10)
    ap.add_argument("--beta", type=float, default=1.0)
    ap.add_argument(
        "--strategy-option", action="append", default=[], metavar="K=V",
        help="per-strategy option (repeatable), e.g. --strategy-option "
        "lal_trees=2000 --strategy-option lal_model_path=/tmp/lal.npz; "
        "values parse as int/float when they look like one",
    )
    ap.add_argument("--trees", type=int, default=10)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument(
        "--kernel", choices=["gemm", "pallas", "gather"], default="gemm",
        help="forest evaluation kernel: gemm (exact MXU path-matrix form, "
        "default), pallas (fused VMEM kernel, ~2.5x faster scoring; bf16 "
        "feature compares), gather (traversal form)",
    )
    ap.add_argument(
        "--fused-round", action="store_true",
        help="route score + select through the round megakernel "
        "(ops/round_fused.py): forest eval, acquisition score, and top-k in "
        "ONE pass over the pool slab — a pallas megakernel under --kernel "
        "pallas, an XLA tile stream under --kernel gemm. Bit-identical "
        "picks; needs --fit device, a vote-fraction strategy (uncertainty/"
        "entropy/full_entropy/margin), a binary pool, and no --metrics-out "
        "(refused loudly otherwise)",
    )
    ap.add_argument(
        "--quantize", choices=["none", "bf16", "int8"], default="none",
        help="quantized forest storage (device fit only): bf16 thresholds + "
        "bf16/int8 leaf stats, dequantized inside the eval kernels — 2-4x "
        "less HBM traffic. bf16 decision paths are bit-identical (thresholds "
        "are bf16-snapped bin edges); int8 shifts leaf probabilities by "
        "<= 1/254",
    )
    ap.add_argument(
        "--fit", choices=["host", "device"], default="host",
        help="forest training: host (sklearn on the labeled subset, the "
        "JVM-fit equivalent) or device (jitted histogram trainer; the whole "
        "round runs as device programs)",
    )
    # Scenario engine (scenarios/): perturb the loop without forking it.
    ap.add_argument(
        "--scenario", default="none",
        choices=["none", "noisy_oracle", "cost_budget", "rare_event", "drift"],
        help="run the experiment under a scenario (scenarios/): noisy_oracle "
        "(label flips + probabilistic abstaining reveal — budget accounting "
        "counts REVEALED labels; --rounds required when abstaining), "
        "cost_budget (per-point labeling costs, greedy knapsack top-k under "
        "a per-round spend cap), rare_event (recall-at-budget of the rare "
        "class rides RoundMetrics), drift (the test stream drifts per round "
        "index). Needs --fit device; with --sweep-seeds the run routes "
        "through the grid launcher (scenario x seed)",
    )
    ap.add_argument(
        "--scenarios", default=None, metavar="A,B,...",
        help="comma-separated scenario list: adds a SCENARIO axis to the "
        "grid launch (scenario x strategy x seed [x dataset] as one "
        "pipelined stream; runtime/sweep.py run_grid). Entries share the "
        "scenario knobs below; 'none' cells stay bit-identical to the "
        "clean grid. Overrides --scenario",
    )
    ap.add_argument("--flip-prob", type=float, default=0.0,
                    help="noisy_oracle: per-point label-flip probability")
    ap.add_argument("--abstain-prob", type=float, default=0.0,
                    help="noisy_oracle: per-reveal abstain probability")
    ap.add_argument("--cost-budget", type=float, default=0.0,
                    help="cost_budget: per-round labeling spend cap")
    ap.add_argument("--cost-spread", type=float, default=4.0,
                    help="cost_budget: synthetic costs in [1, 1+spread]")
    ap.add_argument("--rare-class", type=int, default=1,
                    help="rare_event: the hunted class id")
    ap.add_argument("--drift-kind", choices=["mean_shift", "rotation"],
                    default="mean_shift")
    ap.add_argument("--drift-rate", type=float, default=0.0,
                    help="drift: per-round drift magnitude")
    ap.add_argument("--scenario-seed", type=int, default=0,
                    help="seed for scenario randomness (flips, costs, drift "
                    "direction) — separate from --seed so clean cells' PRNG "
                    "streams are untouched")
    ap.add_argument("--n-start", type=int, default=10)
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--budget", type=int, default=None, help="stop at N labeled")
    ap.add_argument(
        "--rounds-per-launch", type=int, default=1,
        help="fuse this many AL rounds into one jitted lax.scan launch (host "
        "touches down only at chunk boundaries; results identical, stopping "
        "exact). Applies to --fit device on the forest path and to the "
        "fusable deep strategies (MC-score family/random/density) on the "
        "neural path. 1 = per-round driver",
    )
    ap.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="chunk launches allowed in flight at once (with "
        "--rounds-per-launch > 1): 2 overlaps each chunk's host touchdown "
        "(record append/log/checkpoint) with the next chunk's device "
        "execution — results stay bit-identical; 1 = strict serial "
        "launch -> block -> touchdown order",
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--sweep-seeds", type=int, default=1, metavar="N",
        help="run N seeds (--seed .. --seed+N-1) as ONE batched launch "
        "stream: the fused chunk program vmapped over a leading experiment "
        "axis sharing the pool (runtime/sweep.py). Per-seed results are "
        "bit-identical to N serial runs; stdout prints each seed's log under "
        "a '# sweep seed' header, --out writes per-seed files "
        "(out_s<seed>.txt). Needs --fit device for the batched path (host "
        "fit falls back to N serial runs); forest loop only",
    )
    # Observability (runtime/telemetry.py): structured JSONL metrics stream
    # and jax.profiler trace capture.
    ap.add_argument(
        "--metrics-out", default=None, metavar="PATH",
        help="write rank-tagged JSONL telemetry events: one 'round' event per "
        "AL round (with device-computed score/entropy/histogram metrics — "
        "fused runs emit them from the scan itself, no extra host syncs), "
        "plus launch accounting, transfer counters, and memory gauges; "
        "summarize with benches/summarize_metrics.py",
    )
    ap.add_argument(
        "--stream-rounds", action="store_true",
        help="with --metrics-out and a fused launch (--rounds-per-launch > "
        "1): emit one 'round_stream' JSONL event per round from INSIDE the "
        "running chunk via jax.debug.callback — live progress during long "
        "chunks instead of only at touchdowns. Off by default (the callback "
        "rides the traced program; the zero-overhead fast path stays "
        "untouched without the flag)",
    )
    ap.add_argument(
        "--profile-dir", default=None, metavar="DIR",
        help="capture a jax.profiler trace of the whole run into DIR (open "
        "in TensorBoard's Profile plugin or Perfetto); phases and hot ops "
        "are name-scoped, so device time is attributable per AL phase",
    )
    ap.add_argument(
        "--roofline", action="store_true",
        help="with --metrics-out and a fused forest run (--fit device, "
        "--rounds-per-launch > 1): price the launched chunk program with "
        "XLA's cost model (analysis/roofline.py) after the run and emit a "
        "'roofline' JSONL event — static flops/bytes joined with measured "
        "launch seconds into achieved FLOP/s, bandwidth, MFU, and a "
        "compute-vs-bandwidth bound verdict. Pays one extra (AOT) compile "
        "after the run finishes",
    )
    ap.add_argument(
        "--flight-recorder", default=None, metavar="PATH",
        help="record launch/touchdown/veto/recompile events into a bounded "
        "in-process ring buffer and dump the last N as one JSON artifact at "
        "PATH on SIGUSR1 (probe a live run), SIGTERM, unhandled crash, and "
        "normal exit — a dead run leaves a trace of what it was doing "
        "(runtime/telemetry.py FlightRecorder)",
    )
    ap.add_argument(
        "--ops-port", type=int, default=0, metavar="PORT",
        help="serve the live ops plane (runtime/obs.py) on localhost:PORT "
        "for the duration of the run — /metrics (Prometheus text: launch "
        "counters, pipeline depth, grid progress/frozen-cell/ETA gauges), "
        "/healthz (liveness + last-touchdown age), /varz, /flightz — so a "
        "multi-hour grid launch is watchable mid-flight instead of only "
        "post-hoc; 0 (default) = off",
    )
    ap.add_argument(
        "--phase-detail", action="store_true",
        help="force per-phase (train/round/eval) host wall splits; with "
        "--rounds-per-launch > 1 this disables scan fusion (phases cannot "
        "be attributed inside one fused launch) — prefer --profile-dir for "
        "attribution that keeps fusion",
    )
    ap.add_argument("--out", default=None, help="write reference-format results log")
    ap.add_argument("--plot", default=None, help="save accuracy/time curves as PNG")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=0)
    # Device mesh for the sharded round (1x1 = single device). Pool rows ride
    # the data axis, trees the model axis; non-divisible pools are padded.
    ap.add_argument("--mesh-data", type=int, default=1)
    ap.add_argument("--mesh-model", type=int, default=1)
    ap.add_argument(
        "--audit", action="store_true",
        help="statically audit the program this run would launch BEFORE "
        "running it (analysis/ jaxpr auditor + recompile-hazard lint over "
        "runtime/ and strategies/): traces the fused chunk/sweep/neural "
        "program for this strategy and placement and refuses to run on any "
        "error-severity finding. Seconds of tracing to rule out a silent "
        "perf regression before hours of experiment",
    )
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--json", action="store_true", help="print per-round records as JSON lines")
    ap.add_argument("--list", action="store_true", help="list datasets and strategies")
    # Neural (deep-AL) mode: a neural learner over the pool with MC-dropout
    # acquisition. Selected by --neural or a "deep.*"-namespaced strategy name.
    ap.add_argument("--neural", action="store_true", help="use the neural-learner path")
    ap.add_argument(
        "--model", choices=["auto", "mlp", "cnn", "transformer"], default="auto",
        help="neural learner (auto: cnn for image pools, transformer for "
        "token pools, mlp for tabular)",
    )
    ap.add_argument("--train-steps", type=int, default=200)
    ap.add_argument("--mc-samples", type=int, default=8)
    # BatchBALD bounds (deep.batchbald): the exact joint is tracked while the
    # config count stays under --batchbald-max-configs, and the greedy batch is
    # drawn from the top --candidate-pool unlabeled points by marginal BALD.
    ap.add_argument("--batchbald-max-configs", type=int, default=4096)
    ap.add_argument(
        "--batchbald-samples", type=int, default=256,
        help="MC configurations carried past the exact-joint cap (picks "
        "beyond log_C(max-configs) stay joint-aware via Kirsch et al.'s "
        "sampled estimator)",
    )
    ap.add_argument("--candidate-pool", type=int, default=512)
    ap.add_argument(
        "--coreset-space", choices=["input", "embedding"], default="input",
        help="deep.coreset feature space: raw pool features or the trained "
        "network's penultimate representation",
    )
    ap.add_argument("--hidden", default="128,64", help="MLP hidden sizes (neural mode)")
    # Transformer encoder size (--model transformer)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--d-ff", type=int, default=256)
    return ap


def _parse_strategy_options(pairs) -> dict:
    """Parse repeated ``K=V`` flags; numeric-looking values become int/float
    (the LAL knobs — lal_trees, lal_depth, lal_experiments — are ints; paths
    stay strings)."""
    options = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--strategy-option needs K=V, got {pair!r}")
        k, v = pair.split("=", 1)
        for cast in (int, float):
            try:
                v = cast(v)
                break
            except ValueError:
                continue
        options[k] = v
    return options


def main(argv=None) -> int:
    ap = build_parser()
    args = ap.parse_args(argv)
    args.strategy = _STRATEGY_ALIASES.get(args.strategy, args.strategy)

    if args.list:
        from distributed_active_learning_tpu.data import available_datasets
        from distributed_active_learning_tpu.runtime.neural_loop import (
            available_deep_strategies,
        )
        from distributed_active_learning_tpu.strategies import available_strategies

        print("datasets:", ", ".join(available_datasets()))
        print("strategies:", ", ".join(available_strategies()))
        print("deep strategies:", ", ".join(available_deep_strategies()))
        return 0

    # Join a multi-host job before any jax device use, iff one is configured
    # (explicit coordinator env or Cloud TPU pod metadata); otherwise a pod
    # launch would run each host as an independent process-0 job and every
    # host would write checkpoints/results (the process-0-only gates would
    # never engage). Placed after --list so metadata queries on one pod
    # worker never block at the distributed barrier; JAX_NUM_PROCESSES=1
    # opts a worker out explicitly.
    from distributed_active_learning_tpu.parallel import multihost

    multihost.maybe_initialize()

    from distributed_active_learning_tpu.runtime.debugger import Debugger
    from distributed_active_learning_tpu.runtime.loop import run_experiment

    if args.flight_recorder:
        from distributed_active_learning_tpu.runtime import telemetry

        telemetry.install_flight_recorder(args.flight_recorder)

    if args.ops_port:
        # Primary host only: on a multihost pod every worker runs this same
        # main(), and N hosts binding the same --ops-port would collide (and
        # per-host metrics registries already merge into the primary's
        # export). Non-primary hosts log the skip so an operator probing a
        # worker's port gets a pointer instead of silence.
        if multihost.is_primary():
            # Bound before any compile so /healthz answers from second one;
            # the serve thread is a daemon — it dies with the run, no
            # teardown path needed across this function's many exits.
            from distributed_active_learning_tpu.runtime.obs import OpsServer

            ops_server = OpsServer(port=args.ops_port).start()
            print(
                f"# ops plane: http://127.0.0.1:{ops_server.port}/metrics "
                "(/healthz /varz /flightz)",
                file=sys.stderr, flush=True,
            )
        else:
            import jax

            print(
                f"# ops plane: skipped on host {jax.process_index()} "
                "(primary host binds --ops-port)",
                file=sys.stderr, flush=True,
            )

    # phase_detail defaults False since the telemetry PR: an enabled Debugger
    # no longer costs a fused run its scan fusion (per-round visibility comes
    # from the in-scan RoundMetrics instead); --phase-detail opts back into
    # host-timed phases. --quiet --rounds-per-launch K is therefore the
    # zero-overhead fast path: no printer calls, chunked driver engaged.
    dbg = Debugger(enabled=not args.quiet, phase_detail=args.phase_detail)
    # Fail fast on an unwritable --profile-dir: jax.profiler only errors when
    # the trace is flushed at run END, which would waste the whole experiment.
    if args.profile_dir:
        from distributed_active_learning_tpu.runtime.telemetry import (
            prepare_profile_dir,
        )

        try:
            prepare_profile_dir(args.profile_dir)
        except ValueError as e:
            ap.error(str(e))
    # Both loops gate persistence on dir AND interval; half a request would be
    # silently ignored, dropping the user's crash-resume protection.
    if bool(args.checkpoint_dir) != bool(args.checkpoint_every):
        ap.error(
            "checkpointing needs both --checkpoint-dir and --checkpoint-every"
        )
    if args.stream_rounds and (
        args.sweep_seeds > 1 or args.strategies or args.datasets
    ):
        # The batched sweep/grid chunks carry no in-scan stream callback (E
        # unordered per-experiment streams under vmap); refuse rather than
        # silently drop the user's requested live events.
        ap.error(
            "--stream-rounds is not supported with --sweep-seeds > 1 / "
            "--strategies / --datasets; per-round events still arrive at "
            "every chunk touchdown via --metrics-out"
        )
    if args.fused_round and (
        args.sweep_seeds > 1 or args.strategies or args.datasets
        or args.neural or args.strategy.startswith("deep.")
    ):
        # The megakernel is wired into the single forest chunk only
        # (loop.make_chunk_fn); the sweep/grid/neural launchers never read
        # cfg.fused_round, so honor the loud-refusal contract
        # (loop._fused_round_reason) instead of silently running unfused —
        # and note the neural loop already fuses every built-in strategy
        # into its scan without this flag.
        ap.error(
            "--fused-round serves the single forest experiment only; the "
            "sweep/grid launchers (--sweep-seeds > 1 / --strategies / "
            "--datasets) and the neural loop run their own fused chunks "
            "without it (ROADMAP: serving the megakernel from the batched "
            "launchers is a follow-up)"
        )
    # Scenario engine flags (scenarios/): one base ScenarioConfig carries the
    # knobs; --scenarios crosses kinds into a grid axis sharing those knobs.
    from distributed_active_learning_tpu.config import ScenarioConfig

    base_scenario = ScenarioConfig(
        kind=args.scenario,
        flip_prob=args.flip_prob,
        abstain_prob=args.abstain_prob,
        cost_budget=args.cost_budget,
        cost_spread=args.cost_spread,
        rare_class=args.rare_class,
        drift_kind=args.drift_kind,
        drift_rate=args.drift_rate,
        seed=args.scenario_seed,
    )
    scenario_names = (
        [s.strip() for s in args.scenarios.split(",") if s.strip()]
        if args.scenarios else None
    )
    scenario_cfgs = None
    if scenario_names is not None:
        from distributed_active_learning_tpu.scenarios import (
            SCENARIO_KINDS,
            scenario_from_name,
        )

        unknown = [s for s in scenario_names if s not in SCENARIO_KINDS]
        if unknown:
            ap.error(
                f"unknown scenarios {unknown}; one of {list(SCENARIO_KINDS)}"
            )
        if len(set(scenario_names)) != len(scenario_names):
            ap.error(f"duplicate scenarios in --scenarios: {scenario_names}")
        scenario_cfgs = [
            scenario_from_name(s, base_scenario) for s in scenario_names
        ]
    scenario_on = scenario_cfgs is not None and any(
        s.active for s in scenario_cfgs
    )
    if scenario_cfgs is not None and not scenario_on:
        scenario_cfgs = None  # `--scenarios none` IS the clean grid
    scenario_on = scenario_on or base_scenario.active
    if scenario_on:
        if args.neural or args.strategy.startswith("deep."):
            ap.error(
                "scenarios drive the forest loop; the neural path has no "
                "scenario wiring yet (a named ROADMAP follow-up)"
            )
        if args.fused_round:
            ap.error(
                "--fused-round fuses the CLEAN eval->score->top-k chain; "
                "scenarios perturb the round body (probabilistic reveal / "
                "knapsack select / drifted eval) — drop one of the two"
            )
        if args.fit != "device":
            ap.error(
                "scenarios run inside the jitted round and need --fit device"
            )
        if args.mesh_data * args.mesh_model > 1:
            ap.error(
                "scenarios are single-device for now (the sharded scenario "
                "round rides the pod-sharding ROADMAP item)"
            )

    # The neural (deep-AL) loop runs only when asked for explicitly: via
    # --neural or a namespaced "deep.*" strategy name. Names living in both
    # registries (e.g. "entropy") default to the classic forest path, which is
    # the reference-parity target (density_weighting.py:148).
    if args.neural or args.strategy.startswith("deep."):
        if args.strategies or args.datasets:
            ap.error(
                "--strategies/--datasets drive the forest grid launcher; "
                "the neural path batches the seed axis only (--sweep-seeds)"
            )
        if args.sweep_seeds > 1:
            # Every deep strategy batches since PR 10 folded the greedy
            # selects (batchbald/coreset/badge) into the scanned chunk; the
            # one remaining sweep restriction is checkpointing (one file per
            # seed needs the grid format, a named ROADMAP follow-up).
            if args.checkpoint_dir:
                ap.error(
                    "checkpointing is not supported by the batched neural "
                    "sweep; run the seeds serially"
                )
        if args.mesh_model != 1:
            ap.error(
                "the neural path shards pool rows only (--mesh-data); "
                "--mesh-model applies to the forest ensemble axis"
            )
        from distributed_active_learning_tpu.runtime.neural_loop import (
            available_deep_strategies,
            is_deep_strategy,
        )

        if not is_deep_strategy(args.strategy):
            ap.error(
                f"--neural needs a deep strategy, got {args.strategy!r}; "
                f"pick one of: {', '.join(available_deep_strategies())}"
            )
        if args.audit:
            from distributed_active_learning_tpu.runtime.neural_loop import (
                _normalize_deep_name,
            )

            _audit_or_die(
                args,
                neural_strategy=_normalize_deep_name(args.strategy),
                neural_sweep=args.sweep_seeds > 1,
            )
        writer = _make_writer(args)
        try:
            with _profile(args):
                result = _run_neural(args, dbg, metrics=writer)
        finally:
            if writer is not None:
                writer.close()
        if args.sweep_seeds > 1:
            seeds = list(range(args.seed, args.seed + args.sweep_seeds))
            _emit_sweep(args, result, seeds, dbg)
        else:
            _emit(args, result, dbg)
        _flight_exit_dump(args)
        return 0

    from distributed_active_learning_tpu.runtime.neural_loop import is_deep_strategy
    from distributed_active_learning_tpu.strategies import available_strategies

    if args.strategy not in available_strategies() and is_deep_strategy(args.strategy):
        # Round-1 accepted bare deep names ("bald"); now they are namespaced so
        # classic/deep collisions are unambiguous — point movers at the new
        # spelling instead of an uncaught registry KeyError.
        ap.error(
            f"{args.strategy!r} is a deep strategy; spell it "
            f"'deep.{args.strategy}' (or pass --neural)"
        )

    # Grid axes (--strategies / --datasets): comma lists routed through the
    # grid launcher; the base cfg carries the first entry of each axis so
    # config-derived identities (fit budget defaults, fingerprints) anchor on
    # a real cell.
    grid_strategies = (
        [
            _STRATEGY_ALIASES.get(s.strip(), s.strip())
            for s in args.strategies.split(",") if s.strip()
        ]
        if args.strategies else None
    )
    grid_datasets = (
        [d.strip() for d in args.datasets.split(",") if d.strip()]
        if args.datasets else None
    )
    if grid_strategies is not None:
        unknown = [
            s for s in grid_strategies if s not in available_strategies()
        ]
        if unknown:
            ap.error(
                f"unknown strategies {unknown}; the grid launcher drives the "
                f"classic registry: {', '.join(available_strategies())}"
            )
        if len(set(grid_strategies)) != len(grid_strategies):
            # Post-alias duplicates ("us,uncertainty") would run identical
            # groups and overwrite each other's per-cell output files.
            ap.error(
                f"duplicate strategies in --strategies: {grid_strategies}"
            )
    if grid_datasets is not None and len(set(grid_datasets)) != len(grid_datasets):
        ap.error(f"duplicate datasets in --datasets: {grid_datasets}")

    cfg = ExperimentConfig(
        data=DataConfig(
            name=grid_datasets[0] if grid_datasets else args.dataset,
            path=args.data_path,
            n_samples=args.n_samples,
            seed=args.seed,
        ),
        forest=ForestConfig(
            n_trees=args.trees, max_depth=args.depth, kernel=args.kernel,
            fit=args.fit, quantize=args.quantize,
        ),
        strategy=StrategyConfig(
            name=grid_strategies[0] if grid_strategies else args.strategy,
            window_size=args.window,
            beta=args.beta,
            options=_parse_strategy_options(args.strategy_option),
        ),
        mesh=MeshConfig(data=args.mesh_data, model=args.mesh_model),
        # The single-scenario spelling rides the config; the --scenarios AXIS
        # rides run_grid's scenarios= parameter instead (the base cfg stays
        # clean so config-derived identities anchor on the shared knobs).
        scenario=(
            base_scenario
            if base_scenario.active and scenario_cfgs is None
            else ScenarioConfig()
        ),
        n_start=args.n_start,
        max_rounds=args.rounds,
        label_budget=args.budget,
        rounds_per_launch=args.rounds_per_launch,
        pipeline_depth=args.pipeline_depth,
        sweep_seeds=args.sweep_seeds,
        stream_round_events=args.stream_rounds,
        fused_round=args.fused_round,
        roofline=args.roofline,
        seed=args.seed,
        results_path=None,  # _emit handles --out for both loop kinds
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
    )
    use_grid = (
        grid_strategies is not None
        or grid_datasets is not None
        or scenario_cfgs is not None
        # a single active scenario with a seed sweep routes through the grid
        # launcher too: the batched seed sweep has no scenario wiring, the
        # grid's S=1 shape is exactly a scenario x seed sweep
        or (base_scenario.active and args.sweep_seeds > 1)
    )
    if args.audit:
        # A --datasets-only (or single-entry --strategies) invocation still
        # launches the grid program, so the audit must trace the grid chunk —
        # the same group list run_grid receives — not the chunk/sweep one.
        _audit_or_die(
            args, cfg=cfg,
            grid_strategies=(
                (grid_strategies or [cfg.strategy.name]) if use_grid else None
            ),
        )
    writer = _make_writer(args)
    try:
        with _profile(args):
            if use_grid:
                from distributed_active_learning_tpu.runtime.sweep import run_grid

                seeds = list(range(args.seed, args.seed + args.sweep_seeds))
                grid = run_grid(
                    cfg,
                    grid_strategies or [cfg.strategy.name],
                    seeds,
                    datasets=grid_datasets,
                    scenarios=scenario_cfgs,
                    debugger=dbg,
                    metrics=writer,
                )
            elif args.sweep_seeds > 1:
                from distributed_active_learning_tpu.runtime.sweep import run_sweep

                seeds = list(range(args.seed, args.seed + args.sweep_seeds))
                results = run_sweep(cfg, seeds, debugger=dbg, metrics=writer)
            else:
                result = run_experiment(cfg, debugger=dbg, metrics=writer)
    finally:
        if writer is not None:
            writer.close()
    if use_grid:
        _emit_grid(args, grid, dbg)
    elif args.sweep_seeds > 1:
        _emit_sweep(args, results, seeds, dbg)
    else:
        _emit(args, result, dbg)
    _flight_exit_dump(args)
    return 0


def _flight_exit_dump(args) -> None:
    """--flight-recorder: a normal exit also leaves the artifact (the crash
    and signal triggers are armed by install_flight_recorder; this covers
    the run that simply finished)."""
    if getattr(args, "flight_recorder", None):
        from distributed_active_learning_tpu.runtime import telemetry

        telemetry.flight_dump("exit")


def _audit_or_die(
    args, cfg=None, neural_strategy=None, grid_strategies=None,
    neural_sweep=False,
):
    """``--audit``: trace the fused program this configuration would launch
    (plus the recompile-hazard lint over the driver surfaces) and refuse to
    run on any error-severity finding. A mesh placement that cannot be
    audited here (fewer than 8 devices on a CPU rig) falls back to the
    single-device program — same strategy pipeline, still worth gating on."""
    from distributed_active_learning_tpu.analysis import (
        default_lint_targets,
        lint_paths,
        run_audit,
        specs_for_experiment,
    )

    specs = specs_for_experiment(
        cfg, neural_strategy=neural_strategy, grid_strategies=grid_strategies,
        neural_sweep=neural_sweep,
    )
    report = run_audit(specs)
    if not report.programs and report.skipped:
        # every spec was skipped (mesh placement, too few devices): re-audit
        # the same launch at the cpu placement instead of gating nothing —
        # and SAY so, since the traced program then differs from the one the
        # run launches. Rebuilt through specs_for_experiment (mesh forced to
        # 1x1) rather than a registry name filter: a custom grid group set
        # ("uncertainty+margin") has no registry entry, so filtering the
        # fixed-name registry would audit zero programs and pass silently.
        print(
            "# audit: mesh program unavailable here "
            f"({'; '.join(report.skipped.values())}); auditing the "
            "single-device program instead",
            file=sys.stderr,
        )
        if cfg is not None:
            import dataclasses

            cpu_specs = specs_for_experiment(
                dataclasses.replace(cfg, mesh=MeshConfig(data=1, model=1)),
                neural_strategy=neural_strategy,
                grid_strategies=grid_strategies,
                neural_sweep=neural_sweep,
            )
        else:
            from distributed_active_learning_tpu.analysis import build_registry

            cpu_specs = build_registry(
                strategies=sorted({s.strategy for s in specs}),
                kinds=sorted({s.kind for s in specs}),
                placements=["cpu"],
            )
        specs = cpu_specs
        report = run_audit(specs)
    report.extend(lint_paths(default_lint_targets()))
    # The static memory planner: compile the SAME programs this run would
    # launch and gate their peak HBM / megakernel VMEM against the chip's
    # budget (analysis/roofline.py capacity tables; DAL_MEMORY_BUDGET names
    # a JSON override — {"hbm_bytes": N, "vmem_bytes": N} — the test route
    # and the operator escape hatch). Pricing happens at the CONFIGURED
    # pool scale when it is statically known (--n-samples): compiling is
    # shape-independent work, so the 10M-row program the run would actually
    # allocate is what gets priced — not the registry's 64-row stand-in,
    # which no real budget could ever refuse. An over-budget program
    # REFUSES the launch with the overage named, so an OOM death on the
    # rig becomes a pre-flight finding instead of rc 124 with no artifact.
    import os

    from distributed_active_learning_tpu.analysis import memory as memory_lib
    from distributed_active_learning_tpu.analysis import programs as programs_lib

    budget_path = os.environ.get("DAL_MEMORY_BUDGET")
    budget = (
        memory_lib.load_budget_table(budget_path)
        if budget_path
        else memory_lib.device_budget()
    )
    pool_rows = getattr(getattr(cfg, "data", None), "n_samples", None)
    forest_cfg = getattr(cfg, "forest", None)
    if not pool_rows:
        print(
            "# audit: pool scale unknown before data load; memory gate "
            f"priced at the {programs_lib.POOL_ROWS}-row audit shapes "
            "(pass --n-samples to price the configured scale)",
            file=sys.stderr,
        )
    else:
        # feature width is a data property the pre-flight cannot see; the
        # n x d pool buffer is therefore priced at the audit width — say so
        # rather than letting the gate read as exact
        print(
            f"# audit: memory gate priced at {pool_rows} pool rows, "
            f"{programs_lib.FEATURES}-feature audit width (dataset width "
            "is unknown before data load)",
            file=sys.stderr,
        )
    _mem_table, mem_findings = memory_lib.price_specs(
        specs, budget,
        pool_rows=pool_rows or None,
        n_trees=getattr(forest_cfg, "n_trees", None),
        max_depth=getattr(forest_cfg, "max_depth", None),
    )
    report.extend(mem_findings)
    if report.findings:
        print(report.render_table(), file=sys.stderr)
    if report.gate("error"):
        raise SystemExit(
            "audit failed: error-severity findings in the traced program "
            "(see above); fix them or re-run without --audit"
        )
    if not args.quiet:
        audited = ", ".join(report.programs)
        print(f"# audit clean: {audited}", file=sys.stderr)


def _make_writer(args):
    """Open the ``--metrics-out`` JSONL sink (None when the flag is absent).

    Constructed on EVERY process of a multihost job — the writer's collective
    gauge gathers must be symmetric — but only the primary holds the file.
    """
    if not args.metrics_out:
        return None
    from distributed_active_learning_tpu.runtime.telemetry import MetricsWriter

    return MetricsWriter(args.metrics_out)


def _profile(args):
    """``--profile-dir`` jax.profiler session (no-op context when unset).
    validate=False: main() already probed writability so a bad directory
    fails as a clean argparse error before any work."""
    from distributed_active_learning_tpu.runtime.telemetry import profile_session

    return profile_session(args.profile_dir, validate=False)


def _run_neural(args, dbg, metrics=None):
    """Deep-AL CLI path: a neural learner + MC-dropout over a registry dataset.

    Model selection covers BASELINE.json configs 4-5: ``--dataset cifar10
    --model cnn`` (SmallCNN over image pools) and ``--dataset agnews --model
    transformer`` (encoder over token-id pools); ``mlp`` serves tabular pools.
    """
    import dataclasses

    import numpy as np

    from distributed_active_learning_tpu.data import get_dataset
    from distributed_active_learning_tpu.models.neural import MLP, NeuralLearner, SmallCNN
    from distributed_active_learning_tpu.runtime.neural_loop import (
        NeuralExperimentConfig,
        run_neural_experiment,
    )

    data_cfg = DataConfig(
        name=args.dataset, path=args.data_path, n_samples=args.n_samples, seed=args.seed
    )
    bundle = get_dataset(data_cfg)
    n_classes = max(int(bundle.train_y.max()) + 1, 2)

    kind = args.model
    if kind == "auto":
        if bundle.train_x.ndim == 4:
            kind = "cnn"
        elif np.issubdtype(np.asarray(bundle.train_x).dtype, np.integer):
            kind = "transformer"
        else:
            kind = "mlp"

    if kind == "cnn":
        if bundle.train_x.ndim != 4:
            raise ValueError(f"--model cnn needs an image pool, got shape {bundle.train_x.shape}")
        module = SmallCNN(n_classes=n_classes)
        input_shape = bundle.train_x.shape[1:]
    elif kind == "transformer":
        from distributed_active_learning_tpu.models.transformer import TransformerClassifier

        if bundle.train_x.ndim != 2:
            raise ValueError(f"--model transformer needs a token pool, got shape {bundle.train_x.shape}")
        max_len = bundle.train_x.shape[1]
        vocab = bundle.vocab_size or int(np.asarray(bundle.train_x).max()) + 1
        module = TransformerClassifier(
            vocab_size=vocab, max_len=max_len, n_classes=n_classes,
            d_model=args.d_model, n_layers=args.n_layers,
            n_heads=args.n_heads, d_ff=args.d_ff,
        )
        input_shape = (max_len,)
    else:
        hidden = tuple(int(h) for h in args.hidden.split(",") if h)
        module = MLP(n_classes=n_classes, hidden=hidden)
        if bundle.train_x.ndim > 2:
            # flatten image pools for the MLP baseline
            flat = int(np.prod(bundle.train_x.shape[1:]))
            bundle = bundle._replace(
                train_x=np.asarray(bundle.train_x).reshape(len(bundle.train_x), flat),
                test_x=np.asarray(bundle.test_x).reshape(len(bundle.test_x), flat),
            )
        input_shape = (bundle.train_x.shape[1],)

    learner = NeuralLearner(
        module,
        input_shape,
        train_steps=args.train_steps,
        mc_samples=args.mc_samples,
    )
    cfg = NeuralExperimentConfig(
        strategy=args.strategy,
        window_size=args.window,
        n_start=args.n_start,
        max_rounds=args.rounds,
        label_budget=args.budget,
        seed=args.seed,
        batchbald_max_configs=args.batchbald_max_configs,
        batchbald_candidate_pool=args.candidate_pool,
        batchbald_mc_samples=args.batchbald_samples,
        beta=args.beta,
        coreset_space=args.coreset_space,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        rounds_per_launch=args.rounds_per_launch,
        pipeline_depth=args.pipeline_depth,
        stream_round_events=args.stream_rounds,
        mesh=MeshConfig(data=args.mesh_data, model=args.mesh_model),
    )
    # Dataset identity feeds the checkpoint fingerprint, so a resume against a
    # different dataset/subsample is refused (same guard as the forest loop).
    if args.sweep_seeds > 1:
        from distributed_active_learning_tpu.runtime.neural_loop import (
            run_neural_sweep,
        )

        return run_neural_sweep(
            cfg, learner, bundle.train_x, bundle.train_y,
            bundle.test_x, bundle.test_y,
            seeds=list(range(args.seed, args.seed + args.sweep_seeds)),
            debugger=dbg, data_ident=dataclasses.asdict(data_cfg),
            metrics=metrics,
        )
    return run_neural_experiment(
        cfg, learner, bundle.train_x, bundle.train_y, bundle.test_x, bundle.test_y,
        debugger=dbg, data_ident=dataclasses.asdict(data_cfg), metrics=metrics,
    )


def _emit_sweep(args, results, seeds, dbg):
    """Per-seed emission for a batched sweep: stdout logs under '# sweep
    seed' headers, --out as per-seed files, --plot as the mean +/- sd band
    over the sweep (the paper's learning-curve aggregation)."""
    import dataclasses as dc

    from distributed_active_learning_tpu.runtime.sweep import _sweep_result_path

    for seed, result in zip(seeds, results):
        if args.json:
            for r in result.records:
                sys.stdout.write(
                    json.dumps({"seed": seed, **dc.asdict(r)}) + "\n"
                )
        else:
            sys.stdout.write(f"# sweep seed {seed}\n")
            sys.stdout.write(result.to_reference_log())
        if args.out:
            result.save(_sweep_result_path(args.out, seed), fmt="reference")
    if args.plot:
        from distributed_active_learning_tpu.runtime.results import plot_seed_band

        plot_seed_band(
            results, args.plot,
            title=f"{args.dataset} / {args.strategy} ({len(seeds)} seeds)",
        )
    if not args.quiet and results and results[0].final_accuracy is not None:
        import numpy as np

        finals = [r.final_accuracy for r in results if r.final_accuracy is not None]
        print(
            f"# sweep final: {len(seeds)} seeds, accuracy "
            f"{np.mean(finals) * 100:.2f}% +/- {np.std(finals) * 100:.2f}%, "
            f"total {dbg.total_time():.1f}s",
            file=sys.stderr,
        )


def _emit_grid(args, grid, dbg):
    """Per-cell emission for a grid launch: stdout logs under '# grid cell'
    headers, --out as per-cell files, --plot as per-strategy x dataset
    mean +/- sd bands (the paper's results-matrix figure from ONE run)."""
    import dataclasses as dc

    from distributed_active_learning_tpu.runtime.sweep import _grid_result_path

    datasets = sorted({c.dataset for c in grid.cells})
    with_ds = len(datasets) > 1
    scenarios = sorted({getattr(c, "scenario", "none") for c in grid.cells})
    with_scn = scenarios != ["none"]
    for cell in grid.cells:
        scn = getattr(cell, "scenario", "none")
        if args.json:
            for r in cell.result.records:
                row = {
                    "strategy": cell.strategy,
                    "dataset": cell.dataset,
                    "seed": cell.seed,
                }
                if with_scn:
                    row["scenario"] = scn
                sys.stdout.write(json.dumps({**row, **dc.asdict(r)}) + "\n")
        else:
            sc = f"/{scn}" if with_scn else ""
            sys.stdout.write(
                f"# grid cell {cell.strategy}/{cell.dataset}{sc}"
                f"/seed {cell.seed}\n"
            )
            sys.stdout.write(cell.result.to_reference_log())
        if args.out:
            cell.result.save(
                _grid_result_path(
                    args.out, cell.strategy, cell.dataset, cell.seed, with_ds,
                    scenario=scn, with_scenario=with_scn,
                ),
                fmt="reference",
            )
    if args.plot:
        from distributed_active_learning_tpu.runtime.results import plot_grid_bands

        plot_grid_bands(grid, args.plot, title=f"grid ({len(grid.cells)} cells)")
    if not args.quiet:
        import numpy as np

        finals = [
            c.result.final_accuracy
            for c in grid.cells
            if c.result.final_accuracy is not None
        ]
        strategies = sorted({c.strategy for c in grid.cells})
        acc = (
            f"accuracy {np.mean(finals) * 100:.2f}% +/- "
            f"{np.std(finals) * 100:.2f}%"
            if finals else "no accuracy records"
        )
        scn_part = f" x {len(scenarios)} scenarios" if with_scn else ""
        print(
            f"# grid final: {len(grid.cells)} cells "
            f"({len(strategies)} strategies x {len(datasets)} datasets"
            f"{scn_part}), "
            f"{acc}, "
            f"launches={grid.launches} "
            f"recompiles_after_warmup={grid.recompiles_after_warmup}, "
            f"total {dbg.total_time():.1f}s",
            file=sys.stderr,
        )


def _emit(args, result, dbg):
    if args.json:
        sys.stdout.write(result.to_jsonl())
    else:
        sys.stdout.write(result.to_reference_log())
    if args.out:
        result.save(args.out, fmt="reference")
    if args.plot:
        from distributed_active_learning_tpu.runtime.results import plot_result

        plot_result(result, args.plot, title=f"{args.dataset} / {args.strategy}")
    if result.final_accuracy is not None and not args.quiet:
        print(
            f"# final: {result.records[-1].n_labeled} labeled, "
            f"accuracy {result.final_accuracy * 100:.2f}%, "
            f"total {dbg.total_time():.1f}s",
            file=sys.stderr,
        )


if __name__ == "__main__":
    raise SystemExit(main())
