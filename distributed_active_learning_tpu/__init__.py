"""TPU-native distributed active-learning framework.

A from-scratch JAX/XLA re-design of the capabilities of the Spark-based reference
``dv66/Distributed-Active-Learning`` (see SURVEY.md): pool-based active learning
with random / uncertainty / entropy / density-weighted / LAL query strategies over
random-forest (and neural) base learners.

Design stance (SURVEY.md §7): the unlabeled pool is a fixed dense array resident in
device memory; labeled/unlabeled sets are boolean masks (never dynamically-shaped
subsets); one AL round is a single jitted function; the forest is a packed tensor
ensemble traversed by gather and vmapped over (trees x points); similarity is a
blocked MXU matmul; ``lax.top_k`` replaces distributed sort+take; ``shard_map`` +
collectives over a ``jax.sharding.Mesh`` replace Spark RDD shuffles.

Package layout:
  data/       dataset loaders, scaling, synthetic generators  (ref L0/L3)
  models/     forest + neural base learners                    (ref L2)
  ops/        jitted kernels: tree traversal, similarity, scoring, top-k
  parallel/   mesh construction, shardings, collectives        (ref L1)
  strategies/ query-strategy registry                          (ref L4)
  runtime/    AL state, driver loop, checkpointing, tracing    (ref L5)
"""

__version__ = "0.1.0"

import jax as _jax

# Sharding-invariant PRNG, non-negotiable for a distributed system: with the
# legacy (non-partitionable) threefry lowering, jax.random draws change VALUE
# with the surrounding program's GSPMD partitioning — observed concretely as
# the device trainer's bootstrap weights differing between the per-round
# program and the scan-fused chunk program on a >1-device mesh, silently
# breaking chunked == per-round parity (runtime/loop.py make_chunk_fn).
# Partitionable threefry guarantees draws depend only on (key, position),
# never placement; it is the default from JAX 0.5 onward — this pins the
# same semantics on the 0.4.x the rig ships.
_jax.config.update("jax_threefry_partitionable", True)

from distributed_active_learning_tpu import config  # noqa: F401, E402
