"""On-device random-forest training: histogram splits, level-wise, under jit.

The reference trains its forests in the JVM (``RandomForest.trainClassifier``,
``final_thesis/uncertainty_sampling.py:71-76``) — which is itself a *binned*
histogram trainer (MLlib's ``maxBins=32`` is exactly the bin count passed
there). This module is the TPU-native equivalent (SURVEY.md §7 "hard parts"):
the one component that previously still ran on a non-TPU substrate (host
sklearn) in the AL hot loop.

Design — everything is static-shape and jit-friendly:

- **Binning**: per-feature quantile edges computed once per experiment from the
  pool; features become int32 codes in ``[0, n_bins)``. ``code <= b`` is
  equivalent to ``x <= edges[b]`` (searchsorted-left), so trained splits
  transfer to raw-feature inference exactly.
- **Level-wise complete trees in heap layout**: every tree is grown to the full
  ``max_depth`` with node ``v``'s children at ``2v+1``/``2v+2``. Pure or empty
  nodes keep splitting degenerately — their descendants inherit the node value,
  which predicts identically to early stopping but keeps every shape static.
- **Histogram build as MXU matmuls**: per level, per-(node, class) one-hot
  row weights ``A [m, J*C]`` against the shared one-hot binned features
  ``B [m, d*n_bins]`` gives all class histograms for all nodes of the level in
  one batched GEMM — the vectorized replacement for MLlib's per-executor
  histogram aggregation + driver reduce.
- **Bootstrap** via Poisson(1) row weights (the standard multinomial
  approximation), **feature subsampling** per node (``sqrt(d)`` like
  MLlib's 'auto'/sklearn default) via masked gains.
- **Split criterion**: weighted Gini impurity decrease (``'gini'``,
  ``uncertainty_sampling.py:75``).

Because the trees are complete, the GEMM path-matrix form (``ops/trees_gemm``)
has *data-independent* structure: :func:`heap_gemm_forest` builds a
:class:`GemmForest` by slicing — no host round-trip — so fit + convert +
score + select can run as one jitted program.

Measured split of the device AL round (v5e, 284,807x30 pool, 100 trees,
depth 8, 5k labeled window): fit 115 ms wall / ~25 ms device, pallas scoring
~23 ms — full round 0.14 s, ~63,000x the derived Spark baseline. The r4
profile work found the real costs were never the histogram GEMMs (which ride
the MXU in bf16 and are trivial at this size) but three per-element routing
GATHERS per level — take_along_axis of the per-row node's (feature, bin) and
codes[row, feature] — at ~25 ms/level on the v5e; they are now a one-hot
selector GEMM + membership-masked reduction (gather-free, see the routing
comment in ``fit_forest_device``), and the bin prefix-sum rides the MXU as a
triangular matmul instead of lowering to reduce-window. Device time for the
whole 7-chunk fit is now ~25 ms; the residual wall clock is the tunnel's
per-program sync latency (~100 ms on the attached-chip rig, absent on a
local TPU), so further kernel work is not the lever here.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from distributed_active_learning_tpu.ops.trees import LEAF, PackedForest
from distributed_active_learning_tpu.ops.trees_gemm import GemmForest


@struct.dataclass
class BinnedPool:
    """Per-feature quantile binning of a (pool) matrix.

    ``edges [d, n_bins-1]`` are ascending boundaries; ``codes [n, d] int32``
    satisfy ``codes <= b  <=>  x <= edges[:, b]``.
    """

    edges: jnp.ndarray  # [d, n_bins - 1] float32
    codes: jnp.ndarray  # [n, d] int32

    @property
    def n_bins(self) -> int:
        return self.edges.shape[1] + 1


def make_bins(
    x: jnp.ndarray, n_bins: int = 32, quantize: str = "none"
) -> BinnedPool:
    """Quantile-bin the pool once per experiment (MLlib finds its candidate
    splits the same way, on a sample of the input).

    ``quantize != "none"`` snaps the edges onto the bf16 grid BEFORE codes
    are computed: trained thresholds are always bin edges (``edges[bf, bb]``
    in :func:`fit_forest_device`), so snapping here makes bf16 threshold
    storage exactly lossless — the quantized forest's decision paths are
    bit-identical to f32 storage of the same fitted forest by construction
    (``code <= b  <=>  x <= edges[b]`` holds for whatever edge values are
    used consistently between binning and inference).
    """
    x = jnp.asarray(x, jnp.float32)
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]  # interior quantiles
    edges = jnp.quantile(x, qs, axis=0).T  # [d, n_bins-1]
    if quantize != "none":
        edges = edges.astype(jnp.bfloat16).astype(jnp.float32)
    codes = code_features(x, edges)
    return BinnedPool(edges=edges, codes=codes)


def code_features(x: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Map raw features to bin codes: ``code = #{edges < x}`` (so that
    ``code <= b <=> x <= edges[b]``)."""
    # Per-feature binary search — no [n, d, n_bins] broadcast intermediate
    # (the benchmark pool is 284,807 x 30; a dense compare would transiently
    # cost ~0.5 GB just to bin it).
    return jax.vmap(
        lambda e, col: jnp.searchsorted(e, col, side="left"), in_axes=(0, 1), out_axes=1
    )(edges, x).astype(jnp.int32)


# Poisson(1) CDF, truncated where it saturates f32 (P[w > 12] ~ 1e-13).
_POISSON1_CDF = np.cumsum(
    [np.exp(-1.0) / math.factorial(k) for k in range(13)]
).astype(np.float32)


def poisson1(key: jax.Array, shape) -> jnp.ndarray:
    """Poisson(1) draws via inverse-CDF on one uniform per element.

    NOT ``jax.random.poisson``: its rejection-sampling loop compiles to
    different draw sequences depending on the surrounding program's GSPMD
    partitioning (observed on the virtual CPU mesh: same key, different
    bootstrap weights once the fit is fused into the chunked scan driver,
    silently breaking chunked == per-round parity). ``uniform`` is an
    elementwise counter-mode draw, stable under any partitioning, and the
    inverse-CDF lookup is elementwise too — so every compilation context
    agrees bit-for-bit.
    """
    u = jax.random.uniform(key, shape)
    return jnp.searchsorted(jnp.asarray(_POISSON1_CDF), u, side="right").astype(
        jnp.int32
    )


def _gini_gain(
    left: jnp.ndarray, parent: jnp.ndarray
) -> jnp.ndarray:
    """Weighted Gini impurity decrease for every candidate split.

    ``left [..., C, S]``: class counts routed left per split candidate;
    ``parent [..., C]``: the node's class counts. Returns ``[..., S]`` gains
    scaled by the parent weight (the constant factor does not change the
    argmax; it avoids dividing by tiny node weights).
    """
    right = parent[..., :, None] - left
    wl = jnp.sum(left, axis=-2)
    wr = jnp.sum(right, axis=-2)
    w = jnp.sum(parent, axis=-1)[..., None]
    # sum_c n_c^2 / w  (safe at w == 0)
    def _purity(counts, weight):
        return jnp.sum(counts * counts, axis=-2) / jnp.maximum(weight, 1e-9)

    child = _purity(left, wl) + _purity(right, wr)
    parent_purity = jnp.sum(parent * parent, axis=-1)[..., None] / jnp.maximum(w, 1e-9)
    # gain * w = (child purity sum) - (parent purity); >= 0, 0 for pure/empty.
    return child - parent_purity


@functools.partial(
    jax.jit,
    static_argnames=("n_trees", "max_depth", "n_bins", "tree_chunk", "n_classes"),
)
def fit_forest_device(
    codes: jnp.ndarray,     # [m, d] int32 — binned rows (the fit window)
    y: jnp.ndarray,         # [m] int32 in [0, n_classes)
    weights: jnp.ndarray,   # [m] float32 — 0 for invalid/unlabeled rows
    edges: jnp.ndarray,     # [d, n_bins - 1] float32
    key: jax.Array,
    n_trees: int,
    max_depth: int,
    n_bins: int = 32,
    tree_chunk: int = 16,
    n_classes: int = 2,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Train ``n_trees`` complete depth-``max_depth`` trees on device.

    Returns heap-layout arrays ``(feature [T, I], threshold [T, I],
    value [T, 2^(D+1)-1, C])`` where ``I = 2^D - 1`` internal nodes precede
    the ``2^D`` leaves; node ``v``'s children are ``2v+1``/``2v+2``. ``value``
    rows are per-node class distributions (``C = n_classes``; the histogram
    GEMM, Gini gains, and routing are class-count-generic, so multiclass costs
    only a wider class axis).
    """
    m, d = codes.shape
    D = max_depth
    C = n_classes
    if n_bins > 256:
        # The routing GEMM carries bin codes in bf16 (exact only below 256);
        # beyond that rows near split boundaries would silently misroute.
        raise ValueError(f"fit_forest_device supports n_bins <= 256, got {n_bins}")
    n_feat_sub = max(int(np.ceil(np.sqrt(d))), 1)

    # Shared one-hot (class, bin) features [m, C * d * n_bins] — built once
    # per fit. Carrying the CLASS axis here (data-dependent only) instead of
    # on the per-level row-weight operand keeps that operand at [Tc, m, J]:
    # the level loop's elementwise build — the fit's measured bottleneck —
    # shrinks by the class factor, and the histogram GEMM cost is unchanged
    # (same contraction, same output volume).
    bmat = (
        (codes[:, :, None] == jnp.arange(n_bins)[None, None, :])
        .reshape(m, d * n_bins)
        .astype(jnp.bfloat16)
    )
    y_oh = jax.nn.one_hot(y, C, dtype=jnp.bfloat16)  # [m, C]
    ybmat = (y_oh[:, :, None] * bmat[:, None, :]).reshape(m, C * d * n_bins)

    def fit_chunk(args):
        k_chunk = args
        Tc = tree_chunk
        k_boot, k_feat = jax.random.split(k_chunk)
        # Poisson(1) bootstrap weights, zeroed outside the labeled window.
        # bf16 end-to-end: weights are small integers (exact in bf16) and the
        # per-level one-hot build below is memory-bound.
        # poisson1, not jax.random.poisson: the latter's rejection loop is
        # not GSPMD-partitioning-stable (see poisson1 docstring), which broke
        # chunked-scan vs per-round fit parity on >1-device meshes.
        w = poisson1(k_boot, (Tc, m)).astype(jnp.bfloat16)
        w = w * weights[None, :].astype(jnp.bfloat16)

        node = jnp.zeros((Tc, m), dtype=jnp.int32)  # level-local node index
        feat_out = []
        thr_out = []
        values = [
            # Root counts accumulate ~thousands of weights: sum in f32 (bf16
            # addition loses integer exactness past 256).
            jax.lax.dot_general(
                w, y_oh, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )[:, None, :]  # [Tc, 1, C]
        ]

        # Bin codes as bf16 for the routing GEMM below: values are small ints
        # (< n_bins <= 256), exact in bf16.
        codes_bf = codes.astype(jnp.bfloat16)

        for level in range(D):
            J = 1 << level
            # Node-membership one-hot [Tc, m, J] — shared by the histogram
            # GEMM (weighted) and the routing reduction (boolean).
            a01 = node[:, :, None] == jnp.arange(J)[None, None, :]
            a = a01.astype(jnp.bfloat16) * w[:, :, None]
            # All histograms of the level in one batched GEMM:
            # [Tc, J, m] x [m, C*d*n_bins] -> [Tc, J, C*d*n_bins].
            hist = jax.lax.dot_general(
                a,
                ybmat,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(Tc, J, C, d, n_bins)

            parent = values[level]  # [Tc, J, C] — counts computed a level up
            # Left counts for split-at-bin-b: prefix sums over bins, as a
            # triangular matmul (cumsum lowers to reduce-window, ~1/3 of the
            # fit's device time; a [B, B-1] mask contraction rides the MXU).
            tri = (
                jnp.arange(n_bins)[:, None] <= jnp.arange(n_bins - 1)[None, :]
            ).astype(hist.dtype)
            # precision="highest": counts reach thousands; the default TPU
            # matmul precision would demote them to bf16 (exact only to 256)
            # and silently perturb near-tie splits vs the exact cumsum.
            left = jnp.einsum(
                "tjcdb,bs->tjcds", hist, tri, precision="highest"
            )  # [Tc,J,C,d,B-1]
            n_splits = d * (n_bins - 1)
            gain = _gini_gain(left.reshape(Tc, J, C, n_splits), parent)
            gain = gain.reshape(Tc, J, d, n_bins - 1)
            # Mask features outside the node's random subset (sqrt(d) of them).
            k_lvl = jax.random.fold_in(k_feat, level)
            scores = jax.random.uniform(k_lvl, (Tc, J, d))
            kth = jax.lax.top_k(scores, n_feat_sub)[0][..., -1]
            fmask = scores >= kth[..., None]  # exactly n_feat_sub True per node
            gain = jnp.where(fmask[..., None], gain, -jnp.inf)

            best = jnp.argmax(gain.reshape(Tc, J, n_splits), axis=2)  # [Tc, J]
            bf = (best // (n_bins - 1)).astype(jnp.int32)  # feature id
            bb = (best % (n_bins - 1)).astype(jnp.int32)   # split bin
            feat_out.append(bf)
            thr_out.append(edges[bf, bb])

            # Children class counts from the chosen split.
            left_best = jnp.take_along_axis(
                left.reshape(Tc, J, C, -1),
                (bf * (n_bins - 1) + bb)[:, :, None, None],
                axis=3,
            )[..., 0]  # [Tc, J, C]
            right_best = parent - left_best
            children = jnp.stack([left_best, right_best], axis=2).reshape(
                Tc, 2 * J, C
            )
            values.append(children)

            # Route rows: left iff code[row, feat*(node)] <= bin*(node).
            # NOT per-element gathers (take_along_axis of [Tc, m] indices +
            # codes[row, feat] cost ~25 ms/level on a v5e — they were 2/3 of
            # the whole fit); instead select each node's feature column with
            # a one-hot GEMM and pick each row's verdict through the already
            # built membership one-hot — gather-free, MXU/VPU-friendly.
            sel = jax.nn.one_hot(bf, d, dtype=jnp.bfloat16)  # [Tc, J, d]
            codef = jax.lax.dot_general(
                sel, codes_bf,
                dimension_numbers=(((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [Tc, J, m] — exact: small-int values in bf16
            left_j = codef <= bb[:, :, None].astype(jnp.float32)  # [Tc, J, m]
            go_left = jnp.any(a01 & left_j.transpose(0, 2, 1), axis=2)
            node = 2 * node + jnp.where(go_left, 0, 1)

        # Heap-order internal arrays: level l occupies [2^l - 1, 2^(l+1) - 1).
        feature = jnp.concatenate(feat_out, axis=1)      # [Tc, 2^D - 1]
        threshold = jnp.concatenate(thr_out, axis=1)     # [Tc, 2^D - 1]
        # Node values: class distributions; empty nodes inherit the parent's.
        vals = []
        root = values[0].astype(jnp.float32)
        root_v = root / jnp.maximum(root.sum(-1, keepdims=True), 1e-9)
        vals.append(root_v)  # [Tc, 1, C]
        for level in range(1, D + 1):
            cnt = values[level].astype(jnp.float32)  # [Tc, 2^level, C]
            tot = cnt.sum(-1, keepdims=True)
            v = cnt / jnp.maximum(tot, 1e-9)
            parent_v = jnp.repeat(vals[level - 1], 2, axis=1)
            vals.append(jnp.where(tot > 0, v, parent_v))
        value = jnp.concatenate(vals, axis=1)  # [Tc, 2^(D+1) - 1, C]
        return feature, threshold, value

    n_chunks = -(-n_trees // tree_chunk)
    keys = jax.random.split(key, n_chunks)
    # Trace attribution: the level-loop GEMMs dominate a device fit; the
    # named scope makes them one labelled block in a --profile-dir trace.
    with jax.named_scope("trees/fit_forest_device"):
        feature, threshold, value = jax.lax.map(fit_chunk, keys)
    merge = lambda t: t.reshape(-1, *t.shape[2:])[:n_trees]
    return merge(feature), merge(threshold), merge(value)


def _scalar_value_planes(value: jnp.ndarray):
    """Resolve the trainer's value output into scalar planes.

    ``value`` rank 2 (legacy scalar P(1)) or rank 3 ``[T, nodes, C]``: C=2
    keeps the binary scalar convention (plane = P(class 1)); C>2 yields one
    plane per class for a :class:`~.ops.trees_multi.MultiForest`.
    """
    if value.ndim == 2:
        return None, value
    C = value.shape[-1]
    if C == 2:
        return None, value[..., 1]
    return C, value


def heap_packed_forest(
    feature: jnp.ndarray, threshold: jnp.ndarray, value: jnp.ndarray, max_depth: int
):
    """Wrap heap-layout trained arrays as a :class:`PackedForest` (gather
    kernel compatible; children of ``v`` at ``2v+1``/``2v+2``). Multiclass
    value tensors (``[T, nodes, C]``, C>2) wrap as a ``MultiForest`` of
    per-class planes sharing the structure arrays."""
    C, value = _scalar_value_planes(value)
    if C is not None:
        from distributed_active_learning_tpu.ops.trees_multi import MultiForest

        return MultiForest(planes=tuple(
            heap_packed_forest(feature, threshold, value[..., c], max_depth)
            for c in range(C)
        ))
    T, I = feature.shape
    n_nodes = 2 * I + 1  # 2^(D+1) - 1
    node = jnp.arange(n_nodes, dtype=jnp.int32)
    internal = node < I
    full_feature = jnp.concatenate(
        [feature, jnp.full((T, n_nodes - I), LEAF, jnp.int32)], axis=1
    )
    full_threshold = jnp.concatenate(
        [threshold, jnp.zeros((T, n_nodes - I), jnp.float32)], axis=1
    )
    left = jnp.where(internal, 2 * node + 1, node)
    right = jnp.where(internal, 2 * node + 2, node)
    return PackedForest(
        feature=full_feature,
        threshold=full_threshold,
        left=jnp.broadcast_to(left, (T, n_nodes)),
        right=jnp.broadcast_to(right, (T, n_nodes)),
        value=value.astype(jnp.float32),
        max_depth=max_depth,
    )


@functools.lru_cache(maxsize=None)
def _heap_path_target(depth: int) -> Tuple[np.ndarray, np.ndarray]:
    """Static path matrix/targets of the complete depth-``depth`` heap tree.

    ``path [I, L]`` is +1/-1/0 as in :class:`GemmForest`; ``target [L]`` is the
    leaf's left-ancestor count. Data-independent, so device-fit forests convert
    to GEMM form by slicing (no host round-trip, unlike
    ``gemm_forest_from_packed``).
    """
    I = (1 << depth) - 1
    L = 1 << depth
    path = np.zeros((I, L), dtype=np.float32)
    target = np.zeros((L,), dtype=np.float32)
    for l in range(L):
        node = I + l  # heap id of the leaf
        while node > 0:
            parent = (node - 1) // 2
            went_left = node == 2 * parent + 1
            path[parent, l] = 1.0 if went_left else -1.0
            target[l] += float(went_left)
            node = parent
    return path, target


def heap_gemm_forest(
    feature: jnp.ndarray, threshold: jnp.ndarray, value: jnp.ndarray, max_depth: int
):
    """Build the MXU path-matrix form of a device-fit (complete-heap) forest.

    Pure slicing + a static constant — jit-friendly, so the full AL round
    (fit + convert + score + select) compiles into one XLA program.
    Multiclass value tensors wrap as a ``MultiForest`` (one GEMM plane per
    class over the shared path matrix).
    """
    C, value = _scalar_value_planes(value)
    if C is not None:
        from distributed_active_learning_tpu.ops.trees_multi import MultiForest

        return MultiForest(planes=tuple(
            heap_gemm_forest(feature, threshold, value[..., c], max_depth)
            for c in range(C)
        ))
    T, I = feature.shape
    L = I + 1
    path_np, target_np = _heap_path_target(max_depth)
    leaf_value = value[:, I:]  # leaves occupy the heap tail
    return GemmForest(
        feat_ids=feature,
        thresholds=threshold,
        path=jnp.broadcast_to(jnp.asarray(path_np), (T, I, L)),
        target=jnp.broadcast_to(jnp.asarray(target_np), (T, L)),
        value=leaf_value.astype(jnp.float32),
    )


def quantize_forest(forest, mode: str):
    """Quantize a fitted forest's storage (thresholds + leaf stats) in-place
    in the pytree sense: ``bf16`` stores thresholds and leaves in bfloat16,
    ``int8`` stores thresholds bf16 and leaf probabilities on the fixed
    int8 grid (``models.forest.INT8_LEAF_SCALE``). Dequantization happens at
    the point of use inside the evaluation kernels (trees_gemm /
    trees_pallas / round_fused) — 2-4x memory-bandwidth headroom for the
    bandwidth-bound phases the PR-8 roofline names, with zero extra HBM
    round-trips.

    jit-friendly (pure casts/rounds), so the device fit quantizes inside its
    own program and the stored forest leaves the fit at the narrow dtypes —
    which the ``quantized-leaf-upcast`` audit rule checks statically.

    Only path-matrix forms quantize (``GemmForest``, plus its pallas/multi
    wrappers); thresholds must be bf16-snapped bin edges (``make_bins``
    ``quantize != "none"``) for bf16 storage to be lossless.
    """
    from distributed_active_learning_tpu.models.forest import (
        VALID_QUANTIZE_MODES,
        quantize_leaf_values,
    )

    if mode not in VALID_QUANTIZE_MODES:
        raise ValueError(
            f"unknown quantize mode {mode!r}; one of {VALID_QUANTIZE_MODES}"
        )
    if mode == "none":
        return forest
    from distributed_active_learning_tpu.ops.trees_multi import MultiForest
    from distributed_active_learning_tpu.ops.trees_pallas import PallasForest

    if isinstance(forest, MultiForest):
        return MultiForest(
            planes=tuple(quantize_forest(p, mode) for p in forest.planes)
        )
    if isinstance(forest, PallasForest):
        return PallasForest(gf=quantize_forest(forest.gf, mode))
    if not isinstance(forest, GemmForest):
        raise ValueError(
            "quantized storage applies to the path-matrix (gemm/pallas) "
            f"forms only, got {type(forest).__name__}; use kernel='gemm' or "
            "'pallas' with a depth within the path-matrix budget"
        )
    return GemmForest(
        feat_ids=forest.feat_ids,
        thresholds=forest.thresholds.astype(jnp.bfloat16),
        path=forest.path,
        target=forest.target,
        value=quantize_leaf_values(forest.value, mode),
    )


def gather_fit_window(
    codes: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray, budget: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pack the labeled rows into a fixed-size window on device.

    The labeled set grows every round; gathering it into a static
    ``budget``-row buffer (surplus rows weighted 0) keeps the jitted fit from
    recompiling — the mask-not-shapes rule of SURVEY.md §7 applied to training.

    Compaction is cumsum + scatter, not ``argsort(~mask)``: a full sort of the
    284k-row benchmark pool cost ~280 ms on a v5e — 900x the histogram fit it
    was feeding — while the scan/scatter form is bandwidth-bound (~1 ms).
    Labeled rows land in their stable index order exactly as the stable sort
    produced; unfilled slots read row 0 at weight 0 (weight is all the fit
    consumes, so the window is fit-equivalent).
    """
    with jax.named_scope("trees/gather_fit_window"):
        n = codes.shape[0]
        pos = jnp.cumsum(mask) - 1  # target slot per labeled row, in index order
        n_labeled = pos[-1] + 1
        slot = jnp.where(mask & (pos < budget), pos, budget)  # overflow -> dump slot
        idx = (
            jnp.zeros((budget + 1,), jnp.int32)
            .at[slot].set(jnp.arange(n, dtype=jnp.int32), mode="drop")[:budget]
        )
        sel = jnp.arange(budget) < n_labeled
        return codes[idx], y[idx], sel.astype(jnp.float32)
