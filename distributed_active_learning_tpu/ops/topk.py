"""Masked top-k / bottom-k selection over the unlabeled pool.

Replaces the reference's distributed ``sortBy(score).take(window)``
(``uncertainty_sampling.py:106-109``, ``density_weighting.py:168-172``) — a
full shuffle sort plus driver round-trip — with ``lax.top_k`` over
mask-neutralized scores: already-labeled points are forced to -inf (or +inf)
so they can never be selected (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax

# Plain Python floats, NOT jnp scalars: materializing a device value at
# import time would initialize the XLA backend before a multi-host launch
# can call jax.distributed.initialize() (run.py calls it lazily for exactly
# this reason).
NEG_INF = float("-inf")
POS_INF = float("inf")


def select_top_k(
    scores: jnp.ndarray, unlabeled_mask: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the k highest-scoring unlabeled points.

    Returns ``(values [k], indices [k])``. If fewer than k points are
    unlabeled, the tail indices point at -inf entries; callers scatter into the
    labeled mask, where re-labeling a labeled point is a no-op — matching the
    reference's behavior of just taking what remains.
    """
    masked = jnp.where(unlabeled_mask, scores, NEG_INF)
    return lax.top_k(masked, k)


def select_bottom_k(
    scores: jnp.ndarray, unlabeled_mask: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the k lowest-scoring unlabeled points (ascending selection,
    e.g. least-confidence: ``sortBy`` ascending + take at
    ``uncertainty_sampling.py:106-109``)."""
    masked = jnp.where(unlabeled_mask, scores, POS_INF)
    vals, idx = lax.top_k(-masked, k)
    return -vals, idx
