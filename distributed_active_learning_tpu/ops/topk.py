"""Masked top-k / bottom-k selection over the unlabeled pool.

Replaces the reference's distributed ``sortBy(score).take(window)``
(``uncertainty_sampling.py:106-109``, ``density_weighting.py:168-172``) — a
full shuffle sort plus driver round-trip — with ``lax.top_k`` over
mask-neutralized scores: already-labeled points are forced to -inf (or +inf)
so they can never be selected (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

# Plain Python floats, NOT jnp scalars: materializing a device value at
# import time would initialize the XLA backend before a multi-host launch
# can call jax.distributed.initialize() (run.py calls it lazily for exactly
# this reason).
NEG_INF = float("-inf")
POS_INF = float("inf")


def select_top_k(
    scores: jnp.ndarray, unlabeled_mask: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the k highest-scoring unlabeled points.

    Returns ``(values [k], indices [k])``. If fewer than k points are
    unlabeled, the tail indices point at -inf entries; callers scatter into the
    labeled mask, where re-labeling a labeled point is a no-op — matching the
    reference's behavior of just taking what remains.
    """
    masked = jnp.where(unlabeled_mask, scores, NEG_INF)
    return lax.top_k(masked, k)


def select_bottom_k(
    scores: jnp.ndarray, unlabeled_mask: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the k lowest-scoring unlabeled points (ascending selection,
    e.g. least-confidence: ``sortBy`` ascending + take at
    ``uncertainty_sampling.py:106-109``)."""
    masked = jnp.where(unlabeled_mask, scores, POS_INF)
    vals, idx = lax.top_k(-masked, k)
    return -vals, idx


def knapsack_top_k(
    scores: jnp.ndarray,
    costs: jnp.ndarray,
    unlabeled_mask: jnp.ndarray,
    k: int,
    budget: float,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Greedy budget-constrained selection: up to ``k`` unlabeled points by
    score-per-cost ratio under a spend cap (the cost_budget scenario's
    selection kernel, scenarios/engine.py).

    Each of the ``k`` greedy steps picks the highest ``score/cost`` ratio
    among the points still AFFORDABLE under the remaining-budget carry, then
    deducts that point's cost; once nothing affordable remains, the tail
    steps emit sentinel picks (``keep=False``, value ``NEG_INF``, index
    redirected at position 0 — the reveal's masked write ignores them
    either way, the :func:`select_top_k` tail contract).

    Assumes nonnegative, higher-is-better scores and strictly positive
    costs (validated at config time, ``scenarios.validate_scenario``):
    ratio-greedy ordering is only meaningful there. Ties break to the
    lowest pool index (``argmax`` semantics), matching the host reference
    in tests/test_scenarios.py exactly — the kernel is pinned exact, not
    approximate.

    Returns ``(vals [k], idx [k], keep [k] bool, spent scalar f32)``.
    """
    ratio = scores / costs

    def step(carry, _):
        avail, remaining = carry
        cand = avail & (costs <= remaining)
        masked = jnp.where(cand, ratio, NEG_INF)
        i = jnp.argmax(masked)
        ok = cand[i]  # False iff NO candidate was affordable (argmax of -inf)
        avail = jnp.where(ok, avail.at[i].set(False), avail)
        remaining = remaining - jnp.where(ok, costs[i], 0.0)
        val = jnp.where(ok, scores[i], NEG_INF)
        return (avail, remaining), (val, i, ok)

    (_, remaining), (vals, idx, keep) = jax.lax.scan(
        step,
        (unlabeled_mask, jnp.asarray(budget, jnp.float32)),
        None,
        length=k,
    )
    spent = jnp.asarray(budget, jnp.float32) - remaining
    # Sentinel tail: redirect dropped picks at the first pick (an excluded
    # or already-dropped target; the masked reveal writes nothing for them)
    # so downstream pick-indexed gathers stay in-bounds and deterministic.
    idx = jnp.where(keep, idx, idx[0])
    return vals, idx, keep, spent


def merge_tile_topk(
    tile_vals: jnp.ndarray, tile_idx: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-tile top-k candidate lists into the global top-k.

    The streaming half of the fused round (``ops/round_fused.py``): each pool
    tile contributes its own descending top-``k`` (values + pool-level
    indices) so the full score vector never materializes in HBM; this final
    static merge reduces the ``[tiles, k]`` candidates to the global winners.

    Exactness: the global top-k is a subset of the union of per-tile top-ks
    (any global winner is among its own tile's k best), so ``top_k`` over the
    flattened candidates returns the same SET as ``top_k`` over the full
    vector. Order matches too: ``lax.top_k`` breaks value ties by lowest
    position, each tile's candidates arrive in descending order with
    within-tile ties already in ascending index order, and tiles are
    concatenated in ascending base-index order — so the position order of the
    flattened candidates agrees with the index order of the full vector
    wherever values tie. (The one divergence: if fewer than ``k`` finite
    candidates exist globally, the sentinel tail's indices are per-tile
    placeholders rather than the full vector's first masked positions —
    callers scatter picks into an already-labeled mask, where those are
    no-ops either way, matching :func:`select_top_k`'s tail contract.)
    """
    flat_vals = tile_vals.reshape(-1)
    flat_idx = tile_idx.reshape(-1)
    vals, pos = lax.top_k(flat_vals, k)
    return vals, flat_idx[pos]
