"""Jitted compute kernels: tree traversal, similarity, scoring, selection.

These replace the reference's L2 MLlib ops (SURVEY.md §1): per-tree
``DecisionTreeModel.predict`` Spark jobs become one vmapped traversal, BlockMatrix
similarity multiplies become blocked MXU matmuls, and distributed sort+take
becomes ``lax.top_k``.
"""

from distributed_active_learning_tpu.ops.trees import (
    PackedForest,
    predict_leaves,
    predict_proba,
    predict_votes,
    predict_value,
)
from distributed_active_learning_tpu.ops.trees_gemm import (
    GemmForest,
    gemm_forest_from_packed,
    predict_leaves_gemm,
    predict_proba_gemm,
    predict_votes_gemm,
)
from distributed_active_learning_tpu.ops import forest_eval
from distributed_active_learning_tpu.ops.scoring import (
    uncertainty_score,
    positive_entropy,
    full_entropy,
    margin_score,
    vote_sd,
)
from distributed_active_learning_tpu.ops.topk import select_top_k, select_bottom_k
