"""Ring attention: sequence-parallel exact attention via ppermute.

Long-context support for the text-encoder AL path (BASELINE.json config 5).
The sequence axis is sharded over a mesh axis; each device computes attention
of its local query block against a K/V block that circulates around the ring
(one ``lax.ppermute`` per step), merging partial results with an online-softmax
accumulator. Exact (not approximate) attention with O(seq/devices) activation
memory per device and all communication riding ICI neighbor links.

The reference has nothing comparable (no sequence models at all, SURVEY.md
§5.7); this is the capability that lets the framework scale the "big dimension"
of text pools the way the reference chunked its similarity matrix over
BlockMatrix partitions.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

SEQ_AXIS = "sp"


def _online_softmax_step(o, l, m, scores, v_cur):
    """Merge one block's scores/values into the running (o, l, m) accumulator.

    o: [B, H, Tq, D] weighted-value accumulator (unnormalized)
    l: [B, H, Tq]    running normalizer
    m: [B, H, Tq]    running max logit
    scores: [B, H, Tq, Tk]; v_cur: [B, Tk, H, D]
    """
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # rescale old accumulator, accumulate this block
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])  # [B, H, Tq, Tk]
    o = o * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_cur)
    l = l * alpha + jnp.sum(p, axis=-1)
    return o, l, m_new


def _ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool,
    scale: float,
) -> jnp.ndarray:
    """Per-shard kernel. q/k/v: [B, T_blk, H, D] (local block)."""
    # jax 0.4.x has no lax.axis_size; psum of 1 over the axis is the
    # portable spelling (a trace-time constant, not a runtime collective)
    n_dev = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, T, H, D = q.shape

    neg = jnp.asarray(-1e30, dtype=q.dtype)
    q_pos = my * T + jnp.arange(T)  # global positions of local queries

    # accumulators derive a zero from q so they inherit q's varying-axis type
    # under shard_map (fresh constants would fail the fori_loop carry check)
    zero_bht = jnp.transpose(q[:, :, :, 0], (0, 2, 1)) * 0  # [B, H, T]
    o0 = jnp.zeros((B, H, T, D), dtype=q.dtype) + zero_bht[..., None]
    l0 = zero_bht
    m0 = zero_bht - jnp.inf

    def body(i, carry):
        o, l, m, k_cur, v_cur = carry
        # the block currently held originated on device (my - i) mod n_dev
        src = lax.rem(my - i + n_dev, n_dev)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        if causal:
            k_pos = src * T + jnp.arange(T)
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            scores = jnp.where(mask[None, None], scores, neg)
        o, l, m = _online_softmax_step(o, l, m, scores, v_cur)
        # circulate K/V to the right neighbor
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o, l, m, k_nxt, v_nxt

    o, l, m, _, _ = lax.fori_loop(0, n_dev, body, (o0, l0, m0, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3))  # [B, T, H, D]


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention with the sequence dim sharded over ``axis_name``.

    q/k/v: [B, T, H, D] with T sharded; returns [B, T, H, D], same sharding.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, axis_name, None, None)
    kernel = functools.partial(
        _ring_attention_sharded, axis_name=axis_name, causal=causal, scale=scale
    )
    from distributed_active_learning_tpu.utils.compat import shard_map

    return shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def full_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Single-device reference attention (the oracle for ring_attention and the
    fast path when the sequence fits one chip). Same [B, T, H, D] layout."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)
