"""Kernel-agnostic forest evaluation.

Three device representations of the same fitted forest exist:

- :class:`~distributed_active_learning_tpu.ops.trees.PackedForest` — gather
  traversal, ``O(depth)`` memory, bound by per-element gather throughput;
- :class:`~distributed_active_learning_tpu.ops.trees_gemm.GemmForest` — the
  path-matrix form whose dominant work is two batched GEMMs the MXU tiles;
- :class:`~distributed_active_learning_tpu.ops.trees_pallas.PallasForest` —
  the same path-matrix data evaluated by one fused Pallas kernel that keeps
  the compare/hit intermediates in VMEM (lifting the HBM-bandwidth cap of the
  two-GEMM form);
- :class:`~distributed_active_learning_tpu.ops.trees_pallas.ShardedPallasForest`
  — the mesh-aware twin of ``PallasForest``: carries a ``jax.sharding.Mesh``
  as static metadata and evaluates the fused kernel PER SHARD under
  ``shard_map`` (pool rows over ``data``, trees over ``model``), since
  ``pallas_call`` has no GSPMD partitioning rule. Built by
  ``trees_pallas.attach_mesh``; multi-device rounds use it so the flagship
  kernel survives sharding instead of falling back to the two-GEMM form.

Strategies and the round function call through these dispatchers so the kernel
choice is a config knob (``ForestConfig.kernel``), not a code path: the pytree
*type* of the forest argument selects the implementation at trace time, and
all kernels agree bit-for-bit on votes/probabilities on bf16-exact inputs
(asserted in ``tests/test_trees_gemm.py`` / ``tests/test_trees_pallas.py``).
This is the single launch that replaces the reference's per-tree Spark-job
loop (``classes/active_learner.py:169-184``).
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

from distributed_active_learning_tpu.ops import trees, trees_gemm, trees_pallas

Forest = Union[
    trees.PackedForest,
    trees_gemm.GemmForest,
    trees_pallas.PallasForest,
    trees_pallas.ShardedPallasForest,
]

# Deepest forest converted to path-matrix form; beyond this the O(4^depth)
# path tensor outgrows its MXU advantage (and, eventually, host memory).
_GEMM_MAX_DEPTH = 10


def _is_gemm(forest: Forest) -> bool:
    return isinstance(forest, trees_gemm.GemmForest)


def _is_pallas(forest: Forest) -> bool:
    return isinstance(
        forest, (trees_pallas.PallasForest, trees_pallas.ShardedPallasForest)
    )


def leaves(forest: Forest, x: jnp.ndarray) -> jnp.ndarray:
    """Per-tree leaf values ``[n, T]`` via whichever kernel the forest carries."""
    with jax.named_scope("forest/leaves"):
        if _is_pallas(forest):
            return trees_pallas.predict_leaves(forest, x)
        if _is_gemm(forest):
            return trees_gemm.predict_leaves_gemm(forest, x)
        return trees.predict_leaves(forest, x)


def proba(forest: Forest, x: jnp.ndarray) -> jnp.ndarray:
    """P(class 1) per point ``[n]`` (mean of per-tree leaf probabilities)."""
    with jax.named_scope("forest/proba"):
        if _is_pallas(forest):
            return trees_pallas.predict_proba(forest, x)
        if _is_gemm(forest):
            return trees_gemm.predict_proba_gemm(forest, x)
        return trees.predict_proba(forest, x)


def votes(forest: Forest, x: jnp.ndarray) -> jnp.ndarray:
    """Hard positive-vote count per point ``[n]`` (``uncertainty_sampling.py:96``)."""
    with jax.named_scope("forest/votes"):
        if _is_pallas(forest):
            return trees_pallas.predict_votes(forest, x)
        if _is_gemm(forest):
            return trees_gemm.predict_votes_gemm(forest, x)
        return trees.predict_votes(forest, x)


def value(forest: Forest, x: jnp.ndarray) -> jnp.ndarray:
    """Regression prediction per point ``[n]`` (the LAL-regressor predict,
    ``active_learner.py:319-321``)."""
    with jax.named_scope("forest/value"):
        if _is_pallas(forest):
            return trees_pallas.predict_proba(forest, x)
        if _is_gemm(forest):
            return trees_gemm.predict_proba_gemm(forest, x)
        return trees.predict_value(forest, x)


def for_kernel(forest: trees.PackedForest, kernel: str) -> Forest:
    """Convert a freshly packed forest to the representation ``kernel`` names.

    ``"gemm"`` (the default in :class:`ForestConfig`) builds the path-matrix
    form once per fit — a host-side restructure that is trivial next to the
    sklearn fit itself; ``"pallas"`` wraps the same form for the fused VMEM
    kernel; ``"gather"`` keeps the traversal form.
    """
    from distributed_active_learning_tpu.ops import trees_multi  # lazy: cycle

    if isinstance(forest, trees_multi.MultiForest):
        # Convert each class plane; structure is shared so every plane gets
        # the same representation.
        return trees_multi.MultiForest(
            planes=tuple(for_kernel(p, kernel) for p in forest.planes)
        )
    if kernel in ("gemm", "pallas"):
        # The path matrix is O(T · 4^depth); past depth 10 (~4 MB/tree) the
        # form stops paying for itself and would eventually OOM the host, so
        # deep forests keep the gather traversal. Callers can detect which
        # representation they got from the returned type.
        d = forest.max_depth
        if d > _GEMM_MAX_DEPTH:
            return forest
        # Depth-derived I/L budgets keep the path-matrix shapes identical
        # across per-round refits, so the jitted round never recompiles.
        gf = trees_gemm.gemm_forest_from_packed(
            forest, n_internal=2**d - 1, n_leaves=2**d
        )
        return trees_pallas.PallasForest(gf=gf) if kernel == "pallas" else gf
    if kernel == "gather":
        return forest
    raise ValueError(
        f"unknown forest kernel {kernel!r}; use 'gemm', 'pallas', or 'gather'"
    )
