"""Fused Pallas TPU kernel for path-matrix forest evaluation.

The XLA GEMM kernel (``ops/trees_gemm.py``) lowers to two batched matmuls with
elementwise stages between them; its ``[chunk, T, I]`` compare and
``[chunk, T, L]`` hit tensors round-trip through HBM, which caps it at ~5% MFU
— the classic bandwidth-bound fusion gap. This kernel performs the whole chain

    select features -> compare thresholds -> path GEMM -> leaf-hit test ->
    leaf-value gather

for a (row-block x tree-block) tile entirely in VMEM, so HBM traffic drops to
the inputs and the per-tree intermediates never leave the chip.

r4 redesign (transposed layout), measured on the BASELINE workload
(284,807x30 pool, 100 trees, depth 8, one v5e chip; interleaved medians —
see the instrument note below):

- ``x`` streams transposed (``[d_pad, n]``), every stage is tree-major, and
  the output tile ``[bt, BN]`` needs no in-kernel transpose.
- The main path GEMM runs in **int8** (compare bits in {0,1}, path entries
  in {-1,0,+1}: exact, and 2x the bf16 MXU rate on v5e).
- The per-tree leaf matvecs are f32 ``[1, L] x [L, BN]`` rows — full output
  lanes (the r3 kernel's ``[BN, L] x [L]`` orientation used 1 of 128 output
  lanes and cost as much MXU time as the main GEMM).
- One selection matmul per tile covers the whole tree block; its f32
  accumulator is downcast to bf16 before spilling (compare runs in f32 from
  the bf16 values, so semantics are unchanged).

Result (CORRECTED, late r4): the kernel executes the BASELINE workload in
**22.8 ms of device time — 12.5M scores/s at ~81% of bf16 peak MFU**
(jax.profiler device timeline, cross-checked by differential batching in
``bench.py::_device_time_per_call``). Every earlier figure for this kernel
(r3's "2.1M / 13.9%", early-r4's "2.27M / 15.1%") was a per-call *wall*
median, which on the tunnel-attached rig includes a fixed ~90 ms
per-program sync latency — the kernel was never VPU-bound; it was
latency-polluted measurement. Implications for the r4 redesign notes
below: the transposed/int8/full-lane redesign was a ~4x device-side win
over the r3 kernel (not the ~1.45x the wall deltas suggested), and the
"feature-segmented variant measures the same" observation in
``benches/pallas_variants.py`` compared latency-dominated walls — within
that noise floor, genuinely different device times are indistinguishable.
At ~81% of peak there is no meaningful headroom left in this formulation;
the residual ~19% covers the selection matmul's d=30-in-128-lanes padding
and the VPU compare stages.

- Instrument note: the tunnel-attached chip drifts +-30% across minutes,
  small ops under-report via block_until_ready (async completion), and
  every synced call pays ~90 ms rig latency. Trust only (a) profiler
  device timelines and (b) differential batched timings; interleave
  variants when comparing.

Feature selection is expressed as an MXU matmul against a one-hot
``[T*I, d_pad]`` selector (gathers are the one primitive the MXU cannot
help with); d pads to 128 lanes, so at d=30 the selection matmul spends ~4x
its useful FLOPs — structural to the formulation, see the roofline note.

Numerics: features are compared in bfloat16 (they ride the MXU), thresholds
stay f32, leaf payloads are gathered in full f32 (the hit one-hot is exact) —
identical contract to the r3 kernel. A vote can differ from the exact f32
kernels only when a feature value sits within bf16 rounding distance (~0.4%)
of a threshold. For device-fit forests (``ops/trees_train.py``) thresholds
are quantile-bin edges and inputs can be integer bin codes — exact in bf16 —
so there the kernel is bit-identical. The reference's own MLlib trainer bins
features to 32 levels (``uncertainty_sampling.py:74``), far coarser than bf16
resolution.

Shape limits: tree blocks are 8 trees, so the path tile is
``8 * 2^depth * 2^depth`` int8 bytes; past depth 8 (or d_pad > 512) the
VMEM budget is blown and evaluation falls back to the exact GEMM kernel
(``predict_leaves_gemm``) — still one fused XLA program, just HBM-resident
intermediates.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.experimental import pallas as pl

from distributed_active_learning_tpu.ops.trees_gemm import (
    GemmForest,
    predict_leaves_gemm,
)


@struct.dataclass
class PallasForest:
    """Marker wrapper selecting the fused kernel at trace time.

    Same path-matrix data as :class:`GemmForest`; the pytree *type* is what
    ``ops.forest_eval`` dispatches on (mirroring the gather/gemm split), so
    ``ForestConfig(kernel="pallas")`` is a config knob, not a code path.
    """

    gf: GemmForest

    @property
    def n_trees(self) -> int:
        return self.gf.n_trees


@struct.dataclass
class ShardedPallasForest:
    """Mesh-aware twin of :class:`PallasForest`: evaluation runs the fused
    kernel PER SHARD under ``shard_map`` (pool rows over ``data``, trees over
    ``model``) instead of asking GSPMD to partition ``pallas_call`` — which it
    cannot (no partitioning rule), so before r5 any >1-device round silently
    fell back to the ~20x slower two-GEMM form (r4 VERDICT weak #2).

    ``gf`` holds the GLOBAL forest (its leaves may carry model-axis
    NamedShardings); ``mesh`` rides as static pytree metadata so the wrapper
    survives ``jax.tree.map`` placement and jit caching keys on it. Inside the
    shard_map body each device sees plain local shapes, exactly the regime the
    kernel was written for; the per-tree leaf outputs come back as one global
    ``[n, T]`` array sharded ``P(data, model)``, and downstream reductions
    over trees (votes/proba) become XLA psums over ``model`` automatically.
    """

    gf: GemmForest
    mesh: jax.sharding.Mesh = struct.field(pytree_node=False)

    @property
    def n_trees(self) -> int:
        return self.gf.n_trees


def attach_mesh(forest, mesh):
    """Wrap pallas forests in a forest pytree with ``mesh`` so their
    evaluation shard_maps the fused kernel (multiclass ``MultiForest`` planes
    included); non-pallas forests pass through untouched."""
    from distributed_active_learning_tpu.ops.trees_multi import MultiForest

    if isinstance(forest, MultiForest):
        return MultiForest(planes=tuple(attach_mesh(p, mesh) for p in forest.planes))
    if isinstance(forest, ShardedPallasForest):
        return ShardedPallasForest(gf=forest.gf, mesh=mesh)
    if isinstance(forest, PallasForest):
        return ShardedPallasForest(gf=forest.gf, mesh=mesh)
    return forest


# Tree block (out-tile sublane count: 8 is the f32 minimum) and the VMEM
# budget gates. A v5e sweep (benches/pallas_variants.py) put BN=2048/BT=8
# ahead of the r3 512x16 tiling; small pools drop to BN=512 to bound padding.
_BT = 8
_MAX_I_PAD = 256   # depth 8: past this the [BT, L, I] path tile blows VMEM
_MAX_D_PAD = 512   # x tile [d_pad, BN] budget


def _pad_to(a: jnp.ndarray, axis: int, mult: int, value=0) -> jnp.ndarray:
    pad = (-a.shape[axis]) % mult
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths, constant_values=value)


def _leaf_rows(xT_ref, selT_ref, thr_ref, pathT_ref, tgt_ref, val_ref):
    """Per-tree leaf-value rows ``[bt, BN]`` for one (row, tree) tile — the
    shared eval body of the plain kernel and the fused-round megakernel
    (``ops/round_fused.py`` closes over this so the two cannot drift).

    Quantized storage dequantizes HERE, inside the kernel: bf16 thresholds
    widen before the compare (lossless — they are bf16-snapped bin edges)
    and int8/bf16 leaf stats rescale right before the leaf matvec, so the
    narrow representation is what streams through HBM/VMEM.
    """
    from distributed_active_learning_tpu.models.forest import (
        dequantize_leaf_values,
    )

    bt, i_pad = thr_ref.shape
    l_pad = pathT_ref.shape[1]
    # One selection matmul covers the tree block: [BT*I, d_pad] x [d_pad, BN]
    # routes each node slot's feature value to it. The f32 accumulator is
    # downcast before it spills (values are bf16-exact copies of x).
    fv_all = jnp.dot(
        selT_ref[:], xT_ref[:], preferred_element_type=jnp.float32
    ).astype(jnp.bfloat16)
    rows = []
    for t in range(bt):
        fvT = fv_all[t * i_pad:(t + 1) * i_pad]
        # bf16 [N,1]-broadcast compares crash Mosaic; compare in f32.
        thr_t = thr_ref[t][:, None].astype(jnp.float32)
        cT = (fvT.astype(jnp.float32) <= thr_t).astype(jnp.int8)
        # Ancestor-agreement counts: int8 x int8 -> int32, exact and 2x the
        # bf16 MXU rate.
        sT = jnp.dot(pathT_ref[t], cT, preferred_element_type=jnp.int32)
        # Exactly one hit per column (the reached leaf).
        hit = (sT.astype(jnp.float32) == tgt_ref[t][:, None]).astype(
            jnp.float32)
        # Leaf gather as a full-lane f32 matvec row: exact payload (int8
        # stats rescale onto their fixed grid first).
        val_t = dequantize_leaf_values(val_ref[t]).reshape(1, l_pad)
        rows.append(jnp.dot(val_t, hit, preferred_element_type=jnp.float32))
    return rows


def _kernel(xT_ref, selT_ref, thr_ref, pathT_ref, tgt_ref, val_ref, out_ref):
    out_ref[:] = jnp.concatenate(
        _leaf_rows(xT_ref, selT_ref, thr_ref, pathT_ref, tgt_ref, val_ref),
        axis=0,
    )


def tile_dims(gf: GemmForest, n: int, d: int):
    """The kernel's padded tile dimensions ``(i_pad, l_pad, d_pad, bn)``, or
    ``None`` when the shapes exceed the VMEM tiling budget (depth > 8 or
    d_pad > 512) and callers must fall back to the exact GEMM kernel. Shared
    with the fused-round megakernel (``ops/round_fused.py``) so both kernels
    tile — and fall back — identically."""
    T, I = gf.feat_ids.shape
    L = gf.value.shape[1]
    i_pad = max(-(-I // 128) * 128, 128)
    l_pad = max(-(-L // 128) * 128, 128)
    d_pad = max(-(-d // 128) * 128, 128)
    if i_pad > _MAX_I_PAD or d_pad > _MAX_D_PAD:
        return None
    bn = 2048 if n >= 1536 else 512
    return i_pad, l_pad, d_pad, bn


def forest_operands(gf: GemmForest, i_pad: int, l_pad: int, d_pad: int):
    """Pad + transpose the forest arrays into the kernel's tree-major operand
    layout: ``(selT, thr, pathT, tgt, val)`` with the tree axis padded to a
    multiple of the ``_BT`` tree block. Quantized forests keep their storage
    dtypes here (thr bf16 / val int8|bf16) — dequantization is in-kernel."""
    feat = _pad_to(gf.feat_ids, 1, i_pad)  # padded slots select feature 0...
    thr = _pad_to(gf.thresholds, 1, i_pad, value=-np.inf)  # ...compare False
    path = _pad_to(_pad_to(gf.path, 1, i_pad), 2, l_pad)
    # Padded leaves carry an unreachable target, padded internal slots a 0
    # path row — they add 0 to s and never hit.
    tgt = _pad_to(gf.target, 1, l_pad, value=1.0e6)
    val = _pad_to(gf.value, 1, l_pad)

    feat = _pad_to(feat, 0, _BT)
    thr = _pad_to(thr, 0, _BT, value=-np.inf)
    path = _pad_to(path, 0, _BT)
    tgt = _pad_to(tgt, 0, _BT, value=1.0e6)
    val = _pad_to(val, 0, _BT)

    selT = jax.nn.one_hot(feat.reshape(-1), d_pad, dtype=jnp.bfloat16)
    pathT = jnp.swapaxes(path, 1, 2).astype(jnp.int8)
    return selT, thr, pathT, tgt, val


def x_operand(x: jnp.ndarray, d_pad: int, bn: int) -> jnp.ndarray:
    """The transposed ``[d_pad, n_pad]`` bf16 pool operand (row-block
    padded)."""
    return _pad_to(_pad_to(x.astype(jnp.bfloat16), 1, d_pad), 0, bn).T


@functools.partial(jax.jit, static_argnames=("interpret",))
def predict_leaves_pallas(
    gf: GemmForest, x: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Per-tree leaf values ``[n, T]`` via the fused VMEM-resident kernel.

    Falls back to the exact GEMM kernel when the forest/feature shapes exceed
    the kernel's VMEM tiling budget (depth > 8 or d_pad > 512).
    """
    n, d = x.shape
    T, I = gf.feat_ids.shape

    dims = tile_dims(gf, n, d)
    if dims is None:
        return predict_leaves_gemm(gf, x)
    i_pad, l_pad, d_pad, bn = dims

    selT, thr, pathT, tgt, val = forest_operands(gf, i_pad, l_pad, d_pad)
    t_pad = thr.shape[0]
    xT = x_operand(x, d_pad, bn)
    n_pad = xT.shape[1]

    grid = (n_pad // bn, t_pad // _BT)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((d_pad, bn), lambda i, j: (0, i)),
            pl.BlockSpec((_BT * i_pad, d_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, i_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, l_pad, i_pad), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((_BT, l_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, l_pad), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((_BT, bn), lambda i, j: (j, i)),
        out_shape=jax.ShapeDtypeStruct((t_pad, n_pad), jnp.float32),
        interpret=interpret,
    )(xT, selT, thr, pathT, tgt, val)
    return out[:T, :n].T


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _unwrap(f) -> GemmForest:
    return f.gf if isinstance(f, (PallasForest, ShardedPallasForest)) else f


def _predict_leaves_sharded(f: ShardedPallasForest, x: jnp.ndarray) -> jnp.ndarray:
    """``[n, T]`` leaves via one fused-kernel launch per (data, model) shard.

    Rows are embarrassingly parallel and the tree axis is the ensemble axis,
    so the body needs NO collectives — the output's ``P(data, model)``
    sharding states the decomposition, and the vote/proba reductions that
    follow psum over ``model`` under GSPMD. Row counts not divisible by the
    data axis (e.g. the test split) are padded here and sliced back.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_active_learning_tpu.parallel import mesh as mesh_lib

    n = x.shape[0]
    x = _pad_to(x, 0, f.mesh.shape[mesh_lib.AXIS_DATA])
    gf_specs = mesh_lib.forest_tree_specs(f.gf)

    from distributed_active_learning_tpu.utils.compat import shard_map

    @functools.partial(
        shard_map,
        mesh=f.mesh,
        in_specs=(gf_specs, P(mesh_lib.AXIS_DATA, None)),
        out_specs=P(mesh_lib.AXIS_DATA, mesh_lib.AXIS_MODEL),
        # pallas_call declares its out_shape without varying-mesh-axes
        # annotations (same waiver as parallel.kernels.sharded_votes).
        check_vma=False,
    )
    def kern(gf_local, x_blk):
        return predict_leaves_pallas(gf_local, x_blk, interpret=_use_interpret())

    return kern(f.gf, x)[:n]


def predict_leaves(f, x: jnp.ndarray) -> jnp.ndarray:
    # named_scope: the fused kernel is the flagship hot op — give the profiler
    # a label that distinguishes it from the GEMM fallback's dot_generals.
    with jax.named_scope("pallas/forest_leaves"):
        if isinstance(f, ShardedPallasForest):
            return _predict_leaves_sharded(f, x)
        return predict_leaves_pallas(_unwrap(f), x, interpret=_use_interpret())


def predict_proba(f, x: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(predict_leaves(f, x), axis=1)


def predict_votes(f, x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(predict_leaves(f, x) > 0.5, axis=1).astype(jnp.int32)
