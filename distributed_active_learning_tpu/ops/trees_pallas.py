"""Fused Pallas TPU kernel for path-matrix forest evaluation.

The XLA GEMM kernel (``ops/trees_gemm.py``) lowers to two batched matmuls with
elementwise stages between them; its ``[chunk, T, I]`` compare and
``[chunk, T, L]`` hit tensors round-trip through HBM, which caps it at ~5% MFU
(BENCH_r02/r03: ~10 bf16 TFLOP/s on a v5e whose peak is 197) — the classic
bandwidth-bound fusion gap. This kernel performs the whole chain

    select features -> compare thresholds -> path GEMM -> leaf-hit test ->
    leaf-value contraction

for a (row-block x tree-block) tile entirely in VMEM, so HBM traffic drops to
the inputs (x once per tree-block sweep, path matrices once per row-block) and
the [BN, I]/[BN, L] intermediates never leave the chip. Measured on the
BASELINE workload (284,807x30 pool, 100 trees, depth 8, one v5e chip):
2.07M scores/s at 13.8% MFU vs 0.82M at 5.4% for the two-GEMM form — the
fusion recovers the 2.5x the bandwidth cap was costing. Remaining headroom is
the one-hot selection matmul (d=30 pads to 128 lanes: ~4x its useful FLOPs)
and the vector-unit compare/equality stages between the MXU ops.

Feature selection is itself expressed as an MXU matmul against a one-hot
``[d, T*I]`` selector (gathers are the one primitive the MXU cannot help
with), which costs ``2*BN*d_pad*I`` — ~12-50% of the main ``2*BN*I*L`` GEMM
depending on feature-count padding.

Numerics: features are compared in bfloat16 (they ride the MXU), so a vote can
differ from the exact f32 kernels only when a feature value sits within bf16
rounding distance (~0.4%) of a threshold. For device-fit forests
(``ops/trees_train.py``) thresholds are quantile-bin edges and inputs can be
integer bin codes — exact in bf16 — so there the kernel is bit-identical.
The reference's own MLlib trainer bins features to 32 levels
(``uncertainty_sampling.py:74``), far coarser than bf16 resolution.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.experimental import pallas as pl

from distributed_active_learning_tpu.ops.trees_gemm import GemmForest


@struct.dataclass
class PallasForest:
    """Marker wrapper selecting the fused kernel at trace time.

    Same path-matrix data as :class:`GemmForest`; the pytree *type* is what
    ``ops.forest_eval`` dispatches on (mirroring the gather/gemm split), so
    ``ForestConfig(kernel="pallas")`` is a config knob, not a code path.
    """

    gf: GemmForest

    @property
    def n_trees(self) -> int:
        return self.gf.n_trees

# Row-block and tree-block tile sizes. A v5e sweep put 512x32/2048x8 ~5%
# ahead of 512x16 standalone, but those tilings exceed the 16 MB scoped-VMEM
# limit once the kernel is fused into the full acquisition program, so the
# defaults stay at the proven 512x16 (2.07M scores/s, 13.8% MFU on the
# 284,807x30/100-tree workload). The effective tree block shrinks with depth
# so the [BT, I, L] path tile stays bounded (depth 10 ⇒ 2 MB/tree ⇒ BT=1).
_BN = 512
_BT = 16
_PATH_TILE_BYTES = 2 << 20


def _tree_block(t_cnt: int, i_pad: int, l_pad: int) -> int:
    budget = max(_PATH_TILE_BYTES // (i_pad * l_pad * 2), 1)
    return max(min(_BT, t_cnt, budget), 1)


def _kernel(x_ref, sel_ref, thr_ref, path_ref, tgt_ref, val_ref, out_ref):
    bn = x_ref.shape[0]
    bt, i_dim = thr_ref.shape
    # One selection matmul covers every tree in the block: [BN, dp] x
    # [dp, BT*I] -> feature values routed to each internal-node slot.
    fv = jnp.dot(x_ref[:], sel_ref[:], preferred_element_type=jnp.float32)
    c = (fv.reshape(bn, bt, i_dim) <= thr_ref[:][None, :, :]).astype(jnp.bfloat16)
    preds = []
    for t in range(bt):
        # Ancestor-agreement counts: the main MXU GEMM, per tree.
        s = jnp.dot(c[:, t, :], path_ref[t], preferred_element_type=jnp.float32)
        hit = (s == tgt_ref[t][None, :]).astype(jnp.float32)  # exactly one 1/row
        # Leaf payload selection: [BN, L] x [L] matvec (f32: hit is one-hot,
        # so this is an exact gather-by-matmul of the leaf value).
        preds.append(jnp.dot(hit, val_ref[t], preferred_element_type=jnp.float32))
    # Tree-major output: the [bt, BN] tile is lane-aligned (BN % 128 == 0)
    # where [BN, bt] would violate the TPU's last-dim-128 tiling rule.
    out_ref[:] = jnp.stack(preds, axis=0)


def _pad_to(a: jnp.ndarray, axis: int, mult: int, value=0) -> jnp.ndarray:
    pad = (-a.shape[axis]) % mult
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths, constant_values=value)


@functools.partial(jax.jit, static_argnames=("interpret",))
def predict_leaves_pallas(
    gf: GemmForest, x: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Per-tree leaf values ``[n, T]`` via the fused VMEM-resident kernel."""
    n, d = x.shape
    T, I = gf.feat_ids.shape
    L = gf.value.shape[1]

    # Lane-align the tile dims (last dim 128 for f32/bf16 tiling).
    i_pad = max(-(-I // 128) * 128, 128)
    l_pad = max(-(-L // 128) * 128, 128)
    d_pad = max(-(-d // 128) * 128, 128)

    # One-hot feature selector [d_pad, T*i_pad] (tree-major columns).
    feat = _pad_to(gf.feat_ids, 1, i_pad)  # padded slots select feature 0...
    thr = _pad_to(gf.thresholds, 1, i_pad, value=-np.inf)  # ...and compare False
    sel = jax.nn.one_hot(feat.reshape(-1), d_pad, dtype=jnp.bfloat16)  # [T*ip, dp]

    path = _pad_to(_pad_to(gf.path, 1, i_pad), 2, l_pad).astype(jnp.bfloat16)
    # Padded leaves carry an unreachable target, padded internal slots a 0 path
    # row — they add 0 to s and never hit.
    tgt = _pad_to(gf.target, 1, l_pad, value=1.0e6)
    val = _pad_to(gf.value, 1, l_pad)

    # Pad rows/trees to tile multiples.
    xp = _pad_to(x.astype(jnp.bfloat16), 1, d_pad)
    xp = _pad_to(xp, 0, _BN)
    n_pad, t_cnt = xp.shape[0], thr.shape[0]
    bt = _tree_block(t_cnt, i_pad, l_pad)
    sel = _pad_to(sel.reshape(T, i_pad, d_pad), 0, bt)
    thr = _pad_to(thr, 0, bt, value=-np.inf)
    path = _pad_to(path, 0, bt)
    tgt = _pad_to(tgt, 0, bt, value=1.0e6)
    val = _pad_to(val, 0, bt)
    t_pad = thr.shape[0]
    sel = sel.transpose(2, 0, 1).reshape(d_pad, t_pad * i_pad)

    grid = (n_pad // _BN, t_pad // bt)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BN, d_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((d_pad, bt * i_pad), lambda i, j: (0, j)),
            pl.BlockSpec((bt, i_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((bt, i_pad, l_pad), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((bt, l_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((bt, l_pad), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bt, _BN), lambda i, j: (j, i)),
        out_shape=jax.ShapeDtypeStruct((t_pad, n_pad), jnp.float32),
        interpret=interpret,
    )(xp, sel, thr, path, tgt, val)
    return out[:T, :n].T


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _unwrap(f) -> GemmForest:
    return f.gf if isinstance(f, PallasForest) else f


def predict_leaves(f, x: jnp.ndarray) -> jnp.ndarray:
    return predict_leaves_pallas(_unwrap(f), x, interpret=_use_interpret())


def predict_proba(f, x: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(predict_leaves(f, x), axis=1)


def predict_votes(f, x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(predict_leaves(f, x) > 0.5, axis=1).astype(jnp.int32)
