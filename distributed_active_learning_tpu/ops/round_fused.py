"""The round megakernel: forest eval -> acquisition score -> streaming top-k
in ONE pass over the pool.

The unfused round runs three programs' worth of HBM traffic per round: the
forest eval writes the ``[pool, trees]`` leaf/vote matrix, the scoring pass
reads it back to build the ``[pool]`` score vector, and the top-k reads that
again. All three stream the same pool slab; the PR-8 roofline verdicts put
the score/select half bandwidth-bound. This module fuses the chain so each
pool slab crosses VMEM ONCE per round:

- **TPU (pallas forests)**: a megakernel over a ``(row tiles, tree tiles)``
  grid — the tree loop accumulates hard votes for the resident row tile in a
  VMEM scratch (reusing the per-tree-block eval body and tiling machinery of
  ``ops/trees_pallas.py``), and on the LAST tree tile the kernel computes
  the acquisition score and extracts a per-tile top-k in place. Outputs are
  ``[row_tiles, k]`` candidates; neither the vote matrix nor the score
  vector ever lands in HBM.
- **CPU / gemm forests**: the same streaming formulation as XLA: a
  ``lax.map`` over row tiles runs eval -> votes -> score -> per-tile top-k
  with the exact GEMM tile body (``trees_gemm._predict_chunk``), so
  per-tile intermediates stay cache-resident instead of round-tripping a
  ``[pool, trees]`` tensor through memory.
- **mesh (ShardedPallasForest)**: fully-distributed selection in ONE
  ``shard_map`` (rows over ``data``, trees over ``model``): each shard runs
  the fused vote kernel on its (row block, tree shard), one psum over
  ``model`` completes the votes, and the shard scores + extracts its local
  top-k window in place — the ``[n_local, T_local]`` leaf matrix AND the
  global score vector never materialize. The global top-k is then a ring
  merge of k-row candidate windows over ``data`` (``ops/ring_topk.py``):
  ``S - 1`` neighbor hops of ``k * 8`` bytes each, no pool-scale collective
  anywhere — the pod-sharding contract the PR-13 auditor rules gate.

Both single-device paths emit per-tile candidates merged by
``ops.topk.merge_tile_topk``; the merge (and the tie-break argument for its
exactness) lives there.

Bit-identity contract (pinned in tests/test_round_fused.py): with
unquantized storage the fused round reproduces the unfused reference path
bit-for-bit — the supported strategies all score the INTEGER vote fraction
(``votes / n_trees``), vote sums are exact in any accumulation order, the
score formulas are the very functions ``strategies/core.py`` applies
(imported, not re-derived), and the selection's tie-breaking matches
``lax.top_k``'s lowest-index rule (in-kernel: first-index argmax per pick).
One caveat mirrors ``ops/topk.py``: if fewer than ``k`` selectable points
remain globally, sentinel tail indices may differ from the reference's —
both scatter as no-ops into the labeled mask. On real TPUs the entropy
scores' transcendentals may differ in ulps between Mosaic and XLA lowerings;
the rational-arithmetic strategies (uncertainty, margin) are exact
everywhere, and CPU CI (interpret mode) executes identical primitives for
all of them.

Quantized storage (``ForestConfig.quantize``) rides through unchanged: the
shared eval body dequantizes bf16 thresholds / int8 leaf stats in-kernel
(``trees_pallas._leaf_rows``), so the 2-4x narrower forest is what streams
through HBM.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_active_learning_tpu.ops import scoring
from distributed_active_learning_tpu.ops import trees_pallas
from distributed_active_learning_tpu.ops.topk import NEG_INF, merge_tile_topk
from distributed_active_learning_tpu.ops.trees_gemm import (
    GemmForest,
    _predict_chunk,
    predict_leaves_gemm,
)
from distributed_active_learning_tpu.ops.trees_pallas import (
    PallasForest,
    ShardedPallasForest,
    _BT,
    _pad_to,
)

#: Strategies the fused round serves: every binary strategy whose score is a
#: pure function of the hard vote fraction (scoring rules imported from the
#: same module the unfused strategies use — one definition, zero drift).
#: Vote counts are integers, so these are bit-identical under ANY tiling or
#: shard reduction order. The rest fall back by construction: ``random``
#: needs no forest pass at all, ``density``/``lal`` consume O(n^2) similarity
#: or regressor aux inputs that are not per-tile-local, ``soft_uncertainty``
#: scores the f32 mean leaf probability (tile-order-sensitive sums).
FUSED_STRATEGIES: Dict[str, Tuple] = {
    "uncertainty": (scoring.uncertainty_score, False),
    "entropy": (scoring.positive_entropy, True),
    "full_entropy": (scoring.full_entropy, True),
    "margin": (scoring.margin_score, False),
}


def supports(strategy_name: str) -> bool:
    return strategy_name in FUSED_STRATEGIES


def _score_from_votes(votes_f32: jnp.ndarray, n_trees: int, strategy_name: str):
    """Vote counts -> directed score: ``p = votes / T`` exactly as
    ``strategies.core._vote_fraction`` divides, then the strategy's own
    scoring function; negated for ascending strategies so every caller works
    in one maximize space."""
    score_fn, higher = FUSED_STRATEGIES[strategy_name]
    p = votes_f32 / np.float32(n_trees)
    s = score_fn(p)
    return (s if higher else -s), higher


# ---------------------------------------------------------------------------
# XLA streaming formulation (gemm forests; the CPU path)
# ---------------------------------------------------------------------------

def _stream_tile(n: int) -> int:
    """Row-tile width for the lax.map stream: 2048 keeps the [tile, T]
    intermediates cache-resident at bench shapes; small pools shrink to one
    power-of-two tile to bound padding."""
    return min(2048, max(256, 1 << max(n - 1, 1).bit_length()))


def _xla_streamed(
    gf: GemmForest,
    x: jnp.ndarray,
    selectable: jnp.ndarray,
    strategy_name: str,
    k: int,
):
    """Per-tile candidates via a lax.map stream of exact GEMM tile bodies."""
    n, d = x.shape
    T = gf.n_trees
    tile = _stream_tile(n)
    pad = (-n) % tile
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    selp = jnp.pad(selectable, (0, pad))  # padding rows unselectable
    n_tiles = xp.shape[0] // tile
    bases = jnp.arange(n_tiles, dtype=jnp.int32) * tile

    def one_tile(args):
        xb, sb, base = args
        with jax.named_scope("fused_round/tile"):
            leaves = _predict_chunk(gf, xb)  # [tile, T] — never [n, T]
            votes = jnp.sum(leaves > 0.5, axis=1).astype(jnp.int32)
            s, _ = _score_from_votes(
                votes.astype(jnp.float32), T, strategy_name
            )
            work = jnp.where(sb, s, NEG_INF)
            v, i = lax.top_k(work, k)
            return v, base + i

    tv, ti = lax.map(
        one_tile, (xp.reshape(n_tiles, tile, d), selp.reshape(n_tiles, tile), bases)
    )
    return tv, ti


# ---------------------------------------------------------------------------
# the pallas megakernel (TPU; interpret mode on CPU for parity tests)
# ---------------------------------------------------------------------------

def _mega_kernel(
    n_trees, strategy_name, k, nj, bn,
    xT_ref, selT_ref, thr_ref, pathT_ref, tgt_ref, val_ref, pen_ref,
    vals_ref, idx_ref, votes_ref,
):
    """One (row tile, tree tile) grid step.

    The tree axis is the inner grid dimension: the x tile stays VMEM-resident
    across it (its index_map ignores j), votes accumulate in the scratch, and
    the last tree tile computes score + top-k without the row tile ever
    leaving the chip.
    """
    # Both program_ids are read OUTSIDE the pl.when bodies: jax 0.4.37's
    # interpret mode doesn't substitute pl.program_id inside a cond sub-jaxpr.
    i = pl.program_id(0)
    j = pl.program_id(1)
    rows = trees_pallas._leaf_rows(
        xT_ref, selT_ref, thr_ref, pathT_ref, tgt_ref, val_ref
    )
    leaf = jnp.concatenate(rows, axis=0)  # [BT, bn] f32
    # Hard votes; padded trees contribute leaf value 0 -> vote 0. f32
    # accumulation is exact for counts (integers < 2^24).
    part = jnp.sum((leaf > 0.5).astype(jnp.float32), axis=0, keepdims=True)

    @pl.when(j == 0)
    def _init():
        votes_ref[:] = part

    @pl.when(j > 0)
    def _accumulate():
        votes_ref[:] = votes_ref[:] + part

    @pl.when(j == nj - 1)
    def _score_and_select():
        s, _ = _score_from_votes(votes_ref[:], n_trees, strategy_name)
        work = s + pen_ref[:]  # -inf penalty kills labeled/padded columns
        iota = lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        picked_v, picked_i = [], []
        for _ in range(k):
            m = jnp.max(work)
            hit = work == m
            # first-index tie-break — the lax.top_k ordering the unfused
            # reference selection uses
            first = jnp.min(jnp.where(hit, iota, bn))
            picked_v.append(m)
            picked_i.append(first)
            work = jnp.where(iota == first, NEG_INF, work)
        base = i * bn
        k_pad = vals_ref.shape[1]
        vals_row = jnp.stack(picked_v).reshape(1, k)
        idx_row = jnp.stack(picked_i).reshape(1, k) + base
        vals_ref[:] = jnp.pad(
            vals_row, ((0, 0), (0, k_pad - k)), constant_values=NEG_INF
        )
        idx_ref[:] = jnp.pad(idx_row, ((0, 0), (0, k_pad - k))).astype(jnp.int32)


def _megakernel(
    gf: GemmForest,
    x: jnp.ndarray,
    selectable: jnp.ndarray,
    strategy_name: str,
    k: int,
    interpret: bool = False,
):
    """Per-row-tile top-k candidates, one VMEM pass per pool slab."""
    n, d = x.shape
    T = gf.n_trees
    dims = trees_pallas.tile_dims(gf, n, d)
    if dims is None:
        # Same fallback boundary as predict_leaves_pallas: shapes past the
        # VMEM tiling budget stream through the exact GEMM formulation.
        return _xla_streamed(gf, x, selectable, strategy_name, k)
    i_pad, l_pad, d_pad, bn = dims
    if k > bn:
        raise ValueError(f"window {k} exceeds the row tile ({bn})")

    selT, thr, pathT, tgt, val = trees_pallas.forest_operands(
        gf, i_pad, l_pad, d_pad
    )
    t_pad = thr.shape[0]
    xT = trees_pallas.x_operand(x, d_pad, bn)
    n_pad = xT.shape[1]
    pen = jnp.where(selectable, 0.0, NEG_INF).astype(jnp.float32)[None, :]
    pen = _pad_to(pen, 1, bn, value=NEG_INF)

    k_pad = max(-(-k // 128) * 128, 128)
    ni, nj = n_pad // bn, t_pad // _BT
    grid = (ni, nj)
    kernel = functools.partial(_mega_kernel, T, strategy_name, k, nj, bn)
    vals, idx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((d_pad, bn), lambda i, j: (0, i)),
            pl.BlockSpec((_BT * i_pad, d_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, i_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, l_pad, i_pad), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((_BT, l_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, l_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, k_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((1, k_pad), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((ni, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((ni, k_pad), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((1, bn), jnp.float32)],
        interpret=interpret,
    )(xT, selT, thr, pathT, tgt, val, pen)
    return vals[:, :k], idx[:, :k]


# ---------------------------------------------------------------------------
# fused vote accumulation (the mesh per-shard body)
# ---------------------------------------------------------------------------

def _votes_kernel(
    nj, xT_ref, selT_ref, thr_ref, pathT_ref, tgt_ref, val_ref, out_ref
):
    j = pl.program_id(1)
    rows = trees_pallas._leaf_rows(
        xT_ref, selT_ref, thr_ref, pathT_ref, tgt_ref, val_ref
    )
    leaf = jnp.concatenate(rows, axis=0)
    part = jnp.sum((leaf > 0.5).astype(jnp.float32), axis=0, keepdims=True)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = part

    @pl.when(j > 0)
    def _accumulate():
        out_ref[:] = out_ref[:] + part


def fused_votes_pallas(
    gf: GemmForest, x: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Hard vote counts ``[n] int32`` with the ``[n, T]`` leaf matrix kept in
    VMEM (accumulated tree tile by tree tile into a revisited ``[n]`` output).
    Falls back to the exact GEMM eval past the tiling budget — vote sums are
    integers, so every route agrees bit-for-bit."""
    n, d = x.shape
    dims = trees_pallas.tile_dims(gf, n, d)
    if dims is None:
        return jnp.sum(predict_leaves_gemm(gf, x) > 0.5, axis=1).astype(jnp.int32)
    i_pad, l_pad, d_pad, bn = dims
    selT, thr, pathT, tgt, val = trees_pallas.forest_operands(
        gf, i_pad, l_pad, d_pad
    )
    t_pad = thr.shape[0]
    xT = trees_pallas.x_operand(x, d_pad, bn)
    n_pad = xT.shape[1]
    ni, nj = n_pad // bn, t_pad // _BT
    out = pl.pallas_call(
        functools.partial(_votes_kernel, nj),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec((d_pad, bn), lambda i, j: (0, i)),
            pl.BlockSpec((_BT * i_pad, d_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, i_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, l_pad, i_pad), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((_BT, l_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BT, l_pad), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bn), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        interpret=interpret,
    )(xT, selT, thr, pathT, tgt, val)
    return out[0, :n].astype(jnp.int32)


def _sharded_fused_votes(f: ShardedPallasForest, x: jnp.ndarray) -> jnp.ndarray:
    """Global vote counts ``[n]`` with rows over ``data`` and trees over
    ``model``: each shard runs the fused vote kernel on its (row block, tree
    shard) and one psum over ``model`` completes the reduction — the mesh
    twin of ``parallel.kernels.sharded_votes`` minus the per-shard leaf
    matrix."""
    from jax.sharding import PartitionSpec as P

    from distributed_active_learning_tpu.parallel import mesh as mesh_lib
    from distributed_active_learning_tpu.parallel.collectives import (
        vector_accumulate,
    )
    from distributed_active_learning_tpu.utils.compat import shard_map

    n = x.shape[0]
    x = _pad_to(x, 0, f.mesh.shape[mesh_lib.AXIS_DATA])
    gf_specs = mesh_lib.forest_tree_specs(f.gf)

    @functools.partial(
        shard_map,
        mesh=f.mesh,
        in_specs=(gf_specs, P(mesh_lib.AXIS_DATA, None)),
        out_specs=P(mesh_lib.AXIS_DATA),
        # pallas_call declares its out_shape without varying-mesh-axes
        # annotations (same waiver as trees_pallas._predict_leaves_sharded).
        check_vma=False,
    )
    def kern(gf_local, x_blk):
        local = fused_votes_pallas(
            gf_local, x_blk, interpret=trees_pallas._use_interpret()
        )
        return vector_accumulate(local, mesh_lib.AXIS_MODEL)

    return kern(f.gf, x)[:n]


def _sharded_score_select(
    f: ShardedPallasForest,
    x: jnp.ndarray,
    selectable: jnp.ndarray,
    strategy_name: str,
    k: int,
):
    """Fully-distributed fused selection: per-shard votes + score + local
    top-k, then a ring merge of k-row candidate windows over ``data``
    (``ops/ring_topk.py``) — selection never funnels through a global score
    vector or a pool-scale collective.

    Bit-identity with the single-mesh global top-k: inside the shard_map the
    directed score of every row is computed from the SAME psum'd integer
    votes (elementwise, so per-shard blocks carry identical bits to the
    global vector), local windows come from ``lax.top_k`` over the masked
    block (value desc, position asc — position = global index within a
    contiguous block), and the ring merge's (value desc, index asc) order is
    exactly ``lax.top_k``'s full-vector order. Unselectable and padding rows
    are -inf with real/IDX_SENTINEL indices, so the sentinel tail when fewer
    than ``k`` rows remain matches ``select_top_k``'s tail contract too.

    Returns DIRECTED ``(vals [k], idx [k])`` replicated across the mesh; the
    dispatch un-negates ascending strategies, mirroring the tile path.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_active_learning_tpu.ops import ring_topk as ring_lib
    from distributed_active_learning_tpu.parallel import mesh as mesh_lib
    from distributed_active_learning_tpu.parallel.collectives import (
        vector_accumulate,
    )
    from distributed_active_learning_tpu.utils.compat import shard_map

    n_shards = f.mesh.shape[mesh_lib.AXIS_DATA]
    x = _pad_to(x, 0, n_shards)
    selectable = _pad_to(selectable, 0, n_shards)  # pads False: unselectable
    n_local = x.shape[0] // n_shards
    kk = min(k, n_local)
    gf_specs = mesh_lib.forest_tree_specs(f.gf)

    @functools.partial(
        shard_map,
        mesh=f.mesh,
        in_specs=(
            gf_specs,
            P(mesh_lib.AXIS_DATA, None),
            P(mesh_lib.AXIS_DATA),
        ),
        out_specs=(P(), P()),
        # pallas_call declares its out_shape without varying-mesh-axes
        # annotations, and the ring merge's replicated outputs hold by
        # construction (every shard converges to the same global winners) —
        # same waiver as _sharded_fused_votes.
        check_vma=False,
    )
    def kern(gf_local, x_blk, sel_blk):
        local = fused_votes_pallas(
            gf_local, x_blk, interpret=trees_pallas._use_interpret()
        )
        votes = vector_accumulate(local, mesh_lib.AXIS_MODEL)
        s, _ = _score_from_votes(
            votes.astype(jnp.float32), f.n_trees, strategy_name
        )
        work = jnp.where(sel_blk, s, NEG_INF)
        loc_v, loc_i = lax.top_k(work, kk)
        glob_i = (
            lax.axis_index(mesh_lib.AXIS_DATA) * n_local + loc_i
        ).astype(jnp.int32)
        win_v, win_i = ring_lib.pad_window(loc_v, glob_i, k)
        return ring_lib.ring_topk(
            win_v, win_i, k, mesh_lib.AXIS_DATA,
            mesh_axis_names=f.mesh.axis_names,
        )

    with jax.named_scope("fused_round/pod_select"):
        return kern(f.gf, x, selectable)


# ---------------------------------------------------------------------------
# the dispatch
# ---------------------------------------------------------------------------

def fused_score_select(
    forest,
    x: jnp.ndarray,
    selectable_mask: jnp.ndarray,
    strategy_name: str,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused eval -> score -> select: ``(vals [k], picked [k])`` with the
    same value/index contract as ``select_top_k`` / ``select_bottom_k`` over
    the unfused score vector (including the ascending strategies' sign
    convention). Dispatches on the forest pytree type like the rest of
    ``ops/forest_eval``: pallas forests take the megakernel, gemm forests
    the XLA stream, mesh-wrapped forests the pod-sharded path (per-shard
    megakernel + ring-merged top-k, ``_sharded_score_select``).
    """
    if strategy_name not in FUSED_STRATEGIES:
        raise ValueError(
            f"strategy {strategy_name!r} has no fused round; fused: "
            f"{sorted(FUSED_STRATEGIES)}"
        )
    _, higher = FUSED_STRATEGIES[strategy_name]
    with jax.named_scope("fused_round/score_select"):
        if isinstance(forest, ShardedPallasForest):
            vals, idx = _sharded_score_select(
                forest, x, selectable_mask, strategy_name, k
            )
            return (vals, idx) if higher else (-vals, idx)
        gf = forest.gf if isinstance(forest, PallasForest) else forest
        if not isinstance(gf, GemmForest):
            raise TypeError(
                "fused_score_select needs a path-matrix forest (gemm/pallas "
                f"kernels), got {type(forest).__name__}"
            )
        if isinstance(forest, PallasForest):
            tv, ti = _megakernel(
                gf, x, selectable_mask, strategy_name, k,
                interpret=trees_pallas._use_interpret(),
            )
        else:
            tv, ti = _xla_streamed(gf, x, selectable_mask, strategy_name, k)
        vals, idx = merge_tile_topk(tv, ti, k)
        return (vals, idx) if higher else (-vals, idx)
