"""Packed tree-ensemble representation and vmapped traversal kernels.

The reference scores the pool one tree at a time: a Python loop over
``model._java_model.trees()`` launches ``n_estimators`` sequential Spark jobs,
each a full pool scan, because the JVM tree objects are not serializable
(``classes/active_learner.py:169-184``; ``final_thesis/uncertainty_sampling.py:88-93``).
Vote aggregation is then a shuffle (``groupByKey().mapValues(sum)``,
``uncertainty_sampling.py:96``).

Here the whole forest is a packed tensor — one int/float array per node field,
shaped ``[n_trees, n_nodes]`` — and prediction is a fixed-depth gather loop
vmapped over trees and points: every tree and every point is scored in a single
XLA launch, and the vote reduction is a dense axis-sum. Shapes are static (trees
padded to a node budget), so AL rounds never recompile.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

# Sentinel feature id marking a leaf node.
LEAF = -1


@struct.dataclass
class PackedForest:
    """A forest as dense node arrays.

    ``feature[t, i] == LEAF`` marks a leaf; internal nodes route a point ``x``
    left iff ``x[feature] <= threshold`` (sklearn/MLlib convention). ``value``
    holds, per node, the prediction payload: P(class 1) at that node for
    classifiers, the regression value for regressors (valid at every node so
    truncated-depth traversal still returns a sensible estimate).

    Padding trees to a common ``n_nodes`` uses self-looping leaves
    (``left == right == i``), which are fixed points of the traversal.
    """

    feature: jnp.ndarray    # [T, N] int32, LEAF for leaves
    threshold: jnp.ndarray  # [T, N] float32
    left: jnp.ndarray       # [T, N] int32
    right: jnp.ndarray      # [T, N] int32
    value: jnp.ndarray      # [T, N] float32
    max_depth: int = struct.field(pytree_node=False, default=32)

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def n_nodes(self) -> int:
        return self.feature.shape[1]


def _traverse_one(forest: PackedForest, x: jnp.ndarray) -> jnp.ndarray:
    """Route one point through every tree; returns the leaf value per tree [T]."""
    T = forest.n_trees
    t_idx = jnp.arange(T)

    def step(_, nodes):
        feat = forest.feature[t_idx, nodes]          # [T]
        thr = forest.threshold[t_idx, nodes]         # [T]
        go_left = x[jnp.maximum(feat, 0)] <= thr     # [T] (clamped gather on leaves)
        nxt = jnp.where(go_left, forest.left[t_idx, nodes], forest.right[t_idx, nodes])
        return jnp.where(feat == LEAF, nodes, nxt)

    # Derive the initial nodes from both inputs (not a fresh constant) so the
    # loop carry inherits the union of their varying-axis types under
    # shard_map (forest varies over 'model', the point over 'data').
    nodes0 = jnp.zeros_like(forest.feature[:, 0]) + (x[0] * 0).astype(forest.feature.dtype)
    nodes = jax.lax.fori_loop(0, forest.max_depth, step, nodes0)
    return forest.value[t_idx, nodes]


def predict_leaves(forest: PackedForest, x: jnp.ndarray) -> jnp.ndarray:
    """Per-tree leaf values for a batch: ``x [n, d] -> [n, T]``.

    This is the single-launch replacement for the reference's per-tree
    Spark-job loop (``active_learner.py:172-184``).
    """
    return jax.vmap(lambda p: _traverse_one(forest, p))(x)


def predict_proba(forest: PackedForest, x: jnp.ndarray) -> jnp.ndarray:
    """P(class 1) per point as the mean of per-tree leaf probabilities [n]."""
    return jnp.mean(predict_leaves(forest, x), axis=1)


def predict_votes(forest: PackedForest, x: jnp.ndarray) -> jnp.ndarray:
    """Hard-vote count per point [n] — the reference's per-point vote sum
    (``uncertainty_sampling.py:96``): each tree votes its majority class."""
    return jnp.sum(predict_leaves(forest, x) > 0.5, axis=1).astype(jnp.int32)


def predict_value(forest: PackedForest, x: jnp.ndarray) -> jnp.ndarray:
    """Regression prediction per point [n]: mean of per-tree values (the packed
    equivalent of the 2000-tree LAL regressor predict, ``active_learner.py:319-321``)."""
    return jnp.mean(predict_leaves(forest, x), axis=1)


def pad_forest(forest: PackedForest, n_nodes: int) -> PackedForest:
    """Pad every tree's node arrays to ``n_nodes`` with self-looping leaves."""
    T, N = forest.feature.shape
    if N > n_nodes:
        raise ValueError(f"forest has {N} nodes; budget {n_nodes} too small")
    if N == n_nodes:
        return forest
    pad = n_nodes - N
    idx = jnp.arange(N, n_nodes, dtype=jnp.int32)
    return PackedForest(
        feature=jnp.pad(forest.feature, ((0, 0), (0, pad)), constant_values=LEAF),
        threshold=jnp.pad(forest.threshold, ((0, 0), (0, pad))),
        left=jnp.concatenate([forest.left, jnp.broadcast_to(idx, (T, pad))], axis=1),
        right=jnp.concatenate([forest.right, jnp.broadcast_to(idx, (T, pad))], axis=1),
        value=jnp.pad(forest.value, ((0, 0), (0, pad))),
        max_depth=forest.max_depth,
    )
