"""Acquisition scoring primitives (pure functions over probabilities/votes).

Each function reproduces a scoring rule from the reference, cited inline. All
operate elementwise on arrays of pool size and are safe under jit/vmap/shard_map.
"""

from __future__ import annotations

import jax.numpy as jnp


def uncertainty_score(p_pos: jnp.ndarray) -> jnp.ndarray:
    """Least-confidence distance from the decision boundary.

    The reference computes ``abs(0.5 - (1 - votes/n))`` over positive-vote
    fractions and picks the *minimum* (``uncertainty_sampling.py:98,106``;
    ``active_learner.py:197,203``). With ``p_pos = votes/n`` this is
    ``abs(0.5 - (1 - p_pos)) == abs(p_pos - 0.5)``. Lower = more uncertain.
    """
    return jnp.abs(0.5 - (1.0 - p_pos))


def positive_entropy(p_pos: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """The reference's one-sided 'true entropy' ``-(1-p) * log2(1-p)``
    (``density_weighting.py:148``) — kept verbatim for parity (it is not the
    full binary entropy; the reference only uses the negative-class term)."""
    q = jnp.clip(1.0 - p_pos, eps, 1.0)
    return -q * jnp.log2(q)


def full_entropy(p_pos: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """Standard binary entropy in bits — the statistically-correct variant the
    reference approximates; exposed for the neural/deep-AL configs and the
    telemetry pool-entropy gauge.

    ``eps`` must stay float32-representable: with the former 1e-12,
    ``1.0 - eps`` rounds back to exactly 1.0 in f32, so a unanimous forest
    (p = 1) produced ``0 * log2(0) = nan`` — which then poisoned any mean
    over the pool (the telemetry gauge surfaced this; the clip was a no-op
    at both ends).
    """
    p = jnp.clip(p_pos, eps, 1.0 - eps)
    return -(p * jnp.log2(p) + (1.0 - p) * jnp.log2(1.0 - p))


def margin_score(p_pos: jnp.ndarray) -> jnp.ndarray:
    """Margin between top-2 class probabilities (binary case: ``|2p - 1|``).
    Lower = more uncertain. Not in the reference; standard AL companion."""
    return jnp.abs(2.0 * p_pos - 1.0)


def vote_sd(votes: jnp.ndarray, n_trees: int) -> jnp.ndarray:
    """Standard deviation of per-tree Bernoulli votes.

    Reference ``getSD(x, n)`` (``active_learner.py:232-236``): with ``x``
    positive votes out of ``n`` trees, the vote sample has mean ``x/n`` and
    SD ``sqrt((x/n) * (1 - x/n))`` — LAL feature f_2 (``active_learner.py:283``).
    """
    p = votes / n_trees
    return jnp.sqrt(p * (1.0 - p))
