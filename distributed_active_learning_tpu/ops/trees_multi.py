"""Multiclass forest evaluation: per-class value planes over one structure.

The reference is binary end-to-end (``numClasses=2`` at
``uncertainty_sampling.py:71-76``; every scoring rule consumes the positive
vote fraction), and so was this framework through r3 — the forest loop and the
neural loop accepted disjoint problem spaces. This module closes that split
(VERDICT r3 weak #3): a C-class forest rides as ``C`` scalar-valued forests
sharing identical tree structure, one value plane per class, so every existing
kernel (gather / GEMM / fused Pallas) evaluates multiclass forests unchanged —
``P(y=c | x)`` is the mean leaf value of plane ``c``.

Cost: scoring evaluates the structure C times. For the tabular pools the
forest path serves (C <= ~10) this is a small constant over the binary path
and keeps all three kernels' exactness guarantees; folding the class axis into
the kernels' leaf contraction is the known next step if a workload demands it.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from flax import struct

from distributed_active_learning_tpu.ops import forest_eval


@struct.dataclass
class MultiForest:
    """C-class forest: one scalar-value plane (any kernel form) per class.

    Planes share tree structure by construction (same fit, different leaf
    payloads), so per-plane evaluations traverse identically and the stacked
    outputs are the per-class probability means.
    """

    planes: Tuple[forest_eval.Forest, ...]

    @property
    def n_classes(self) -> int:
        return len(self.planes)

    @property
    def n_trees(self) -> int:
        return self.planes[0].n_trees


def is_multi(forest) -> bool:
    return isinstance(forest, MultiForest)


def proba_multi(mf: MultiForest, x: jnp.ndarray) -> jnp.ndarray:
    """Class-probability matrix ``[n, C]`` (mean of per-tree leaf
    distributions — rows sum to 1 because each leaf's plane values do)."""
    return jnp.stack(
        [forest_eval.value(p, x) for p in mf.planes], axis=-1
    )


def predict_class(mf: MultiForest, x: jnp.ndarray) -> jnp.ndarray:
    """Argmax class per point ``[n]`` int32."""
    return jnp.argmax(proba_multi(mf, x), axis=-1).astype(jnp.int32)


def margin_score_multi(probs: jnp.ndarray) -> jnp.ndarray:
    """Top-2 margin per point ``[n]`` (ascending = most uncertain first) —
    the multiclass form of the reference's ``abs(0.5 - p)`` rule."""
    import jax

    top2 = jax.lax.top_k(probs, 2)[0]
    return top2[..., 0] - top2[..., 1]


def entropy_multi(probs: jnp.ndarray) -> jnp.ndarray:
    """Full predictive entropy per point ``[n]`` in bits (descending =
    most uncertain first) — the C-class generalization of the binary
    entropy the reference's one-sided form approximates."""
    return -jnp.sum(probs * jnp.log2(probs + 1e-12), axis=-1)
