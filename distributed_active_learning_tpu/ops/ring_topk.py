"""Ring-merged exact global top-k over per-shard candidate windows.

The pod-scale half of the fused round (ops/round_fused.py): each data shard
runs the megakernel over its own pool block and keeps only a k-row candidate
window ``(values, global indices)``; this module merges those windows into the
global top-k with a ring exchange — ``S - 1`` neighbor hops of k-sized windows
(``ops/ring_attention.py``'s schedule), never a pool-scale collective. Per-hop
per-link traffic is ``k * 8`` bytes (f32 value + i32 index), independent of the
pool size — the property the PR-13 auditor's ``pool-scale-collective`` /
``collective-bytes-over-budget`` rules gate on.

Exactness (the ``ops/topk.py merge_tile_topk`` argument, restated for shards):
any global winner is among its own shard's k best — fewer than k candidates
beat it globally, so fewer than k beat it locally — hence the global top-k is
a subset of the union of the shard windows, and merging windows loses nothing.
Tie-breaks: ``lax.top_k`` over the full vector orders by (value desc, position
asc); here positions ARE global indices (shard blocks are contiguous index
ranges concatenated in shard order), so the two-key merge sort on
``(-value, index)`` reproduces the full-vector order exactly — including the
sentinel tail when fewer than k finite candidates exist (each shard's window
tail holds its lowest-index masked rows, so the merged tail is the full
vector's first masked positions). Padding rows (``k > n_local``, or uneven
windows) carry ``(-inf, IDX_SENTINEL)`` and lose every tie against real rows.
Merging under this total order is associative and commutative, so every shard
converges to the SAME result regardless of hop order — the replicated
``out_specs=P()`` contract of the callers.

The merged scores assume a total order without NaNs and without mixed-sign
zeros among tied candidates — true for the fused strategies (scores are
deterministic functions of the integer vote fraction, so equal candidates
carry equal bits), pinned by the parity tests.

Transport: ``lax.ppermute`` everywhere (the portable path CPU CI executes);
on TPU backends a pallas ``make_async_remote_copy`` hop moves the window
buffers directly over ICI neighbor links (double semaphore pair per hop, the
accelerator guide's ring pattern) — same schedule, same merge, same result.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_active_learning_tpu.ops.topk import NEG_INF

#: Window-padding index: larger than any real pool index, so a padding row
#: (value -inf) loses the index tie-break against every real -inf row and the
#: merged sentinel tail matches ``lax.top_k`` over the full masked vector.
IDX_SENTINEL = int(np.iinfo(np.int32).max)


def pad_window(
    vals: jnp.ndarray, idx: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pad a local candidate window to exactly ``k`` rows.

    A shard whose block holds fewer than ``k`` candidates (``k > n_local``)
    still exchanges fixed ``k``-row windows — the ring's message size is
    static. Padding rows are ``(-inf, IDX_SENTINEL)``: strictly worse than
    every real row under the (value desc, index asc) merge order.
    """
    pad = k - vals.shape[0]
    if pad <= 0:
        return vals[:k], idx[:k]
    return (
        jnp.pad(vals, (0, pad), constant_values=NEG_INF),
        jnp.pad(idx, (0, pad), constant_values=IDX_SENTINEL),
    )


def merge_windows(
    a_vals: jnp.ndarray,
    a_idx: jnp.ndarray,
    b_vals: jnp.ndarray,
    b_idx: jnp.ndarray,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact 2-window merge: top ``k`` of the union under (value desc, index
    asc) — the ``lax.top_k`` order with positions replaced by global indices.

    One two-key ``lax.sort`` over the 2k candidates; the value key is negated
    so ascending sort means descending value (negation is exact for every
    float including infinities, and ``-vals`` is undone on return).
    """
    v = jnp.concatenate([a_vals, b_vals])
    i = jnp.concatenate([a_idx, b_idx])
    neg_v, idx = lax.sort((-v, i), num_keys=2)
    return -neg_v[:k], idx[:k]


# ---------------------------------------------------------------------------
# ring transports: one neighbor hop of the (vals, idx) window pair
# ---------------------------------------------------------------------------

def _hop_ppermute(vals, idx, axis_name: str, perm):
    return (
        lax.ppermute(vals, axis_name, perm),
        lax.ppermute(idx, axis_name, perm),
    )


def _hop_kernel(
    axis_names: Sequence[str],
    ring_axis: str,
    v_ref, i_ref, vo_ref, io_ref, send_sem, recv_sem,
):
    """One right-neighbor window copy over ICI (the guide's ring pattern).

    The barrier semaphore handshake with both ring neighbors guarantees every
    device is inside the kernel (destination buffers live) before any RDMA
    starts; the send/recv DMA semaphore pair then tracks the two window
    copies (values + indices) to the right neighbor.
    """
    n = lax.psum(1, ring_axis)
    my = lax.axis_index(ring_axis)

    def _coords(target):
        # Full logical-mesh coordinates: the ring axis moves to `target`,
        # every other mesh axis keeps this device's own index.
        return tuple(
            target if a == ring_axis else lax.axis_index(a)
            for a in axis_names
        )

    right = _coords(lax.rem(my + 1, n))
    left = _coords(lax.rem(my - 1 + n, n))

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, device_id=left, device_id_type=pltpu.DeviceIdType.MESH
    )
    pltpu.semaphore_signal(
        barrier, device_id=right, device_id_type=pltpu.DeviceIdType.MESH
    )
    pltpu.semaphore_wait(barrier, 2)

    for slot, (src, dst) in enumerate(((v_ref, vo_ref), (i_ref, io_ref))):
        rdma = pltpu.make_async_remote_copy(
            src_ref=src,
            dst_ref=dst,
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        rdma.start()
    # Waits drain both sends and both receives before the kernel returns.
    for slot, (src, dst) in enumerate(((v_ref, vo_ref), (i_ref, io_ref))):
        pltpu.make_async_remote_copy(
            src_ref=src,
            dst_ref=dst,
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.MESH,
        ).wait()


def _hop_pallas(vals, idx, axis_names: Sequence[str], ring_axis: str):
    mem_any = getattr(pltpu, "ANY", None)
    if mem_any is None:  # older pallas spelling
        mem_any = pltpu.TPUMemorySpace.ANY
    compiler_params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return pl.pallas_call(
        functools.partial(_hop_kernel, tuple(axis_names), ring_axis),
        in_specs=[
            pl.BlockSpec(memory_space=mem_any),
            pl.BlockSpec(memory_space=mem_any),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=mem_any),
            pl.BlockSpec(memory_space=mem_any),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(vals.shape, vals.dtype),
            jax.ShapeDtypeStruct(idx.shape, idx.dtype),
        ],
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=compiler_params_cls(collective_id=7),
    )(vals, idx)


def _default_use_pallas() -> bool:
    from distributed_active_learning_tpu.ops import trees_pallas

    return jax.default_backend() == "tpu" and not trees_pallas._use_interpret()


# ---------------------------------------------------------------------------
# the ring merge
# ---------------------------------------------------------------------------

def ring_topk(
    vals: jnp.ndarray,
    idx: jnp.ndarray,
    k: int,
    axis_name: str,
    mesh_axis_names: Optional[Sequence[str]] = None,
    use_pallas: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-shard ``k``-row candidate windows into the global top-k.

    Call INSIDE a ``shard_map`` body: ``vals``/``idx`` are this shard's
    window (``pad_window``-normalized to exactly ``k`` rows, indices global).
    Each shard circulates its ORIGINAL window around the ring — ``S - 1``
    hops, merging the arriving window into a local accumulator per hop — so
    after the loop every shard holds the top ``k`` of the union of all ``S``
    windows: the same replicated ``(vals [k], idx [k])`` on every shard
    (merge-order independence; see the module docstring).
    """
    if vals.shape != (k,) or idx.shape != (k,):
        raise ValueError(
            f"ring_topk needs k-row windows, got {vals.shape}/{idx.shape} "
            f"for k={k}; normalize with pad_window first"
        )
    # jax 0.4.x has no lax.axis_size; psum of 1 over the axis is the portable
    # spelling (a trace-time constant, not a runtime collective).
    n_shards = lax.psum(1, axis_name)
    if n_shards == 1:
        return vals, idx
    if use_pallas is None:
        use_pallas = _default_use_pallas()
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def body(_, carry):
        acc_v, acc_i, cur_v, cur_i = carry
        if use_pallas:
            nxt_v, nxt_i = _hop_pallas(
                cur_v, cur_i,
                mesh_axis_names if mesh_axis_names is not None else (axis_name,),
                axis_name,
            )
        else:
            nxt_v, nxt_i = _hop_ppermute(cur_v, cur_i, axis_name, perm)
        acc_v, acc_i = merge_windows(acc_v, acc_i, nxt_v, nxt_i, k)
        return acc_v, acc_i, nxt_v, nxt_i

    acc_v, acc_i, _, _ = lax.fori_loop(
        0, n_shards - 1, body, (vals, idx, vals, idx)
    )
    return acc_v, acc_i


def remap_indices(
    idx: jnp.ndarray, moved_src: jnp.ndarray, moved_dst: jnp.ndarray
) -> jnp.ndarray:
    """Map selection indices from a rebalanced pool back to pre-epoch rows.

    A rebalance epoch (serving/slab.py ``make_rebalance_fn``) permutes a
    window-sized set of rows and returns the permutation as ``(moved_src,
    moved_dst)`` global-index pairs (negative entries are padding). The
    ring merge's exactness argument needs only contiguous-block index
    recovery — each candidate's global index names a unique resident row —
    so a selection over the rebalanced pool recovers pre-epoch row
    identities by rewriting every picked index that appears in
    ``moved_dst`` with its ``moved_src`` twin; unmoved picks pass through.
    O(k * moved) equality compare, window-sized on both axes — never a
    pool-scale lookup table.
    """
    src = jnp.asarray(moved_src).reshape(-1)
    dst = jnp.asarray(moved_dst).reshape(-1)
    hit = (jnp.asarray(idx)[..., None] == dst[None, :]) & (dst[None, :] >= 0)
    found = jnp.any(hit, axis=-1)
    at = jnp.argmax(hit, axis=-1)
    return jnp.where(found, src[at], idx)
