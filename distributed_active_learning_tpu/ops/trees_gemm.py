"""GEMM-based forest evaluation: tree traversal as MXU matmuls.

The gather-based traversal (``ops/trees.py``) is bound by per-element gather
throughput (~25k points/s for 100 trees x depth 8 on one v5e chip). This module
re-expresses evaluation so the dominant work is a batched matmul the MXU can
tile (the classic "forest as tensor ops" formulation):

1. ``feat_vals[n, T*I] = x[:, feat_ids]`` — a constant-index take along the
   feature axis (same indices for every row: cheap, exact).
2. ``c = feat_vals <= thresholds`` — one vectorized compare -> {0, 1}.
3. ``S[n, t, l] = sum_i path[t, i, l] * c[n, t, i]`` — batched GEMM, where
   ``path`` is +1 if internal node ``i`` is an ancestor of leaf ``l`` whose
   condition must hold (left turn), -1 if it must fail (right turn), 0 if not
   an ancestor. A point reaches leaf ``l`` iff every ancestor condition matches,
   i.e. iff ``S == n_left_ancestors(l)`` (each satisfied left-ancestor adds 1,
   each violated right-ancestor adds 0 = -1 x 0... summed, the unique maximum
   configuration hits the target exactly; all counts are small integers, exact
   in bf16).
4. ``pred[n, t] = sum_l value[t, l] * [S == target]`` — a second batched GEMM.

Intermediates are chunked over the pool axis so HBM never holds the full
``[n, T, I]`` compare tensor. Everything is jit-friendly with static shapes.

Roofline note (v5e, 284,807x30 pool, 100 trees, depth 8): this form is
HBM-bandwidth-bound, not MXU-bound — the [chunk, T, I]/[chunk, T, L]
intermediates round-trip through HBM between the two einsums. Measured
evidence: an int8 variant of the first einsum (2x the MXU rate on v5e,
exact for these {0,1}x{-1,0,1} integers) is *not* faster (0.79M vs 0.83M
scores/s), while fusing the whole chain in VMEM (``ops/trees_pallas.py``)
is 2.5x faster at the same FLOP count. Keep this kernel as the exact,
mesh-shardable default; reach for pallas for raw scoring throughput.

(Measurement caveat, late r4: the figures above are per-call WALL numbers
from the tunnel rig, which adds ~90 ms fixed sync latency per call — the
qualitative conclusion stands, but true device-time ratios are larger;
the pallas kernel's corrected device rate is ~12M scores/s. See
``ops/trees_pallas.py`` and ``bench.py::_device_time_per_call``.)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

from distributed_active_learning_tpu.ops.trees import LEAF, PackedForest

# Sentinel target for padded leaves: S (bounded by +-depth) can never reach it.
_PAD_TARGET = 1.0e6


@struct.dataclass
class GemmForest:
    """Forest in path-matrix form.

    T trees, I internal-node slots, L leaf slots (padded to forest-wide max).
    """

    feat_ids: jnp.ndarray    # [T, I] int32 (0 for padded slots)
    thresholds: jnp.ndarray  # [T, I] float32
    path: jnp.ndarray        # [T, I, L] float32 in {-1, 0, +1}
    target: jnp.ndarray      # [T, L] float32 — required S value (left-ancestor count)
    value: jnp.ndarray       # [T, L] float32 — leaf payload (P(class1) / regression)

    @property
    def n_trees(self) -> int:
        return self.feat_ids.shape[0]


def gemm_forest_from_packed(
    packed: PackedForest,
    n_internal: int | None = None,
    n_leaves: int | None = None,
) -> GemmForest:
    """Convert the gather representation to path-matrix form (host-side).

    ``n_internal``/``n_leaves`` pad the I/L axes to fixed sizes (defaults: the
    forest's actual maxima). AL refits a forest every round and fitted node
    counts vary, so callers that jit over the result must pass depth-derived
    budgets (``2^D - 1`` / ``2^D``) to keep shapes static across rounds —
    :func:`ops.forest_eval.for_kernel` does.
    """
    feature = np.asarray(packed.feature)
    threshold = np.asarray(packed.threshold)
    left = np.asarray(packed.left)
    right = np.asarray(packed.right)
    value = np.asarray(packed.value)
    T, N = feature.shape

    per_tree = []
    max_I = max_L = 1
    for t in range(T):
        # Reachable nodes only (padding slots self-loop and are unreachable).
        internal, leaves = [], []
        stack = [(0, [])]  # (node, [(internal_idx, went_left), ...])
        while stack:
            node, path_list = stack.pop()
            if feature[t, node] == LEAF:
                leaves.append((node, path_list))
            else:
                i = len(internal)
                internal.append(node)
                stack.append((int(left[t, node]), path_list + [(i, True)]))
                stack.append((int(right[t, node]), path_list + [(i, False)]))
        per_tree.append((internal, leaves))
        max_I = max(max_I, len(internal))
        max_L = max(max_L, len(leaves))

    if n_internal is not None:
        if max_I > n_internal:
            raise ValueError(f"forest has {max_I} internal nodes > budget {n_internal}")
        max_I = n_internal
    if n_leaves is not None:
        if max_L > n_leaves:
            raise ValueError(f"forest has {max_L} leaves > budget {n_leaves}")
        max_L = n_leaves

    feat_ids = np.zeros((T, max_I), dtype=np.int32)
    thresholds = np.full((T, max_I), -np.inf, dtype=np.float32)
    path = np.zeros((T, max_I, max_L), dtype=np.float32)
    target = np.full((T, max_L), _PAD_TARGET, dtype=np.float32)
    leaf_value = np.zeros((T, max_L), dtype=np.float32)

    for t, (internal, leaves) in enumerate(per_tree):
        for i, node in enumerate(internal):
            feat_ids[t, i] = feature[t, node]
            thresholds[t, i] = threshold[t, node]
        for l, (node, path_list) in enumerate(leaves):
            leaf_value[t, l] = value[t, node]
            n_left = 0
            for i, went_left in path_list:
                path[t, i, l] = 1.0 if went_left else -1.0
                n_left += int(went_left)
            target[t, l] = float(n_left)

    return GemmForest(
        feat_ids=jnp.asarray(feat_ids),
        thresholds=jnp.asarray(thresholds),
        path=jnp.asarray(path),
        target=jnp.asarray(target),
        value=jnp.asarray(leaf_value),
    )


def _predict_chunk(gf: GemmForest, x: jnp.ndarray) -> jnp.ndarray:
    """Leaf values for one pool chunk: [chunk, d] -> [chunk, T]."""
    from distributed_active_learning_tpu.models.forest import dequantize_leaf_values

    T, I = gf.feat_ids.shape
    feat_vals = jnp.take(x, gf.feat_ids.reshape(-1), axis=1)  # [chunk, T*I]
    # Quantized storage keeps thresholds bf16 (bf16-snapped bin edges, so the
    # widening compare below is lossless); the f32-vs-bf16 promotion is exact.
    c = (feat_vals <= gf.thresholds.reshape(-1).astype(jnp.float32)).astype(
        jnp.bfloat16
    )
    c = c.reshape(-1, T, I)
    # Batched GEMM over trees; counts are small ints — exact in bf16.
    s = jnp.einsum("nti,til->ntl", c, gf.path.astype(jnp.bfloat16))
    # s holds small integer counts (|s| <= depth): exact in bf16.
    hit = (s.astype(jnp.float32) == gf.target[None]).astype(jnp.float32)
    # Leaf payloads are arbitrary f32 probabilities — keep this contraction in
    # full precision so GEMM and gather kernels agree bit-for-bit on votes.
    # Quantized (bf16/int8) leaf stats dequantize HERE, at the point of use —
    # never between fit and storage (the quantized-leaf-upcast audit rule).
    pred = jnp.einsum(
        "ntl,tl->nt", hit, dequantize_leaf_values(gf.value),
        precision=lax.Precision.HIGHEST,
    )
    return pred


def _auto_chunk(gf: GemmForest) -> int:
    """Pool-axis chunk size bounding the ``[chunk, T, L]`` intermediates.

    The compare/hit tensors scale with T*L; a fixed chunk would let deep/wide
    forests (e.g. the 2000-tree LAL regressor) materialize multi-GB
    intermediates and OOM the device. Cap them at ~512M elements (~2 GB f32),
    power-of-two chunks for stable tiling.
    """
    T, L = gf.value.shape
    budget = max(512 * 1024 * 1024 // (T * L), 256)
    return min(1 << (budget.bit_length() - 1), 8192)


def predict_leaves_gemm(
    gf: GemmForest, x: jnp.ndarray, chunk: int | None = None
) -> jnp.ndarray:
    """Per-tree leaf values ``[n, T]`` via the MXU path, chunked over rows."""
    if chunk is None:
        chunk = _auto_chunk(gf)
    n = x.shape[0]
    if n <= chunk:
        return _predict_chunk(gf, x)
    pad = (-n) % chunk
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    out = lax.map(lambda xb: _predict_chunk(gf, xb), xp.reshape(-1, chunk, x.shape[1]))
    return out.reshape(-1, out.shape[-1])[:n]


def predict_proba_gemm(gf: GemmForest, x: jnp.ndarray, chunk: int | None = None) -> jnp.ndarray:
    return jnp.mean(predict_leaves_gemm(gf, x, chunk), axis=1)


def predict_votes_gemm(gf: GemmForest, x: jnp.ndarray, chunk: int | None = None) -> jnp.ndarray:
    return jnp.sum(predict_leaves_gemm(gf, x, chunk) > 0.5, axis=1).astype(jnp.int32)
