"""Pairwise-cosine similarity kernels.

The reference computes all-pairs cosine similarity three ways — BlockMatrix
multiply ``S = U @ U.T`` over L2-normalized rows (``density_weighting.py:66-75``,
``cosine_similarity.py:26-46``), DIMSUM ``columnSimilarities()``
(``similarity.py:37-38``), and a CoordinateMatrix path (``test.py:29-38``) —
then reduces per-point similarity mass with a join + ``groupByKey().mapValues(sum)``
shuffle over n² entries (``density_weighting.py:158-161``).

TPU-native replacements:

- :func:`pairwise_cosine` — one MXU matmul over normalized rows (the parity
  kernel for the standalone similarity benchmarks).
- :func:`similarity_mass` — the density strategy's actual need is only the
  *row-sum* of the masked similarity matrix, and cosine over normalized rows is
  a dot product, so ``mass_i = sum_j m_j <x̂_i, x̂_j> = <x̂_i, X̂.T @ m>``:
  two matvecs, O(n·d) time, O(n) memory. The reference's O(n²·d) matrix build +
  n²-entry shuffle is algebraically unnecessary — this is the single biggest
  asymptotic win over the reference.
- :func:`blocked_pairwise_cosine_reduce` — for workloads that do need a
  reduction over the explicit n² matrix (e.g. top-k most-similar pairs), a
  row-blocked scan that never materializes more than ``block x n`` entries.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def l2_normalize(x: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """Row-normalize (``density_weighting.py:66`` uses Normalizer semantics)."""
    norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    return x / jnp.maximum(norm, eps)


def pairwise_cosine(x: jnp.ndarray, y: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Full cosine-similarity matrix ``[n, m]`` via one normalized matmul.

    Replaces the BlockMatrix product at ``cosine_similarity.py:39-42`` (XLA
    tiles the matmul onto the MXU; no manual blocking needed at benchmark
    sizes).
    """
    xn = l2_normalize(x)
    yn = xn if y is None else l2_normalize(y)
    # Full f32 accumulation: similarity values feed score *rankings*, where
    # the default bf16-pass matmul's ~4e-3 error can reorder near-ties.
    return jnp.matmul(xn, yn.T, precision=lax.Precision.HIGHEST)


def similarity_mass(
    x: jnp.ndarray, count_mask: jnp.ndarray, normalized: bool = False
) -> jnp.ndarray:
    """Per-point sum of cosine similarities against the masked set, in O(n·d).

    ``mass_i = sum_j count_mask_j * cos(x_i, x_j)`` — the quantity the density
    strategy multiplies with entropy (``density_weighting.py:158-167``). The
    self-term (``cos(x_i, x_i) = 1`` when ``count_mask_i``) is included, as the
    reference's similarity entries include the diagonal.

    Note on masking parity: the reference precomputes similarity entries once
    and removes only pairs touching the *initially labeled seed set*
    (``density_weighting.py:95-100``), so later-labeled points keep
    contributing to mass. Passing the current unlabeled mask (our default in
    the density strategy) is the statistically-intended "density over the
    remaining pool"; passing ``~seed_mask`` reproduces the reference exactly.
    """
    xn = x if normalized else l2_normalize(x)
    pooled = jnp.matmul(xn.T, count_mask.astype(xn.dtype), precision=lax.Precision.HIGHEST)
    return jnp.matmul(xn, pooled, precision=lax.Precision.HIGHEST)


def blocked_pairwise_cosine_reduce(
    x: jnp.ndarray,
    reduce_fn: Callable[[jnp.ndarray], jnp.ndarray],
    block: int = 1024,
) -> jnp.ndarray:
    """Apply ``reduce_fn`` to each ``[block, n]`` row-slab of the cosine matrix.

    ``reduce_fn`` must map ``[block, n] -> [block, ...]`` (e.g. a row-sum or
    row-top-k). Never materializes more than one slab (SURVEY.md §7: "never
    materialize n² for big pools").
    """
    n = x.shape[0]
    xn = l2_normalize(x)
    pad = (-n) % block
    xp = jnp.pad(xn, ((0, pad), (0, 0)))
    slabs = xp.reshape(-1, block, x.shape[1])

    def body(carry, slab):
        del carry
        sims = jnp.matmul(slab, xn.T, precision=lax.Precision.HIGHEST)  # [block, n]
        return None, reduce_fn(sims)

    _, out = lax.scan(body, None, slabs)
    out = out.reshape(-1, *out.shape[2:])
    return out[:n]
