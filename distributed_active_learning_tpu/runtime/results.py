"""Per-round experiment records + reference-format results logs.

The reference's observability is print-only: per-round labeled/unlabeled counts
and accuracy (``uncertainty_sampling.py:65,113``) redirected into
``final_thesis/results/*.txt``. This module writes the same line format (so
curve-comparison tooling works on both) while also keeping structured records
for programmatic analysis and checkpointing.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional


@dataclasses.dataclass
class RoundRecord:
    round: int
    n_labeled: int
    n_unlabeled: int
    accuracy: float
    train_time: float = 0.0
    score_time: float = 0.0
    total_time: float = 0.0


@dataclasses.dataclass
class ExperimentResult:
    records: List[RoundRecord] = dataclasses.field(default_factory=list)

    def append(self, rec: RoundRecord) -> None:
        self.records.append(rec)

    @property
    def final_accuracy(self) -> Optional[float]:
        return self.records[-1].accuracy if self.records else None

    def accuracy_curve(self):
        return [(r.n_labeled, r.accuracy) for r in self.records]

    def to_reference_log(self) -> str:
        """Render in the exact format of ``final_thesis/results/*.txt``::

            labeled =  10  unlabeled =  9990
            Iteration  1  -- accu =  85.05
        """
        lines = []
        for r in self.records:
            lines.append(f"labeled =  {r.n_labeled}  unlabeled =  {r.n_unlabeled}")
            lines.append(f"Iteration  {r.round}  -- accu =  {r.accuracy * 100:.2f}")
        return "\n".join(lines) + "\n"

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(dataclasses.asdict(r)) for r in self.records) + "\n"

    def save(self, path: str, fmt: str = "reference") -> None:
        text = self.to_reference_log() if fmt == "reference" else self.to_jsonl()
        with open(path, "w") as f:
            f.write(text)


def parse_reference_log(text: str) -> ExperimentResult:
    """Parse a reference-format results log back into records (for golden-curve
    regression tests against ``final_thesis/results/*.txt`` numbers)."""
    result = ExperimentResult()
    n_labeled = n_unlabeled = None
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("labeled"):
            # "labeled =  10  unlabeled =  9990"
            n_labeled, n_unlabeled = int(parts[2]), int(parts[5])
        elif line.startswith("Iteration") and n_labeled is not None:
            # "Iteration  1  -- accu =  85.05"
            result.append(
                RoundRecord(
                    round=int(parts[1]),
                    n_labeled=n_labeled,
                    n_unlabeled=n_unlabeled,
                    accuracy=float(parts[-1]) / 100.0,
                )
            )
    return result
