"""Per-round experiment records + reference-format results logs.

The reference's observability is print-only: per-round labeled/unlabeled counts
and accuracy (``uncertainty_sampling.py:65,113``) redirected into
``final_thesis/results/*.txt``. This module writes the same line format (so
curve-comparison tooling works on both) while also keeping structured records
for programmatic analysis and checkpointing.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class RoundRecord:
    round: int
    n_labeled: int
    n_unlabeled: int
    accuracy: float
    train_time: float = 0.0
    score_time: float = 0.0
    # Test-set evaluation wall-clock, kept out of score_time so the
    # acquisition timing is pure (rounds logged before r4 folded it in).
    eval_time: float = 0.0
    total_time: float = 0.0
    # Device-computed RoundMetrics (runtime/telemetry.py) as plain JSON-ready
    # values: score min/mean/max/margin, pool entropy, labeled fraction,
    # picked-class histogram. None when metrics collection is off — the
    # default, so existing logs/checkpoints round-trip unchanged.
    metrics: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class ExperimentResult:
    records: List[RoundRecord] = dataclasses.field(default_factory=list)

    def append(self, rec: RoundRecord) -> None:
        self.records.append(rec)

    def extend_from_arrays(
        self,
        rounds,
        n_labeled,
        n_unlabeled,
        accuracy,
        total_time=None,
        metrics: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        """Bulk append from stacked per-round arrays — the chunked driver's
        touchdown path (runtime/loop.py ``make_chunk_fn``): one ``lax.scan``
        launch returns K rounds of outputs as stacked ys, and the host appends
        them all at once instead of paying a record append + host sync per
        round. ``total_time`` (optional, scalar or per-round) lands in
        ``total_time`` with the per-phase splits zero — phase attribution
        inside a fused scan would need per-round host syncs, exactly what the
        chunk exists to avoid. ``metrics`` (optional) is one plain dict per
        round — the in-scan :class:`~runtime.telemetry.RoundMetrics` already
        converted by ``telemetry.stacked_metrics_to_dicts``, which rode the
        same scan ys and so cost no extra sync either.
        """
        n = len(rounds)
        times = total_time
        if times is None:
            times = [0.0] * n
        elif not hasattr(times, "__len__"):
            times = [float(times)] * n
        if metrics is not None and len(metrics) != n:
            raise ValueError(
                f"{len(metrics)} metric dicts for {n} rounds — the active-row "
                "filter must be applied to both before appending"
            )
        for i in range(n):
            self.append(
                RoundRecord(
                    round=int(rounds[i]),
                    n_labeled=int(n_labeled[i]),
                    n_unlabeled=int(n_unlabeled[i]),
                    accuracy=float(accuracy[i]),
                    total_time=float(times[i]),
                    metrics=None if metrics is None else metrics[i],
                )
            )

    @property
    def final_accuracy(self) -> Optional[float]:
        return self.records[-1].accuracy if self.records else None

    def accuracy_curve(self):
        return [(r.n_labeled, r.accuracy) for r in self.records]

    def to_reference_log(self) -> str:
        """Render in the exact format of ``final_thesis/results/*.txt``::

            labeled =  10  unlabeled =  9990
            Iteration  1  -- accu =  85.05
        """
        lines = []
        for r in self.records:
            lines.append(f"labeled =  {r.n_labeled}  unlabeled =  {r.n_unlabeled}")
            lines.append(f"Iteration  {r.round}  -- accu =  {r.accuracy * 100:.2f}")
        return "\n".join(lines) + "\n"

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(dataclasses.asdict(r)) for r in self.records) + "\n"

    def save(self, path: str, fmt: str = "reference") -> None:
        text = self.to_reference_log() if fmt == "reference" else self.to_jsonl()
        with open(path, "w") as f:
            f.write(text)


def parse_reference_log(text: str) -> ExperimentResult:
    """Parse a reference-format results log back into records (for golden-curve
    regression tests against ``final_thesis/results/*.txt`` numbers)."""
    result = ExperimentResult()
    n_labeled = n_unlabeled = None
    for line in text.splitlines():
        parts = line.split()
        if line.startswith("labeled"):
            # "labeled =  10  unlabeled =  9990"
            n_labeled, n_unlabeled = int(parts[2]), int(parts[5])
        elif line.startswith("Iteration") and n_labeled is not None:
            # "Iteration  1  -- accu =  85.05"
            result.append(
                RoundRecord(
                    round=int(parts[1]),
                    n_labeled=n_labeled,
                    n_unlabeled=n_unlabeled,
                    accuracy=float(parts[-1]) / 100.0,
                )
            )
    return result


def strategy_curves(results):
    """Stack per-seed accuracy curves onto their shared labeled-count grid.

    ``results``: one :class:`ExperimentResult` per seed (e.g. a batched
    sweep's output, ``runtime.sweep.run_sweep``) over the same window/rounds.
    Returns ``(grid, accs)`` where ``grid`` is the n_labeled axis and ``accs``
    is a ``[seeds, rounds]`` array — the aggregation the paper's learning
    curves (mean +/- sd bands, ``plot_mean_band``) are built from. Raises if
    the seeds disagree on the grid (different windows/stops do not share an
    axis; plot those per seed instead).
    """
    import numpy as np

    if not results:
        raise ValueError("strategy_curves needs at least one result")
    grid = [r.n_labeled for r in results[0].records]
    for res in results[1:]:
        g = [r.n_labeled for r in res.records]
        if g != grid:
            raise ValueError(
                f"seed curves disagree on the labeled-count grid ({g[:3]}... "
                f"vs {grid[:3]}...): stack only same-window, same-stop runs"
            )
    accs = np.array([[r.accuracy for r in res.records] for res in results])
    return grid, accs


def plot_seed_band(results, path: str, title: str = "", label: str = "sweep") -> str:
    """Mean +/- 1 sd accuracy band over a sweep's per-seed results — the
    in-memory twin of :func:`plot_mean_band` (which reads log files)."""
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    grid, accs = strategy_curves(results)
    accs = accs * 100
    mean, sd = accs.mean(axis=0), accs.std(axis=0)
    fig, ax = plt.subplots(figsize=(7.5, 4.5))
    (line,) = ax.plot(grid, mean, marker="o", ms=3, label=f"{label} (n={len(results)})")
    ax.fill_between(grid, mean - sd, mean + sd, alpha=0.2, color=line.get_color())
    ax.set_xlabel("labeled points")
    ax.set_ylabel("test accuracy (%)")
    ax.grid(True, alpha=0.3)
    ax.legend()
    if title:
        ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def grid_curves(grid):
    """Per-(strategy, dataset) seed-stacked accuracy curves from one grid run.

    ``grid``: a :class:`~runtime.sweep.GridResult` (or anything with a
    ``.cells`` list of objects carrying ``strategy``/``dataset``/``result``).
    Returns ``{(strategy, dataset): (grid_axis, accs [seeds, rounds])}`` via
    :func:`strategy_curves` — the whole paper results matrix, stacked for
    banding, from a single launch stream. Groups whose seeds disagree on the
    labeled-count axis raise, like :func:`strategy_curves` itself.
    """
    groups = {}
    for cell in grid.cells:
        groups.setdefault((cell.strategy, cell.dataset), []).append(cell.result)
    return {key: strategy_curves(results) for key, results in groups.items()}


def plot_grid_bands(grid, path: str, title: str = "") -> str:
    """Mean +/- 1 sd accuracy bands for every (strategy, dataset) group of a
    grid run — the paper's strategy-comparison figure (distUS vs distRAND
    bands) produced from ONE ``run.py --strategies ... --sweep-seeds N``
    launch instead of S x E hand-collected logs."""
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    curves = grid_curves(grid)
    multi_ds = len({ds for _s, ds in curves}) > 1
    fig, ax = plt.subplots(figsize=(7.5, 4.5))
    for (strat, ds), (grid_axis, accs) in sorted(curves.items()):
        accs = accs * 100
        mean, sd = accs.mean(axis=0), accs.std(axis=0)
        label = f"{strat}/{ds}" if multi_ds else strat
        (line,) = ax.plot(
            grid_axis, mean, marker="o", ms=3,
            label=f"{label} (n={accs.shape[0]})",
        )
        ax.fill_between(
            grid_axis, mean - sd, mean + sd, alpha=0.2, color=line.get_color()
        )
    ax.set_xlabel("labeled points")
    ax.set_ylabel("test accuracy (%)")
    ax.grid(True, alpha=0.3)
    ax.legend()
    if title:
        ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def plot_result(result: ExperimentResult, path: str, title: str = "") -> str:
    """Save the experiment's curves as a PNG — the reference's per-run
    matplotlib artifact (``classes/active_learner.py:369-384`` plots
    per-iteration wall-clock and saves ``alrandom_first.png``). Two panels:
    accuracy vs labeled count (the curve the results logs tabulate) and
    per-round time (the reference's plotted quantity).
    """
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    labels = [r.n_labeled for r in result.records]
    accs = [r.accuracy * 100 for r in result.records]
    times = [r.total_time for r in result.records]

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(10, 4))
    ax1.plot(labels, accs, marker="o", ms=3)
    ax1.set_xlabel("labeled points")
    ax1.set_ylabel("test accuracy (%)")
    ax1.set_title("accuracy vs labels")
    ax1.grid(True, alpha=0.3)
    ax2.plot(range(1, len(times) + 1), times, marker="o", ms=3, color="tab:orange")
    ax2.set_xlabel("iteration")
    ax2.set_ylabel("round time (s)")
    ax2.set_title("per-iteration time")
    ax2.grid(True, alpha=0.3)
    if title:
        fig.suptitle(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def plot_mean_band(named_groups, path: str, title: str = "") -> str:
    """Overlay per-strategy mean accuracy curves with ±1 sd seed bands.

    ``named_groups``: ``[(label, [log_path, ...]), ...]`` — each group is one
    strategy's seeds (reference-format logs on a shared n_labeled grid, i.e.
    same window/rounds). Multi-seed dispersion is the evidence the single-seed
    overlays of earlier rounds lacked: a strategy claim needs its band clear
    of the control's, not one lucky trajectory.
    """
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt
    import numpy as np

    def _read(p):
        with open(p) as f:
            return f.read()

    fig, ax = plt.subplots(figsize=(7.5, 4.5))
    for label, log_paths in named_groups:
        runs = [parse_reference_log(_read(p)) for p in log_paths]
        grid = [r.n_labeled for r in runs[0].records]
        accs = np.array(
            [[r.accuracy * 100 for r in run.records] for run in runs]
        )  # [seeds, rounds]
        mean = accs.mean(axis=0)
        sd = accs.std(axis=0)
        (line,) = ax.plot(grid, mean, label=f"{label} (n={len(runs)})")
        ax.fill_between(grid, mean - sd, mean + sd, alpha=0.2,
                        color=line.get_color())
    ax.set_xlabel("labeled points")
    ax.set_ylabel("test accuracy (%)")
    ax.grid(True, alpha=0.3)
    ax.legend()
    if title:
        ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def plot_comparison(named_logs, path: str, title: str = "") -> str:
    """Overlay accuracy-vs-labels curves from reference-format logs.

    ``named_logs``: ``[(label, log_path), ...]`` — each file parsed with
    :func:`parse_reference_log`. The strategy-vs-control overlay is the
    reference's experiment-level evidence (distUS vs distRAND curves in
    ``final_thesis/results/``), which it only ever produced by hand.
    """
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for label, log_path in named_logs:
        with open(log_path) as f:
            res = parse_reference_log(f.read())
        ax.plot(
            [r.n_labeled for r in res.records],
            [r.accuracy * 100 for r in res.records],
            marker="o", ms=3, label=label,
        )
    ax.set_xlabel("labeled points")
    ax.set_ylabel("test accuracy (%)")
    ax.grid(True, alpha=0.3)
    ax.legend()
    if title:
        ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path
