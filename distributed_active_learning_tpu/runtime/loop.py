"""The AL driver loop: jitted round function + host experiment driver.

Reference shape (``final_thesis/uncertainty_sampling.py:60-114``): a driver-side
``while True`` that re-joins index RDDs to data, trains an RF in the JVM, runs
one Spark job per tree over the pool, shuffles votes, sorts, takes the window,
and rebuilds the pool sets — every step crossing the Py4J and executor
boundaries.

TPU shape (SURVEY.md §7): one jitted function
``(forest, state, aux) -> (new_state, picked, scores)`` does score + select +
mask-update entirely on device; the host loop only (a) fits the forest on the
labeled subset (the JVM-fit equivalent), (b) calls the round function, and
(c) logs. The only data that crosses the host boundary per round is the labeled
subset and a scalar accuracy.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.config import ExperimentConfig
from distributed_active_learning_tpu.data.datasets import DataBundle, get_dataset
from distributed_active_learning_tpu.models.forest import (
    fit_forest_classifier,
)
from distributed_active_learning_tpu.ops import forest_eval
from distributed_active_learning_tpu.ops.topk import select_bottom_k, select_top_k
from distributed_active_learning_tpu.runtime import state as state_lib
from distributed_active_learning_tpu.runtime.debugger import Debugger
from distributed_active_learning_tpu.runtime.results import ExperimentResult, RoundRecord
from distributed_active_learning_tpu.strategies import Strategy, StrategyAux, get_strategy


def _round_core(
    strategy: Strategy,
    window_size: int,
    with_metrics: bool,
    n_classes: int,
    forest: forest_eval.Forest,
    state: state_lib.PoolState,
    aux: StrategyAux,
    window=None,
    fused: bool = False,
    scenario=None,
    costs=None,
    emit_rare: bool = False,
    emit_cost: bool = False,
):
    """The AL round body shared by the plain and padded round functions.

    ``window`` (a traced scalar <= ``window_size``, or None) restricts the
    reveal to the first ``window`` picks: the batched-sweep driver
    (runtime/sweep.py) pads every experiment to the sweep's widest window so
    the vmapped top-k keeps one static k. ``lax.top_k`` returns picks in
    selection order, so the first ``w`` of a top-``window_size`` selection ARE
    the top-``w`` selection — truncation never changes which points a
    narrower experiment reveals. Masked-out picks are neutralized exactly like
    ops/topk.py's short-window sentinels (values to +/-inf, indices onto an
    already-excluded pick), so the metrics' finite-pick filter and the
    margin's candidate set both match a serial run at that window bit-for-bit.

    ``scenario`` (a static :class:`~config.ScenarioConfig`, or None) is the
    scenario engine's hook (scenarios/): ``cost_budget`` swaps the top-k for
    the greedy knapsack (``costs`` is the per-point cost vector; unaffordable
    picks are neutralized exactly like short-window sentinels),
    ``noisy_oracle`` makes the reveal probabilistic (the abstain draw comes
    from a THIRD split of the carried key — the clean two-way split is
    untouched when no abstention is configured), and ``rare_event`` /
    ``emit_rare``/``emit_cost`` attach the scenario metrics to the
    RoundMetrics pytree (the emit flags let a mixed-scenario grid keep one
    uniform ys structure across groups; run_grid filters per cell). With
    ``scenario=None`` every branch below reduces to the pre-scenario body,
    byte-identically.
    """
    scn_active = scenario is not None and scenario.active
    abstain = (
        scenario.abstain_prob
        if scn_active and scenario.kind == "noisy_oracle"
        else 0.0
    )
    if abstain > 0.0:
        # The scenario's per-round abstain key: a third split so the score
        # key and the carried stream stay on the clean path's lattice only
        # when no abstention is configured (a scenario run may diverge from
        # clean — it is a different oracle — but must agree with ITS OWN
        # serial twin, which runs this same body).
        key, k_score, k_abstain = jax.random.split(state.key, 3)
    else:
        key, k_score = jax.random.split(state.key)
        k_abstain = None
    state = state.replace(key=key)
    # unlabeled_mask (not ~labeled_mask): streaming slab pools additionally
    # exclude allocated-but-unfilled rows past the dynamic fill watermark;
    # for batch pools (n_filled is None) this is the same expression.
    unlabeled = state.unlabeled_mask
    spent = None
    cost_keep = None
    if fused:
        # Round megakernel (ops/round_fused.py): eval -> score -> top-k in
        # one pass over the pool slab; same (vals, picked) contract as the
        # select_* calls below, bit-identical on CPU and the mesh. The key
        # split above still happens so the carried PRNG stream matches the
        # unfused round exactly. No score vector exists to return (that is
        # the point) — callers all discard it, and metrics are validated
        # off at config time (_fused_round_reason).
        from distributed_active_learning_tpu.ops import round_fused

        with jax.named_scope("al/fused_round"):
            vals, picked = round_fused.fused_score_select(
                forest, state.x, unlabeled, strategy.name, window_size
            )
        scores = None
    elif scn_active and scenario.kind == "cost_budget":
        from distributed_active_learning_tpu.ops.topk import knapsack_top_k

        with jax.named_scope("al/score"):
            scores = strategy.score(forest, state, k_score, aux)
        with jax.named_scope("al/select_knapsack"):
            vals, picked, cost_keep, spent = knapsack_top_k(
                scores, costs, unlabeled, window_size, scenario.cost_budget
            )
    else:
        with jax.named_scope("al/score"):
            scores = strategy.score(forest, state, k_score, aux)
        with jax.named_scope("al/select"):
            if strategy.higher_is_better:
                vals, picked = select_top_k(scores, unlabeled, window_size)
            else:
                vals, picked = select_bottom_k(scores, unlabeled, window_size)
    keep = None
    if window is not None:
        keep = jnp.arange(window_size) < window
    if cost_keep is not None:
        keep = cost_keep if keep is None else (keep & cost_keep)
        # Spend accounted over the FINAL kept picks — under a padded window
        # (the grid's heterogeneous-window discipline) the knapsack ran at
        # the pad width, and picks masked out by a narrower cell's window
        # are never revealed, so they must not consume reported budget.
        # Dropped picks carry keep=False and contribute zero regardless of
        # their redirected index; one formula for serial and grid keeps
        # cost_spent bit-identical between the two drivers.
        spent = jnp.sum(jnp.where(keep, costs[picked], 0.0))
    if keep is not None:
        from distributed_active_learning_tpu.ops.topk import NEG_INF, POS_INF

        sentinel = NEG_INF if strategy.higher_is_better else POS_INF
        vals = jnp.where(keep, vals, sentinel)
        picked = jnp.where(keep, picked, picked[0])
    with jax.named_scope("al/reveal"):
        if keep is None and k_abstain is None:
            new_state = state_lib.reveal(state, picked)
        else:
            if keep is None:
                keep = jnp.ones(picked.shape, dtype=bool)
            new_state = state_lib.reveal_masked(
                state, picked, keep,
                abstain_key=k_abstain,
                abstain_prob=abstain,
            )
    if not with_metrics:
        return new_state, picked, scores
    from distributed_active_learning_tpu.runtime import telemetry

    rm = telemetry.compute_round_metrics(
        forest, state, picked, vals, scores,
        higher_is_better=strategy.higher_is_better,
        n_classes=n_classes,
    )
    want_rare = emit_rare or (scn_active and scenario.kind == "rare_event")
    want_cost = emit_cost or (scn_active and scenario.kind == "cost_budget")
    if want_rare or want_cost:
        from distributed_active_learning_tpu.scenarios import engine as scn_engine

        if want_rare:
            rm = rm.replace(
                rare_recall=scn_engine.rare_recall(
                    new_state.labeled_mask, state.oracle_y, state.valid_mask,
                    scenario.rare_class if scn_active else 1,
                )
            )
        if want_cost:
            rm = rm.replace(
                cost_spent=(
                    spent if spent is not None else jnp.asarray(0.0, jnp.float32)
                )
            )
    return new_state, picked, scores, rm


def make_round_fn(
    strategy: Strategy,
    window_size: int,
    with_metrics: bool = False,
    n_classes: int = 2,
    fused: bool = False,
    scenario=None,
    emit_rare: bool = False,
    emit_cost: bool = False,
):
    """Build the jitted AL round: score pool -> masked top-k -> reveal.

    Static over (strategy, window_size); all dynamic state is pytree args, so
    successive rounds reuse one compiled executable. With ``with_metrics`` the
    round additionally computes a :class:`~runtime.telemetry.RoundMetrics`
    pytree ON DEVICE (score summary, boundary margin, pool entropy, picked
    histogram, labeled fraction) and returns it as a fourth output — both
    drivers (per-round and scan-fused) then run the SAME metrics program, so
    their metrics agree bit-for-bit like their accuracies do.

    ``fused`` routes score + select through the round megakernel
    (``ops/round_fused.py``) — one pass over the pool slab, bit-identical
    picks, ``scores`` output replaced by ``None``. Mutually exclusive with
    ``with_metrics`` (the metrics reductions need the score vector the fused
    round never materializes); callers validate via
    :func:`_fused_round_reason` before asking.

    ``scenario`` wires the scenario engine into the round body (see
    :func:`_round_core`). A ``cost_budget`` scenario changes the signature to
    ``round_fn(forest, state, aux, costs)`` — the per-point cost vector is a
    pool-shaped runtime input, not a compile-time constant.
    """
    if fused and with_metrics:
        raise ValueError(
            "fused_round cannot compute RoundMetrics: the metrics reductions "
            "consume the full score vector the megakernel avoids "
            "materializing — drop collect_metrics/--metrics-out or fused_round"
        )
    with_costs = scenario is not None and scenario.kind == "cost_budget"

    if with_costs:
        @jax.jit
        def round_fn(
            forest: forest_eval.Forest,
            state: state_lib.PoolState,
            aux: StrategyAux,
            costs: jnp.ndarray,
        ):
            return _round_core(
                strategy, window_size, with_metrics, n_classes, forest, state,
                aux, fused=fused, scenario=scenario, costs=costs,
                emit_rare=emit_rare, emit_cost=emit_cost,
            )
    else:
        @jax.jit
        def round_fn(
            forest: forest_eval.Forest,
            state: state_lib.PoolState,
            aux: StrategyAux,
        ):
            return _round_core(
                strategy, window_size, with_metrics, n_classes, forest, state,
                aux, fused=fused, scenario=scenario,
                emit_rare=emit_rare, emit_cost=emit_cost,
            )

    return round_fn


def make_padded_round_fn(
    strategy: Strategy,
    window_pad: int,
    with_metrics: bool = False,
    n_classes: int = 2,
    scenario=None,
    emit_rare: bool = False,
    emit_cost: bool = False,
):
    """:func:`make_round_fn` with a per-call reveal width.

    Returns ``round_fn(forest, state, aux, window)`` where ``window`` is a
    traced scalar <= ``window_pad``: selection runs at the static pad width,
    the reveal (and every pick-derived metric) is masked to the first
    ``window`` picks. The batched-sweep driver vmaps this over experiments so
    one compiled program serves heterogeneous window sizes; with
    ``window == window_pad`` it is bit-identical to :func:`make_round_fn`.

    ``scenario``/``emit_*`` mirror :func:`make_round_fn`; a ``cost_budget``
    scenario appends the per-point ``costs`` vector to the signature.
    """
    with_costs = scenario is not None and scenario.kind == "cost_budget"

    if with_costs:
        @jax.jit
        def round_fn(
            forest: forest_eval.Forest,
            state: state_lib.PoolState,
            aux: StrategyAux,
            window: jnp.ndarray,
            costs: jnp.ndarray,
        ):
            return _round_core(
                strategy, window_pad, with_metrics, n_classes, forest, state,
                aux, window=window, scenario=scenario, costs=costs,
                emit_rare=emit_rare, emit_cost=emit_cost,
            )
    else:
        @jax.jit
        def round_fn(
            forest: forest_eval.Forest,
            state: state_lib.PoolState,
            aux: StrategyAux,
            window: jnp.ndarray,
        ):
            return _round_core(
                strategy, window_pad, with_metrics, n_classes, forest, state,
                aux, window=window, scenario=scenario,
                emit_rare=emit_rare, emit_cost=emit_cost,
            )

    return round_fn


def _fused_round_reason(
    cfg: ExperimentConfig, want_metrics: bool, n_classes: int
) -> Optional[str]:
    """Why this config cannot take the round megakernel (None = it can).

    ``fused_round`` is an opt-in perf flag, so an unservable combination is
    REFUSED with the named reason rather than silently falling back — the
    user asked for one HBM pass per round and must know they did not get it.
    """
    from distributed_active_learning_tpu.ops import round_fused

    scn = getattr(cfg, "scenario", None)
    if scn is not None and scn.active:
        return (
            f"scenario {scn.kind!r} perturbs the round body (probabilistic "
            "reveal / knapsack selection / drifted eval); the megakernel "
            "fuses the clean eval -> score -> top-k chain only — a fused "
            "scenario spelling is a named ROADMAP follow-up"
        )
    if not round_fused.supports(cfg.strategy.name):
        return (
            f"strategy {cfg.strategy.name!r} is not a pure vote-fraction "
            f"score; fused: {sorted(round_fused.FUSED_STRATEGIES)}"
        )
    if cfg.forest.fit != "device":
        return "host fit re-enters the host every round; use --fit device"
    if cfg.forest.kernel not in ("gemm", "pallas"):
        return (
            f"kernel {cfg.forest.kernel!r} has no fused round; use 'gemm' "
            "(XLA stream) or 'pallas' (megakernel)"
        )
    if cfg.forest.max_depth > forest_eval._GEMM_MAX_DEPTH:
        return (
            f"max_depth {cfg.forest.max_depth} exceeds the path-matrix "
            f"budget ({forest_eval._GEMM_MAX_DEPTH}); the fit would emit a "
            "gather-form forest the fused round cannot evaluate"
        )
    if n_classes > 2:
        return "fused round scores binary vote fractions; pool is multiclass"
    if cfg.strategy.window_size > 2048:
        # Both fused paths keep a per-tile top-k no wider than the row tile
        # (gemm stream tiles cap at 2048, round_fused._stream_tile; the
        # pallas megakernel's row tile is narrower still) — name the limit
        # here instead of surfacing lax.top_k's k-vs-axis error mid-trace.
        return (
            f"window {cfg.strategy.window_size} exceeds the fused per-tile "
            "top-k width (2048); the streaming merge keeps k candidates "
            "per row tile"
        )
    if want_metrics:
        return (
            "RoundMetrics consume the full score vector the megakernel "
            "avoids materializing; drop --metrics-out/collect_metrics"
        )
    return None


def _validate_quantize(cfg: ExperimentConfig) -> None:
    """Quantized storage needs the device fit (bf16-snapped bin-edge
    thresholds are what make bf16 storage lossless) and a path-matrix
    kernel form (the dequantizing eval bodies live in trees_gemm /
    trees_pallas / round_fused)."""
    from distributed_active_learning_tpu.models.forest import VALID_QUANTIZE_MODES

    q = cfg.forest.quantize
    if q not in VALID_QUANTIZE_MODES:
        raise ValueError(
            f"unknown ForestConfig.quantize {q!r}; one of {VALID_QUANTIZE_MODES}"
        )
    if q == "none":
        return
    if cfg.forest.fit != "device":
        raise ValueError(
            "quantized forest storage requires the device fit (host-fit "
            "sklearn midpoints are not bf16-snapped bin edges, so bf16 "
            "threshold storage would silently move decision boundaries); "
            "use --fit device or quantize='none'"
        )
    if cfg.forest.kernel not in ("gemm", "pallas"):
        raise ValueError(
            f"quantized storage applies to the path-matrix kernels, not "
            f"{cfg.forest.kernel!r}; use kernel='gemm' or 'pallas'"
        )
    if cfg.forest.max_depth > forest_eval._GEMM_MAX_DEPTH:
        raise ValueError(
            f"max_depth {cfg.forest.max_depth} exceeds the path-matrix "
            f"budget ({forest_eval._GEMM_MAX_DEPTH}); quantized storage "
            "has no gather-form dequantizer"
        )


@jax.jit
def _accuracy(forest, test_x: jnp.ndarray, test_y: jnp.ndarray) -> jnp.ndarray:
    """Test accuracy on device (``uncertainty_sampling.py:79-83``)."""
    from distributed_active_learning_tpu.ops import trees_multi

    with jax.named_scope("al/eval"):
        if trees_multi.is_multi(forest):
            pred = trees_multi.predict_class(forest, test_x)
        else:
            pred = (forest_eval.proba(forest, test_x) > 0.5).astype(jnp.int32)
        return jnp.mean((pred == test_y).astype(jnp.float32))


@jax.jit
def _accuracy_masked(
    forest, test_x: jnp.ndarray, test_y: jnp.ndarray, test_n: jnp.ndarray
) -> jnp.ndarray:
    """:func:`_accuracy` over the first ``test_n`` rows of a padded test set.

    The grid launcher pads per-dataset test sets to a common slab width so
    the vmapped accuracy pass keeps one static shape; padding rows must not
    dilute the mean. With ``test_n == test_x.shape[0]`` (no padding) the
    masked sum/count equals the plain mean — but the grid driver routes
    that case to :func:`_accuracy` anyway so equal-width grids share the
    serial program bit-for-bit."""
    from distributed_active_learning_tpu.ops import trees_multi

    with jax.named_scope("al/eval"):
        if trees_multi.is_multi(forest):
            pred = trees_multi.predict_class(forest, test_x)
        else:
            pred = (forest_eval.proba(forest, test_x) > 0.5).astype(jnp.int32)
        ok = (pred == test_y) & (jnp.arange(test_y.shape[0]) < test_n)
        return jnp.sum(ok.astype(jnp.float32)) / test_n.astype(jnp.float32)


def _labeled_subset(
    state: state_lib.PoolState,
    host_x: Optional[np.ndarray] = None,
    host_y: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side gather of the labeled subset for the sklearn fit.

    This is the one legitimate host round-trip: the reference does the same
    gather as a leftOuterJoin shuffle + JVM fit (``active_learner.py:65-76``).
    Pass ``host_x``/``host_y`` (the immutable pool arrays, held host-side once)
    so only the boolean mask crosses the device boundary per round — not the
    full [n, d] pool.
    """
    from distributed_active_learning_tpu.parallel.multihost import host_np

    # Slice off mesh-padding rows: host arrays are unpadded. host_np handles
    # multi-process data-sharded masks (collective; the loop calls this at
    # the same point on every process).
    mask = host_np(state.labeled_mask)[: state.n_valid]
    x = (host_x if host_x is not None else host_np(state.x)[: state.n_valid])[mask]
    y = (host_y if host_y is not None else host_np(state.oracle_y)[: state.n_valid])[mask]
    return x, y


def _resolve_fit_budget(cfg: ExperimentConfig, n_pool: int, n_labeled: int) -> int:
    """Static row capacity for the device trainer's labeled window.

    Defaults to the experiment's label cap (the starting labeled count plus
    all windows, or the label budget plus one overshooting window) so the
    jitted fit compiles once and never truncates. ``n_labeled`` is the count
    at loop start — after a checkpoint restore it exceeds ``n_start``, and
    ``max_rounds`` grants that many *further* rounds.
    """
    if cfg.forest.fit_budget is not None:
        return min(cfg.forest.fit_budget, n_pool)
    caps = [n_pool]
    if cfg.label_budget is not None:
        caps.append(cfg.label_budget + cfg.strategy.window_size)
    if cfg.max_rounds is not None:
        caps.append(n_labeled + cfg.max_rounds * cfg.strategy.window_size)
    return min(caps)


def _device_fit_core(cfg: ExperimentConfig, budget: int, n_classes: int):
    """The traced body shared by :func:`make_device_fit` (edges closed over)
    and :func:`make_grid_device_fit` (edges as a per-call argument): one
    labeled-window gather + histogram fit + kernel-form conversion. A single
    definition so the two entry points cannot drift — grid cells and serial
    runs must fit bit-identically."""
    from distributed_active_learning_tpu.ops import trees_train

    fc = cfg.forest
    to_gemm = (
        fc.kernel in ("gemm", "pallas")
        and fc.max_depth <= forest_eval._GEMM_MAX_DEPTH
    )

    def _wrap_pallas(forest):
        # Fused-kernel scoring compares float features in bf16; a point
        # within bf16 rounding of a threshold can flip a vote
        # (trees_pallas module docstring — numerics).
        from distributed_active_learning_tpu.ops.trees_multi import MultiForest
        from distributed_active_learning_tpu.ops.trees_pallas import PallasForest

        if isinstance(forest, MultiForest):
            return MultiForest(
                planes=tuple(PallasForest(gf=p) for p in forest.planes)
            )
        return PallasForest(gf=forest)

    def fit_body(codes, edges, state: state_lib.PoolState, key: jax.Array):
        with jax.named_scope("al/fit"):
            mask = state.labeled_mask & state.valid_mask
            c, yy, w = trees_train.gather_fit_window(codes, state.oracle_y, mask, budget)
            f, th, v = trees_train.fit_forest_device(
                c, yy, w, edges, key,
                n_trees=fc.n_trees, max_depth=fc.max_depth, n_bins=fc.max_bins,
                n_classes=n_classes,
            )
            if to_gemm:
                gf = trees_train.heap_gemm_forest(f, th, v, fc.max_depth)
                if fc.quantize != "none":
                    # Storage narrows INSIDE the fit program, so the forest
                    # leaves the launch at the narrow dtypes — what the
                    # quantized-leaf-upcast audit rule pins statically.
                    gf = trees_train.quantize_forest(gf, fc.quantize)
                return _wrap_pallas(gf) if fc.kernel == "pallas" else gf
            if fc.quantize != "none":
                raise ValueError(
                    "quantized storage needs the path-matrix (gemm/pallas) "
                    f"form; depth {fc.max_depth} fits emit packed forests "
                    "(see runtime.loop._validate_quantize)"
                )
            return trees_train.heap_packed_forest(f, th, v, fc.max_depth)

    return fit_body


def make_device_fit(
    cfg: ExperimentConfig, edges: jnp.ndarray, budget: int, n_classes: int = 2
):
    """Jitted device train phase: labeled-window gather + histogram fit +
    kernel-form conversion, all in one XLA program (no host round-trip —
    the replacement for the JVM fit at ``uncertainty_sampling.py:71-76``)."""
    fit_body = _device_fit_core(cfg, budget, n_classes)

    @jax.jit
    def fit(codes: jnp.ndarray, state: state_lib.PoolState, key: jax.Array):
        return fit_body(codes, edges, state, key)

    return fit


def make_grid_device_fit(cfg: ExperimentConfig, budget: int, n_classes: int = 2):
    """:func:`make_device_fit` with the bin edges as a per-call argument.

    The grid launcher (runtime/sweep.py ``make_grid_chunk_fn``) stacks one
    binning per dataset along a leading ``[D]`` axis and hands each cell its
    own edges through the vmapped round body — one fit program serves the
    whole dataset axis. With the same ``edges`` every call, this is the same
    traced body as :func:`make_device_fit`."""
    fit_body = _device_fit_core(cfg, budget, n_classes)

    @jax.jit
    def fit(
        codes: jnp.ndarray,
        edges: jnp.ndarray,
        state: state_lib.PoolState,
        key: jax.Array,
    ):
        return fit_body(codes, edges, state, key)

    return fit


def make_chunk_fn(
    strategy: Strategy,
    window_size: int,
    chunk_size: int,
    fit_fn,
    label_cap: int,
    mesh=None,
    wrap_pallas: bool = False,
    with_metrics: bool = False,
    n_classes: int = 2,
    donate: bool = True,
    stream_cb=None,
    fused_round: bool = False,
    scenario=None,
):
    """Fuse ``chunk_size`` AL rounds into ONE jitted ``lax.scan`` program.

    The per-round driver pays three host round-trips per round (fit, round,
    accuracy) — ~90-100 ms of pure launch latency each on the tunnel rig
    (bench.py ``_device_time_per_call``), the dominant cost of small/medium
    pools. When the fit itself is on device (``ForestConfig.fit="device"``)
    the whole round is pure XLA, so K rounds scan into one launch: the carry
    is the :class:`~runtime.state.PoolState` (mask + PRNG key + round
    counter), and per-round outputs come back as stacked scan ys.

    Stopping stays EXACT, not chunk-quantized: each scan step computes
    ``active = (labeled < label_cap) & (round < end_round)`` and an inactive
    step is a masked no-op — the carried state (mask, key, round) passes
    through untouched via :func:`~runtime.state.select_state`, so a chunk may
    overrun the stopping point and the final state still matches the
    per-round driver bit-for-bit. Inactive steps still compute a (discarded)
    fit/score — wasted work bounded by one chunk tail, bought for launch
    latency on every earlier chunk.

    Under a mesh, ``constrain_forest`` asserts the freshly fitted forest's
    model-axis placement inside the scan (``shard_forest``'s ``device_put``
    is host-side and cannot run in traced code), and ``wrap_pallas`` rewraps
    it as a :class:`~ops.trees_pallas.ShardedPallasForest` so the fused
    kernel shard_maps per (data, model) block exactly like the per-round
    path.

    Returns ``chunk_fn(codes, state, aux, fit_key, test_x, test_y,
    end_round) -> (new_state, extras, (rounds, n_labeled, accuracy, picked,
    active[, metrics]))`` where each y is stacked ``[chunk_size, ...]``;
    ``n_labeled`` is the pre-reveal count (what the evaluated forest was
    trained on, the reference's print ordering) and ``end_round`` rides as a
    traced scalar so ``max_rounds`` changes never recompile. ``extras`` is a
    :class:`~runtime.pipeline.ChunkExtras` — the exact post-chunk labeled
    count and the active-round count as two int32 scalars, the ONLY values
    the pipelined driver blocks on per chunk (the bulk ys transfer stays
    asynchronous). With ``with_metrics`` a stacked
    :class:`~runtime.telemetry.RoundMetrics` pytree rides as a sixth y —
    per-round observability for fused runs at the cost of a few extra KB in
    the touchdown fetch, zero extra syncs.

    ``stream_cb`` (optional host callable ``(round, n_labeled, accuracy,
    active) -> None``) is invoked from INSIDE the scan via
    ``jax.debug.callback`` once per round — live round events during a long
    chunk instead of only at its touchdown. Callback events are unordered
    (each carries its round number) and the hook is absent from the traced
    program when ``stream_cb is None``, so the default fast path is untouched.

    ``donate`` donates the carried ``state``'s buffers to the launch
    (``donate_argnums``): the scan carry aliases the input pool arrays
    instead of copying them, which matters once pools are HBM-scale. The
    driver threads each chunk's output state into the next call, so the
    donated input is never reused — callers that DO reuse a state across
    calls (benchmarks re-running one launch from a fixed state) must pass
    ``donate=False``. NOTE the donated ``labeled_mask`` may be aliased by
    ``aux.seed_mask`` at round 0; the driver copies the seed mask before the
    first launch for exactly this reason.

    ``scenario`` (a :class:`~config.ScenarioConfig`, or None) routes the
    scenario engine through the scan body: the round runs the scenario
    round (:func:`_round_core`), a ``drift`` scenario transforms the test
    batch per round index BEFORE the in-scan accuracy pass
    (``scenarios.drift_apply`` at the carry's round counter), and the
    chunk's signature gains a trailing ``costs`` argument (the per-point
    cost vector; pass None for non-cost scenarios). With ``scenario=None``
    the signature and traced program are byte-identical to the pre-scenario
    chunk. The stop scalar semantics are UNCHANGED by design:
    ``n_labeled_after`` reduces the labeled mask, so an abstaining oracle's
    budget accounting counts revealed labels, never picks.
    """
    round_fn = make_round_fn(
        strategy, window_size, with_metrics=with_metrics, n_classes=n_classes,
        fused=fused_round, scenario=scenario,
    )
    scn_active = scenario is not None and scenario.active
    with_costs = scenario is not None and scenario.kind == "cost_budget"

    def chunk_body(codes, state, aux, fit_key, test_x, test_y, end_round, costs):
        def body(carry: state_lib.PoolState, _):
            n_labeled = state_lib.labeled_count(carry)
            active = (n_labeled < label_cap) & (carry.round < end_round)
            forest = fit_fn(
                codes, carry, jax.random.fold_in(fit_key, carry.round + 1)
            )
            if mesh is not None:
                from distributed_active_learning_tpu.parallel import (
                    constrain_forest,
                )

                forest = constrain_forest(forest, mesh)
                if wrap_pallas:
                    from distributed_active_learning_tpu.ops.trees_pallas import (
                        attach_mesh,
                    )

                    forest = attach_mesh(forest, mesh)
            round_args = (forest, carry, aux) + ((costs,) if with_costs else ())
            if with_metrics:
                new_state, picked, _, rm = round_fn(*round_args)
            else:
                new_state, picked, _ = round_fn(*round_args)
            eval_x = test_x
            if scn_active and scenario.kind == "drift":
                from distributed_active_learning_tpu.scenarios import (
                    engine as scn_engine,
                )

                eval_x = scn_engine.drift_apply(scenario, test_x, carry.round)
            acc = _accuracy(forest, eval_x, test_y)
            out = state_lib.select_state(active, new_state, carry)
            if stream_cb is not None:
                jax.debug.callback(stream_cb, carry.round + 1, n_labeled, acc, active)
            ys = (carry.round + 1, n_labeled, acc, picked, active)
            if with_metrics:
                ys = ys + (rm,)
            return out, ys

        out_state, ys = jax.lax.scan(body, state, None, length=chunk_size)
        from distributed_active_learning_tpu.runtime.pipeline import ChunkExtras

        extras = ChunkExtras(
            n_labeled_after=state_lib.labeled_count(out_state),
            n_active=jnp.sum(ys[4].astype(jnp.int32)),
        )
        return out_state, extras, ys

    if scenario is not None:
        @functools.partial(jax.jit, donate_argnums=(1,) if donate else ())
        def chunk_fn(
            codes: jnp.ndarray,
            state: state_lib.PoolState,
            aux: StrategyAux,
            fit_key: jax.Array,
            test_x: jnp.ndarray,
            test_y: jnp.ndarray,
            end_round: jnp.ndarray,
            costs,
        ):
            return chunk_body(
                codes, state, aux, fit_key, test_x, test_y, end_round, costs
            )
    else:
        @functools.partial(jax.jit, donate_argnums=(1,) if donate else ())
        def chunk_fn(
            codes: jnp.ndarray,
            state: state_lib.PoolState,
            aux: StrategyAux,
            fit_key: jax.Array,
            test_x: jnp.ndarray,
            test_y: jnp.ndarray,
            end_round: jnp.ndarray,
        ):
            return chunk_body(
                codes, state, aux, fit_key, test_x, test_y, end_round, None
            )

    return chunk_fn


@jax.jit
def ckpt_snapshot(mask: jnp.ndarray, key: jax.Array, rnd: jnp.ndarray):
    """Fresh-buffer device copy of the carry fields a checkpoint needs.

    The chunk program donates its carried state, and the pipelined driver
    dispatches chunk N+1 (consuming chunk N's output buffers) BEFORE chunk
    N's touchdown runs — so a checkpointing touchdown cannot read the carry
    itself. This tiny launch, run right after each chunk returns and before
    the next dispatch, copies just (mask, key-data, round) into buffers the
    donation cannot touch: ``optimization_barrier`` defeats both jax's
    pass-through-output shortcut (which would hand back the very arrays the
    next launch deletes) and XLA CSE, and a no-donation executable's outputs
    never alias its inputs. Checkpointed chunked runs therefore keep carry
    donation (ROADMAP PR-4 follow-up; pinned by the no-donation-warning +
    resume tests in tests/test_chunked_driver.py).
    """
    return jax.lax.optimization_barrier((mask, jax.random.key_data(key), rnd))


def build_aux(cfg: ExperimentConfig, state: state_lib.PoolState) -> StrategyAux:
    """Assemble strategy aux inputs (LAL regressor, seed mask) from config."""
    lal_forest = None
    options = dict(cfg.strategy.options)
    if cfg.strategy.name == "lal":
        from distributed_active_learning_tpu.models.lal_training import (
            load_or_train_lal_regressor,
        )

        lal_forest = load_or_train_lal_regressor(options)
    return StrategyAux(lal_forest=lal_forest, seed_mask=state.labeled_mask)


def run_experiment(
    cfg: ExperimentConfig,
    bundle: Optional[DataBundle] = None,
    debugger: Optional[Debugger] = None,
    metrics=None,
) -> ExperimentResult:
    """Run a full AL experiment; returns per-round records.

    Equivalent of the reference's per-strategy driver scripts
    (``uncertainty_sampling.py`` etc.) and the experiment tail of
    ``active_learner.py:369-384``, with the gaps the reference left filled in:
    configurable stopping, structured timing, optional checkpoint/resume.

    ``metrics`` (a :class:`~runtime.telemetry.MetricsWriter`, or None) turns
    on the structured JSONL event stream — one ``round`` event per AL round
    (including the device-computed RoundMetrics), launch accounting, transfer
    counters, and memory gauges — and implies ``cfg.collect_metrics``.
    """
    dbg = debugger or Debugger(enabled=False)
    if bundle is None:
        bundle = get_dataset(cfg.data)
    want_metrics = metrics is not None or cfg.collect_metrics

    test_x = jnp.asarray(bundle.test_x)
    test_y = jnp.asarray(bundle.test_y)
    # (replicated onto the mesh below once one is configured — required when
    # the mesh spans processes, harmless single-process)
    # Immutable pool arrays kept host-side: per-round fits index these, so only
    # the labeled mask crosses the device boundary each round.
    host_x = np.ascontiguousarray(bundle.train_x, dtype=np.float32)
    host_y = np.asarray(bundle.train_y, dtype=np.int32)

    # Class count from the full pool (not the labeled subset, whose early
    # rounds may miss classes): fixes plane counts so shapes stay static.
    n_classes = max(int(host_y.max()) + 1, 2) if host_y.size else 2

    state = state_lib.init_pool_state(bundle.train_x, bundle.train_y, jax.random.key(cfg.seed))
    state = state_lib.set_start_state(state, cfg.n_start, n_classes=n_classes)

    strategy = get_strategy(cfg.strategy)

    _validate_quantize(cfg)
    # Scenario engine (scenarios/): validated up front, wired below. The
    # start-state draw runs on the CLEAN labels above (the grid launcher
    # seeds cells the same way, so serial and grid cells agree bit-for-bit);
    # label flips replace the oracle AFTER seeding, costs are a derived
    # per-point vector, drift transforms the eval batch per round.
    scn = cfg.scenario if getattr(cfg, "scenario", None) is not None else None
    scn = scn if (scn is not None and scn.active) else None
    costs = None
    if scn is not None:
        from distributed_active_learning_tpu.scenarios import engine as scn_engine

        scn_engine.validate_scenario(
            scn, strategy=strategy, max_rounds=cfg.max_rounds
        )
        if cfg.forest.fit != "device":
            raise ValueError(
                f"scenario {scn.kind!r} runs inside the jitted round and "
                "needs the device fit; use --fit device"
            )
        if cfg.mesh.data * cfg.mesh.model > 1 and scn.kind != "noisy_oracle":
            # noisy_oracle rides the mesh: flips are applied to the oracle
            # labels HERE, before shard_pool_state places them (so shards
            # carry pre-flipped blocks), and the abstaining reveal's draw is
            # a window-sized function of the replicated round key
            # (scenarios/engine.py abstain_draw + the per-shard reveal
            # spelling runtime/state.py reveal_masked_local), so GSPMD
            # partitions the scenario round like the clean one. The other
            # kinds still need single-device plumbing (knapsack selection,
            # drift's eval transform, rare-recall metrics).
            raise ValueError(
                f"scenario {scn.kind!r} is single-device for now (only "
                "noisy_oracle rides the pod mesh); drop "
                "--mesh-data/--mesh-model"
            )
        if scn.kind == "noisy_oracle" and scn.flip_prob > 0.0:
            flips = scn_engine.flip_mask(scn, cfg.seed, state.n_pool)
            state = state.replace(
                oracle_y=scn_engine.apply_flips(state.oracle_y, flips, n_classes)
            )
        if scn.kind == "cost_budget":
            costs = scn_engine.make_costs(scn, state.n_pool, cfg.data.name)
    if cfg.fused_round:
        reason = _fused_round_reason(cfg, want_metrics, n_classes)
        if reason is not None:
            raise ValueError(f"fused_round unavailable: {reason}")

    # Distribution: when the config names a >1-device mesh, pad the pool to
    # data-axis divisibility, place state/forest shardings, and let GSPMD
    # compile the same round function into one SPMD program (the replacement
    # for the reference's executor-partitioned RDDs, SURVEY.md §2.4).
    mesh = None
    if cfg.mesh.data * cfg.mesh.model > 1:
        from distributed_active_learning_tpu.parallel import (
            make_mesh,
            make_sharded_round_fn,
            mesh as mesh_lib,
            shard_forest,
            shard_pool_state,
        )

        if cfg.forest.n_trees % cfg.mesh.model:
            raise ValueError(
                f"n_trees={cfg.forest.n_trees} not divisible by mesh "
                f"model axis {cfg.mesh.model}"
            )
        mesh = make_mesh(data=cfg.mesh.data, model=cfg.mesh.model)
        state = state_lib.pad_for_sharding(state, cfg.mesh.data)
        state = shard_pool_state(state, mesh)
        round_fn = make_sharded_round_fn(
            strategy, cfg.strategy.window_size, mesh,
            with_metrics=want_metrics, n_classes=n_classes,
            fused=cfg.fused_round, scenario=scn,
        )
        if cfg.forest.kernel == "pallas":
            # pallas_call has no GSPMD partitioning rule, so the fused kernel
            # runs per-shard under shard_map instead (rows over data, trees
            # over model) — multi-device rounds keep the flagship kernel
            # rather than silently dropping to the ~20x slower GEMM form
            # (the r4 gap; see ops.trees_pallas.ShardedPallasForest).
            from distributed_active_learning_tpu.ops.trees_pallas import attach_mesh

            place_forest = lambda f: attach_mesh(shard_forest(f, mesh), mesh)
        else:
            place_forest = lambda f: shard_forest(f, mesh)
        test_x = mesh_lib.global_put(test_x, mesh, mesh_lib.replicated_spec())
        test_y = mesh_lib.global_put(test_y, mesh, mesh_lib.replicated_spec())
    else:
        round_fn = make_round_fn(
            strategy, cfg.strategy.window_size,
            with_metrics=want_metrics, n_classes=n_classes,
            fused=cfg.fused_round, scenario=scn,
        )
        place_forest = lambda f: f

    aux = build_aux(cfg, state)

    if metrics is not None:
        from distributed_active_learning_tpu.config import asdict as cfg_asdict

        metrics.meta(
            config=cfg_asdict(cfg),
            backend=jax.default_backend(),
            n_devices=jax.device_count(),
            process_count=jax.process_count(),
        )

    if cfg.forest.fit not in ("host", "device"):
        raise ValueError(f"unknown ForestConfig.fit {cfg.forest.fit!r}; use 'host' or 'device'")

    result = ExperimentResult()
    start_round = int(state.round)

    if cfg.checkpoint_dir and cfg.checkpoint_every:
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        ckpt_fp = ckpt_lib.config_fingerprint(cfg)
        ckpt_kernel = ckpt_lib.kernel_ident(cfg)
        restored = ckpt_lib.restore_latest(
            cfg.checkpoint_dir, state, result,
            fingerprint=ckpt_lib.accepted_fingerprints(cfg),
            kernel=ckpt_kernel,
        )
        if restored is not None:
            state, result = restored
            if mesh is not None:
                from distributed_active_learning_tpu.parallel import shard_pool_state

                state = shard_pool_state(state, mesh)  # re-place restored arrays
            start_round = int(state.round)
            dbg.debug(f"resumed at round {start_round}")

    # Device training path: bin the pool once; per round the fit is one jitted
    # program over the masked labeled window (static shapes, no recompiles).
    # Built after any checkpoint restore so the fit window's capacity accounts
    # for the labels the resumed run already holds.
    device_fit = None
    if cfg.forest.fit == "device":
        from distributed_active_learning_tpu.ops import trees_train

        binned = trees_train.make_bins(
            jnp.asarray(host_x), cfg.forest.max_bins,
            quantize=cfg.forest.quantize,
        )
        codes = binned.codes
        if state.n_pool > codes.shape[0]:  # align with mesh padding rows
            codes = jnp.pad(codes, ((0, state.n_pool - codes.shape[0]), (0, 0)))
        fit_budget = _resolve_fit_budget(
            cfg, state.n_valid, int(state_lib.labeled_count(state))
        )
        device_fit = make_device_fit(cfg, binned.edges, fit_budget, n_classes)
        fit_key = jax.random.key(cfg.seed + 0x5EED)
        if mesh is not None:
            # Under a (possibly multi-process) mesh every jit input must be a
            # global array: codes ride the pool's row sharding, the fit key
            # is replicated. Single-process meshes pass through device_put.
            codes = mesh_lib.global_put(codes, mesh, mesh_lib.pool_spec())
            fit_key = mesh_lib.global_put(fit_key, mesh, mesh_lib.replicated_spec())

    n_pool = state.n_valid  # real rows only; padding is never selectable
    round_idx = start_round

    # Chunked (scan-fused) driver: only when the whole round is device-
    # resident. Host fit needs a host round-trip per round by construction,
    # and a Debugger explicitly asking for per-phase (train/score/eval) wall
    # splits needs per-program syncs a fused scan cannot attribute — those
    # two fall back to the per-round path below. A merely-*enabled* Debugger
    # no longer forces the fallback (the pre-telemetry coupling): fused runs
    # now regain per-round visibility through the in-scan RoundMetrics and
    # the touchdown iteration logs, so only phase_detail=True (opt-in) is
    # genuinely host-bound.
    use_chunked = (
        cfg.rounds_per_launch > 1
        and device_fit is not None
        and not getattr(dbg, "phase_detail", False)
    )
    if use_chunked:
        from distributed_active_learning_tpu.runtime import (
            pipeline as pipeline_lib,
            telemetry,
        )

        K, window = cfg.rounds_per_launch, cfg.strategy.window_size
        label_cap = n_pool if cfg.label_budget is None else min(cfg.label_budget, n_pool)
        depth = max(int(getattr(cfg, "pipeline_depth", 1) or 1), 1)
        ckpt_enabled = bool(cfg.checkpoint_dir and cfg.checkpoint_every)
        # Mid-chunk round streaming (ROADMAP PR-3 follow-up): a host callback
        # riding jax.debug.callback inside the scan, behind the explicit flag
        # so the zero-overhead fast path's traced program is unchanged.
        stream_cb = None
        if metrics is not None and cfg.stream_round_events:
            def stream_cb(round_, n_labeled_cb, acc_cb, active_cb):
                if bool(active_cb):
                    metrics.event(
                        "round_stream",
                        round=int(round_),
                        n_labeled=int(n_labeled_cb),
                        accuracy=float(acc_cb),
                    )
        chunk_fn = make_chunk_fn(
            strategy, window, K, device_fit, label_cap,
            mesh=mesh,
            wrap_pallas=(mesh is not None and cfg.forest.kernel == "pallas"),
            with_metrics=want_metrics,
            n_classes=n_classes,
            stream_cb=stream_cb,
            fused_round=cfg.fused_round,
            scenario=scn,
        )
        # The chunk donates the carried state's buffers; at round 0
        # aux.seed_mask aliases state.labeled_mask, and a donated alias would
        # be a deleted buffer on the second launch — copy it once up front.
        if aux.seed_mask is not None:
            aux = aux.replace(seed_mask=jnp.array(aux.seed_mask, copy=True))
        launches = telemetry.LaunchTracker(metrics, "chunk_scan", fn=chunk_fn)
        end_round = (
            start_round + cfg.max_rounds
            if cfg.max_rounds is not None
            else int(np.iinfo(np.int32).max)
        )
        # One sync at loop entry; afterwards the driver blocks only on each
        # chunk's two stop scalars (ChunkExtras). All stop/veto/checkpoint
        # arithmetic lives in the shared ChunkDriveControl (the neural loop
        # runs the identical logic).
        n_known = int(state_lib.labeled_count(state))
        # An abstaining oracle reveals FEWER than `window` labels per round,
        # so the control's label-cap lattice (which assumes window-sized
        # steps) would overestimate progress and veto dispatches while the
        # cell still has work — ending the drive early with an empty launch
        # window. Lattice window 0 disables exactly that veto (stop decisions
        # still come from the REAL revealed-count scalar), which is what
        # makes "an all-abstain oracle never terminates a cell early" hold.
        lattice_window = (
            0 if (scn is not None and scn.kind == "noisy_oracle"
                  and scn.abstain_prob > 0.0) else window
        )
        ctl = pipeline_lib.ChunkDriveControl(
            K, lattice_window, label_cap, cfg.max_rounds, n_known, start_round
        )
        if not ctl.already_done:
            # Projected upper bound on any ACTIVE fit's labeled rows over the
            # WHOLE run: raised here (loop entry) instead of mid-round — an
            # in-scan fit cannot raise, and letting gather_fit_window silently
            # truncate would corrupt the curve. Pre-reveal counts advance on
            # the n_known + j*window lattice and an active round needs its
            # count < label_cap, so the largest reachable ACTIVE fit size is
            # the last lattice point under the cap (not label_cap - 1, which
            # may be unreachable and would falsely reject configs the
            # per-round driver completes), further capped by max_rounds.
            j_cap = -(-(label_cap - n_known) // window) - 1  # ceil-div - 1
            if cfg.max_rounds is not None:
                j_cap = min(cfg.max_rounds - 1, j_cap)
            projected = n_known + max(j_cap, 0) * window
            if projected > fit_budget:
                raise ValueError(
                    f"up to {projected} labeled rows would exceed the device "
                    f"fit window ({fit_budget}); raise ForestConfig.fit_budget "
                    "or lower label_budget/max_rounds"
                )

        # Donation-safe checkpointing: the carry stays donated even for
        # checkpointed runs; each dispatch snapshots the post-chunk
        # (mask, key, round) into fresh buffers before the NEXT dispatch can
        # consume the carry (see ckpt_snapshot), and the touchdown persists
        # the snapshot instead of the carry.
        snapshots = pipeline_lib.CarrySnapshots(ckpt_snapshot)
        state_template = state
        key_impl = jax.random.key_impl(state.key)

        chunk_tail = (costs,) if scn is not None else ()

        def dispatch(st, idx):
            out = chunk_fn(
                codes, st, aux, fit_key, test_x, test_y, end_round, *chunk_tail
            )
            if ckpt_enabled:
                new_state = out[0]
                snapshots.take(
                    idx, new_state.labeled_mask, new_state.key, new_state.round
                )
            return out

        def touchdown(_idx, _n_labeled_after, n_active, ys, _out_state, wall):
            # The chunk's host touchdown: materialize the (already async-
            # copied) stacked ys, bulk-append records, log, maybe checkpoint.
            # Runs overlapped with the next chunk's execution when depth > 1.
            snap = snapshots.pop(_idx)
            if n_active == 0:
                return  # wholly-inactive (speculative tail) chunk: no-op
            rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
            active_np = np.asarray(active_y)
            rounds_np = np.asarray(rounds_y)[active_np]
            labeled_np = np.asarray(labeled_y)[active_np]
            acc_np = np.asarray(acc_y)[active_np]
            round_dicts = (
                telemetry.stacked_metrics_to_dicts(ys[5], active_np)
                if want_metrics
                else None
            )
            result.extend_from_arrays(
                rounds_np, labeled_np, n_pool - labeled_np, acc_np,
                total_time=wall / n_active,
                metrics=round_dicts,
            )
            ctl.note_round(int(rounds_np[-1]))
            if metrics is not None:
                # Touchdown accounting: bytes actually fetched to the host
                # this launch (stacked ys + metrics), then one round event per
                # active round — the fused run's per-round stream the PR-2
                # design gave up. Shape*itemsize (.nbytes on the device
                # arrays) — counting the transfer must not add transfers.
                fetched = (
                    active_y.nbytes
                    + rounds_y.nbytes
                    + labeled_y.nbytes
                    + acc_y.nbytes
                )
                if want_metrics:
                    fetched += telemetry.metrics_nbytes(ys[5])
                metrics.counter("host_transfer_bytes", int(fetched))
                for i in range(n_active):
                    metrics.round(
                        round=int(rounds_np[i]),
                        n_labeled=int(labeled_np[i]),
                        accuracy=float(acc_np[i]),
                        **(round_dicts[i] if round_dicts else {}),
                    )
                mem = telemetry.device_memory_gauges()
                if mem:
                    metrics.gauges(mem, allgather=True)
            if cfg.log_every and dbg.enabled:
                for r, nl, a in zip(rounds_np, labeled_np, acc_np):
                    if int(r) % cfg.log_every == 0:
                        dbg.debug(
                            f"Iteration {int(r)} -- labeled={int(nl)} "
                            f"accu={float(a) * 100:.2f}"
                        )
            if ckpt_enabled and ctl.checkpoint_due(cfg.checkpoint_every):
                # Chunk-boundary checkpointing: saved at the first touchdown
                # after each checkpoint_every multiple (steps need not align
                # with the multiple itself — runtime/checkpoint.py notes).
                # The post-chunk carry was donated to the next launch; the
                # dispatch-time snapshot holds the same (mask, key, round)
                # in buffers donation cannot touch (see ckpt_snapshot).
                from distributed_active_learning_tpu.runtime import (
                    checkpoint as ckpt_lib,
                )

                s_mask, s_kd, s_rnd = snap
                ckpt_state = state_template.replace(
                    labeled_mask=s_mask,
                    key=jax.random.wrap_key_data(s_kd, impl=key_impl),
                    round=s_rnd,
                )
                ckpt_lib.save(
                    cfg.checkpoint_dir, ckpt_state, result,
                    fingerprint=ckpt_fp, kernel=ckpt_kernel,
                )
                ctl.checkpoint_done()

        if not ctl.already_done:
            state, _stats = pipeline_lib.run_pipelined(
                state,
                dispatch=dispatch,
                touchdown=touchdown,
                continue_after=ctl.continue_after,
                depth=depth,
                on_launch=launches.record,
                may_dispatch=ctl.may_dispatch,
                on_veto=lambda idx: launches.veto(idx, ctl.veto_reason(idx)),
            )

        if metrics is not None and getattr(cfg, "roofline", False):
            # Roofline attribution of the launched chunk program (run.py
            # --roofline): price it with XLA's cost model and join the
            # tracker's steady-state launch seconds. After the drive, not
            # during — the AOT lower().compile() pays one extra compile.
            # The post-run carry has the exact avals the launches used (the
            # carry-aval audit rule guarantees it), so it serves as the
            # pricing input without keeping the initial state alive.
            telemetry.emit_roofline(
                metrics, launches, chunk_fn,
                (codes, state, aux, fit_key, test_x, test_y, end_round)
                + chunk_tail,
                n_devices=mesh.devices.size if mesh is not None else 1,
            )

        if cfg.results_path:
            result.save(cfg.results_path, fmt="reference")
        return result

    while True:
        n_labeled = int(state_lib.labeled_count(state))
        if n_labeled >= n_pool:
            break
        if cfg.label_budget is not None and n_labeled >= cfg.label_budget:
            break
        if cfg.max_rounds is not None and round_idx - start_round >= cfg.max_rounds:
            break
        round_idx += 1

        with dbg.phase("train"):
            if device_fit is not None:
                if n_labeled > fit_budget:
                    raise ValueError(
                        f"{n_labeled} labeled rows exceed the device fit "
                        f"window ({fit_budget}); raise ForestConfig.fit_budget"
                    )
                forest = place_forest(
                    device_fit(codes, state, jax.random.fold_in(fit_key, round_idx))
                )
                # keep phase timings honest
                jax.block_until_ready(forest)  # audit: ok[DAL101]
            else:
                lx, ly = _labeled_subset(state, host_x, host_y)
                packed = fit_forest_classifier(
                    lx, ly, cfg.forest, seed=cfg.seed + round_idx,
                    n_classes=n_classes,
                )
                # One representation conversion per fit; the round + accuracy
                # then run on the configured kernel (MXU GEMM by default).
                forest = place_forest(forest_eval.for_kernel(packed, cfg.forest.kernel))
        train_time = dbg.records[-1][1]

        with dbg.phase("round"):
            round_args = (forest, state, aux) + (
                (costs,) if scn is not None and scn.kind == "cost_budget" else ()
            )
            if want_metrics:
                state, picked, _, rm = round_fn(*round_args)
            else:
                state, picked, _ = round_fn(*round_args)
            jax.block_until_ready(picked)  # audit: ok[DAL101] — phase timing
        score_time = dbg.records[-1][1]
        with dbg.phase("eval"):
            eval_x = test_x
            if scn is not None and scn.kind == "drift":
                from distributed_active_learning_tpu.scenarios import (
                    engine as scn_engine,
                )

                # round_idx - 1 is the chunk scan's pre-reveal carry.round
                # for this round — the per-round and chunked drivers must
                # drift the SAME evaluation batch for a given round.
                eval_x = scn_engine.drift_apply(scn, test_x, round_idx - 1)
            acc = float(_accuracy(forest, eval_x, test_y))
        eval_time = dbg.records[-1][1]
        round_dict = None
        if want_metrics:
            from distributed_active_learning_tpu.runtime import telemetry

            round_dict = telemetry.metrics_to_dict(rm)

        # The record pairs the accuracy with the labeled count the evaluated
        # forest was *trained on* (pre-reveal), matching the reference's print
        # ordering ("labeled = 10 ... accu(trained on 10)",
        # uncertainty_sampling.py:65,113).
        rec = RoundRecord(
            round=round_idx,
            n_labeled=n_labeled,
            n_unlabeled=n_pool - n_labeled,
            accuracy=acc,
            train_time=train_time,
            score_time=score_time,
            eval_time=eval_time,
            total_time=train_time + score_time + eval_time,
            metrics=round_dict,
        )
        result.append(rec)
        if metrics is not None:
            metrics.round(
                round=round_idx,
                n_labeled=n_labeled,
                accuracy=acc,
                train_time=train_time,
                score_time=score_time,
                eval_time=eval_time,
                **(round_dict or {}),
            )
        if cfg.log_every and round_idx % cfg.log_every == 0:
            dbg.debug(
                f"Iteration {round_idx} -- labeled={n_labeled} accu={acc * 100:.2f}"
            )
        if cfg.checkpoint_dir and cfg.checkpoint_every and round_idx % cfg.checkpoint_every == 0:
            from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

            ckpt_lib.save(
                cfg.checkpoint_dir, state, result,
                fingerprint=ckpt_fp, kernel=ckpt_kernel,
            )

    if metrics is not None:
        from distributed_active_learning_tpu.runtime import telemetry

        mem = telemetry.device_memory_gauges()
        if mem:
            metrics.gauges(mem, allgather=True)

    if cfg.results_path:
        result.save(cfg.results_path, fmt="reference")
    return result
