"""Deep-AL experiment driver: neural learner + MC acquisition over the pool.

The neural counterpart of ``runtime.loop``: per round, (re)train the network on
the masked labeled subset entirely on device, draw MC-dropout predictive
samples over the pool, score with a deep acquisition function, select the
window, reveal. Serves BASELINE.json configs 4-5 (CIFAR CNN, text encoder +
BatchBALD), which the reference never reached.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.config import MeshConfig
from distributed_active_learning_tpu.models.neural import NeuralLearner, TrainState
from distributed_active_learning_tpu.ops.topk import select_top_k
from distributed_active_learning_tpu.runtime import state as state_lib
from distributed_active_learning_tpu.runtime.debugger import Debugger
from distributed_active_learning_tpu.runtime.results import ExperimentResult, RoundRecord
from distributed_active_learning_tpu.strategies import deep


# score_fn: probs_samples [S, n, C] -> scores [n] (higher = more informative)
_SCORES: Dict[str, Callable[[jnp.ndarray], jnp.ndarray]] = {
    "entropy": deep.predictive_entropy,
    "bald": deep.bald_score,
    "mean_std": deep.mean_std_score,
    "variation_ratio": deep.variation_ratio,
    "margin": deep.margin_score,
}


def _deep_names():
    """The one source of truth for valid deep-strategy (bare) names."""
    return set(_SCORES) | {"batchbald", "random", "coreset", "badge", "density"}


def available_deep_strategies():
    """Namespaced names ("deep.bald", ...) — the CLI routes on the prefix so
    names shared with the classic registry (e.g. "entropy") stay unambiguous."""
    return sorted("deep." + n for n in _deep_names())


def _normalize_deep_name(name: str) -> str:
    return name[len("deep."):] if name.startswith("deep.") else name


def is_deep_strategy(name: str) -> bool:
    """True if ``name`` (bare or "deep."-prefixed) names a deep strategy."""
    return _normalize_deep_name(name) in _deep_names()


@dataclasses.dataclass(frozen=True)
class NeuralExperimentConfig:
    strategy: str = "bald"
    window_size: int = 10
    n_start: int = 20
    max_rounds: Optional[int] = 10
    label_budget: Optional[int] = None
    seed: int = 0
    retrain_from_scratch: bool = True  # standard deep-AL protocol
    batchbald_max_configs: int = 4096
    # Greedy BatchBALD candidates (top-k unlabeled by marginal BALD); larger
    # pools are truncated to this many — logged when it happens.
    batchbald_candidate_pool: int = 512
    # MC configurations carried past the exact-joint cap (Kirsch et al.'s
    # sampled estimator; picks beyond log_C(max_configs) stay joint-aware).
    batchbald_mc_samples: int = 256
    # Information-density exponent (deep.density: entropy x mass**beta, the
    # neural form of density_weighting.py's beta at :33).
    beta: float = 1.0
    # Feature space for deep.coreset: "input" (raw pool features, model-free)
    # or "embedding" (the trained network's penultimate representation, the
    # space Sener & Savarese actually use).
    coreset_space: str = "input"
    # Same persistence + distribution knobs as the forest ExperimentConfig
    # (round-2 gap: the neural path was a parallel universe with neither).
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0
    # Scan-fuse K AL rounds (fit + acquire + eval) into ONE jitted lax.scan
    # launch, exactly like the forest loop's knob of the same name: the carry
    # is (net TrainState, PoolState, loop key), stopping stays exact via
    # masked in-scan no-ops, and results are bit-identical to the per-round
    # loop (tests/test_pipeline.py). Every deep strategy engages: the greedy
    # batch selects (batchbald/coreset/badge) unroll window_size times inside
    # the scan BODY, which is traced once regardless of K — the same compile
    # cost their standalone jitted selects already paid per round.
    rounds_per_launch: int = 1
    # Chunk launches in flight at once (runtime/pipeline.py; 1 = strict
    # serial launch -> block -> touchdown). Performance-only.
    pipeline_depth: int = 2
    # Emit live "round_stream" JSONL events from INSIDE running chunks via
    # jax.debug.callback (needs a MetricsWriter and rounds_per_launch > 1) —
    # same flag and semantics as ExperimentConfig.stream_round_events.
    stream_round_events: bool = False
    # Pool rows ride the data axis (DP over the mesh); the network itself is
    # replicated — its parameters are tiny next to a CIFAR-50k pool, so data
    # parallelism is the whole win and model sharding stays out of scope.
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)


def neural_fingerprint(
    cfg: NeuralExperimentConfig, learner: NeuralLearner, data_ident: Optional[dict] = None
) -> str:
    """Identity hash for neural checkpoints (counterpart of
    ``checkpoint.config_fingerprint``): everything that changes the *curve* —
    strategy, seeding, training protocol, network architecture, dataset —
    participates; loop controls and the mesh (performance-only) do not.
    """
    from distributed_active_learning_tpu.runtime.checkpoint import fingerprint_from_ident

    ident = {
        "strategy": _normalize_deep_name(cfg.strategy),
        "window_size": cfg.window_size,
        "n_start": cfg.n_start,
        "seed": cfg.seed,
        "retrain_from_scratch": cfg.retrain_from_scratch,
        "batchbald": (
            cfg.batchbald_max_configs,
            cfg.batchbald_candidate_pool,
            cfg.batchbald_mc_samples,
        ),
        "beta": cfg.beta,
        "coreset_space": cfg.coreset_space,
        # flax modules are dataclasses: repr() pins the architecture + sizes.
        "module": repr(learner.module),
        "input_shape": learner.input_shape,
        "train": (
            learner.train_steps,
            learner.batch_size,
            learner.mc_samples,
            learner.learning_rate,
        ),
        "data": data_ident or {},
    }
    return fingerprint_from_ident(ident)


def _place_on_mesh(cfg: MeshConfig, state, pool_x, net_state):
    """DP placement: pad the pool to data-axis divisibility, shard its rows
    (and the state's per-row arrays) over ``data``, replicate the network.

    GSPMD then partitions the already-jitted ``fit_on_mask`` /
    ``predict_proba_samples`` programs — same math, rows spread over ICI
    (threefry is partitionable, so dropout draws match the single-device run
    bit-for-bit). The reference's analogue is RDD-partitioning the pool while
    the model rides the driver (SURVEY.md §2.4).
    """
    from jax.sharding import PartitionSpec as P

    from distributed_active_learning_tpu.parallel import make_mesh, shard_pool_state
    from distributed_active_learning_tpu.parallel.mesh import global_put

    if cfg.model > 1:
        raise ValueError(
            "the neural path shards pool rows only (--mesh-data); model "
            f"parallelism of the network (mesh model={cfg.model}) is out of scope"
        )
    mesh = make_mesh(data=cfg.data, model=1)
    state = state_lib.pad_for_sharding(state, cfg.data)
    state = shard_pool_state(state, mesh)
    pad = state.n_pool - pool_x.shape[0]
    if pad:
        pool_x = jnp.pad(pool_x, ((0, pad),) + ((0, 0),) * (pool_x.ndim - 1))
    # global_put: placement works on multi-process meshes too (device_put
    # only accepts fully-addressable shardings).
    pool_x = global_put(pool_x, mesh, P("data", *([None] * (pool_x.ndim - 1))))
    net_state = jax.tree.map(lambda l: global_put(l, mesh, P()), net_state)
    return mesh, state, pool_x, net_state


#: Deep strategies whose acquire program fuses into the scanned chunk: ALL of
#: them. The MC-score family plus random and density are a fixed pipeline of
#: predict/score/top-k ops; batchbald/coreset/badge unroll their greedy
#: selection ``window_size`` times — but a ``lax.scan`` body is traced ONCE,
#: so inside a K-round chunk the compile cost is the same k-fold unroll their
#: standalone jitted selects already pay (NOT k*K, the misreading that kept
#: them on the per-round loop until PR 10). The paper's strongest batch
#: baselines (BatchBALD — Kirsch et al. 2019; coreset k-Center-Greedy —
#: Sener & Savarese 2018; BADGE — Ash et al. 2020) therefore no longer drop
#: out of fused dispatch.
FUSABLE_STRATEGIES = frozenset(_deep_names())

#: Default (max_configs, candidate_pool, mc_samples) for the in-scan
#: BatchBALD select — the NeuralExperimentConfig defaults.
_BATCHBALD_DEFAULTS = (4096, 512, 256)


def _make_neural_round_core(
    learner: NeuralLearner,
    strat: str,
    window_size: int,
    beta: float,
    with_metrics: bool,
    n_classes: int,
    coreset_space: str = "input",
    batchbald_params=_BATCHBALD_DEFAULTS,
):
    """The fit → MC-score → select → reveal → accuracy body shared by the
    serial chunk and the seed-sweep lane (vmapped there), factored out so the
    two entry points cannot drift — the neural twin of
    ``runtime.loop._device_fit_core``. Returns ``(net, new_st, acc, picked,
    metrics-or-None)``; the callers own the key split, the active/no-op cond,
    and the ys layout.

    The per-round PRNG protocol matches ``run_neural_experiment``'s fallback
    loop branch-for-branch: MC samples always draw from ``k_mc``, the
    selection randomness (random's uniform, badge's k-means++ draws,
    batchbald's MC-config draws) from ``k_rand`` — so fused and per-round
    curves agree bit-for-bit for every strategy.

    The greedy strategies' RoundMetrics score vectors are per-point proxies
    (their selection values are inherently batch-sequential): coreset uses
    distance-to-nearest-center (``deep.coreset_min_dists`` — exactly its own
    greedy init, so XLA CSEs the duplicate), badge the hallucinated-gradient
    embedding norm ``|g_i ⊗ h_i|²``, batchbald the marginal BALD score.
    """
    if coreset_space not in ("input", "embedding"):
        raise ValueError(
            f"unknown coreset_space {coreset_space!r}; use 'input' or "
            "'embedding'"
        )

    def round_core(st, net_in, pool_x, test_x, test_y, k_fit, k_mc, k_rand):
        fit_mask = st.labeled_mask
        if st.n_valid != st.n_pool:
            fit_mask = fit_mask & st.valid_mask
        net = learner.fit_on_mask(net_in, pool_x, st.oracle_y, fit_mask, k_fit)

        unlabeled = ~st.labeled_mask
        probs = None
        # random/coreset/badge need no MC posterior; with_metrics still draws
        # it (RoundMetrics' pool_entropy column reads the predictive samples).
        if strat not in ("random", "coreset", "badge") or with_metrics:
            probs = learner.predict_proba_samples(net, pool_x, k_mc)
        if strat == "random":
            scores = jax.random.uniform(k_rand, (st.n_pool,))
        elif strat == "density":
            from distributed_active_learning_tpu.ops.similarity import (
                similarity_mass,
            )

            ent = deep.predictive_entropy(probs)
            emb = learner.embed(net, pool_x)
            mass = jnp.maximum(similarity_mass(emb, unlabeled), 0.0)
            scores = ent * jnp.power(mass, beta)
        elif strat == "coreset":
            # k-Center-Greedy in-scan: centers are the real labeled rows
            # (mesh-padding sentinels excluded), same as the per-round loop.
            space = (
                learner.embed(net, pool_x)
                if coreset_space == "embedding"
                else pool_x
            )
            picked, vals = deep.coreset_select(
                space, fit_mask, window_size, selectable_mask=unlabeled
            )
            scores = deep.coreset_min_dists(space, fit_mask)
        elif strat == "badge":
            mean_probs = learner.predict_proba(net, pool_x)
            emb = learner.embed(net, pool_x)
            picked = deep.badge_select(
                mean_probs, emb, unlabeled, window_size, k_rand
            )
            # Proxy score vector for RoundMetrics: the gradient-embedding
            # norm |g ⊗ h|² (badge's own D² seed weights; CSE'd in-program).
            g = mean_probs - jax.nn.one_hot(
                jnp.argmax(mean_probs, axis=-1), mean_probs.shape[-1]
            )
            h = emb.reshape(emb.shape[0], -1).astype(jnp.float32)
            scores = jnp.sum(g * g, axis=1) * jnp.sum(h * h, axis=1)
            vals = scores[picked]
        elif strat == "batchbald":
            max_configs, candidate_pool, mc_samples = batchbald_params
            picked, vals = deep.batchbald_select(
                probs, unlabeled, window_size,
                max_configs, candidate_pool, mc_samples,
                key=k_rand,
            )
            scores = deep.bald_score(probs)
        else:
            scores = _SCORES[strat](probs)
        if strat not in ("coreset", "badge", "batchbald"):
            vals, picked = select_top_k(scores, unlabeled, window_size)
        new_st = state_lib.reveal(st, picked)

        acc = jnp.mean(
            (
                jnp.argmax(learner.predict_proba(net, test_x), -1) == test_y
            ).astype(jnp.float32)
        )
        metrics = None
        if with_metrics:
            from distributed_active_learning_tpu.runtime import telemetry

            metrics = telemetry.selection_metrics(
                st, picked, vals, scores,
                higher_is_better=True,
                n_classes=n_classes,
                pool_entropy=deep.predictive_entropy(probs),
            )
        return net, new_st, acc, picked, metrics

    return round_core


def make_neural_chunk_fn(
    learner: NeuralLearner,
    strat: str,
    window_size: int,
    chunk_size: int,
    label_cap: int,
    retrain_from_scratch: bool = True,
    beta: float = 1.0,
    with_metrics: bool = False,
    n_classes: int = 2,
    stream_cb=None,
    coreset_space: str = "input",
    batchbald_params=_BATCHBALD_DEFAULTS,
):
    """Fuse ``chunk_size`` neural AL rounds into ONE jitted ``lax.scan``.

    The neural counterpart of ``runtime.loop.make_chunk_fn``: per scan step,
    (re)train the network on the masked labeled subset (``fit_on_mask`` is
    already a fully-jitted train scan), draw the strategy's MC predictive
    samples, score + select + reveal, and evaluate test accuracy — all inside
    one launch. The carry is ``(net TrainState, PoolState, loop key)``;
    stopping stays exact via the same masked no-op discipline as the forest
    chunk (``active = labeled < cap  &  round < end_round``; an inactive step
    passes the whole carry through a ``lax.cond`` untouched, key included, so
    a chunk overrunning the stop point is bit-free).

    The per-round PRNG protocol is IDENTICAL to the per-round loop —
    ``key, k_fit, k_mc, k_rand = jax.random.split(key, 4)`` at each step — so
    fused and per-round curves match bit-for-bit (tests/test_pipeline.py).

    Returns ``chunk_fn(net_state, state, key, pool_x, init_net, test_x,
    test_y, end_round) -> ((net, state, key), ChunkExtras, (rounds,
    n_labeled, accuracy, picked, active[, metrics]))`` with each y stacked
    ``[chunk_size, ...]``; ``extras`` carries the post-chunk labeled count and
    active-round count — the only scalars the pipelined driver blocks on.
    With ``with_metrics`` a stacked :class:`~runtime.telemetry.RoundMetrics`
    rides as a sixth y (``telemetry.selection_metrics`` over the acquisition
    scores, pool entropy from the MC predictive samples — closing the
    ROADMAP follow-up that fused runs had host-side round events only).

    Every registered deep strategy is in :data:`FUSABLE_STRATEGIES` as of
    PR 10 (the greedy batch selects — batchbald/coreset/badge — run their
    static unrolls inside the scan body, which is traced once regardless of
    K). The carry is NOT donated: the pipelined driver's touchdown may
    checkpoint the post-chunk ``(net, state, key)`` after the next chunk
    already launched, which donation would have deleted
    (runtime/pipeline.py notes).
    """
    if strat not in FUSABLE_STRATEGIES:
        raise ValueError(
            f"strategy {strat!r} cannot fuse in-scan; fusable: "
            f"{sorted(FUSABLE_STRATEGIES)}"
        )
    from distributed_active_learning_tpu.runtime.pipeline import ChunkExtras

    round_core = _make_neural_round_core(
        learner, strat, window_size, beta, with_metrics, n_classes,
        coreset_space=coreset_space, batchbald_params=batchbald_params,
    )

    @jax.jit
    def chunk_fn(net_state, state, key, pool_x, init_net, test_x, test_y, end_round):
        def body(carry, _):
            net_c, st, k = carry
            n_labeled = state_lib.labeled_count(st)
            active = (n_labeled < label_cap) & (st.round < end_round)
            k_next, k_fit, k_mc, k_rand = jax.random.split(k, 4)

            net_in = init_net if retrain_from_scratch else net_c
            net, new_st, acc, picked, rm = round_core(
                st, net_in, pool_x, test_x, test_y, k_fit, k_mc, k_rand
            )
            out = jax.lax.cond(
                active,
                lambda: (net, new_st, k_next),
                lambda: carry,
            )
            if stream_cb is not None:
                # Live in-scan round events (same contract as the forest
                # chunk: unordered, each carries its round number; absent
                # from the traced program when the flag is off).
                jax.debug.callback(stream_cb, st.round + 1, n_labeled, acc, active)
            ys = (st.round + 1, n_labeled, acc, picked, active)
            if with_metrics:
                ys = ys + (rm,)
            return out, ys

        (net_out, st_out, key_out), ys = jax.lax.scan(
            body, (net_state, state, key), None, length=chunk_size
        )
        extras = ChunkExtras(
            n_labeled_after=state_lib.labeled_count(st_out),
            n_active=jnp.sum(ys[4].astype(jnp.int32)),
        )
        return (net_out, st_out, key_out), extras, ys

    return chunk_fn


def make_neural_sweep_chunk_fn(
    learner: NeuralLearner,
    strat: str,
    window_size: int,
    chunk_size: int,
    label_cap: int,
    retrain_from_scratch: bool = True,
    beta: float = 1.0,
    with_metrics: bool = False,
    n_classes: int = 2,
    coreset_space: str = "input",
    batchbald_params=_BATCHBALD_DEFAULTS,
):
    """:func:`make_neural_chunk_fn` vmapped over a leading experiment axis E.

    The ``--sweep-seeds`` discipline applied to the deep loop (the ROADMAP
    PR-5 follow-up): the carry's ``TrainState`` batches like the labeled
    mask — ``net_states`` / ``init_nets`` are per-seed pytrees stacked on a
    leading ``[E]`` axis, masks ``[E, n]``, loop keys ``[E]``, round
    counters ``[E]`` — while the pool (``pool_x`` / ``oracle_y``) and test
    arrays stay SHARED across the batch. Each lane runs the serial chunk's
    exact per-round body (same 4-way key split, same masked no-op freeze),
    so per-seed records are bit-identical to E serial
    ``run_neural_experiment`` runs; vmap is a compilation strategy, never a
    semantic one.

    Returns ``chunk_fn(net_states, masks, keys, rounds, pool_x, oracle_y,
    init_nets, test_x, test_y, end_rounds) -> ((nets, masks, keys, rounds),
    extras, ys)`` with every y stacked ``[chunk_size, E, ...]`` and
    ``extras`` the batch-reduced :class:`~runtime.pipeline.ChunkExtras`
    (MIN labeled count, MAX active rounds — the sweep stop contract). The
    carry is NOT donated, matching the serial neural chunk.
    """
    if strat not in FUSABLE_STRATEGIES:
        raise ValueError(
            f"strategy {strat!r} cannot fuse in-scan; fusable: "
            f"{sorted(FUSABLE_STRATEGIES)}"
        )
    from distributed_active_learning_tpu.runtime.pipeline import ChunkExtras

    round_core = _make_neural_round_core(
        learner, strat, window_size, beta, with_metrics, n_classes,
        coreset_space=coreset_space, batchbald_params=batchbald_params,
    )

    @jax.jit
    def chunk_fn(
        net_states, masks, keys, rounds, pool_x, oracle_y, init_nets,
        test_x, test_y, end_rounds,
    ):
        n = pool_x.shape[0]

        def body(carry, _):
            nets_c, masks_c, keys_c, rounds_c = carry

            def one(net_c, mask, k, rnd, init_net, end_round):
                # Per-lane round: the shared serial body (same key protocol,
                # same reveal, same masked no-op freeze) over a lane-local
                # PoolState view of the shared pool.
                st = state_lib.PoolState(
                    x=jnp.zeros((n, 0), jnp.float32), oracle_y=oracle_y,
                    labeled_mask=mask, key=k, round=rnd,
                )
                n_labeled = state_lib.labeled_count(st)
                active = (n_labeled < label_cap) & (rnd < end_round)
                k_next, k_fit, k_mc, k_rand = jax.random.split(k, 4)

                net_in = init_net if retrain_from_scratch else net_c
                net, new_st, acc, picked, rm = round_core(
                    st, net_in, pool_x, test_x, test_y, k_fit, k_mc, k_rand
                )
                out = jax.lax.cond(
                    active,
                    lambda: (net, new_st.labeled_mask, k_next, new_st.round),
                    lambda: (net_c, mask, k, rnd),
                )
                ys = (rnd + 1, n_labeled, acc, picked, active)
                if with_metrics:
                    ys = ys + (rm,)
                return out, ys

            (nets, m, k, r), ys = jax.vmap(one)(
                nets_c, masks_c, keys_c, rounds_c, init_nets, end_rounds
            )
            return (nets, m, k, r), ys

        (nets_out, masks_out, keys_out, rounds_out), ys = jax.lax.scan(
            body, (net_states, masks, keys, rounds), None, length=chunk_size
        )
        extras = ChunkExtras(
            n_labeled_after=jnp.min(
                jnp.sum(masks_out.astype(jnp.int32), axis=1)
            ),
            n_active=jnp.max(jnp.sum(ys[4].astype(jnp.int32), axis=0)),
        )
        return (nets_out, masks_out, keys_out, rounds_out), extras, ys

    return chunk_fn


def run_neural_sweep(
    cfg: NeuralExperimentConfig,
    learner: NeuralLearner,
    train_x,
    train_y,
    test_x,
    test_y,
    seeds,
    debugger: Optional[Debugger] = None,
    data_ident: Optional[dict] = None,
    metrics=None,
):
    """Run E = len(seeds) deep-AL experiments over one shared pool as a
    single batched launch stream; returns one :class:`ExperimentResult` per
    seed (the neural twin of ``runtime.sweep.run_sweep``).

    Per-seed records are bit-identical to E serial
    :func:`run_neural_experiment` runs with ``seed=s`` substituted: every
    per-seed key (pool state, loop key, network init) derives exactly as the
    serial driver derives it, and the vmapped chunk runs the serial round
    body per lane. Falls back to E serial runs for per-phase debugging (every
    registered deep strategy fuses as of PR 10). Mesh sharding
    and checkpointing are not supported by the batched path (a mesh config
    falls back serially; ``checkpoint_dir`` raises — one file per seed would
    need the grid format, a follow-up).
    """
    dbg = debugger or Debugger(enabled=False)
    seeds = [int(s) for s in seeds]
    if not seeds:
        raise ValueError("run_neural_sweep needs at least one seed")
    strat = _normalize_deep_name(cfg.strategy)
    if strat not in _deep_names():
        raise KeyError(
            f"unknown deep strategy {cfg.strategy!r}; available: "
            f"{available_deep_strategies()}"
        )
    if cfg.checkpoint_dir and cfg.checkpoint_every:
        raise ValueError(
            "checkpointing is not supported by the batched neural sweep; "
            "run the seeds serially or drop --checkpoint-dir"
        )

    def _serial():
        out = []
        for s in seeds:
            out.append(
                run_neural_experiment(
                    dataclasses.replace(cfg, seed=s), learner,
                    train_x, train_y, test_x, test_y,
                    debugger=debugger, data_ident=data_ident, metrics=metrics,
                )
            )
        return out

    sharded = cfg.mesh.data * cfg.mesh.model > 1
    if (
        strat not in FUSABLE_STRATEGIES
        or getattr(dbg, "phase_detail", False)
        or sharded
    ):
        return _serial()

    x = jnp.asarray(train_x)
    y = jnp.asarray(train_y)
    test_x = jnp.asarray(test_x)
    test_y = jnp.asarray(test_y)
    n = x.shape[0]
    n_classes = int(jnp.max(y)) + 1

    # Per-seed state exactly as the serial driver builds it, then stacked.
    states = []
    for s in seeds:
        st = state_lib.init_pool_state(
            jnp.zeros((n, 0), jnp.float32), y, jax.random.key(s)
        )
        states.append(
            state_lib.set_start_state(st, cfg.n_start, n_classes=max(n_classes, 2))
        )
    masks0 = jnp.stack([st.labeled_mask for st in states])
    keys0 = jnp.stack([jax.random.key(s + 1) for s in seeds])
    rounds0 = jnp.zeros((len(seeds),), dtype=jnp.int32)
    init_nets = jax.tree.map(
        lambda *ls: jnp.stack(ls),
        *[learner.init(jax.random.key(s + 2)) for s in seeds],
    )

    if metrics is not None:
        metrics.meta(
            config=dataclasses.asdict(cfg),
            loop="neural_sweep",
            backend=jax.default_backend(),
            n_devices=jax.device_count(),
            process_count=jax.process_count(),
            sweep_seeds=seeds,
        )

    from distributed_active_learning_tpu.runtime import (
        pipeline as pipeline_lib,
        telemetry,
    )

    E = len(seeds)
    K = max(int(cfg.rounds_per_launch or 1), 1)
    window = cfg.window_size
    label_cap = n if cfg.label_budget is None else min(cfg.label_budget, n)
    depth = max(int(getattr(cfg, "pipeline_depth", 1) or 1), 1)
    want_metrics = metrics is not None
    chunk_fn = make_neural_sweep_chunk_fn(
        learner, strat, window, K, label_cap,
        retrain_from_scratch=cfg.retrain_from_scratch,
        beta=cfg.beta,
        with_metrics=want_metrics,
        n_classes=max(n_classes, 2),
        coreset_space=cfg.coreset_space,
        batchbald_params=(
            cfg.batchbald_max_configs,
            cfg.batchbald_candidate_pool,
            cfg.batchbald_mc_samples,
        ),
    )
    launches = telemetry.LaunchTracker(
        metrics, "neural_sweep_chunk_scan", fn=chunk_fn
    )
    end_rounds = jnp.full(
        (E,),
        cfg.max_rounds if cfg.max_rounds is not None else np.iinfo(np.int32).max,
        dtype=jnp.int32,
    )
    counts0 = [int(c) for c in np.asarray(jnp.sum(masks0, axis=1))]
    ctl = pipeline_lib.ChunkDriveControl(
        K, window, label_cap, cfg.max_rounds, min(counts0), 0
    )
    results = [ExperimentResult() for _ in seeds]

    def dispatch(carry, _idx):
        nets, m, k, r = carry
        return chunk_fn(
            nets, m, k, r, x, y, init_nets, test_x, test_y, end_rounds
        )

    def touchdown(_idx, _n_labeled_after, n_active, ys, _out, wall):
        if n_active == 0:
            return
        rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
        active_np = np.asarray(active_y)  # [K, E]
        rounds_np = np.asarray(rounds_y)
        labeled_np = np.asarray(labeled_y)
        acc_np = np.asarray(acc_y)
        total_active = int(active_np.sum())
        md = (
            telemetry.stacked_sweep_metrics_to_dicts(ys[5], active_np)
            if want_metrics
            else None
        )
        last_round = ctl.round_idx
        for e in range(E):
            act = active_np[:, e]
            if not act.any():
                continue
            r_e = rounds_np[act, e]
            l_e = labeled_np[act, e]
            a_e = acc_np[act, e]
            results[e].extend_from_arrays(
                r_e, l_e, n - l_e, a_e,
                total_time=wall / total_active,
                metrics=md[e] if md is not None else None,
            )
            last_round = max(last_round, int(r_e[-1]))
            if metrics is not None:
                for i in range(len(r_e)):
                    metrics.round(
                        exp=e,
                        seed=seeds[e],
                        round=int(r_e[i]),
                        n_labeled=int(l_e[i]),
                        accuracy=float(a_e[i]),
                        **(md[e][i] if md is not None else {}),
                    )
        ctl.note_round(last_round)

    if not ctl.already_done:
        pipeline_lib.run_pipelined(
            (init_nets, masks0, keys0, rounds0),
            dispatch=dispatch,
            touchdown=touchdown,
            continue_after=ctl.continue_after,
            depth=depth,
            on_launch=launches.record,
            may_dispatch=ctl.may_dispatch,
            on_veto=lambda idx: launches.veto(idx, ctl.veto_reason(idx)),
        )
    if metrics is not None:
        mem = telemetry.device_memory_gauges()
        if mem:
            metrics.gauges(mem, allgather=True)
    return results


def run_neural_experiment(
    cfg: NeuralExperimentConfig,
    learner: NeuralLearner,
    train_x,
    train_y,
    test_x,
    test_y,
    debugger: Optional[Debugger] = None,
    data_ident: Optional[dict] = None,
    metrics=None,
) -> ExperimentResult:
    """``metrics`` (a :class:`~runtime.telemetry.MetricsWriter`, or None)
    streams one rank-tagged ``round`` JSONL event per AL round — counts,
    accuracy, phase wall times — plus end-of-run device memory gauges; the
    same sink ``run.py --metrics-out`` feeds on the forest path. The neural
    loop is per-round by construction (its fit is already one fused jitted
    scan), so its events are host-emitted, not scan ys."""
    dbg = debugger or Debugger(enabled=False)
    strat = _normalize_deep_name(cfg.strategy)
    if strat not in _deep_names():
        raise KeyError(
            f"unknown deep strategy {cfg.strategy!r}; available: {available_deep_strategies()}"
        )

    x = jnp.asarray(train_x)
    y = jnp.asarray(train_y)
    test_x = jnp.asarray(test_x)
    test_y = jnp.asarray(test_y)

    # The PoolState masks are the source of truth for the labeled split; the
    # network consumes ``pool_x`` directly, so the state carries only a [n, 0]
    # feature placeholder — no duplicate float32 copy of the pool in HBM
    # (CIFAR-50k would otherwise hold ~600 MB twice).
    n = x.shape[0]
    state = state_lib.init_pool_state(jnp.zeros((n, 0), jnp.float32), y, jax.random.key(cfg.seed))
    n_classes = int(jnp.max(y)) + 1
    state = state_lib.set_start_state(state, cfg.n_start, n_classes=max(n_classes, 2))
    pool_x = x

    key = jax.random.key(cfg.seed + 1)
    net_state: TrainState = learner.init(jax.random.key(cfg.seed + 2))

    sharded = cfg.mesh.data * cfg.mesh.model > 1
    if sharded:
        from jax.sharding import PartitionSpec as P

        mesh, state, pool_x, net_state = _place_on_mesh(
            cfg.mesh, state, pool_x, net_state
        )
        # Test arrays and the loop key ride the mesh replicated so every jit
        # input is global (mixed committed placements fail under jit, and a
        # process-local input is invalid when the mesh spans processes).
        from distributed_active_learning_tpu.parallel.mesh import global_put

        test_x = global_put(test_x, mesh, P())
        test_y = global_put(test_y, mesh, P())
        key = global_put(key, mesh, P())
    init_net_state = net_state

    result = ExperimentResult()
    start_round = 0
    if cfg.checkpoint_dir and cfg.checkpoint_every:
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        ckpt_fp = neural_fingerprint(cfg, learner, data_ident)
        restored = ckpt_lib.restore_latest_neural(
            cfg.checkpoint_dir, state, result, net_state, fingerprint=ckpt_fp
        )
        if restored is not None:
            state, result, net_state, key = restored
            if sharded:
                _, state, _, net_state = _place_on_mesh(
                    cfg.mesh, state, pool_x, net_state
                )
            start_round = int(state.round)
            dbg.debug(f"resumed at round {start_round}")

    if metrics is not None:
        metrics.meta(
            config=dataclasses.asdict(cfg),
            loop="neural",
            backend=jax.default_backend(),
            n_devices=jax.device_count(),
            process_count=jax.process_count(),
        )

    n_pool = state.n_valid  # real rows; mesh padding is never selectable

    # Scan-fused + pipelined driver (the forest loop's PR-2/PR-4 discipline
    # applied to the neural path): K rounds per launch, touchdowns overlapped
    # with the next chunk's execution, stop decisions off two scalars. Every
    # deep strategy fuses (PR 10 folded the greedy batch selects in); only
    # explicit per-phase timing requests take the per-round loop below.
    use_chunked = (
        cfg.rounds_per_launch > 1
        and strat in FUSABLE_STRATEGIES
        and not getattr(dbg, "phase_detail", False)
    )
    if use_chunked:
        from distributed_active_learning_tpu.runtime import (
            pipeline as pipeline_lib,
            telemetry,
        )

        K, window = cfg.rounds_per_launch, cfg.window_size
        label_cap = n_pool if cfg.label_budget is None else min(cfg.label_budget, n_pool)
        depth = max(int(getattr(cfg, "pipeline_depth", 1) or 1), 1)
        want_metrics = metrics is not None
        stream_cb = None
        if metrics is not None and cfg.stream_round_events:
            def stream_cb(round_, n_labeled_cb, acc_cb, active_cb):
                if bool(active_cb):
                    metrics.event(
                        "round_stream",
                        round=int(round_),
                        n_labeled=int(n_labeled_cb),
                        accuracy=float(acc_cb),
                    )
        chunk_fn = make_neural_chunk_fn(
            learner, strat, window, K, label_cap,
            retrain_from_scratch=cfg.retrain_from_scratch,
            beta=cfg.beta,
            with_metrics=want_metrics,
            n_classes=max(n_classes, 2),
            stream_cb=stream_cb,
            coreset_space=cfg.coreset_space,
            batchbald_params=(
                cfg.batchbald_max_configs,
                cfg.batchbald_candidate_pool,
                cfg.batchbald_mc_samples,
            ),
        )
        launches = telemetry.LaunchTracker(metrics, "neural_chunk_scan", fn=chunk_fn)
        end_round = (
            start_round + cfg.max_rounds
            if cfg.max_rounds is not None
            else int(np.iinfo(np.int32).max)
        )
        # Stop/veto/checkpoint arithmetic shared verbatim with the forest
        # driver (runtime/pipeline.py ChunkDriveControl): only the chunk
        # program and the touchdown body differ between the two loops.
        n_known = int(state_lib.labeled_count(state))
        ctl = pipeline_lib.ChunkDriveControl(
            K, window, label_cap, cfg.max_rounds, n_known, start_round
        )
        ckpt_enabled = bool(cfg.checkpoint_dir and cfg.checkpoint_every)

        def dispatch(carry, _idx):
            net_c, st, k = carry
            return chunk_fn(
                net_c, st, k, pool_x, init_net_state, test_x, test_y, end_round
            )

        def touchdown(_idx, _n_labeled_after, n_active, ys, out_carry, wall):
            if n_active == 0:
                return  # wholly-inactive (speculative tail) chunk
            rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
            active_np = np.asarray(active_y)
            rounds_np = np.asarray(rounds_y)[active_np]
            labeled_np = np.asarray(labeled_y)[active_np]
            acc_np = np.asarray(acc_y)[active_np]
            round_dicts = (
                telemetry.stacked_metrics_to_dicts(ys[5], active_np)
                if want_metrics
                else None
            )
            result.extend_from_arrays(
                rounds_np, labeled_np, n_pool - labeled_np, acc_np,
                total_time=wall / n_active,
                metrics=round_dicts,
            )
            ctl.note_round(int(rounds_np[-1]))
            if metrics is not None:
                for i in range(n_active):
                    metrics.round(
                        round=int(rounds_np[i]),
                        n_labeled=int(labeled_np[i]),
                        accuracy=float(acc_np[i]),
                        **(round_dicts[i] if round_dicts else {}),
                    )
            if ckpt_enabled and ctl.checkpoint_due(cfg.checkpoint_every):
                # Chunk-boundary checkpointing (first touchdown at/after each
                # checkpoint_every multiple). The carry is un-donated, so the
                # post-chunk (net, state, key) is valid to persist here even
                # though the next chunk already launched from it.
                from distributed_active_learning_tpu.runtime import (
                    checkpoint as ckpt_lib,
                )

                net_o, st_o, key_o = out_carry
                ckpt_lib.save_neural(
                    cfg.checkpoint_dir, st_o, result, net_o, key_o,
                    fingerprint=ckpt_fp,
                )
                ctl.checkpoint_done()

        if not ctl.already_done:
            _carry, _stats = pipeline_lib.run_pipelined(
                (net_state, state, key),
                dispatch=dispatch,
                touchdown=touchdown,
                continue_after=ctl.continue_after,
                depth=depth,
                on_launch=launches.record,
                may_dispatch=ctl.may_dispatch,
                on_veto=lambda idx: launches.veto(idx, ctl.veto_reason(idx)),
            )
        if metrics is not None:
            mem = telemetry.device_memory_gauges()
            if mem:
                metrics.gauges(mem, allgather=True)
        return result

    round_idx = start_round
    while True:
        n_labeled = int(state_lib.labeled_count(state))
        if n_labeled >= n_pool:
            break
        if cfg.label_budget is not None and n_labeled >= cfg.label_budget:
            break
        if cfg.max_rounds is not None and round_idx - start_round >= cfg.max_rounds:
            break
        round_idx += 1
        key, k_fit, k_mc, k_rand = jax.random.split(key, 4)

        with dbg.phase("train"):
            if cfg.retrain_from_scratch:
                net_state = init_net_state
            # Padding rows are labeled_mask=True sentinels — the fit must
            # sample real labeled rows only (same guard as the forest loop's
            # device fit).
            fit_mask = state.labeled_mask
            if state.n_valid != state.n_pool:
                fit_mask = fit_mask & state.valid_mask
            net_state = learner.fit_on_mask(
                net_state, pool_x, state.oracle_y, fit_mask, k_fit
            )
            # keep phase timings honest: fit_on_mask returns async — without
            # the block its cost books under the acquire phase
            jax.block_until_ready(net_state.params)  # audit: ok[DAL101]
        train_time = dbg.records[-1][1]

        with dbg.phase("acquire"):
            unlabeled = ~state.labeled_mask  # padding rows read as labeled
            if strat == "random":
                scores = jax.random.uniform(k_rand, (state.n_pool,))
                _, picked = select_top_k(scores, unlabeled, cfg.window_size)
            elif strat == "coreset":
                # k-Center-Greedy over pool features ("input": model-free) or
                # the trained penultimate representation ("embedding").
                # Centers = real labeled rows; mesh-padding sentinels (zero
                # features) are neither centers nor selectable.
                if cfg.coreset_space == "embedding":
                    space = learner.embed(net_state, pool_x)
                elif cfg.coreset_space == "input":
                    space = pool_x
                else:
                    raise ValueError(
                        f"unknown coreset_space {cfg.coreset_space!r}; "
                        "use 'input' or 'embedding'"
                    )
                centers = state.labeled_mask
                if state.n_valid != state.n_pool:
                    centers = centers & state.valid_mask
                picked, _ = deep.coreset_select(
                    space, centers, cfg.window_size,
                    selectable_mask=unlabeled,
                )
            elif strat == "density":
                # Information density, neural form (BASELINE config 4:
                # "entropy + density-weighted"): MC predictive entropy
                # weighted by cosine-similarity mass over the *learned*
                # penultimate embeddings (the reference weighted by raw
                # feature similarity, density_weighting.py:148-168).
                from distributed_active_learning_tpu.ops.similarity import (
                    similarity_mass,
                )

                probs = learner.predict_proba_samples(net_state, pool_x, k_mc)
                ent = deep.predictive_entropy(probs)
                emb = learner.embed(net_state, pool_x)
                mass = jnp.maximum(similarity_mass(emb, unlabeled), 0.0)
                scores = ent * jnp.power(mass, cfg.beta)
                _, picked = select_top_k(scores, unlabeled, cfg.window_size)
            elif strat == "badge":
                # Hallucinated-gradient k-means++ (deterministic softmax +
                # penultimate features; D² draws from this round's key).
                probs = learner.predict_proba(net_state, pool_x)
                emb = learner.embed(net_state, pool_x)
                picked = deep.badge_select(
                    probs, emb, unlabeled, cfg.window_size, k_rand
                )
            elif strat == "batchbald":
                probs = learner.predict_proba_samples(net_state, pool_x, k_mc)
                n_unlabeled = n_pool - n_labeled
                if n_unlabeled > cfg.batchbald_candidate_pool:
                    dbg.debug(
                        "batchbald: candidate pool truncated to top "
                        f"{cfg.batchbald_candidate_pool} of {n_unlabeled} "
                        "unlabeled points (marginal-BALD ranking); raise "
                        "--candidate-pool to widen"
                    )
                picked, _ = deep.batchbald_select(
                    probs,
                    unlabeled,
                    cfg.window_size,
                    cfg.batchbald_max_configs,
                    cfg.batchbald_candidate_pool,
                    cfg.batchbald_mc_samples,
                    key=k_rand,
                )
            else:
                probs = learner.predict_proba_samples(net_state, pool_x, k_mc)
                scores = _SCORES[strat](probs)
                _, picked = select_top_k(scores, unlabeled, cfg.window_size)
            state = state_lib.reveal(state, picked)
            jax.block_until_ready(state.labeled_mask)  # audit: ok[DAL101]
        score_time = dbg.records[-1][1]
        with dbg.phase("eval"):
            acc = learner.accuracy(net_state, test_x, test_y)
        eval_time = dbg.records[-1][1]

        # Pre-reveal count: the accuracy was measured on the network trained on
        # this many labels (same record semantics as runtime.loop).
        result.append(
            RoundRecord(
                round=round_idx,
                n_labeled=n_labeled,
                n_unlabeled=n_pool - n_labeled,
                accuracy=acc,
                train_time=train_time,
                score_time=score_time,
                eval_time=eval_time,
                total_time=train_time + score_time + eval_time,
            )
        )
        if metrics is not None:
            metrics.round(
                round=round_idx,
                n_labeled=n_labeled,
                accuracy=acc,
                train_time=train_time,
                score_time=score_time,
                eval_time=eval_time,
            )
        if (
            cfg.checkpoint_dir
            and cfg.checkpoint_every
            and round_idx % cfg.checkpoint_every == 0
        ):
            from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

            ckpt_lib.save_neural(
                cfg.checkpoint_dir, state, result, net_state, key, fingerprint=ckpt_fp
            )
    if metrics is not None:
        from distributed_active_learning_tpu.runtime import telemetry

        mem = telemetry.device_memory_gauges()
        if mem:
            metrics.gauges(mem, allgather=True)
    return result
