"""Structured phase timing — the reference's ``Debugger`` made useful.

The reference duplicates a wall-clock tracer in both main dirs
(``final_thesis/debugger.py:6-27``; ``classes/debugger.py:6-42``):
``TIMESTAMP(label)`` prints a banner with per-phase elapsed and cumulative
seconds, plus ``DEBUG(arg)`` pretty-prints of collect()ed RDDs. Results were
captured by redirecting stdout (``classes/RESULTS.txt``).

This version keeps the same phase-segmentation idea but records structured
``(label, elapsed)`` pairs, supports nesting via context managers, and can emit
a ``jax.profiler`` trace for real TPU profiling (SURVEY.md §5.1).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Tuple


class Debugger:
    """Phase timer with the reference's TIMESTAMP semantics + structured records."""

    def __init__(self, enabled: bool = True, printer=print, phase_detail=None):
        self.enabled = enabled
        self.printer = printer
        # Whether per-phase (train/score/eval) wall splits are wanted. An
        # enabled debugger implies yes by default — and the chunked driver
        # (runtime/loop.py make_chunk_fn) cannot attribute phases inside one
        # fused scan launch, so it falls back to the per-round path when this
        # is set. Pass phase_detail=False to keep prints/logs while opting
        # into scan fusion (run.py does this for --rounds-per-launch > 1).
        self.phase_detail = enabled if phase_detail is None else phase_detail
        self.records: List[Tuple[str, float]] = []
        self._start = time.perf_counter()
        self._last = self._start

    def timestamp(self, label: str) -> float:
        """Record elapsed time since the previous timestamp under ``label``.

        Mirrors ``Debugger.TIMESTAMP`` (``final_thesis/debugger.py:15-27``):
        per-phase elapsed + running total, then resets the phase timer.
        """
        now = time.perf_counter()
        elapsed = now - self._last
        self._last = now
        self.records.append((label, elapsed))
        if self.enabled:
            total = now - self._start
            self.printer(f"[{label}] {elapsed:.3f}s (total {total:.3f}s)")
        return elapsed

    def debug(self, *args) -> None:
        """Pretty-print hook (``classes/debugger.py:14-22``)."""
        if self.enabled:
            self.printer("[DEBUG]", *args)

    @contextlib.contextmanager
    def phase(self, label: str):
        """Nested phase timing as a context manager."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - t0
            self.records.append((label, elapsed))
            if self.enabled:
                self.printer(f"[{label}] {elapsed:.3f}s")

    def totals(self) -> Dict[str, float]:
        """Aggregate elapsed seconds per label."""
        out: Dict[str, float] = {}
        for label, elapsed in self.records:
            out[label] = out.get(label, 0.0) + elapsed
        return out

    def total_time(self) -> float:
        return time.perf_counter() - self._start


@contextlib.contextmanager
def profiler_trace(log_dir: Optional[str]):
    """Wrap a block in a ``jax.profiler`` trace when ``log_dir`` is set."""
    if log_dir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
