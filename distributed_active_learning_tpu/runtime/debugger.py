"""Structured phase timing — the reference's ``Debugger`` made useful.

The reference duplicates a wall-clock tracer in both main dirs
(``final_thesis/debugger.py:6-27``; ``classes/debugger.py:6-42``):
``TIMESTAMP(label)`` prints a banner with per-phase elapsed and cumulative
seconds, plus ``DEBUG(arg)`` pretty-prints of collect()ed RDDs. Results were
captured by redirecting stdout (``classes/RESULTS.txt``).

This version keeps the same phase-segmentation idea but records structured
``(label, elapsed)`` pairs, supports nesting via context managers, and can emit
a ``jax.profiler`` trace for real TPU profiling (SURVEY.md §5.1).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Tuple


class Debugger:
    """Phase timer with the reference's TIMESTAMP semantics + structured records."""

    def __init__(self, enabled: bool = True, printer=print, phase_detail=None):
        self.enabled = enabled
        self.printer = printer
        # Whether per-phase (train/score/eval) wall splits are REQUIRED. The
        # chunked driver (runtime/loop.py make_chunk_fn) cannot attribute
        # phases inside one fused scan launch, so phase_detail=True forces the
        # per-round fallback. Default is False — since the in-scan
        # RoundMetrics landed (runtime/telemetry.py), an enabled debugger no
        # longer implies host-side phase syncs: fused runs keep per-round
        # logs/metrics, and phase timing is an explicit opt-in. (Pre-telemetry
        # this defaulted to `enabled`, which silently cost every logged run
        # its scan fusion.)
        self.phase_detail = bool(phase_detail) if phase_detail is not None else False
        self.records: List[Tuple[str, float]] = []
        self._start = time.perf_counter()
        self._last = self._start

    def timestamp(self, label: str) -> float:
        """Record elapsed time since the previous timestamp under ``label``.

        Mirrors ``Debugger.TIMESTAMP`` (``final_thesis/debugger.py:15-27``):
        per-phase elapsed + running total, then resets the phase timer.
        """
        now = time.perf_counter()
        elapsed = now - self._last
        self._last = now
        self.records.append((label, elapsed))
        if self.enabled:
            total = now - self._start
            self.printer(f"[{label}] {elapsed:.3f}s (total {total:.3f}s)")
        return elapsed

    def debug(self, *args) -> None:
        """Pretty-print hook (``classes/debugger.py:14-22``)."""
        if self.enabled:
            self.printer("[DEBUG]", *args)

    @contextlib.contextmanager
    def phase(self, label: str):
        """Nested phase timing as a context manager.

        Each phase also opens a ``jax.profiler.TraceAnnotation`` span, so a
        ``--profile-dir`` trace shows the host-side train/round/eval segments
        by name alongside the device ops' ``jax.named_scope`` labels — the
        attribution the reference's TIMESTAMP banners could never give.
        """
        import jax.profiler  # lazy: the Debugger must not force backend init

        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(f"al_phase/{label}"):
                yield
        finally:
            elapsed = time.perf_counter() - t0
            self.records.append((label, elapsed))
            if self.enabled:
                self.printer(f"[{label}] {elapsed:.3f}s")

    def totals(self) -> Dict[str, float]:
        """Aggregate elapsed seconds per label."""
        out: Dict[str, float] = {}
        for label, elapsed in self.records:
            out[label] = out.get(label, 0.0) + elapsed
        return out

    def total_time(self) -> float:
        return time.perf_counter() - self._start


@contextlib.contextmanager
def profiler_trace(log_dir: Optional[str]):
    """Wrap a block in a ``jax.profiler`` trace when ``log_dir`` is set."""
    if log_dir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
