"""Batched experiment sweeps: one launch stream advances E experiments.

The paper's deliverable is never a single AL run — it is a grid of runs
(strategies x seeds x window sizes) averaged into learning curves, and the
LAL regressor's MC training set is itself hundreds of tiny simulated AL
experiments. PRs 2-4 made ONE experiment launch-efficient (scan fusion +
pipelined dispatch), but a sweep still paid E full serial drives. This module
closes that gap with the batched-simulation discipline (podracer-style
batched actors / EvoJAX-style vmapped populations, PAPERS.md): ``jax.vmap``
over a leading experiment axis of the existing chunk program.

Design:

- **One pool, E experiments.** The pool feature matrix (and its binned codes,
  test set, LAL regressor) is SHARED across the batch — only the per-
  experiment state (labeled mask, PRNG key, round counter: :class:`SweepState`)
  carries a leading ``[E]`` axis. A seed sweep therefore costs E bitmasks of
  extra memory, not E pools.

- **The chunk program is the unit of batching.** :func:`make_sweep_chunk_fn`
  vmaps the SAME round body the serial chunk driver runs (device fit —
  Poisson(1) bootstrap weights are partitioning-stable — scoring, masked
  top-k reveal, accuracy eval, RoundMetrics) inside the same ``lax.scan``:
  one jitted launch advances all E experiments by K rounds. Per-seed results
  are bit-identical to E serial runs (tests/test_sweep.py, CPU and the 4x2
  mesh): vmap is a compilation strategy here, never a semantic one.

- **Heterogeneous windows via padding + masked reveal.** Experiments may use
  different window sizes: selection runs at the sweep's widest window (one
  static top-k) and the reveal (plus every pick-derived metric) is masked to
  each experiment's own width (``runtime.loop.make_padded_round_fn``,
  ``state.reveal_masked``) — ``lax.top_k`` is sorted, so the first w of a
  top-W selection are exactly the top-w selection.

- **Stopping reduces to one scalar pair.** Experiments hit their budgets at
  different rounds; finished experiments continue as the chunk's existing
  masked no-ops (state frozen bit-for-bit). The batched
  :class:`~runtime.pipeline.ChunkExtras` reduce over the batch — MIN labeled
  count, MAX active rounds — so the sweep runs until ALL experiments are done
  and routes through ``runtime.pipeline.run_pipelined`` UNCHANGED (pipelined
  dispatch, speculative chunks, async ys fetch all compose with batching).

- **Mesh composition.** Under a device mesh the batch axis is vmapped OUTSIDE
  the data-sharded pool: pool rows stay sharded over ``data``, masks shard as
  ``[E(replicated), data]``, and ``constrain_forest`` asserts each
  experiment's fitted forest placement inside the vmapped scan exactly as the
  serial chunk does (the pallas kernel's shard_map wrapper batches too).

Touchdowns unstack the ``[K, E, ...]`` ys into E independent
:class:`~runtime.results.ExperimentResult` s — the per-seed records feeding
``results.strategy_curves``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from distributed_active_learning_tpu.config import ExperimentConfig
from distributed_active_learning_tpu.runtime import state as state_lib
from distributed_active_learning_tpu.runtime.results import ExperimentResult
from distributed_active_learning_tpu.strategies import Strategy, StrategyAux, get_strategy


@struct.dataclass
class SweepState:
    """The per-experiment slice of E concurrent AL experiments.

    Exactly the fields of :class:`~runtime.state.PoolState` that differ
    between experiments sharing one pool — the chunk carry, donated
    launch-to-launch like the serial driver's state. Shared pool arrays
    (features, oracle labels, binned codes, test set) ride as separate
    un-batched arguments.
    """

    labeled_mask: jnp.ndarray  # [E, n] bool
    key: jax.Array             # [E] typed PRNG keys
    round: jnp.ndarray         # [E] int32

    @property
    def n_experiments(self) -> int:
        return self.labeled_mask.shape[0]


def _labeled_counts(mask: jnp.ndarray, n_valid_static: int) -> jnp.ndarray:
    """Per-experiment real-row labeled counts for a ``[E, n]`` mask batch."""
    if n_valid_static >= 0:
        valid = jnp.arange(mask.shape[1]) < n_valid_static
        mask = mask & valid[None, :]
    return jnp.sum(mask.astype(jnp.int32), axis=1)


def make_sweep_chunk_fn(
    strategy: Strategy,
    window_pad: int,
    chunk_size: int,
    fit_fn,
    label_cap: int,
    *,
    n_valid_static: int = -1,
    mesh=None,
    wrap_pallas: bool = False,
    with_metrics: bool = False,
    n_classes: int = 2,
    donate: bool = True,
):
    """Vmap the fused AL chunk over a leading experiment axis E.

    The body is the serial chunk's round (``runtime.loop.make_chunk_fn``):
    device fit keyed per experiment, padded-window round, accuracy eval,
    masked no-op freeze past each experiment's own stop — vmapped per scan
    step, so one ``lax.scan`` launch advances every experiment by
    ``chunk_size`` rounds. ``window_pad`` is the static selection width (the
    sweep's widest window); each experiment's own width rides in the traced
    ``windows`` vector.

    Returns ``sweep_chunk_fn(codes, x, oracle_y, sweep, seed_masks,
    lal_forest, fit_keys, windows, test_x, test_y, end_rounds) ->
    (new_sweep, extras, ys)`` where every y is stacked ``[chunk_size, E,
    ...]`` and ``extras`` is the batch-reduced
    :class:`~runtime.pipeline.ChunkExtras`: MIN post-chunk labeled count and
    MAX active-round count over experiments — ``>= label_cap`` / ``<
    chunk_size`` therefore mean ALL experiments are done, which is exactly the
    stop contract ``ChunkDriveControl``/``run_pipelined`` already implement,
    so the sweep drives through the pipelined dispatcher unchanged.

    ``donate`` donates the carried :class:`SweepState` buffers (the ``[E, n]``
    masks dominate); the driver copies ``seed_masks`` so the round-0 alias
    with the donated masks cannot dangle, exactly like the serial driver.
    """
    from distributed_active_learning_tpu.runtime.loop import (
        _accuracy,
        make_padded_round_fn,
    )

    round_fn = make_padded_round_fn(
        strategy, window_pad, with_metrics=with_metrics, n_classes=n_classes
    )

    @functools.partial(jax.jit, donate_argnums=(3,) if donate else ())
    def sweep_chunk_fn(
        codes: jnp.ndarray,
        x: jnp.ndarray,
        oracle_y: jnp.ndarray,
        sweep: SweepState,
        seed_masks: jnp.ndarray,
        lal_forest,
        fit_keys: jax.Array,
        windows: jnp.ndarray,
        test_x: jnp.ndarray,
        test_y: jnp.ndarray,
        end_rounds: jnp.ndarray,
    ):
        def body(carry: SweepState, _):
            def one(mask, key, rnd, seed_mask, fit_key, window, end_round):
                # Rebuild the experiment's PoolState view over the SHARED
                # pool arrays — same pytree the serial round consumes.
                state = state_lib.PoolState(
                    x=x, oracle_y=oracle_y, labeled_mask=mask, key=key,
                    round=rnd, n_valid_static=n_valid_static,
                )
                aux = StrategyAux(lal_forest=lal_forest, seed_mask=seed_mask)
                n_labeled = state_lib.labeled_count(state)
                active = (n_labeled < label_cap) & (rnd < end_round)
                forest = fit_fn(
                    codes, state, jax.random.fold_in(fit_key, rnd + 1)
                )
                if mesh is not None:
                    from distributed_active_learning_tpu.parallel import (
                        constrain_forest,
                    )

                    forest = constrain_forest(forest, mesh)
                    if wrap_pallas:
                        from distributed_active_learning_tpu.ops.trees_pallas import (
                            attach_mesh,
                        )

                        forest = attach_mesh(forest, mesh)
                if with_metrics:
                    new_state, picked, _, rm = round_fn(forest, state, aux, window)
                else:
                    new_state, picked, _ = round_fn(forest, state, aux, window)
                acc = _accuracy(forest, test_x, test_y)
                out = state_lib.select_state(active, new_state, state)
                ys = (rnd + 1, n_labeled, acc, picked, active)
                if with_metrics:
                    ys = ys + (rm,)
                return (out.labeled_mask, out.key, out.round), ys

            (m, k, r), ys = jax.vmap(one)(
                carry.labeled_mask, carry.key, carry.round,
                seed_masks, fit_keys, windows, end_rounds,
            )
            return SweepState(labeled_mask=m, key=k, round=r), ys

        out_sweep, ys = jax.lax.scan(body, sweep, None, length=chunk_size)
        from distributed_active_learning_tpu.runtime.pipeline import ChunkExtras

        counts = _labeled_counts(out_sweep.labeled_mask, n_valid_static)
        active_per_exp = jnp.sum(ys[4].astype(jnp.int32), axis=0)  # [E]
        extras = ChunkExtras(
            # min/max reductions so the scalar pair means "ALL experiments":
            # min labeled >= cap and max active < K only once every
            # experiment hit its own stop.
            n_labeled_after=jnp.min(counts),
            n_active=jnp.max(active_per_exp),
        )
        return out_sweep, extras, ys

    return sweep_chunk_fn


def _resolve_sweep_fit_budget(
    cfg: ExperimentConfig, n_pool: int, n_labeled_max: int, window_pad: int
) -> int:
    """Static fit-window capacity covering the WIDEST experiment in the batch
    (the twin of ``runtime.loop._resolve_fit_budget``; the fit program is
    shared by every experiment, so its capacity must cover the max)."""
    if cfg.forest.fit_budget is not None:
        return min(cfg.forest.fit_budget, n_pool)
    caps = [n_pool]
    if cfg.label_budget is not None:
        caps.append(cfg.label_budget + window_pad)
    if cfg.max_rounds is not None:
        caps.append(n_labeled_max + cfg.max_rounds * window_pad)
    return min(caps)


def _sweep_result_path(path: str, seed: int) -> str:
    """Per-seed results file: ``curve.txt`` -> ``curve_s3.txt``."""
    import os

    stem, ext = os.path.splitext(path)
    return f"{stem}_s{seed}{ext}"


def run_sweep(
    cfg: ExperimentConfig,
    seeds: Sequence[int],
    windows: Optional[Sequence[int]] = None,
    bundle=None,
    debugger=None,
    metrics=None,
) -> List[ExperimentResult]:
    """Run E = len(seeds) AL experiments over one shared pool as a single
    batched launch stream; returns one :class:`ExperimentResult` per seed.

    Per-seed records are bit-identical to running
    ``runtime.loop.run_experiment`` once per seed with the same config
    (``dataclasses.replace(cfg, seed=s)`` — and, when ``windows`` vary, the
    matching ``window_size``) PROVIDED the fit budget is pinned: the device
    fit's bootstrap draws depend on the fit window's static size, and the
    default budget derives from the window size, so heterogeneous-window
    parity needs an explicit ``ForestConfig.fit_budget``.

    Falls back to E serial ``run_experiment`` calls for configurations the
    batched chunk cannot express (host fit, or a Debugger demanding per-phase
    wall splits) — the sweep entry point always runs.

    ``windows`` (optional, per experiment) enables the padded-window path;
    default is ``cfg.strategy.window_size`` everywhere.
    ``cfg.stream_round_events`` is not supported by the batched chunk (a
    per-experiment ``jax.debug.callback`` stream under vmap would interleave
    E unordered streams) — round events still arrive per touchdown.
    Checkpointing writes
    ONE ``sweepstate_<round>.npz`` covering all experiments (donation-safe:
    the carry snapshot rides ``runtime.loop.ckpt_snapshot``), and a resumed
    sweep continues each experiment from its own frozen round.
    """
    from distributed_active_learning_tpu.data.datasets import get_dataset
    from distributed_active_learning_tpu.runtime import (
        pipeline as pipeline_lib,
        telemetry,
    )
    from distributed_active_learning_tpu.runtime.debugger import Debugger
    from distributed_active_learning_tpu.runtime.loop import (
        build_aux,
        ckpt_snapshot,
        make_device_fit,
        run_experiment,
    )

    seeds = [int(s) for s in seeds]
    E = len(seeds)
    if E == 0:
        raise ValueError("run_sweep needs at least one seed")
    windows = (
        [int(cfg.strategy.window_size)] * E
        if windows is None
        else [int(w) for w in windows]
    )
    if len(windows) != E:
        raise ValueError(f"{len(windows)} windows for {E} seeds")
    window_pad = max(windows)
    dbg = debugger or Debugger(enabled=False)

    def _serial_fallback():
        import os

        out = []
        for s, w in zip(seeds, windows):
            scfg = dataclasses.replace(
                cfg,
                seed=s,
                strategy=dataclasses.replace(cfg.strategy, window_size=w),
                results_path=(
                    _sweep_result_path(cfg.results_path, s)
                    if cfg.results_path else None
                ),
                # one checkpoint dir per seed: the seed is part of the
                # checkpoint identity, so a shared dir would make seed B's
                # restore trip over seed A's state and refuse to resume
                checkpoint_dir=(
                    os.path.join(cfg.checkpoint_dir, f"seed_{s}")
                    if cfg.checkpoint_dir else None
                ),
            )
            out.append(
                run_experiment(scfg, bundle=bundle, debugger=debugger,
                               metrics=metrics)
            )
        return out

    # The batched chunk needs the whole round device-resident, like the
    # serial chunked driver: host fit and per-phase debugging fall back to E
    # serial runs rather than fail (the sweep entry point always works).
    if cfg.forest.fit != "device" or getattr(dbg, "phase_detail", False):
        return _serial_fallback()

    if cfg.stream_round_events:
        # The batched chunk carries no in-scan stream callback, and silently
        # dropping the flag here while the serial fallback above honors it
        # would make the same config stream or not depending on fit mode.
        raise ValueError(
            "stream_round_events is not supported by the batched sweep "
            "chunk; per-round events still arrive at every touchdown via "
            "the MetricsWriter, or run the seeds serially"
        )

    if bundle is None:
        bundle = get_dataset(cfg.data)
    want_metrics = metrics is not None or cfg.collect_metrics

    test_x = jnp.asarray(bundle.test_x)
    test_y = jnp.asarray(bundle.test_y)
    host_x = np.ascontiguousarray(bundle.train_x, dtype=np.float32)
    host_y = np.asarray(bundle.train_y, dtype=np.int32)
    n_classes = max(int(host_y.max()) + 1, 2) if host_y.size else 2

    # Per-seed start states over ONE shared pool: exactly run_experiment's
    # init -> set_start_state sequence per seed, so masks/keys agree with the
    # serial runs bit-for-bit — but the pool arrays are placed once and
    # shared by reference (replace() keeps base.x/oracle_y), so E seeds cost
    # E bitmasks of device memory, not E pools.
    base = state_lib.init_pool_state(host_x, host_y, jax.random.key(seeds[0]))
    states = [
        state_lib.set_start_state(
            base.replace(key=jax.random.key(s)), cfg.n_start, n_classes=n_classes
        )
        for s in seeds
    ]

    mesh = None
    mesh_lib = None
    if cfg.mesh.data * cfg.mesh.model > 1:
        from distributed_active_learning_tpu.parallel import (
            make_mesh,
            mesh as mesh_lib,
        )

        if cfg.forest.n_trees % cfg.mesh.model:
            raise ValueError(
                f"n_trees={cfg.forest.n_trees} not divisible by mesh "
                f"model axis {cfg.mesh.model}"
            )
        mesh = make_mesh(data=cfg.mesh.data, model=cfg.mesh.model)
        # Pad the shared pool ONCE; the other experiments re-share the padded
        # arrays and pad only their own masks (padding rows read labeled, the
        # pad_for_sharding rule).
        padded0 = state_lib.pad_for_sharding(states[0], cfg.mesh.data)
        row_pad = padded0.n_pool - states[0].n_pool
        states = [padded0] + [
            padded0.replace(
                labeled_mask=jnp.pad(
                    st.labeled_mask, (0, row_pad), constant_values=True
                ),
                key=st.key,
                round=st.round,
            )
            for st in states[1:]
        ]
        test_x = mesh_lib.global_put(test_x, mesh, mesh_lib.replicated_spec())
        test_y = mesh_lib.global_put(test_y, mesh, mesh_lib.replicated_spec())

    n_valid_static = states[0].n_valid_static
    n_pool = states[0].n_valid
    x = states[0].x
    oracle_y = states[0].oracle_y
    masks0 = jnp.stack([st.labeled_mask for st in states])
    # The strategies' seed masks are the INITIAL start masks — captured here,
    # before a checkpoint restore advances masks0, exactly like the serial
    # driver builds aux from the pre-restore start state. (A copy, not a
    # view: at round 0 of a fresh run the carried masks alias these, and the
    # chunk donates its carry.)
    seed_masks = jnp.array(masks0, copy=True)
    keys0 = jnp.stack([st.key for st in states])
    rounds0 = jnp.stack([st.round for st in states])

    strategy = get_strategy(cfg.strategy)
    # Shared strategy aux: one LAL regressor for the whole batch; the
    # per-experiment seed masks ride batched (seed_masks above).
    lal_forest = build_aux(cfg, states[0]).lal_forest

    if metrics is not None:
        from distributed_active_learning_tpu.config import asdict as cfg_asdict

        metrics.meta(
            config=cfg_asdict(cfg),
            backend=jax.default_backend(),
            n_devices=jax.device_count(),
            process_count=jax.process_count(),
            sweep_seeds=seeds,
            sweep_windows=windows,
        )

    results = [ExperimentResult() for _ in range(E)]
    start_rounds = [0] * E

    ckpt_enabled = bool(cfg.checkpoint_dir and cfg.checkpoint_every)
    ckpt_fp = None
    key_impl = jax.random.key_impl(keys0)
    if ckpt_enabled:
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        ckpt_fp = ckpt_lib.sweep_fingerprint(cfg, seeds, windows)
        restored = ckpt_lib.restore_latest_sweep(
            cfg.checkpoint_dir, n_valid=n_pool, n_experiments=E,
            fingerprint=ckpt_fp,
        )
        if restored is not None:
            r_masks, r_keys, r_rounds, results = restored
            pad = masks0.shape[1] - r_masks.shape[1]
            if pad:
                # mesh padding rows read as labeled (pad_for_sharding rule)
                r_masks = np.pad(r_masks, ((0, 0), (0, pad)), constant_values=True)
            masks0 = jnp.asarray(r_masks)
            keys0 = jax.random.wrap_key_data(jnp.asarray(r_keys), impl=key_impl)
            rounds0 = jnp.asarray(r_rounds, dtype=jnp.int32)
            start_rounds = [int(r) for r in np.asarray(r_rounds)]
            dbg.debug(f"resumed sweep at rounds {start_rounds}")

    # Device fit shared by every experiment: one binning of the shared pool,
    # one fit program wide enough for the widest window.
    from distributed_active_learning_tpu.ops import trees_train

    binned = trees_train.make_bins(jnp.asarray(host_x), cfg.forest.max_bins)
    codes = binned.codes
    if states[0].n_pool > codes.shape[0]:
        codes = jnp.pad(codes, ((0, states[0].n_pool - codes.shape[0]), (0, 0)))
    counts0 = [int(c) for c in np.asarray(_labeled_counts(masks0, n_valid_static))]
    fit_budget = _resolve_sweep_fit_budget(cfg, n_pool, max(counts0), window_pad)
    device_fit = make_device_fit(cfg, binned.edges, fit_budget, n_classes)
    fit_keys = jnp.stack([jax.random.key(s + 0x5EED) for s in seeds])

    windows_dev = jnp.asarray(windows, dtype=jnp.int32)
    label_cap = n_pool if cfg.label_budget is None else min(cfg.label_budget, n_pool)
    end_rounds = jnp.asarray(
        [
            (sr + cfg.max_rounds) if cfg.max_rounds is not None
            else int(np.iinfo(np.int32).max)
            for sr in start_rounds
        ],
        dtype=jnp.int32,
    )

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = mesh_lib.global_put(x, mesh, mesh_lib.pool_spec())
        oracle_y = mesh_lib.global_put(oracle_y, mesh, mesh_lib.mask_spec())
        codes = mesh_lib.global_put(codes, mesh, mesh_lib.pool_spec())
        # batch axis OUTSIDE the data-sharded pool: E replicated, rows sharded
        batch_mask_spec = P(None, mesh_lib.AXIS_DATA)
        masks0 = jax.device_put(masks0, NamedSharding(mesh, batch_mask_spec))
        seed_masks = jax.device_put(seed_masks, NamedSharding(mesh, batch_mask_spec))
        rep = NamedSharding(mesh, P())
        keys0 = mesh_lib.global_put(keys0, mesh, mesh_lib.replicated_spec())
        fit_keys = mesh_lib.global_put(fit_keys, mesh, mesh_lib.replicated_spec())
        rounds0 = jax.device_put(rounds0, rep)
        windows_dev = jax.device_put(windows_dev, rep)
        end_rounds = jax.device_put(end_rounds, rep)

    K = max(int(cfg.rounds_per_launch or 1), 1)
    depth = max(int(getattr(cfg, "pipeline_depth", 1) or 1), 1)
    sweep_chunk = make_sweep_chunk_fn(
        strategy, window_pad, K, device_fit, label_cap,
        n_valid_static=n_valid_static,
        mesh=mesh,
        wrap_pallas=(mesh is not None and cfg.forest.kernel == "pallas"),
        with_metrics=want_metrics,
        n_classes=n_classes,
    )
    launches = telemetry.LaunchTracker(metrics, "sweep_chunk_scan", fn=sweep_chunk)

    # Host stop/veto arithmetic: ChunkDriveControl over the batch-reduced
    # scalars. The conservative lattice (MIN known count, MIN window) can only
    # under-veto, and MAX active per chunk counts the laggard experiment —
    # exactly the one max_rounds must bound.
    ctl = pipeline_lib.ChunkDriveControl(
        K, min(windows), label_cap, cfg.max_rounds,
        min(counts0), max(start_rounds),
    )

    if not ctl.already_done:
        # Whole-run fit-capacity guard, per experiment (the serial driver's
        # lattice projection, run for each (count, window) pair in the batch).
        worst = 0
        for c0, w in zip(counts0, windows):
            j_cap = -(-(label_cap - c0) // w) - 1
            if cfg.max_rounds is not None:
                j_cap = min(cfg.max_rounds - 1, j_cap)
            worst = max(worst, c0 + max(j_cap, 0) * w)
        if worst > fit_budget:
            raise ValueError(
                f"up to {worst} labeled rows would exceed the device fit "
                f"window ({fit_budget}); raise ForestConfig.fit_budget or "
                "lower label_budget/max_rounds"
            )

    sweep_state = SweepState(labeled_mask=masks0, key=keys0, round=rounds0)
    snapshots = pipeline_lib.CarrySnapshots(ckpt_snapshot)

    def dispatch(sw, idx):
        out = sweep_chunk(
            codes, x, oracle_y, sw, seed_masks, lal_forest, fit_keys,
            windows_dev, test_x, test_y, end_rounds,
        )
        if ckpt_enabled:
            new_sweep = out[0]
            snapshots.take(
                idx, new_sweep.labeled_mask, new_sweep.key, new_sweep.round
            )
        return out

    def touchdown(idx, _n_labeled_after, n_active, ys, _out, wall):
        snap = snapshots.pop(idx)
        if n_active == 0:
            return
        rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
        active_np = np.asarray(active_y)  # [K, E]
        rounds_np = np.asarray(rounds_y)
        labeled_np = np.asarray(labeled_y)
        acc_np = np.asarray(acc_y)
        total_active = int(active_np.sum())
        md = (
            telemetry.stacked_sweep_metrics_to_dicts(ys[5], active_np)
            if want_metrics
            else None
        )
        last_round = ctl.round_idx
        for e in range(E):
            act = active_np[:, e]
            if not act.any():
                continue
            r_e = rounds_np[act, e]
            l_e = labeled_np[act, e]
            a_e = acc_np[act, e]
            results[e].extend_from_arrays(
                r_e, l_e, n_pool - l_e, a_e,
                # wall attributed per experiment-round: the launch advanced
                # total_active rounds across the whole batch.
                total_time=wall / total_active,
                metrics=md[e] if md is not None else None,
            )
            last_round = max(last_round, int(r_e[-1]))
            if metrics is not None:
                for i in range(len(r_e)):
                    metrics.round(
                        exp=e,
                        seed=seeds[e],
                        round=int(r_e[i]),
                        n_labeled=int(l_e[i]),
                        accuracy=float(a_e[i]),
                        **(md[e][i] if md is not None else {}),
                    )
            if cfg.log_every and dbg.enabled:
                for r, nl, a in zip(r_e, l_e, a_e):
                    if int(r) % cfg.log_every == 0:
                        dbg.debug(
                            f"[seed {seeds[e]}] Iteration {int(r)} -- "
                            f"labeled={int(nl)} accu={float(a) * 100:.2f}"
                        )
        ctl.note_round(last_round)
        if metrics is not None:
            fetched = (
                active_y.nbytes + rounds_y.nbytes + labeled_y.nbytes
                + acc_y.nbytes
            )
            if want_metrics:
                fetched += telemetry.metrics_nbytes(ys[5])
            metrics.counter("host_transfer_bytes", int(fetched))
            mem = telemetry.device_memory_gauges()
            if mem:
                metrics.gauges(mem, allgather=True)
        if ckpt_enabled and ctl.checkpoint_due(cfg.checkpoint_every):
            from distributed_active_learning_tpu.runtime import (
                checkpoint as ckpt_lib,
            )

            s_masks, s_kd, s_rounds = snap
            ckpt_lib.save_sweep(
                cfg.checkpoint_dir, s_masks, s_kd, s_rounds, results,
                n_valid=n_pool, fingerprint=ckpt_fp,
            )
            ctl.checkpoint_done()

    if not ctl.already_done:
        pipeline_lib.run_pipelined(
            sweep_state,
            dispatch=dispatch,
            touchdown=touchdown,
            continue_after=ctl.continue_after,
            depth=depth,
            on_launch=launches.record,
            may_dispatch=ctl.may_dispatch,
            on_veto=lambda idx: launches.veto(idx, ctl.veto_reason(idx)),
        )

    if cfg.results_path:
        for s, res in zip(seeds, results):
            res.save(_sweep_result_path(cfg.results_path, s), fmt="reference")
    return results
