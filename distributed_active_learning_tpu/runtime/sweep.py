"""Batched experiment sweeps: one launch stream advances E experiments.

The paper's deliverable is never a single AL run — it is a grid of runs
(strategies x seeds x window sizes) averaged into learning curves, and the
LAL regressor's MC training set is itself hundreds of tiny simulated AL
experiments. PRs 2-4 made ONE experiment launch-efficient (scan fusion +
pipelined dispatch), but a sweep still paid E full serial drives. This module
closes that gap with the batched-simulation discipline (podracer-style
batched actors / EvoJAX-style vmapped populations, PAPERS.md): ``jax.vmap``
over a leading experiment axis of the existing chunk program.

Design:

- **One pool, E experiments.** The pool feature matrix (and its binned codes,
  test set, LAL regressor) is SHARED across the batch — only the per-
  experiment state (labeled mask, PRNG key, round counter: :class:`SweepState`)
  carries a leading ``[E]`` axis. A seed sweep therefore costs E bitmasks of
  extra memory, not E pools.

- **The chunk program is the unit of batching.** :func:`make_sweep_chunk_fn`
  vmaps the SAME round body the serial chunk driver runs (device fit —
  Poisson(1) bootstrap weights are partitioning-stable — scoring, masked
  top-k reveal, accuracy eval, RoundMetrics) inside the same ``lax.scan``:
  one jitted launch advances all E experiments by K rounds. Per-seed results
  are bit-identical to E serial runs (tests/test_sweep.py, CPU and the 4x2
  mesh): vmap is a compilation strategy here, never a semantic one.

- **Heterogeneous windows via padding + masked reveal.** Experiments may use
  different window sizes: selection runs at the sweep's widest window (one
  static top-k) and the reveal (plus every pick-derived metric) is masked to
  each experiment's own width (``runtime.loop.make_padded_round_fn``,
  ``state.reveal_masked``) — ``lax.top_k`` is sorted, so the first w of a
  top-W selection are exactly the top-w selection.

- **Stopping reduces to one scalar pair.** Experiments hit their budgets at
  different rounds; finished experiments continue as the chunk's existing
  masked no-ops (state frozen bit-for-bit). The batched
  :class:`~runtime.pipeline.ChunkExtras` reduce over the batch — MIN labeled
  count, MAX active rounds — so the sweep runs until ALL experiments are done
  and routes through ``runtime.pipeline.run_pipelined`` UNCHANGED (pipelined
  dispatch, speculative chunks, async ys fetch all compose with batching).

- **Mesh composition.** Under a device mesh the batch axis is vmapped OUTSIDE
  the data-sharded pool: pool rows stay sharded over ``data``, masks shard as
  ``[E(replicated), data]``, and ``constrain_forest`` asserts each
  experiment's fitted forest placement inside the vmapped scan exactly as the
  serial chunk does (the pallas kernel's shard_map wrapper batches too).

Touchdowns unstack the ``[K, E, ...]`` ys into E independent
:class:`~runtime.results.ExperimentResult` s — the per-seed records feeding
``results.strategy_curves``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from distributed_active_learning_tpu.config import ExperimentConfig
from distributed_active_learning_tpu.runtime import obs
from distributed_active_learning_tpu.runtime import state as state_lib
from distributed_active_learning_tpu.runtime.results import ExperimentResult
from distributed_active_learning_tpu.strategies import Strategy, StrategyAux, get_strategy


@struct.dataclass
class SweepState:
    """The per-experiment slice of E concurrent AL experiments.

    Exactly the fields of :class:`~runtime.state.PoolState` that differ
    between experiments sharing one pool — the chunk carry, donated
    launch-to-launch like the serial driver's state. Shared pool arrays
    (features, oracle labels, binned codes, test set) ride as separate
    un-batched arguments.
    """

    labeled_mask: jnp.ndarray  # [E, n] bool
    key: jax.Array             # [E] typed PRNG keys
    round: jnp.ndarray         # [E] int32

    @property
    def n_experiments(self) -> int:
        return self.labeled_mask.shape[0]


def _labeled_counts(mask: jnp.ndarray, n_valid_static: int) -> jnp.ndarray:
    """Per-experiment real-row labeled counts for a ``[E, n]`` mask batch."""
    if n_valid_static >= 0:
        valid = jnp.arange(mask.shape[1]) < n_valid_static
        mask = mask & valid[None, :]
    return jnp.sum(mask.astype(jnp.int32), axis=1)


def make_sweep_chunk_fn(
    strategy: Strategy,
    window_pad: int,
    chunk_size: int,
    fit_fn,
    label_cap: int,
    *,
    n_valid_static: int = -1,
    mesh=None,
    wrap_pallas: bool = False,
    with_metrics: bool = False,
    n_classes: int = 2,
    donate: bool = True,
):
    """Vmap the fused AL chunk over a leading experiment axis E.

    The body is the serial chunk's round (``runtime.loop.make_chunk_fn``):
    device fit keyed per experiment, padded-window round, accuracy eval,
    masked no-op freeze past each experiment's own stop — vmapped per scan
    step, so one ``lax.scan`` launch advances every experiment by
    ``chunk_size`` rounds. ``window_pad`` is the static selection width (the
    sweep's widest window); each experiment's own width rides in the traced
    ``windows`` vector.

    Returns ``sweep_chunk_fn(codes, x, oracle_y, sweep, seed_masks,
    lal_forest, fit_keys, windows, test_x, test_y, end_rounds) ->
    (new_sweep, extras, ys)`` where every y is stacked ``[chunk_size, E,
    ...]`` and ``extras`` is the batch-reduced
    :class:`~runtime.pipeline.ChunkExtras`: MIN post-chunk labeled count and
    MAX active-round count over experiments — ``>= label_cap`` / ``<
    chunk_size`` therefore mean ALL experiments are done, which is exactly the
    stop contract ``ChunkDriveControl``/``run_pipelined`` already implement,
    so the sweep drives through the pipelined dispatcher unchanged.

    ``donate`` donates the carried :class:`SweepState` buffers (the ``[E, n]``
    masks dominate); the driver copies ``seed_masks`` so the round-0 alias
    with the donated masks cannot dangle, exactly like the serial driver.
    """
    from distributed_active_learning_tpu.runtime.loop import (
        _accuracy,
        make_padded_round_fn,
    )

    round_fn = make_padded_round_fn(
        strategy, window_pad, with_metrics=with_metrics, n_classes=n_classes
    )

    @functools.partial(jax.jit, donate_argnums=(3,) if donate else ())
    def sweep_chunk_fn(
        codes: jnp.ndarray,
        x: jnp.ndarray,
        oracle_y: jnp.ndarray,
        sweep: SweepState,
        seed_masks: jnp.ndarray,
        lal_forest,
        fit_keys: jax.Array,
        windows: jnp.ndarray,
        test_x: jnp.ndarray,
        test_y: jnp.ndarray,
        end_rounds: jnp.ndarray,
    ):
        def body(carry: SweepState, _):
            def one(mask, key, rnd, seed_mask, fit_key, window, end_round):
                # Rebuild the experiment's PoolState view over the SHARED
                # pool arrays — same pytree the serial round consumes.
                state = state_lib.PoolState(
                    x=x, oracle_y=oracle_y, labeled_mask=mask, key=key,
                    round=rnd, n_valid_static=n_valid_static,
                )
                aux = StrategyAux(lal_forest=lal_forest, seed_mask=seed_mask)
                n_labeled = state_lib.labeled_count(state)
                active = (n_labeled < label_cap) & (rnd < end_round)
                forest = fit_fn(
                    codes, state, jax.random.fold_in(fit_key, rnd + 1)
                )
                if mesh is not None:
                    from distributed_active_learning_tpu.parallel import (
                        constrain_forest,
                    )

                    forest = constrain_forest(forest, mesh)
                    if wrap_pallas:
                        from distributed_active_learning_tpu.ops.trees_pallas import (
                            attach_mesh,
                        )

                        forest = attach_mesh(forest, mesh)
                if with_metrics:
                    new_state, picked, _, rm = round_fn(forest, state, aux, window)
                else:
                    new_state, picked, _ = round_fn(forest, state, aux, window)
                acc = _accuracy(forest, test_x, test_y)
                out = state_lib.select_state(active, new_state, state)
                ys = (rnd + 1, n_labeled, acc, picked, active)
                if with_metrics:
                    ys = ys + (rm,)
                return (out.labeled_mask, out.key, out.round), ys

            (m, k, r), ys = jax.vmap(one)(
                carry.labeled_mask, carry.key, carry.round,
                seed_masks, fit_keys, windows, end_rounds,
            )
            return SweepState(labeled_mask=m, key=k, round=r), ys

        out_sweep, ys = jax.lax.scan(body, sweep, None, length=chunk_size)
        from distributed_active_learning_tpu.runtime.pipeline import ChunkExtras

        counts = _labeled_counts(out_sweep.labeled_mask, n_valid_static)
        active_per_exp = jnp.sum(ys[4].astype(jnp.int32), axis=0)  # [E]
        extras = ChunkExtras(
            # min/max reductions so the scalar pair means "ALL experiments":
            # min labeled >= cap and max active < K only once every
            # experiment hit its own stop.
            n_labeled_after=jnp.min(counts),
            n_active=jnp.max(active_per_exp),
        )
        return out_sweep, extras, ys

    return sweep_chunk_fn


def _resolve_sweep_fit_budget(
    cfg: ExperimentConfig, n_pool: int, n_labeled_max: int, window_pad: int
) -> int:
    """Static fit-window capacity covering the WIDEST experiment in the batch
    (the twin of ``runtime.loop._resolve_fit_budget``; the fit program is
    shared by every experiment, so its capacity must cover the max)."""
    if cfg.forest.fit_budget is not None:
        return min(cfg.forest.fit_budget, n_pool)
    caps = [n_pool]
    if cfg.label_budget is not None:
        caps.append(cfg.label_budget + window_pad)
    if cfg.max_rounds is not None:
        caps.append(n_labeled_max + cfg.max_rounds * window_pad)
    return min(caps)


def _sweep_result_path(path: str, seed: int) -> str:
    """Per-seed results file: ``curve.txt`` -> ``curve_s3.txt``."""
    import os

    stem, ext = os.path.splitext(path)
    return f"{stem}_s{seed}{ext}"


def run_sweep(
    cfg: ExperimentConfig,
    seeds: Sequence[int],
    windows: Optional[Sequence[int]] = None,
    bundle=None,
    debugger=None,
    metrics=None,
) -> List[ExperimentResult]:
    """Run E = len(seeds) AL experiments over one shared pool as a single
    batched launch stream; returns one :class:`ExperimentResult` per seed.

    Per-seed records are bit-identical to running
    ``runtime.loop.run_experiment`` once per seed with the same config
    (``dataclasses.replace(cfg, seed=s)`` — and, when ``windows`` vary, the
    matching ``window_size``) PROVIDED the fit budget is pinned: the device
    fit's bootstrap draws depend on the fit window's static size, and the
    default budget derives from the window size, so heterogeneous-window
    parity needs an explicit ``ForestConfig.fit_budget``.

    Falls back to E serial ``run_experiment`` calls for configurations the
    batched chunk cannot express (host fit, or a Debugger demanding per-phase
    wall splits) — the sweep entry point always runs.

    ``windows`` (optional, per experiment) enables the padded-window path;
    default is ``cfg.strategy.window_size`` everywhere.
    ``cfg.stream_round_events`` is not supported by the batched chunk (a
    per-experiment ``jax.debug.callback`` stream under vmap would interleave
    E unordered streams) — round events still arrive per touchdown.
    Checkpointing writes
    ONE ``sweepstate_<round>.npz`` covering all experiments (donation-safe:
    the carry snapshot rides ``runtime.loop.ckpt_snapshot``), and a resumed
    sweep continues each experiment from its own frozen round.
    """
    from distributed_active_learning_tpu.data.datasets import get_dataset
    from distributed_active_learning_tpu.runtime import (
        pipeline as pipeline_lib,
        telemetry,
    )
    from distributed_active_learning_tpu.runtime.debugger import Debugger
    from distributed_active_learning_tpu.runtime.loop import (
        build_aux,
        ckpt_snapshot,
        make_device_fit,
        run_experiment,
    )

    seeds = [int(s) for s in seeds]
    E = len(seeds)
    if E == 0:
        raise ValueError("run_sweep needs at least one seed")
    windows = (
        [int(cfg.strategy.window_size)] * E
        if windows is None
        else [int(w) for w in windows]
    )
    if len(windows) != E:
        raise ValueError(f"{len(windows)} windows for {E} seeds")
    window_pad = max(windows)
    dbg = debugger or Debugger(enabled=False)

    def _serial_fallback():
        import os

        out = []
        for s, w in zip(seeds, windows):
            scfg = dataclasses.replace(
                cfg,
                seed=s,
                strategy=dataclasses.replace(cfg.strategy, window_size=w),
                results_path=(
                    _sweep_result_path(cfg.results_path, s)
                    if cfg.results_path else None
                ),
                # one checkpoint dir per seed: the seed is part of the
                # checkpoint identity, so a shared dir would make seed B's
                # restore trip over seed A's state and refuse to resume
                checkpoint_dir=(
                    os.path.join(cfg.checkpoint_dir, f"seed_{s}")
                    if cfg.checkpoint_dir else None
                ),
            )
            out.append(
                run_experiment(scfg, bundle=bundle, debugger=debugger,
                               metrics=metrics)
            )
        return out

    if getattr(cfg, "scenario", None) is not None and cfg.scenario.active:
        # Scenario runs sweep through the grid launcher (run_grid grew the
        # scenario axis; a seed sweep is its S=1 shape) — refusing here
        # instead of silently running the clean batched chunk keeps the
        # scenario contract loud. run.py routes --scenario --sweep-seeds
        # through run_grid for exactly this reason.
        raise ValueError(
            f"scenario {cfg.scenario.kind!r} is not wired into the batched "
            "seed sweep; run it as a grid axis (runtime.sweep.run_grid "
            "scenarios=..., or run.py --scenario with --sweep-seeds, which "
            "routes there)"
        )

    # The batched chunk needs the whole round device-resident, like the
    # serial chunked driver: host fit and per-phase debugging fall back to E
    # serial runs rather than fail (the sweep entry point always works).
    if cfg.forest.fit != "device" or getattr(dbg, "phase_detail", False):
        return _serial_fallback()

    if cfg.stream_round_events:
        # The batched chunk carries no in-scan stream callback, and silently
        # dropping the flag here while the serial fallback above honors it
        # would make the same config stream or not depending on fit mode.
        raise ValueError(
            "stream_round_events is not supported by the batched sweep "
            "chunk; per-round events still arrive at every touchdown via "
            "the MetricsWriter, or run the seeds serially"
        )

    if bundle is None:
        bundle = get_dataset(cfg.data)
    want_metrics = metrics is not None or cfg.collect_metrics

    test_x = jnp.asarray(bundle.test_x)
    test_y = jnp.asarray(bundle.test_y)
    host_x = np.ascontiguousarray(bundle.train_x, dtype=np.float32)
    host_y = np.asarray(bundle.train_y, dtype=np.int32)
    n_classes = max(int(host_y.max()) + 1, 2) if host_y.size else 2

    # Per-seed start states over ONE shared pool: exactly run_experiment's
    # init -> set_start_state sequence per seed, so masks/keys agree with the
    # serial runs bit-for-bit — but the pool arrays are placed once and
    # shared by reference (replace() keeps base.x/oracle_y), so E seeds cost
    # E bitmasks of device memory, not E pools.
    base = state_lib.init_pool_state(host_x, host_y, jax.random.key(seeds[0]))
    states = [
        state_lib.set_start_state(
            base.replace(key=jax.random.key(s)), cfg.n_start, n_classes=n_classes
        )
        for s in seeds
    ]

    mesh = None
    mesh_lib = None
    if cfg.mesh.data * cfg.mesh.model > 1:
        from distributed_active_learning_tpu.parallel import (
            make_mesh,
            mesh as mesh_lib,
        )

        if cfg.forest.n_trees % cfg.mesh.model:
            raise ValueError(
                f"n_trees={cfg.forest.n_trees} not divisible by mesh "
                f"model axis {cfg.mesh.model}"
            )
        mesh = make_mesh(data=cfg.mesh.data, model=cfg.mesh.model)
        # Pad the shared pool ONCE; the other experiments re-share the padded
        # arrays and pad only their own masks (padding rows read labeled, the
        # pad_for_sharding rule).
        padded0 = state_lib.pad_for_sharding(states[0], cfg.mesh.data)
        row_pad = padded0.n_pool - states[0].n_pool
        states = [padded0] + [
            padded0.replace(
                labeled_mask=jnp.pad(
                    st.labeled_mask, (0, row_pad), constant_values=True
                ),
                key=st.key,
                round=st.round,
            )
            for st in states[1:]
        ]
        test_x = mesh_lib.global_put(test_x, mesh, mesh_lib.replicated_spec())
        test_y = mesh_lib.global_put(test_y, mesh, mesh_lib.replicated_spec())

    n_valid_static = states[0].n_valid_static
    n_pool = states[0].n_valid
    x = states[0].x
    oracle_y = states[0].oracle_y
    masks0 = jnp.stack([st.labeled_mask for st in states])
    # The strategies' seed masks are the INITIAL start masks — captured here,
    # before a checkpoint restore advances masks0, exactly like the serial
    # driver builds aux from the pre-restore start state. (A copy, not a
    # view: at round 0 of a fresh run the carried masks alias these, and the
    # chunk donates its carry.)
    seed_masks = jnp.array(masks0, copy=True)
    keys0 = jnp.stack([st.key for st in states])
    rounds0 = jnp.stack([st.round for st in states])

    strategy = get_strategy(cfg.strategy)
    # Shared strategy aux: one LAL regressor for the whole batch; the
    # per-experiment seed masks ride batched (seed_masks above).
    lal_forest = build_aux(cfg, states[0]).lal_forest

    if metrics is not None:
        from distributed_active_learning_tpu.config import asdict as cfg_asdict

        metrics.meta(
            config=cfg_asdict(cfg),
            backend=jax.default_backend(),
            n_devices=jax.device_count(),
            process_count=jax.process_count(),
            sweep_seeds=seeds,
            sweep_windows=windows,
        )

    results = [ExperimentResult() for _ in range(E)]
    start_rounds = [0] * E

    ckpt_enabled = bool(cfg.checkpoint_dir and cfg.checkpoint_every)
    ckpt_fp = None
    key_impl = jax.random.key_impl(keys0)
    if ckpt_enabled:
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        ckpt_fp = ckpt_lib.sweep_fingerprint(cfg, seeds, windows)
        restored = ckpt_lib.restore_latest_sweep(
            cfg.checkpoint_dir, n_valid=n_pool, n_experiments=E,
            fingerprint=ckpt_fp,
        )
        if restored is not None:
            r_masks, r_keys, r_rounds, results = restored
            pad = masks0.shape[1] - r_masks.shape[1]
            if pad:
                # mesh padding rows read as labeled (pad_for_sharding rule)
                r_masks = np.pad(r_masks, ((0, 0), (0, pad)), constant_values=True)
            masks0 = jnp.asarray(r_masks)
            keys0 = jax.random.wrap_key_data(jnp.asarray(r_keys), impl=key_impl)
            rounds0 = jnp.asarray(r_rounds, dtype=jnp.int32)
            start_rounds = [int(r) for r in np.asarray(r_rounds)]
            dbg.debug(f"resumed sweep at rounds {start_rounds}")

    # Device fit shared by every experiment: one binning of the shared pool,
    # one fit program wide enough for the widest window.
    from distributed_active_learning_tpu.ops import trees_train

    binned = trees_train.make_bins(
        jnp.asarray(host_x), cfg.forest.max_bins, quantize=cfg.forest.quantize
    )
    codes = binned.codes
    if states[0].n_pool > codes.shape[0]:
        codes = jnp.pad(codes, ((0, states[0].n_pool - codes.shape[0]), (0, 0)))
    counts0 = [int(c) for c in np.asarray(_labeled_counts(masks0, n_valid_static))]
    fit_budget = _resolve_sweep_fit_budget(cfg, n_pool, max(counts0), window_pad)
    device_fit = make_device_fit(cfg, binned.edges, fit_budget, n_classes)
    fit_keys = jnp.stack([jax.random.key(s + 0x5EED) for s in seeds])

    windows_dev = jnp.asarray(windows, dtype=jnp.int32)
    label_cap = n_pool if cfg.label_budget is None else min(cfg.label_budget, n_pool)
    end_rounds = jnp.asarray(
        [
            (sr + cfg.max_rounds) if cfg.max_rounds is not None
            else int(np.iinfo(np.int32).max)
            for sr in start_rounds
        ],
        dtype=jnp.int32,
    )

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = mesh_lib.global_put(x, mesh, mesh_lib.pool_spec())
        oracle_y = mesh_lib.global_put(oracle_y, mesh, mesh_lib.mask_spec())
        codes = mesh_lib.global_put(codes, mesh, mesh_lib.pool_spec())
        # batch axis OUTSIDE the data-sharded pool: E replicated, rows sharded
        batch_mask_spec = P(None, mesh_lib.AXIS_DATA)
        masks0 = jax.device_put(masks0, NamedSharding(mesh, batch_mask_spec))
        seed_masks = jax.device_put(seed_masks, NamedSharding(mesh, batch_mask_spec))
        rep = NamedSharding(mesh, P())
        keys0 = mesh_lib.global_put(keys0, mesh, mesh_lib.replicated_spec())
        fit_keys = mesh_lib.global_put(fit_keys, mesh, mesh_lib.replicated_spec())
        rounds0 = jax.device_put(rounds0, rep)
        windows_dev = jax.device_put(windows_dev, rep)
        end_rounds = jax.device_put(end_rounds, rep)

    K = max(int(cfg.rounds_per_launch or 1), 1)
    depth = max(int(getattr(cfg, "pipeline_depth", 1) or 1), 1)
    sweep_chunk = make_sweep_chunk_fn(
        strategy, window_pad, K, device_fit, label_cap,
        n_valid_static=n_valid_static,
        mesh=mesh,
        wrap_pallas=(mesh is not None and cfg.forest.kernel == "pallas"),
        with_metrics=want_metrics,
        n_classes=n_classes,
    )
    launches = telemetry.LaunchTracker(metrics, "sweep_chunk_scan", fn=sweep_chunk)

    # Host stop/veto arithmetic: ChunkDriveControl over the batch-reduced
    # scalars. The conservative lattice (MIN known count, MIN window) can only
    # under-veto, and MAX active per chunk counts the laggard experiment —
    # exactly the one max_rounds must bound.
    ctl = pipeline_lib.ChunkDriveControl(
        K, min(windows), label_cap, cfg.max_rounds,
        min(counts0), max(start_rounds),
    )

    if not ctl.already_done:
        # Whole-run fit-capacity guard, per experiment (the serial driver's
        # lattice projection, run for each (count, window) pair in the batch).
        worst = 0
        for c0, w in zip(counts0, windows):
            j_cap = -(-(label_cap - c0) // w) - 1
            if cfg.max_rounds is not None:
                j_cap = min(cfg.max_rounds - 1, j_cap)
            worst = max(worst, c0 + max(j_cap, 0) * w)
        if worst > fit_budget:
            raise ValueError(
                f"up to {worst} labeled rows would exceed the device fit "
                f"window ({fit_budget}); raise ForestConfig.fit_budget or "
                "lower label_budget/max_rounds"
            )

    sweep_state = SweepState(labeled_mask=masks0, key=keys0, round=rounds0)
    snapshots = pipeline_lib.CarrySnapshots(ckpt_snapshot)

    def dispatch(sw, idx):
        out = sweep_chunk(
            codes, x, oracle_y, sw, seed_masks, lal_forest, fit_keys,
            windows_dev, test_x, test_y, end_rounds,
        )
        if ckpt_enabled:
            new_sweep = out[0]
            snapshots.take(
                idx, new_sweep.labeled_mask, new_sweep.key, new_sweep.round
            )
        return out

    def touchdown(idx, _n_labeled_after, n_active, ys, _out, wall):
        snap = snapshots.pop(idx)
        if n_active == 0:
            return
        rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
        active_np = np.asarray(active_y)  # [K, E]
        rounds_np = np.asarray(rounds_y)
        labeled_np = np.asarray(labeled_y)
        acc_np = np.asarray(acc_y)
        total_active = int(active_np.sum())
        md = (
            telemetry.stacked_sweep_metrics_to_dicts(ys[5], active_np)
            if want_metrics
            else None
        )
        last_round = ctl.round_idx
        for e in range(E):
            act = active_np[:, e]
            if not act.any():
                continue
            r_e = rounds_np[act, e]
            l_e = labeled_np[act, e]
            a_e = acc_np[act, e]
            results[e].extend_from_arrays(
                r_e, l_e, n_pool - l_e, a_e,
                # wall attributed per experiment-round: the launch advanced
                # total_active rounds across the whole batch.
                total_time=wall / total_active,
                metrics=md[e] if md is not None else None,
            )
            last_round = max(last_round, int(r_e[-1]))
            if metrics is not None:
                for i in range(len(r_e)):
                    metrics.round(
                        exp=e,
                        seed=seeds[e],
                        round=int(r_e[i]),
                        n_labeled=int(l_e[i]),
                        accuracy=float(a_e[i]),
                        **(md[e][i] if md is not None else {}),
                    )
            if cfg.log_every and dbg.enabled:
                for r, nl, a in zip(r_e, l_e, a_e):
                    if int(r) % cfg.log_every == 0:
                        dbg.debug(
                            f"[seed {seeds[e]}] Iteration {int(r)} -- "
                            f"labeled={int(nl)} accu={float(a) * 100:.2f}"
                        )
        ctl.note_round(last_round)
        if metrics is not None:
            fetched = (
                active_y.nbytes + rounds_y.nbytes + labeled_y.nbytes
                + acc_y.nbytes
            )
            if want_metrics:
                fetched += telemetry.metrics_nbytes(ys[5])
            metrics.counter("host_transfer_bytes", int(fetched))
            mem = telemetry.device_memory_gauges()
            if mem:
                metrics.gauges(mem, allgather=True)
        if ckpt_enabled and ctl.checkpoint_due(cfg.checkpoint_every):
            from distributed_active_learning_tpu.runtime import (
                checkpoint as ckpt_lib,
            )

            s_masks, s_kd, s_rounds = snap
            ckpt_lib.save_sweep(
                cfg.checkpoint_dir, s_masks, s_kd, s_rounds, results,
                n_valid=n_pool, fingerprint=ckpt_fp,
            )
            ctl.checkpoint_done()

    if not ctl.already_done:
        pipeline_lib.run_pipelined(
            sweep_state,
            dispatch=dispatch,
            touchdown=touchdown,
            continue_after=ctl.continue_after,
            depth=depth,
            on_launch=launches.record,
            may_dispatch=ctl.may_dispatch,
            on_veto=lambda idx: launches.veto(idx, ctl.veto_reason(idx)),
        )

    if cfg.results_path:
        for s, res in zip(seeds, results):
            res.save(_sweep_result_path(cfg.results_path, s), fmt="reference")
    return results


# ---------------------------------------------------------------------------
# The full paper grid: strategies x seeds x datasets as ONE launch stream
# ---------------------------------------------------------------------------
#
# run_sweep batches the seed axis of one (strategy, dataset) cell; the grid
# launcher below generalizes it to the reference paper's whole results matrix.
# Three ideas on top of the sweep machinery:
#
# - **Heterogeneous strategies group by scoring family.** Cells are laid out
#   strategy-major (cell c = g*D*E + d*E + e); each strategy group is then a
#   STATIC contiguous slice of the cell axis, so the scan body runs one
#   score + top-k program per group (its own direction and score function,
#   zero wasted scoring work) and concatenates the group outputs back in cell
#   order — the "masked merge" is a static concat, not a lax.switch over all
#   branches.
#
# - **The dataset axis is a second vmap, not a gather.** Pool arrays stack
#   per dataset ([D, n_pad, ...], padded to a common slab width); the round
#   body vmaps over D OUTSIDE the seed vmap, so each dataset's pool is shared
#   by its E seeds exactly like the sweep shares its single pool — no
#   per-cell pool copies. Heterogeneous pool widths ride PoolState's dynamic
#   ``n_filled`` watermark (the PR-7 slab mechanism): padding rows are
#   labeled=True sentinels AND excluded from fit gathers / counts / metrics
#   by the fill mask, so per-dataset statistics match unpadded serial runs.
#
# - **Stopping reduces to the worst remaining budget.** Cells own per-cell
#   label caps (min(label_budget, n_valid_d) differs per dataset), so the
#   batch-reduced stop scalar is ``-max_c(cap_c - count_c)`` — >= 0 exactly
#   when EVERY cell reached its cap. ChunkDriveControl runs unchanged with
#   ``label_cap=0`` and ``n_known=-max_remaining``; its veto lattice stays
#   safe (min-window steps under-estimate every cell's progress).


@dataclasses.dataclass
class GridCell:
    """One (strategy, dataset, seed[, scenario]) cell of a grid run."""

    strategy: str
    dataset: str
    seed: int
    window: int
    scenario: str = "none"
    result: ExperimentResult = dataclasses.field(default_factory=ExperimentResult)


@dataclasses.dataclass
class GridResult:
    """All cells of one grid launch stream, in cell order (strategy-major,
    then dataset, then seed), plus the launch accounting the acceptance
    gates key on (``recompiles_after_warmup == 0`` after the first grid
    launch)."""

    cells: List[GridCell]
    launches: int = 0
    recompiles_after_warmup: int = 0
    serial_fallback: bool = False

    def cell(
        self,
        strategy: str,
        dataset: str,
        seed: int,
        scenario: Optional[str] = None,
    ) -> GridCell:
        for c in self.cells:
            if (c.strategy, c.dataset, c.seed) == (strategy, dataset, int(seed)):
                if scenario is None or c.scenario == scenario:
                    return c
        raise KeyError(f"no grid cell ({strategy}, {dataset}, {seed}, {scenario})")

    def results_for(
        self,
        strategy: str,
        dataset: Optional[str] = None,
        scenario: Optional[str] = None,
    ):
        """Per-seed results of one strategy (optionally one dataset /
        scenario) in seed order — the input shape
        ``results.strategy_curves`` stacks."""
        return [
            c.result
            for c in self.cells
            if c.strategy == strategy
            and (dataset is None or c.dataset == dataset)
            and (scenario is None or c.scenario == scenario)
        ]


def _grid_result_path(
    path: str,
    strategy: str,
    dataset: str,
    seed: int,
    with_dataset: bool,
    scenario: str = "none",
    with_scenario: bool = False,
) -> str:
    """Per-cell results file: ``curve.txt`` -> ``curve_margin_s3.txt`` (plus
    the dataset name once the grid has a dataset axis, and the scenario name
    once it has a scenario axis)."""
    import os

    stem, ext = os.path.splitext(path)
    ds = f"_{dataset}" if with_dataset else ""
    sc = f"_{scenario}" if with_scenario else ""
    return f"{stem}_{strategy}{ds}{sc}_s{seed}{ext}"


def _grid_counts(mask: jnp.ndarray, n_valids_cell: jnp.ndarray) -> jnp.ndarray:
    """Per-cell real-row labeled counts for a ``[C, n]`` mask batch with
    per-cell valid widths (padding rows are labeled=True sentinels)."""
    valid = jnp.arange(mask.shape[1])[None, :] < n_valids_cell[:, None]
    return jnp.sum((mask & valid).astype(jnp.int32), axis=1)


def make_grid_chunk_fn(
    strategies: Sequence[Strategy],
    window_pad: int,
    chunk_size: int,
    fit_fn,
    *,
    n_datasets: int,
    n_seeds: int,
    static_n_valid: int = -1,
    use_fill: bool = False,
    use_test_fill: bool = False,
    mesh=None,
    wrap_pallas: bool = False,
    with_metrics: bool = False,
    n_classes: int = 2,
    donate: bool = True,
    scenarios=None,
):
    """One jitted launch advancing the whole S x D x E grid by ``chunk_size``
    rounds.

    ``strategies`` is one :class:`~strategies.Strategy` per group in cell
    order; ``fit_fn`` is the edges-as-argument device fit
    (``runtime.loop.make_grid_device_fit``). Cell layout is strategy-major
    (``c = g*D*E + d*E + e``): per scan step each group runs its OWN padded
    round program (score family, selection direction, top-k at the grid's
    widest window) over a ``vmap(datasets) o vmap(seeds)`` nest sharing the
    stacked pool arrays, and the group outputs concatenate back in cell
    order. ``use_fill`` routes heterogeneous pool widths through PoolState's
    dynamic ``n_filled`` watermark; ``use_test_fill`` masks the accuracy
    pass to each dataset's real test rows.

    Returns ``grid_chunk_fn(codes, x, oracle_y, grid, seed_masks,
    lal_forests, fit_keys, windows, test_x, test_y, end_rounds, label_caps,
    edges, n_valids, test_ns) -> (new_grid, extras, ys)`` with every y
    stacked ``[chunk_size, C, ...]``. ``extras.n_labeled_after`` is
    ``-max_c(label_cap_c - count_c)`` (>= 0 iff every cell hit its cap) and
    ``extras.n_active`` the max active-round count — the exact scalar pair
    ``ChunkDriveControl(label_cap=0, n_known=-max_remaining)`` drives
    through ``run_pipelined`` unchanged.

    ``scenarios`` (one :class:`~config.ScenarioConfig` or None per group,
    aligned with ``strategies``) is the scenario engine's grid spelling:
    each group's round runs ITS OWN scenario body (noisy reveal / knapsack
    select / drifted eval / rare metric — static per group, so inactive
    groups trace the clean body), the chunk signature gains per-cell label
    FLIP masks and per-dataset COST vectors as runtime inputs, the
    accuracy pass moves into the group loop (drift transforms the test
    batch per group AND per round, so the shared pass cannot serve it),
    and scenario metrics emit UNIFORMLY across groups (one ys pytree;
    run_grid filters per cell at touchdown). ``scenarios=None`` keeps the
    pre-scenario signature and traced program byte-for-byte.
    """
    from distributed_active_learning_tpu.runtime.loop import (
        _accuracy,
        _accuracy_masked,
        make_padded_round_fn,
    )

    G, D, E = len(strategies), n_datasets, n_seeds
    DE = D * E
    C_ = G * DE
    scn_on = scenarios is not None
    if scn_on:
        if len(scenarios) != G:
            raise ValueError(f"{len(scenarios)} scenarios for {G} strategy groups")
        from distributed_active_learning_tpu.scenarios import engine as scn_engine

        emit_rare = any(
            s is not None and s.kind == "rare_event" for s in scenarios
        )
        emit_cost = any(
            s is not None and s.kind == "cost_budget" for s in scenarios
        )
        round_fns = [
            make_padded_round_fn(
                s, window_pad, with_metrics=with_metrics, n_classes=n_classes,
                scenario=scenarios[i], emit_rare=emit_rare, emit_cost=emit_cost,
            )
            for i, s in enumerate(strategies)
        ]
    else:
        round_fns = [
            make_padded_round_fn(
                s, window_pad, with_metrics=with_metrics, n_classes=n_classes
            )
            for s in strategies
        ]

    def grid_body(
        codes: jnp.ndarray,      # [D, n, f] per-dataset bin codes
        x: jnp.ndarray,          # [D, n, d] stacked pools
        oracle_y: jnp.ndarray,   # [D, n]
        grid: SweepState,        # [C, ...] donated carry
        seed_masks: jnp.ndarray, # [C, n]
        lal_forests,             # tuple, one (or None) per strategy group
        fit_keys: jax.Array,     # [C]
        windows: jnp.ndarray,    # [C]
        test_x: jnp.ndarray,     # [D, t, d]
        test_y: jnp.ndarray,     # [D, t]
        end_rounds: jnp.ndarray, # [C]
        label_caps: jnp.ndarray, # [C]
        edges: jnp.ndarray,      # [D, d, bins-1]
        n_valids: jnp.ndarray,   # [D] real pool rows per dataset
        test_ns: jnp.ndarray,    # [D] real test rows per dataset
        flip_masks=None,         # [C, n] bool per-cell label flips (scenario)
        costs_ds=None,           # [D, n] f32 per-point label costs (scenario)
    ):
        # Cell-axis <-> dataset-major reshapes for the strategy-independent
        # passes: cells are strategy-major ([G, D, E] in cell order), but the
        # fit and accuracy programs batch most cheaply with the dataset axis
        # leading ([D, G*E]) so ONE program instance serves every group —
        # the strategy loop below then pays only its score/select body.
        def to_dm(leaf):
            l = leaf.reshape((G, D, E) + leaf.shape[1:])
            return jnp.moveaxis(l, 1, 0).reshape((D, G * E) + leaf.shape[1:])

        def from_dm(leaf):
            l = leaf.reshape((D, G, E) + leaf.shape[2:])
            return jnp.moveaxis(l, 0, 1).reshape((C_,) + leaf.shape[2:])

        def body(carry: SweepState, _):
            def fit_one(x_d, oy_d, codes_d, edges_d, nv_d, mask, key, rnd,
                        fit_key, flip=None):
                # The cell's PoolState view over its dataset's shared
                # (stacked) pool arrays — same pytree the serial fit
                # consumes; heterogeneous widths ride n_filled. A scenario
                # grid's per-cell flip mask corrupts the oracle view here
                # (never the stored labels), matching the serial driver's
                # setup-time flip bit-for-bit (all-False rows select every
                # original element).
                if flip is not None:
                    oy_d = scn_engine.apply_flips(oy_d, flip, n_classes)
                state = state_lib.PoolState(
                    x=x_d, oracle_y=oy_d, labeled_mask=mask, key=key,
                    round=rnd, n_valid_static=static_n_valid,
                    n_filled=nv_d if use_fill else None,
                )
                forest = fit_fn(
                    codes_d, edges_d, state,
                    jax.random.fold_in(fit_key, rnd + 1),
                )
                if mesh is not None:
                    from distributed_active_learning_tpu.parallel import (
                        constrain_forest,
                    )

                    forest = constrain_forest(forest, mesh)
                    if wrap_pallas:
                        from distributed_active_learning_tpu.ops.trees_pallas import (  # noqa: E501
                            attach_mesh,
                        )

                        forest = attach_mesh(forest, mesh)
                return forest

            def acc_one(tx_d, ty_d, tn_d, forest):
                if use_test_fill:
                    return _accuracy_masked(forest, tx_d, ty_d, tn_d)
                return _accuracy(forest, tx_d, ty_d)

            if D == 1:
                # Single-dataset grids (the headline S x E shape) drop the
                # dataset vmap entirely: pool args are static [0] slices
                # shared by one cell-axis vmap — the sweep's exact batching
                # shape, and a materially smaller compile than the nested
                # form.
                fit_args = (carry.labeled_mask, carry.key, carry.round, fit_keys)
                if scn_on:
                    fit_args = fit_args + (flip_masks,)
                forests = jax.vmap(
                    functools.partial(
                        fit_one, x[0], oracle_y[0], codes[0], edges[0],
                        n_valids[0],
                    )
                )(*fit_args)
                if not scn_on:
                    accs = jax.vmap(
                        functools.partial(
                            acc_one, test_x[0], test_y[0], test_ns[0]
                        )
                    )(forests)
            else:
                n_fit = 5 if scn_on else 4
                fit_args = (
                    x, oracle_y, codes, edges, n_valids,
                    to_dm(carry.labeled_mask), to_dm(carry.key),
                    to_dm(carry.round), to_dm(fit_keys),
                )
                if scn_on:
                    fit_args = fit_args + (to_dm(flip_masks),)
                forests = jax.vmap(
                    jax.vmap(fit_one, in_axes=(None,) * 5 + (0,) * n_fit),
                    in_axes=(0,) * (9 if not scn_on else 10),
                )(*fit_args)
                if not scn_on:
                    accs = jax.vmap(
                        jax.vmap(acc_one, in_axes=(None,) * 3 + (0,)),
                        in_axes=(0,) * 4,
                    )(test_x, test_y, test_ns, forests)
                    accs = from_dm(accs)
                forests = jax.tree.map(from_dm, forests)

            group_states, group_ys = [], []
            for g in range(G):
                sl = slice(g * DE, (g + 1) * DE)
                round_fn = round_fns[g]
                lal_forest = lal_forests[g]
                scn_g = scenarios[g] if scn_on else None
                g_cost = scn_g is not None and scn_g.kind == "cost_budget"
                g_drift = (
                    scn_g is not None and scn_g.kind == "drift"
                    and scn_g.drift_rate > 0.0
                )

                def one(
                    x_d, oy_d, nv_d, forest, acc, mask, key, rnd, seed_mask,
                    window, end_round, cap,
                    _round_fn=round_fn, _lal=lal_forest,
                ):
                    state = state_lib.PoolState(
                        x=x_d, oracle_y=oy_d, labeled_mask=mask, key=key,
                        round=rnd, n_valid_static=static_n_valid,
                        n_filled=nv_d if use_fill else None,
                    )
                    aux = StrategyAux(lal_forest=_lal, seed_mask=seed_mask)
                    n_labeled = state_lib.labeled_count(state)
                    active = (n_labeled < cap) & (rnd < end_round)
                    if with_metrics:
                        new_state, picked, _, rm = _round_fn(
                            forest, state, aux, window
                        )
                    else:
                        new_state, picked, _ = _round_fn(forest, state, aux, window)
                    out = state_lib.select_state(active, new_state, state)
                    ys = (rnd + 1, n_labeled, acc, picked, active)
                    if with_metrics:
                        ys = ys + (rm,)
                    return (out.labeled_mask, out.key, out.round), ys

                def one_scn(
                    x_d, oy_d, nv_d, tx_d, ty_d, tn_d, cost_d,
                    forest, mask, key, rnd, seed_mask,
                    window, end_round, cap, flip,
                    _round_fn=round_fn, _lal=lal_forest, _scn=scn_g,
                    _g_cost=g_cost, _g_drift=g_drift,
                ):
                    # The scenario group's round: flipped oracle view (the
                    # fit above used the same view), the group's own round
                    # body (knapsack/abstain live inside _round_fn), and a
                    # per-round drifted eval — accuracy computed HERE, not
                    # in a shared pass, because drift is per (group, round).
                    oy_v = scn_engine.apply_flips(oy_d, flip, n_classes)
                    state = state_lib.PoolState(
                        x=x_d, oracle_y=oy_v, labeled_mask=mask, key=key,
                        round=rnd, n_valid_static=static_n_valid,
                        n_filled=nv_d if use_fill else None,
                    )
                    aux = StrategyAux(lal_forest=_lal, seed_mask=seed_mask)
                    n_labeled = state_lib.labeled_count(state)
                    active = (n_labeled < cap) & (rnd < end_round)
                    round_args = (forest, state, aux, window) + (
                        (cost_d,) if _g_cost else ()
                    )
                    if with_metrics:
                        new_state, picked, _, rm = _round_fn(*round_args)
                    else:
                        new_state, picked, _ = _round_fn(*round_args)
                    eval_x = (
                        scn_engine.drift_apply(_scn, tx_d, rnd)
                        if _g_drift else tx_d
                    )
                    if use_test_fill:
                        acc = _accuracy_masked(forest, eval_x, ty_d, tn_d)
                    else:
                        acc = _accuracy(forest, eval_x, ty_d)
                    out = state_lib.select_state(active, new_state, state)
                    ys = (rnd + 1, n_labeled, acc, picked, active)
                    if with_metrics:
                        ys = ys + (rm,)
                    return (out.labeled_mask, out.key, out.round), ys

                if D == 1:
                    g_forest = jax.tree.map(lambda l: l[sl], forests)
                    if scn_on:
                        per_cell = jax.vmap(
                            functools.partial(
                                one_scn, x[0], oracle_y[0], n_valids[0],
                                test_x[0], test_y[0], test_ns[0], costs_ds[0],
                            )
                        )
                        (m, k, r), ys = per_cell(
                            g_forest, carry.labeled_mask[sl],
                            carry.key[sl], carry.round[sl], seed_masks[sl],
                            windows[sl], end_rounds[sl], label_caps[sl],
                            flip_masks[sl],
                        )
                    else:
                        per_cell = jax.vmap(
                            functools.partial(
                                one, x[0], oracle_y[0], n_valids[0],
                            )
                        )
                        (m, k, r), ys = per_cell(
                            g_forest, accs[sl], carry.labeled_mask[sl],
                            carry.key[sl], carry.round[sl], seed_masks[sl],
                            windows[sl], end_rounds[sl], label_caps[sl],
                        )
                    group_states.append((m, k, r))
                    group_ys.append(ys)
                    continue

                def cell(leaf):
                    # group slice of a [C, ...] cell-axis leaf -> [D, E, ...]
                    part = leaf[sl]
                    return part.reshape((D, E) + part.shape[1:])

                # inner vmap: seeds share their dataset's pool (broadcast);
                # outer vmap: the dataset axis batches the stacked pools.
                if scn_on:
                    per_cell = jax.vmap(
                        jax.vmap(one_scn, in_axes=(None,) * 7 + (0,) * 9),
                        in_axes=(0,) * 16,
                    )
                    (m, k, r), ys = per_cell(
                        x, oracle_y, n_valids, test_x, test_y, test_ns,
                        costs_ds,
                        jax.tree.map(cell, forests),
                        cell(carry.labeled_mask), cell(carry.key),
                        cell(carry.round), cell(seed_masks),
                        cell(windows), cell(end_rounds), cell(label_caps),
                        cell(flip_masks),
                    )
                else:
                    per_cell = jax.vmap(
                        jax.vmap(one, in_axes=(None,) * 3 + (0,) * 9),
                        in_axes=(0,) * 12,
                    )
                    (m, k, r), ys = per_cell(
                        x, oracle_y, n_valids,
                        jax.tree.map(cell, forests), cell(accs),
                        cell(carry.labeled_mask), cell(carry.key),
                        cell(carry.round), cell(seed_masks),
                        cell(windows), cell(end_rounds), cell(label_caps),
                    )

                def flat(leaf):
                    return leaf.reshape((DE,) + leaf.shape[2:])

                group_states.append((flat(m), flat(k), flat(r)))
                group_ys.append(jax.tree.map(flat, ys))
            merge = lambda *ls: jnp.concatenate(ls, axis=0)  # noqa: E731
            m = merge(*(s[0] for s in group_states))
            k = merge(*(s[1] for s in group_states))
            r = merge(*(s[2] for s in group_states))
            ys = jax.tree.map(merge, *group_ys)
            return SweepState(labeled_mask=m, key=k, round=r), ys

        out_grid, ys = jax.lax.scan(body, grid, None, length=chunk_size)
        from distributed_active_learning_tpu.runtime.pipeline import ChunkExtras

        n_valids_cell = jnp.tile(jnp.repeat(n_valids, E), G)
        counts = _grid_counts(out_grid.labeled_mask, n_valids_cell)
        remaining = label_caps - counts
        active_per_cell = jnp.sum(ys[4].astype(jnp.int32), axis=0)  # [C]
        extras = ChunkExtras(
            # -max remaining budget: >= 0 means EVERY cell hit its own cap;
            # max active counts the laggard cell — the pair ChunkDriveControl
            # consumes with label_cap=0.
            n_labeled_after=-jnp.max(remaining),
            n_active=jnp.max(active_per_cell),
        )
        return out_grid, extras, ys

    if scn_on:
        @functools.partial(jax.jit, donate_argnums=(3,) if donate else ())
        def grid_chunk_fn(
            codes, x, oracle_y, grid, seed_masks, lal_forests, fit_keys,
            windows, test_x, test_y, end_rounds, label_caps, edges,
            n_valids, test_ns, flip_masks, costs_ds,
        ):
            return grid_body(
                codes, x, oracle_y, grid, seed_masks, lal_forests, fit_keys,
                windows, test_x, test_y, end_rounds, label_caps, edges,
                n_valids, test_ns, flip_masks=flip_masks, costs_ds=costs_ds,
            )
    else:
        @functools.partial(jax.jit, donate_argnums=(3,) if donate else ())
        def grid_chunk_fn(
            codes, x, oracle_y, grid, seed_masks, lal_forests, fit_keys,
            windows, test_x, test_y, end_rounds, label_caps, edges,
            n_valids, test_ns,
        ):
            return grid_body(
                codes, x, oracle_y, grid, seed_masks, lal_forests, fit_keys,
                windows, test_x, test_y, end_rounds, label_caps, edges,
                n_valids, test_ns,
            )

    return grid_chunk_fn


def run_grid(
    cfg: ExperimentConfig,
    strategies: Sequence[str],
    seeds: Sequence[int],
    datasets: Optional[Sequence[str]] = None,
    windows: Optional[Sequence[int]] = None,
    scenarios=None,
    bundles=None,
    debugger=None,
    metrics=None,
) -> GridResult:
    """Run the full strategies x seeds x datasets grid as ONE pipelined
    launch stream; returns a :class:`GridResult` with one
    :class:`ExperimentResult` per cell.

    Per-cell records are bit-identical to running
    ``runtime.loop.run_experiment`` once per cell (strategy + dataset + seed
    substituted into ``cfg``) PROVIDED the fit budget is pinned
    (``ForestConfig.fit_budget`` — the bootstrap draw depends on the fit
    window's static size, exactly the :func:`run_sweep` caveat) and, for
    grids whose datasets differ in pool size, the strategy draws no
    per-row randomness (``random``'s uniform vector is shaped by the padded
    slab, so unequal-width grids reproduce it only distribution-wise).

    ``windows`` is per STRATEGY (one reveal width per strategy group,
    default ``cfg.strategy.window_size`` everywhere); selection runs at the
    grid's widest window and reveals mask down, the sweep discipline.
    ``bundles`` (optional) maps dataset name -> :class:`DataBundle` to skip
    registry loads (bench mode). Falls back to the serial S x E x D loop for
    configurations the batched chunk cannot express (host fit, per-phase
    debugging, datasets disagreeing on feature width or class count).
    Checkpoints write ONE ``gridstate_<round>.npz`` covering every cell
    (``checkpoint.save_grid`` / ``grid_fingerprint`` — the sweep format
    extended with the strategy/dataset axes).
    """
    from distributed_active_learning_tpu.data.datasets import get_dataset
    from distributed_active_learning_tpu.runtime import (
        pipeline as pipeline_lib,
        telemetry,
    )
    from distributed_active_learning_tpu.runtime.debugger import Debugger
    from distributed_active_learning_tpu.runtime.loop import (
        ckpt_snapshot,
        make_grid_device_fit,
        run_experiment,
    )

    strategies = [str(s) for s in strategies]
    seeds = [int(s) for s in seeds]
    datasets = (
        [cfg.data.name] if datasets is None else [str(d) for d in datasets]
    )
    S, E, D = len(strategies), len(seeds), len(datasets)
    if S == 0 or E == 0 or D == 0:
        raise ValueError("run_grid needs at least one strategy, seed, and dataset")
    if windows is None:
        windows = [int(cfg.strategy.window_size)] * S
    else:
        windows = [int(w) for w in windows]
    if len(windows) != S:
        raise ValueError(f"{len(windows)} windows for {S} strategies")
    window_pad = max(windows)
    dbg = debugger or Debugger(enabled=False)

    # --- the scenario axis (scenarios/) -------------------------------------
    # Normalized to either None (the clean grid — the pre-scenario path,
    # byte-identical programs) or a list of ScenarioConfigs crossed with the
    # strategy axis into scenario-major groups. A lone inactive entry (or a
    # cfg.scenario of kind "none") IS the clean grid, so `--scenarios none`
    # launches exactly today's program — the scenario-disabled parity pin.
    from distributed_active_learning_tpu.config import ScenarioConfig

    if scenarios is None:
        base_scn = getattr(cfg, "scenario", None)
        if base_scn is not None and base_scn.active:
            scenarios = [base_scn]
    scn_list = None
    if scenarios is not None:
        scn_list = [
            s if isinstance(s, ScenarioConfig) else ScenarioConfig(kind=str(s))
            for s in scenarios
        ]
        if not scn_list:
            raise ValueError("run_grid scenarios axis must not be empty")
        kinds = [s.kind for s in scn_list]
        if len(set(kinds)) != len(kinds):
            raise ValueError(f"duplicate scenario kinds in grid axis: {kinds}")
        if not any(s.active for s in scn_list):
            scn_list = None  # all-none axis == the clean grid
    scenario_axis = scn_list is not None
    base_strategies, base_windows = list(strategies), list(windows)
    group_scns = None
    if scenario_axis:
        from distributed_active_learning_tpu.scenarios import engine as scn_engine

        if cfg.forest.fit != "device":
            raise ValueError(
                "scenario grid axes run inside the jitted round and need "
                "the device fit; use --fit device"
            )
        if cfg.mesh.data * cfg.mesh.model > 1:
            raise ValueError(
                "scenario grid axes are single-device for now (the sharded "
                "scenario round rides the pod-sharding ROADMAP item)"
            )
        # scenario-major groups: cells order (scenario, strategy, dataset,
        # seed) — one launch produces the scenario x strategy x seed table.
        strategies = [st for _ in scn_list for st in base_strategies]
        windows = [w for _ in scn_list for w in base_windows]
        group_scns = [s for s in scn_list for _ in base_strategies]
        S = len(strategies)

    def _group_scn(gi: int):
        return group_scns[gi] if group_scns is not None else None

    def _cell_cfg(strat, ds, seed, window, scn=None):
        import os

        sc_tag = f"_{scn.kind}" if scn is not None and scn.active else ""
        return dataclasses.replace(
            cfg,
            seed=seed,
            data=dataclasses.replace(cfg.data, name=ds),
            strategy=dataclasses.replace(
                cfg.strategy, name=strat, window_size=window
            ),
            scenario=scn if scn is not None else ScenarioConfig(),
            results_path=(
                _grid_result_path(
                    cfg.results_path, strat, ds, seed, D > 1,
                    scenario=scn.kind if scn is not None else "none",
                    with_scenario=scenario_axis,
                )
                if cfg.results_path else None
            ),
            checkpoint_dir=(
                os.path.join(
                    cfg.checkpoint_dir, f"{strat}_{ds}{sc_tag}_seed_{seed}"
                )
                if cfg.checkpoint_dir else None
            ),
        )

    def _cells():
        return [
            GridCell(
                strategy=s, dataset=d, seed=e, window=w,
                scenario=(
                    group_scns[gi].kind if group_scns is not None else "none"
                ),
            )
            for gi, (s, w) in enumerate(zip(strategies, windows))
            for d in datasets
            for e in seeds
        ]

    _bundle_cache = {}

    def _bundle(name):
        # memoized per dataset: the serial fallback asks once per CELL, and a
        # file-backed dataset would otherwise be re-read S*E times
        if bundles is not None and name in bundles:
            return bundles[name]
        if name not in _bundle_cache:
            _bundle_cache[name] = get_dataset(
                dataclasses.replace(cfg.data, name=name)
            )
        return _bundle_cache[name]

    _scn_by_kind = (
        {s.kind: s for s in scn_list} if scn_list is not None else {}
    )

    def _serial_fallback(reason):
        dbg.debug(f"grid launcher falling back to serial cells: {reason}")
        cells = _cells()
        for c in cells:
            c.result = run_experiment(
                _cell_cfg(
                    c.strategy, c.dataset, c.seed, c.window,
                    scn=_scn_by_kind.get(c.scenario),
                ),
                bundle=_bundle(c.dataset),
                debugger=debugger,
                metrics=metrics,
            )
        return GridResult(cells=cells, serial_fallback=True)

    if cfg.forest.fit != "device" or getattr(dbg, "phase_detail", False):
        return _serial_fallback("host fit / phase-detail debugging")
    if cfg.stream_round_events:
        raise ValueError(
            "stream_round_events is not supported by the batched grid chunk; "
            "per-round events still arrive at every touchdown via the "
            "MetricsWriter, or run the cells serially"
        )

    ds_bundles = [_bundle(d) for d in datasets]
    feat_widths = {b.train_x.shape[-1] for b in ds_bundles}
    if len(feat_widths) > 1 or any(
        np.asarray(b.train_x).ndim != 2 for b in ds_bundles
    ):
        return _serial_fallback("datasets disagree on feature width")
    n_classes_per = [
        max(int(np.asarray(b.train_y).max()) + 1, 2) if np.asarray(b.train_y).size
        else 2
        for b in ds_bundles
    ]
    if len(set(n_classes_per)) > 1:
        return _serial_fallback("datasets disagree on class count")
    n_classes = n_classes_per[0]
    want_metrics = metrics is not None or cfg.collect_metrics

    mesh = None
    mesh_lib = None
    mesh_mult = 1
    if cfg.mesh.data * cfg.mesh.model > 1:
        from distributed_active_learning_tpu.parallel import (
            make_mesh,
            mesh as mesh_lib,
        )

        if cfg.forest.n_trees % cfg.mesh.model:
            raise ValueError(
                f"n_trees={cfg.forest.n_trees} not divisible by mesh "
                f"model axis {cfg.mesh.model}"
            )
        mesh = make_mesh(data=cfg.mesh.data, model=cfg.mesh.model)
        mesh_mult = cfg.mesh.data

    # --- pad every dataset to one common slab width -------------------------
    n_valids_host = [int(np.asarray(b.train_y).shape[0]) for b in ds_bundles]
    n_store = max(n_valids_host)          # checkpoint mask width (no mesh pad)
    n_slab = n_store + ((-n_store) % mesh_mult)
    test_ns_host = [int(np.asarray(b.test_y).shape[0]) for b in ds_bundles]
    t_slab = max(test_ns_host)
    # Equal-width grids keep the sweep's static-n_valid path (bit-identical
    # serial programs); only genuinely heterogeneous widths pay the dynamic
    # fill watermark.
    uniform_n = len(set(n_valids_host)) == 1
    use_fill = not uniform_n
    static_n_valid = (
        -1 if (uniform_n and n_slab == n_store) else (n_valids_host[0] if uniform_n else -1)
    )
    use_test_fill = len(set(test_ns_host)) > 1

    from distributed_active_learning_tpu.ops import trees_train

    xs, oys, codes_list, edges_list, tests_x, tests_y = [], [], [], [], [], []
    states_per_ds = []  # [D][E] start states over the unpadded pools
    for b in ds_bundles:
        host_x = np.ascontiguousarray(b.train_x, dtype=np.float32)
        host_y = np.asarray(b.train_y, dtype=np.int32)
        n_d = host_x.shape[0]
        # Exactly run_experiment's init -> set_start_state per (dataset,
        # seed), on the UNPADDED pool (the start draw is shaped by the real
        # pool), then padded below with labeled=True sentinel rows.
        base = state_lib.init_pool_state(host_x, host_y, jax.random.key(seeds[0]))
        states_per_ds.append([
            state_lib.set_start_state(
                base.replace(key=jax.random.key(s)), cfg.n_start,
                n_classes=n_classes,
            )
            for s in seeds
        ])
        binned = trees_train.make_bins(
            jnp.asarray(host_x), cfg.forest.max_bins,
            quantize=cfg.forest.quantize,
        )
        pad = n_slab - n_d
        xs.append(np.pad(host_x, ((0, pad), (0, 0))))
        oys.append(np.pad(host_y, (0, pad)))
        codes_list.append(
            np.pad(np.asarray(binned.codes), ((0, pad), (0, 0)))
        )
        edges_list.append(np.asarray(binned.edges))
        t_pad = t_slab - test_ns_host[len(tests_x)]
        tests_x.append(
            np.pad(np.asarray(b.test_x, dtype=np.float32), ((0, t_pad), (0, 0)))
        )
        tests_y.append(np.pad(np.asarray(b.test_y, dtype=np.int32), (0, t_pad)))

    x = jnp.asarray(np.stack(xs))
    oracle_y = jnp.asarray(np.stack(oys))
    codes = jnp.asarray(np.stack(codes_list))
    edges = jnp.asarray(np.stack(edges_list))
    test_x = jnp.asarray(np.stack(tests_x))
    test_y = jnp.asarray(np.stack(tests_y))
    n_valids = jnp.asarray(n_valids_host, dtype=jnp.int32)
    test_ns = jnp.asarray(test_ns_host, dtype=jnp.int32)

    # --- per-cell vectors in cell order (strategy-major, dataset, seed) -----
    C = S * D * E

    def _pad_mask(mask_np, n_d):
        return np.pad(
            mask_np, (0, n_slab - n_d), constant_values=True
        )

    masks0 = np.stack([
        _pad_mask(np.asarray(states_per_ds[d][e].labeled_mask), n_valids_host[d])
        for _g in range(S)
        for d in range(D)
        for e in range(E)
    ])
    masks0 = jnp.asarray(masks0)
    seed_masks = jnp.array(masks0, copy=True)
    keys0 = jnp.stack([
        states_per_ds[d][e].key
        for _g in range(S)
        for d in range(D)
        for e in range(E)
    ])
    # Only the start masks and keys outlive this point; the start states hold
    # D device copies of the UNPADDED pools (the stacked slab above is the one
    # the grid reads), so drop them rather than hold ~2x pool HBM all run.
    del states_per_ds
    rounds0 = jnp.zeros((C,), dtype=jnp.int32)
    fit_keys = jnp.stack([
        jax.random.key(seeds[e] + 0x5EED)
        for _g in range(S)
        for _d in range(D)
        for e in range(E)
    ])
    # ONE host-side per-cell window expansion (strategy-major cell order) —
    # the device input below and the ops-plane progress gauges both read it,
    # so a future cell-layout change cannot skew one without the other.
    windows_by_cell = [w for w in windows for _ in range(D * E)]
    windows_cell = jnp.asarray(windows_by_cell, dtype=jnp.int32)
    caps_host = [
        n_valids_host[d] if cfg.label_budget is None
        else min(cfg.label_budget, n_valids_host[d])
        for _g in range(S)
        for d in range(D)
        for _e in range(E)
    ]
    label_caps = jnp.asarray(caps_host, dtype=jnp.int32)

    strat_objs = []
    lal_forests = []
    for s, w in zip(strategies, windows):
        scfg = dataclasses.replace(cfg.strategy, name=s, window_size=w)
        strat_objs.append(get_strategy(scfg))
        if s == "lal":
            from distributed_active_learning_tpu.models.lal_training import (
                load_or_train_lal_regressor,
            )

            lal_forests.append(load_or_train_lal_regressor(dict(scfg.options)))
        else:
            lal_forests.append(None)
    lal_forests = tuple(lal_forests)

    # --- scenario inputs: per-cell flip masks, per-dataset cost vectors -----
    flip_masks = None
    costs_ds = None
    if scenario_axis:
        # Pairwise validation (the knapsack's score-direction assumption is
        # per strategy; abstention's termination guard is per run).
        for scn_g, so in zip(group_scns, strat_objs):
            scn_engine.validate_scenario(
                scn_g, strategy=so, max_rounds=cfg.max_rounds
            )
        flip_rows = []
        for gi in range(S):
            for d in range(D):
                for seed in seeds:
                    row = np.asarray(
                        scn_engine.flip_mask(
                            group_scns[gi], seed, n_valids_host[d]
                        )
                    )
                    flip_rows.append(np.pad(row, (0, n_slab - n_valids_host[d])))
        flip_masks = jnp.asarray(np.stack(flip_rows))
        cost_scn = next((s for s in scn_list if s.kind == "cost_budget"), None)
        cost_rows = []
        for d, name in enumerate(datasets):
            if cost_scn is not None:
                row = np.asarray(
                    scn_engine.make_costs(cost_scn, n_valids_host[d], name)
                )
            else:
                row = np.ones(n_valids_host[d], np.float32)
            cost_rows.append(
                np.pad(
                    row, (0, n_slab - n_valids_host[d]), constant_values=1.0
                )
            )
        costs_ds = jnp.asarray(np.stack(cost_rows))

    if metrics is not None:
        from distributed_active_learning_tpu.config import asdict as cfg_asdict

        metrics.meta(
            config=cfg_asdict(cfg),
            backend=jax.default_backend(),
            n_devices=jax.device_count(),
            process_count=jax.process_count(),
            grid_strategies=strategies,
            grid_seeds=seeds,
            grid_datasets=datasets,
            grid_windows=windows,
            grid_scenarios=(
                [s.kind for s in group_scns] if group_scns is not None else None
            ),
        )

    cells = _cells()
    start_rounds = [0] * C

    ckpt_enabled = bool(cfg.checkpoint_dir and cfg.checkpoint_every)
    ckpt_fp = None
    key_impl = jax.random.key_impl(keys0)
    if ckpt_enabled:
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        ckpt_fp = ckpt_lib.grid_fingerprint(
            cfg, strategies, seeds, datasets, windows,
            scenarios=(
                [s.kind for s in scn_list] if scn_list is not None else None
            ),
        )
        restored = ckpt_lib.restore_latest_grid(
            cfg.checkpoint_dir, n_store=n_store, n_cells=C, fingerprint=ckpt_fp
        )
        if restored is not None:
            r_masks, r_keys, r_rounds, r_results = restored
            pad = n_slab - r_masks.shape[1]
            if pad:
                r_masks = np.pad(r_masks, ((0, 0), (0, pad)), constant_values=True)
            masks0 = jnp.asarray(r_masks)
            keys0 = jax.random.wrap_key_data(jnp.asarray(r_keys), impl=key_impl)
            rounds0 = jnp.asarray(r_rounds, dtype=jnp.int32)
            start_rounds = [int(r) for r in np.asarray(r_rounds)]
            for c, res in zip(cells, r_results):
                c.result = res
            dbg.debug(f"resumed grid at rounds {start_rounds}")

    counts0 = [
        int(c) for c in np.asarray(
            _grid_counts(
                masks0, jnp.asarray(
                    [n_valids_host[d] for _g in range(S) for d in range(D)
                     for _e in range(E)],
                    dtype=jnp.int32,
                )
            )
        )
    ]
    fit_budget = _resolve_sweep_fit_budget(
        cfg, max(n_valids_host), max(counts0), window_pad
    )
    grid_fit = make_grid_device_fit(cfg, fit_budget, n_classes)

    end_rounds = jnp.asarray(
        [
            (sr + cfg.max_rounds) if cfg.max_rounds is not None
            else int(np.iinfo(np.int32).max)
            for sr in start_rounds
        ],
        dtype=jnp.int32,
    )

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        row = NamedSharding(mesh, P(None, mesh_lib.AXIS_DATA))
        row2 = NamedSharding(mesh, P(None, mesh_lib.AXIS_DATA, None))
        rep = NamedSharding(mesh, P())
        x = jax.device_put(x, row2)
        codes = jax.device_put(codes, row2)
        oracle_y = jax.device_put(oracle_y, row)
        masks0 = jax.device_put(masks0, row)
        seed_masks = jax.device_put(seed_masks, row)
        test_x = jax.device_put(test_x, rep)
        test_y = jax.device_put(test_y, rep)
        edges = jax.device_put(edges, rep)
        keys0 = mesh_lib.global_put(keys0, mesh, mesh_lib.replicated_spec())
        fit_keys = mesh_lib.global_put(fit_keys, mesh, mesh_lib.replicated_spec())
        rounds0 = jax.device_put(rounds0, rep)
        windows_cell = jax.device_put(windows_cell, rep)
        end_rounds = jax.device_put(end_rounds, rep)
        label_caps = jax.device_put(label_caps, rep)
        n_valids = jax.device_put(n_valids, rep)
        test_ns = jax.device_put(test_ns, rep)

    K = max(int(cfg.rounds_per_launch or 1), 1)
    depth = max(int(getattr(cfg, "pipeline_depth", 1) or 1), 1)
    grid_chunk = make_grid_chunk_fn(
        strat_objs, window_pad, K, grid_fit,
        n_datasets=D,
        n_seeds=E,
        static_n_valid=static_n_valid,
        use_fill=use_fill,
        use_test_fill=use_test_fill,
        mesh=mesh,
        wrap_pallas=(mesh is not None and cfg.forest.kernel == "pallas"),
        with_metrics=want_metrics,
        n_classes=n_classes,
        scenarios=group_scns,
    )
    launches = telemetry.LaunchTracker(metrics, "grid_chunk_scan", fn=grid_chunk)

    # Host stop/veto arithmetic: the negative-remaining transform lets the
    # shared ChunkDriveControl drive per-cell caps — n_known = -max remaining
    # budget, label_cap = 0, so "all cells done" is the existing >= test; the
    # min-window veto lattice under-estimates every cell's progress, hence
    # stays safe (see make_grid_chunk_fn docstring). An abstaining-oracle
    # group breaks the lattice's window-per-round assumption the other way
    # (reveals may be SMALLER than any window), so its grids run with the
    # label lattice disabled — stop decisions come from the real revealed
    # counts, and an all-abstain cell never terminates early.
    rem0 = max(cap - c0 for cap, c0 in zip(caps_host, counts0))
    lattice_window = min(windows)
    if group_scns is not None and any(
        s.kind == "noisy_oracle" and s.abstain_prob > 0.0 for s in group_scns
    ):
        lattice_window = 0
    ctl = pipeline_lib.ChunkDriveControl(
        K, lattice_window, 0, cfg.max_rounds, -rem0, max(start_rounds),
    )

    if not ctl.already_done:
        worst = 0
        for c0, cap, w in zip(
            counts0, caps_host,
            [w for w in windows for _ in range(D * E)],
        ):
            j_cap = -(-(cap - c0) // w) - 1
            if cfg.max_rounds is not None:
                j_cap = min(cfg.max_rounds - 1, j_cap)
            worst = max(worst, c0 + max(j_cap, 0) * w)
        if worst > fit_budget:
            raise ValueError(
                f"up to {worst} labeled rows would exceed the device fit "
                f"window ({fit_budget}); raise ForestConfig.fit_budget or "
                "lower label_budget/max_rounds"
            )

    grid_state = SweepState(labeled_mask=masks0, key=keys0, round=rounds0)
    snapshots = pipeline_lib.CarrySnapshots(ckpt_snapshot)

    # Live ops plane (runtime/obs.py): grid progress gauges, so a multi-hour
    # scenario x strategy x seed launch is finally watchable mid-flight — a
    # /metrics scrape shows cells, completed cell-rounds, how many cells have
    # frozen (hit their own budget/round cap while the stream runs to the
    # slowest cell), and a remaining-wall estimate. Host-side ints only; the
    # traced grid program is untouched. The ETA assumes window-per-round
    # reveals, so an abstaining-oracle group reads as an underestimate —
    # it is an estimate gauge, not a stop decision.
    obs_cell_labeled = list(counts0)
    obs_cell_rounds = [max(sr, 0) for sr in start_rounds]
    obs.gauge("grid_cells", "cells in the running grid launch").set(C)

    def _obs_grid_progress(total_active: int) -> None:
        frozen = 0
        rem_rounds = 0
        for c in range(C):
            w = windows_by_cell[c]
            rem_budget = caps_host[c] - obs_cell_labeled[c]
            r = -(-rem_budget // w) if (w > 0 and rem_budget > 0) else 0
            if cfg.max_rounds is not None:
                r = min(r, max(cfg.max_rounds - obs_cell_rounds[c], 0))
            if r <= 0:
                frozen += 1
            rem_rounds = max(rem_rounds, r)
        obs.counter(
            "grid_cell_rounds", "active cell-rounds completed across the grid"
        ).inc(total_active)
        obs.gauge(
            "grid_cells_frozen", "cells stopped while the grid stream runs on"
        ).set(frozen)
        steady = launches.steady_seconds_mean()
        if steady is not None:
            obs.gauge(
                "grid_eta_seconds",
                "estimated wall seconds until the slowest cell finishes",
            ).set(round(-(-rem_rounds // K) * steady, 3))
        obs.heartbeat("grid_touchdown")

    grid_tail = (flip_masks, costs_ds) if scenario_axis else ()

    def dispatch(gs, idx):
        out = grid_chunk(
            codes, x, oracle_y, gs, seed_masks, lal_forests, fit_keys,
            windows_cell, test_x, test_y, end_rounds, label_caps, edges,
            n_valids, test_ns, *grid_tail,
        )
        if ckpt_enabled:
            new_grid = out[0]
            snapshots.take(
                idx, new_grid.labeled_mask, new_grid.key, new_grid.round
            )
        return out

    def touchdown(idx, _n_labeled_after, n_active, ys, _out, wall):
        snap = snapshots.pop(idx)
        if n_active == 0:
            return
        rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
        active_np = np.asarray(active_y)  # [K, C]
        rounds_np = np.asarray(rounds_y)
        labeled_np = np.asarray(labeled_y)
        acc_np = np.asarray(acc_y)
        total_active = int(active_np.sum())
        md = (
            telemetry.stacked_sweep_metrics_to_dicts(ys[5], active_np)
            if want_metrics
            else None
        )
        if md is not None and group_scns is not None:
            # Scenario metrics emit UNIFORMLY across groups inside the chunk
            # (one ys pytree for the merge); a cell only KEEPS the metrics of
            # its own scenario here, so a none-cell's records match a clean
            # serial run key-for-key.
            for c in range(C):
                kind_c = group_scns[c // (D * E)].kind
                for m in md[c]:
                    if kind_c != "rare_event":
                        m.pop("rare_recall", None)
                    if kind_c != "cost_budget":
                        m.pop("cost_spent", None)
        last_round = ctl.round_idx
        for c in range(C):
            act = active_np[:, c]
            if not act.any():
                continue
            cell = cells[c]
            r_c = rounds_np[act, c]
            l_c = labeled_np[act, c]
            a_c = acc_np[act, c]
            obs_cell_labeled[c] = int(l_c[-1])
            obs_cell_rounds[c] += int(act.sum())
            n_pool_c = n_valids_host[(c // E) % D]
            cell.result.extend_from_arrays(
                r_c, l_c, n_pool_c - l_c, a_c,
                total_time=wall / total_active,
                metrics=md[c] if md is not None else None,
            )
            last_round = max(last_round, int(r_c[-1]))
            if metrics is not None:
                scn_tag = (
                    {"scenario": cell.scenario} if scenario_axis else {}
                )
                for i in range(len(r_c)):
                    metrics.round(
                        exp=c,
                        strategy=cell.strategy,
                        dataset=cell.dataset,
                        seed=cell.seed,
                        round=int(r_c[i]),
                        n_labeled=int(l_c[i]),
                        accuracy=float(a_c[i]),
                        **scn_tag,
                        **(md[c][i] if md is not None else {}),
                    )
            if cfg.log_every and dbg.enabled:
                for r, nl, a in zip(r_c, l_c, a_c):
                    if int(r) % cfg.log_every == 0:
                        dbg.debug(
                            f"[{cell.strategy}/{cell.dataset}/seed "
                            f"{cell.seed}] Iteration {int(r)} -- "
                            f"labeled={int(nl)} accu={float(a) * 100:.2f}"
                        )
        ctl.note_round(last_round)
        _obs_grid_progress(total_active)
        if metrics is not None:
            fetched = (
                active_y.nbytes + rounds_y.nbytes + labeled_y.nbytes
                + acc_y.nbytes
            )
            if want_metrics:
                fetched += telemetry.metrics_nbytes(ys[5])
            metrics.counter("host_transfer_bytes", int(fetched))
            mem = telemetry.device_memory_gauges()
            if mem:
                metrics.gauges(mem, allgather=True)
        if ckpt_enabled and ctl.checkpoint_due(cfg.checkpoint_every):
            from distributed_active_learning_tpu.runtime import (
                checkpoint as ckpt_lib,
            )

            s_masks, s_kd, s_rounds = snap
            ckpt_lib.save_grid(
                cfg.checkpoint_dir, s_masks, s_kd, s_rounds,
                [c.result for c in cells],
                n_store=n_store, fingerprint=ckpt_fp,
            )
            ctl.checkpoint_done()

    if not ctl.already_done:
        pipeline_lib.run_pipelined(
            grid_state,
            dispatch=dispatch,
            touchdown=touchdown,
            continue_after=ctl.continue_after,
            depth=depth,
            on_launch=launches.record,
            may_dispatch=ctl.may_dispatch,
            on_veto=lambda idx: launches.veto(idx, ctl.veto_reason(idx)),
        )
    # The grid is over: a scrape arriving after the stream must read zero
    # remaining wall, not the last mid-flight estimate (pool-exhaustion
    # stops are invisible to the budget arithmetic above).
    obs.gauge(
        "grid_eta_seconds",
        "estimated wall seconds until the slowest cell finishes",
    ).set(0.0)

    if cfg.results_path:
        for c in cells:
            c.result.save(
                _grid_result_path(
                    cfg.results_path, c.strategy, c.dataset, c.seed, D > 1,
                    scenario=c.scenario, with_scenario=scenario_axis,
                ),
                fmt="reference",
            )
    cache = telemetry.jit_cache_size(grid_chunk)
    return GridResult(
        cells=cells,
        launches=launches.calls,
        recompiles_after_warmup=(
            max(int(cache) - 1, 0) if cache is not None and launches.calls else 0
        ),
    )
