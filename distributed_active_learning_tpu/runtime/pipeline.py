"""Pipelined chunk dispatch: overlap device execution with host touchdowns.

PR 2 fused K AL rounds into one ``lax.scan`` launch, but the driver around it
stayed strictly serial: launch -> block on the stacked ys -> append records /
log / checkpoint -> launch the next chunk. Every chunk boundary therefore
stalls the device for the whole host touchdown. This module is the
dispatch-ahead-of-data discipline (Pathways, Barham et al. 2022) applied to
that boundary, shared by BOTH experiment loops (forest ``runtime.loop`` and
neural ``runtime.neural_loop``):

- **Chunks dispatch ahead of their results.** ``dispatch`` returns immediately
  (JAX launches are async); up to ``depth`` chunks are in flight at once. The
  carried state is device-resident and threads launch-to-launch without the
  host ever materializing it.

- **The stop decision blocks only on two scalars.** Each chunk returns its
  post-chunk labeled count and active-round count as tiny scalar outputs
  (:class:`ChunkExtras`); the driver's continue/stop logic needs nothing else,
  so the bulk ys transfer never serializes the loop.

- **The bulk ys fetch is asynchronous.** Right after a chunk is dispatched its
  ys start a non-blocking device-to-host copy (``copy_to_host_async``); by the
  time the touchdown materializes them the transfer has typically already
  completed under the next chunk's execution.

- **Touchdowns overlay the next chunk's execution.** After chunk N's scalars
  arrive, chunk N+2 is dispatched (informed by N's outcome) and only THEN does
  chunk N's touchdown (record append, metrics, logging, checkpoint) run — the
  device crunches chunk N+1/N+2 while the host does its bookkeeping.

- **One speculative chunk may run past the stop point.** With ``depth=2``
  chunk N+1 launches before chunk N's outcome is known; if N stopped, N+1 is
  wholly inactive — the masked no-op rounds freeze the carried state bit-for-
  bit and append nothing, so results are IDENTICAL to the serial driver
  (pinned in tests/test_pipeline.py). ``depth=1`` reproduces today's strict
  launch -> block -> touchdown order exactly (the fallback for host fit and
  ``--phase-detail``).

Donation note: with buffer donation the output carry of chunk N is consumed
(and its buffers deleted) by chunk N+1's launch BEFORE chunk N's touchdown
runs, so a touchdown must not read the carry it is handed unless the caller
disabled donation — the drivers disable it exactly when checkpointing needs
the post-chunk state on the host (runtime/loop.py, runtime/neural_loop.py).

Overlap accounting rides the existing telemetry: each chunk's ``launch`` JSONL
event gains ``touchdown_seconds``, ``overlap_seconds`` (the part of the
touchdown that ran while another chunk was in flight) and
``touchdown_hidden_fraction``; :class:`PipelineStats` aggregates the same
numbers for ``bench.py --mode round``.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, NamedTuple, Optional

import jax

from distributed_active_learning_tpu.runtime import obs, telemetry


class ChunkExtras(NamedTuple):
    """The two scalar chunk outputs the host stop decision blocks on.

    Everything else a chunk produces (the stacked ys, the carried state) is
    fetched asynchronously or never fetched at all; these two int32 scalars
    are the whole launch-to-launch control dependency.
    """

    n_labeled_after: Any  # exact post-chunk labeled count (real rows only)
    n_active: Any         # how many of the chunk's rounds were active


@dataclasses.dataclass
class PipelineStats:
    """Aggregate dispatch-vs-touchdown overlap accounting for one drive."""

    chunks: int = 0
    launch_seconds: float = 0.0     # dispatch -> stop-scalars-ready, summed
    touchdown_seconds: float = 0.0  # host bookkeeping wall, summed
    overlap_seconds: float = 0.0    # touchdown wall spent with a chunk in flight
    vetoed: int = 0                 # speculative launches proven inactive a priori

    @property
    def touchdown_hidden_fraction(self) -> float:
        """Fraction of total touchdown wall the device never saw (it was
        executing another chunk at the time). 0.0 for the serial order
        (depth=1), approaching 1.0 when every touchdown hides behind the next
        chunk's execution."""
        if self.touchdown_seconds <= 0.0:
            return 0.0
        return self.overlap_seconds / self.touchdown_seconds


@dataclasses.dataclass
class _InFlight:
    index: int
    extras: ChunkExtras
    ys: Any
    out_state: Any
    t_dispatch: float


class ChunkDriveControl:
    """Shared stop/veto/checkpoint arithmetic for chunked experiment drivers.

    The forest and neural loops drive different chunk programs but IDENTICAL
    control logic: when a speculative dispatch is provably inactive
    (:meth:`may_dispatch` — max_rounds bound, or the labeled-count lattice
    reaching the label cap), when to stop after a chunk's scalars arrive
    (:meth:`continue_after` — short chunk / cap reached / round quota spent),
    and the first-touchdown-at-or-after-each-multiple checkpoint cadence.
    One implementation here keeps the two drivers from drifting.

    The lattice veto is SAFE, never lossy: pre-reveal counts advance by
    exactly ``window`` per active round except at pool-exhaustion short
    reveals — and after a short reveal the count equals the pool size, so
    every later round is inactive anyway. Hence ``lattice >= cap`` implies
    the real round is inactive too.
    """

    def __init__(
        self,
        chunk_size: int,
        window: int,
        label_cap: int,
        max_rounds: Optional[int],
        n_known: int,
        start_round: int = 0,
    ):
        self.chunk_size = chunk_size
        self.window = window
        self.label_cap = label_cap
        self.max_rounds = max_rounds
        self.n_known = n_known
        self.rounds_done = 0
        self.round_idx = start_round
        self._ckpt_mark = start_round

    @property
    def already_done(self) -> bool:
        """True when not even the first chunk should launch."""
        return self.n_known >= self.label_cap or (
            self.max_rounds is not None and self.max_rounds <= 0
        )

    def veto_reason(self, idx: int) -> Optional[str]:
        """Why chunk ``idx`` would be vetoed (None = dispatchable). The
        reason string rides the driver's ``launch_veto`` JSONL event, so the
        auditor's runtime counterpart can assert veto counts per cause
        instead of inferring them from missing launches."""
        if self.max_rounds is not None and idx * self.chunk_size >= self.max_rounds:
            return "max_rounds_bound"
        if self.n_known + idx * self.chunk_size * self.window >= self.label_cap:
            return "label_cap_lattice"
        return None

    def may_dispatch(self, idx: int) -> bool:
        return self.veto_reason(idx) is None

    def continue_after(self, n_labeled_after: int, n_active: int) -> bool:
        self.rounds_done += n_active
        if n_active < self.chunk_size:
            return False  # an in-chunk round hit the budget/pool/end stop
        if n_labeled_after >= self.label_cap:
            return False
        if self.max_rounds is not None and self.rounds_done >= self.max_rounds:
            return False
        return True

    # -- chunk-boundary checkpoint cadence (runtime/checkpoint.py notes):
    # saved at the first touchdown at/after each checkpoint_every multiple.

    def note_round(self, round_idx: int) -> None:
        """Record the last active round a touchdown appended."""
        self.round_idx = round_idx

    def checkpoint_due(self, every: int) -> bool:
        return self.round_idx // every > self._ckpt_mark // every

    def checkpoint_done(self) -> None:
        self._ckpt_mark = self.round_idx


class CarrySnapshots:
    """Dispatch-time donation-safe carry snapshots, keyed by chunk index.

    Checkpointed chunked drives keep their carry donated (the next launch
    consumes chunk N's output buffers before chunk N's touchdown runs); the
    checkpointable fields are instead copied into fresh buffers right at
    dispatch — ``snap_fn`` is the jitted copy program
    (``runtime.loop.ckpt_snapshot``) — and handed back at the matching
    touchdown. One implementation here serves both the forest driver and the
    batched sweep driver, like :class:`ChunkDriveControl` does for their stop
    arithmetic: the take-at-dispatch / pop-at-touchdown pairing must not
    drift between them.
    """

    def __init__(self, snap_fn):
        self._snap = snap_fn
        self._held: dict = {}

    def take(self, index: int, *leaves) -> None:
        snap = self._snap(*leaves)
        start_host_copy(snap)  # lands host-side under the next chunk's run
        self._held[index] = snap

    def pop(self, index: int):
        """The snapshot taken at ``index``'s dispatch (None if never taken).
        Call from EVERY touchdown — also the ones that skip checkpointing —
        so speculative/inactive chunks' snapshots are released."""
        return self._held.pop(index, None)


def start_host_copy(tree: Any) -> None:
    """Begin a non-blocking device->host copy of every array in ``tree``.

    The copy completes under the next chunk's execution, so the touchdown's
    ``np.asarray`` calls find the bytes already on host. Arrays that don't
    support the call (non-jax leaves, committed multi-device layouts on some
    backends) just skip — the later synchronous fetch stays correct, only
    less overlapped.
    """
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            leaf.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass


def run_pipelined(
    state: Any,
    *,
    dispatch: Callable[[Any, int], tuple],
    touchdown: Callable[[int, int, int, Any, Any, float], None],
    continue_after: Callable[[int, int], bool],
    depth: int = 2,
    on_launch: Optional[Callable[..., None]] = None,
    may_dispatch: Optional[Callable[[int], bool]] = None,
    on_veto: Optional[Callable[[int], None]] = None,
) -> tuple:
    """Drive chunk launches with up to ``depth`` in flight; returns
    ``(final_state, PipelineStats)``.

    - ``dispatch(state, chunk_index) -> (new_state, ChunkExtras, ys)`` must be
      non-blocking (a jitted launch). The returned state is device-resident
      and threads into the next dispatch; the pipeline never reads it.
    - ``continue_after(n_labeled_after, n_active) -> bool`` is the host stop
      decision, called once per chunk IN ORDER with the two scalars as plain
      ints. Returning False stops further dispatch; chunks already in flight
      still get their touchdown (they are wholly-inactive no-ops).
    - ``touchdown(chunk_index, n_labeled_after, n_active, ys, out_state,
      launch_seconds)`` does the host bookkeeping (record append, metrics,
      logging, checkpoint). Runs strictly in chunk order, overlapped with
      in-flight execution when ``depth > 1``. ``out_state`` is that chunk's
      output carry — only valid to read when the chunk program does NOT
      donate its carry (see module docstring).
    - ``on_launch(seconds=, touchdown_seconds=, overlap_seconds=,
      touchdown_hidden_fraction=)`` (optional) receives per-chunk timing once
      the chunk's touchdown finished — the telemetry hook
      (:meth:`runtime.telemetry.LaunchTracker.record`).
    - ``may_dispatch(chunk_index) -> bool`` (optional) vetoes a dispatch the
      caller can PROVE would be wholly inactive (a-priori bounds: max_rounds,
      or the labeled-count lattice reaching the label cap) — the driver then
      skips the speculative launch instead of burning a masked no-op chunk.
      Must be monotone (once False, False forever). Stops the host can NOT
      predict (pool exhaustion short-reveals) still rely on speculation +
      masked no-ops, which stay bit-exact.
    - ``on_veto(chunk_index)`` (optional) fires ONCE per vetoed index, at the
      moment the veto first blocks a would-be dispatch — the structured
      record of the speculative launch that never happened (drivers emit a
      ``launch_veto`` JSONL event carrying ``ChunkDriveControl``'s reason).
      Vetoes are also tallied in ``PipelineStats.vetoed``. A veto observed
      after the stop decision is NOT recorded: nothing would have been
      dispatched regardless, so counting it would overstate the vetoes.

    ``depth=1`` degenerates to the serial launch -> block -> touchdown order:
    no speculation, no overlap, bit-identical behavior AND ordering to the
    pre-pipeline driver.
    """
    if depth < 1:
        raise ValueError(f"pipeline depth must be >= 1, got {depth}")
    stats = PipelineStats()
    inflight: deque = deque()
    stop = False
    next_index = 0
    last_ready = None  # when the previous chunk's scalars resolved
    vetoed_seen = set()  # indices whose veto was already recorded

    def _can_dispatch():
        if stop:
            return False
        if may_dispatch is None or may_dispatch(next_index):
            return True
        if next_index not in vetoed_seen:
            # First observation of this index's veto: the fill loops re-probe
            # the same index every iteration, but the skipped launch happened
            # (didn't happen) exactly once.
            vetoed_seen.add(next_index)
            stats.vetoed += 1
            if on_veto is not None:
                on_veto(next_index)
        return False

    def _dispatch_one():
        nonlocal state, next_index
        t0 = time.perf_counter()
        state, extras, ys = dispatch(state, next_index)
        # Kick off the async D2H copy of everything the touchdown will read.
        start_host_copy((extras, ys))
        inflight.append(_InFlight(next_index, extras, ys, state, t0))
        # Live ops plane: the in-flight depth gauge is what a /metrics scrape
        # of a long chunked run shows moving — the pipeline is alive and how
        # deep its launch window currently sits.
        obs.gauge(
            "pipeline_inflight", "chunk launches currently in flight"
        ).set(len(inflight))
        telemetry.flight_record(
            "dispatch", index=next_index, inflight=len(inflight), depth=depth,
        )
        next_index += 1

    while True:
        # Fill the launch window. The chunk beyond the oldest un-consumed one
        # is speculative (its predecessor's outcome is unknown) — masked
        # no-op rounds make an overrun free and bit-exact. The capacity check
        # runs FIRST: _can_dispatch records vetoes, and a veto only counts
        # when a launch slot was actually open for the skipped dispatch.
        while len(inflight) < depth and _can_dispatch():
            _dispatch_one()
        if not inflight:
            break
        head = inflight.popleft()
        # The ONLY blocking fetch: two scalars. The chunk program must finish
        # for them to resolve.
        n_labeled_after = int(head.extras.n_labeled_after)
        n_active = int(head.extras.n_active)
        ready = time.perf_counter()
        # Wall attributed to THIS chunk: from the later of its dispatch and
        # the previous chunk's completion, to its own completion. At depth 1
        # that is plain dispatch->ready; at depth >= 2 a chunk dispatched
        # while its predecessor still executed must not re-count the
        # predecessor's device time (naive dispatch->ready would ~double
        # every per-launch/per-round figure and make launch seconds sum past
        # real wall clock).
        since = (
            head.t_dispatch
            if last_ready is None
            else max(head.t_dispatch, last_ready)
        )
        launch_wall = ready - since
        last_ready = ready
        if not stop and not continue_after(n_labeled_after, n_active):
            stop = True
        # Refill BEFORE the touchdown so the host bookkeeping below overlays
        # the refilled chunk's execution: the popped chunk has completed, so
        # the launch window has a free slot and chunk N+2 can dispatch now —
        # the device never waits out a long touchdown. depth=1 skips this
        # (the serial contract is touchdown-before-next-dispatch).
        while depth > 1 and len(inflight) < depth and _can_dispatch():
            _dispatch_one()
        t_td = time.perf_counter()
        telemetry.flight_record(
            "touchdown", index=head.index, n_active=n_active,
            n_labeled_after=n_labeled_after, inflight=len(inflight),
        )
        touchdown(
            head.index, n_labeled_after, n_active, head.ys, head.out_state,
            launch_wall,
        )
        td_wall = time.perf_counter() - t_td
        overlapped = td_wall if inflight else 0.0
        stats.chunks += 1
        stats.launch_seconds += launch_wall
        stats.touchdown_seconds += td_wall
        stats.overlap_seconds += overlapped
        # Live ops plane: a fresh pipeline_touchdown heartbeat is /healthz's
        # proof the driver is completing work, not just dispatching; the
        # hidden-fraction gauge is the pipelining win live instead of only
        # in the bench payload.
        obs.heartbeat("pipeline_touchdown")
        obs.gauge(
            "pipeline_inflight", "chunk launches currently in flight"
        ).set(len(inflight))
        obs.gauge(
            "touchdown_hidden_ratio",
            "fraction of host-touchdown wall hidden under device execution",
        ).set(round(stats.touchdown_hidden_fraction, 6))
        obs.counter("pipeline_chunks", "chunk touchdowns completed").inc()
        if on_launch is not None:
            on_launch(
                seconds=launch_wall,
                touchdown_seconds=td_wall,
                overlap_seconds=overlapped,
                touchdown_hidden_fraction=(
                    overlapped / td_wall if td_wall > 0 else 0.0
                ),
            )
    return state, stats
