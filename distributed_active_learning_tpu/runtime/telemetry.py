"""Device-resident telemetry: in-scan round metrics + a structured sink.

The reference's entire observability story is ``Debugger.TIMESTAMP`` banners
and per-round prints redirected into text files (``final_thesis/debugger.py:
6-27``; ``classes/RESULTS.txt``). Our port inherited that ceiling — and the
scan-fused driver (runtime/loop.py ``make_chunk_fn``) is fast *because* the
host never looks inside a chunk, so a fused run used to emit nothing but
chunk-boundary accuracies. This module restores per-round visibility without
giving the win back, in three layers:

1. **In-scan device metrics** — :class:`RoundMetrics`, a small pytree computed
   INSIDE the jitted round (``compute_round_metrics``) and returned as extra
   ``lax.scan`` ys: selection-score summary (min/mean/max of the picked
   window, margin to the best unpicked candidate), mean prediction entropy
   over the pool, the picked-class histogram, and the labeled fraction. The
   host receives K rounds of metrics in the chunk's ONE touchdown — zero
   extra syncs. The pool-entropy pass re-evaluates the forest, but inside one
   XLA program the leaf evaluation is shared with the strategy's own scoring
   via CSE (same kernel, same operands), so the marginal cost is an
   elementwise entropy + reductions, not a second forest pass.

2. **Trace attribution** — the hot ops carry ``jax.named_scope`` labels
   (``al/*`` in runtime/loop.py, ``trees/*`` in ops/trees_train.py,
   ``forest/*`` in ops/forest_eval.py, ``shard/*`` in parallel/kernels.py,
   ``neural/*`` in models/neural.py) and host-side phases emit
   ``jax.profiler.TraceAnnotation`` spans (runtime/debugger.py
   ``Debugger.phase``), so a ``--profile-dir`` trace (run.py) is
   phase-attributable in TensorBoard/Perfetto instead of one anonymous blob.

3. **Structured sink** — :class:`MetricsWriter` emits rank-tagged JSONL
   events (rounds, counters, gauges, launches) behind ``run.py
   --metrics-out``: compile-vs-execute launch accounting with recompile
   detection via the jit cache size, host<->device transfer-byte counters at
   chunk touchdowns, and device memory watermarks from
   ``Device.memory_stats()`` where the backend reports them. Under multihost
   only ``is_primary()`` writes; per-host gauges cross through
   :func:`parallel.multihost.gather_scalar_gauges` (a ``process_allgather``)
   first, so the one file still shows every host.

``benches/summarize_metrics.py`` turns the JSONL back into the per-phase
table the reference printed.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from distributed_active_learning_tpu.runtime import obs


# ---------------------------------------------------------------------------
# Layer 1: in-scan device metrics
# ---------------------------------------------------------------------------


@struct.dataclass
class RoundMetrics:
    """Per-round device metrics, cheap enough to ride every scan step.

    All leaves are scalars except ``picked_hist`` (``[n_classes]``), so K
    rounds of metrics stack into a few KB of scan ys — the host fetches them
    in the chunk's existing touchdown transfer.
    """

    score_min: jnp.ndarray    # worst picked score (selection-order sense)
    score_mean: jnp.ndarray   # mean picked score
    score_max: jnp.ndarray    # best picked score
    score_margin: jnp.ndarray  # gap from worst picked to best unpicked candidate
    pool_entropy: jnp.ndarray  # mean predictive entropy over valid pool rows (bits)
    labeled_frac: jnp.ndarray  # pre-reveal labeled fraction of the real pool
    picked_hist: jnp.ndarray  # [n_classes] int32 oracle classes of the window
    # Scenario-engine metrics (scenarios/): None (an absent pytree leaf —
    # program avals unchanged) unless the matching scenario is active, so the
    # clean path's metrics pytree stays byte-identical to the pre-scenario
    # code. The dict converters below skip None fields.
    rare_recall: Optional[jnp.ndarray] = None  # rare_event: recall-at-budget
    cost_spent: Optional[jnp.ndarray] = None   # cost_budget: this round's spend


def compute_round_metrics(
    forest,
    state,
    picked: jnp.ndarray,
    picked_vals: jnp.ndarray,
    scores: jnp.ndarray,
    *,
    higher_is_better: bool,
    n_classes: int,
) -> RoundMetrics:
    """Build :class:`RoundMetrics` inside the jitted round (traced code).

    ``state`` is the PRE-reveal pool state, ``picked``/``picked_vals`` the
    selected window indices and their scores, ``scores`` the full score
    vector. Called from ``runtime.loop.make_round_fn`` — the per-round and
    scan-fused drivers therefore run the SAME program for metrics, which is
    what makes fused-vs-per-round metric parity bit-exact (pinned in
    tests/test_telemetry.py).
    """
    from distributed_active_learning_tpu.ops import forest_eval, scoring, trees_multi

    with jax.named_scope("al/metrics"):
        # Mean predictive entropy over the pool — the classic AL progress
        # signal (falling entropy = the learner is running out of points it
        # is unsure about). Full entropy in bits for both the binary and the
        # multiclass forest forms.
        if trees_multi.is_multi(forest):
            ent = trees_multi.entropy_multi(trees_multi.proba_multi(forest, state.x))
        else:
            ent = scoring.full_entropy(forest_eval.proba(forest, state.x))
        return selection_metrics(
            state, picked, picked_vals, scores,
            higher_is_better=higher_is_better,
            n_classes=n_classes,
            pool_entropy=ent,
        )


def selection_metrics(
    state,
    picked: jnp.ndarray,
    picked_vals: jnp.ndarray,
    scores: jnp.ndarray,
    *,
    higher_is_better: bool,
    n_classes: int,
    pool_entropy: jnp.ndarray,
) -> RoundMetrics:
    """Model-agnostic half of :func:`compute_round_metrics` (traced code).

    Everything except the pool-entropy pass is a function of the selection
    alone — scores, the picked window, and the pre-reveal state — so the
    NEURAL loop's fused acquire program (runtime/neural_loop.py
    ``make_neural_chunk_fn``) builds the same :class:`RoundMetrics` pytree by
    passing its own per-point predictive entropy as ``pool_entropy`` (a
    ``[n]`` vector, reduced over valid rows here; MC-dropout entropy is in
    nats where the forest's is in bits — consumers read the unit off the
    loop kind in the run's ``meta`` event).
    """
    with jax.named_scope("al/metrics"):
        return _selection_metrics(
            state, picked, picked_vals, scores,
            higher_is_better, n_classes, pool_entropy,
        )


def _selection_metrics(
    state, picked, picked_vals, scores,
    higher_is_better, n_classes, pool_entropy,
) -> RoundMetrics:
    from distributed_active_learning_tpu.runtime import state as state_lib

    valid = state.valid_mask
    # Short final windows: when fewer than window_size unlabeled rows
    # remain, ops/topk.py pads the selection with +/-inf sentinel values
    # whose indices point at already-labeled rows (reveal treats them as
    # no-ops). Every statistic below masks to the FINITE picks so the
    # exhaustion tail yields real numbers, not inf/NaN — which would
    # poison RoundRecord.metrics and serialize as invalid JSON.
    finite = jnp.isfinite(picked_vals)
    n_finite = jnp.maximum(jnp.sum(finite.astype(jnp.int32)), 1)
    score_min = jnp.min(jnp.where(finite, picked_vals, jnp.inf))
    score_max = jnp.max(jnp.where(finite, picked_vals, -jnp.inf))
    score_mean = jnp.sum(jnp.where(finite, picked_vals, 0.0)) / n_finite
    # Margin to the best unpicked candidate: the score gap across the
    # selection boundary. Candidates are unlabeled real rows minus the
    # window just picked; the masked extremum uses the same +/-inf
    # neutralization as ops/topk.py.
    remaining = (~state.labeled_mask).at[picked].set(False) & valid
    if higher_is_better:
        worst_picked = jnp.min(jnp.where(finite, picked_vals, jnp.inf))
        best_rest = jnp.max(jnp.where(remaining, scores, -jnp.inf))
        margin = worst_picked - best_rest
    else:
        worst_picked = jnp.max(jnp.where(finite, picked_vals, -jnp.inf))
        best_rest = jnp.min(jnp.where(remaining, scores, jnp.inf))
        margin = best_rest - worst_picked
    # No finite picks / no remaining candidates (pool exhausted mid- or
    # end-window): report 0 rather than the arithmetic of sentinels.
    score_min = jnp.where(jnp.isfinite(score_min), score_min, 0.0)
    score_max = jnp.where(jnp.isfinite(score_max), score_max, 0.0)
    margin = jnp.where(jnp.isfinite(margin), margin, 0.0)

    # Real-row denominator: static for batch pools; for streaming slab pools
    # (state.n_filled set) the row count is a traced watermark, so it must be
    # reduced from the dynamic valid mask — dividing by the static capacity
    # would dilute entropy/labeled-fraction by the unfilled slab tail.
    if state.n_filled is None:
        n_real = state.n_valid
    else:
        n_real = jnp.maximum(jnp.sum(valid.astype(jnp.int32)), 1)
    ent_mean = jnp.sum(jnp.where(valid, pool_entropy, 0.0)) / n_real

    hist = jnp.sum(
        jax.nn.one_hot(state.oracle_y[picked], n_classes, dtype=jnp.int32)
        * finite[:, None].astype(jnp.int32),  # sentinel picks count nothing
        axis=0,
    )
    labeled_frac = (
        state_lib.labeled_count(state).astype(jnp.float32) / n_real
    )
    return RoundMetrics(
        score_min=score_min.astype(jnp.float32),
        score_mean=score_mean.astype(jnp.float32),
        score_max=score_max.astype(jnp.float32),
        score_margin=margin.astype(jnp.float32),
        pool_entropy=ent_mean.astype(jnp.float32),
        labeled_frac=labeled_frac,
        picked_hist=hist,
    )


# The one source of truth for the metric field names — the dict converters
# below derive from it, so a field added to RoundMetrics cannot silently miss
# the records/JSONL. picked_hist is the only vector field (list-valued).
# Optional scenario fields (rare_recall, cost_spent) are None outside their
# scenario; the converters emit a key only when the leaf exists.
_METRIC_FIELDS = tuple(f.name for f in RoundMetrics.__dataclass_fields__.values())


def _present_fields(host_rm) -> tuple:
    return tuple(
        name for name in _METRIC_FIELDS if getattr(host_rm, name) is not None
    )


def _field_to_py(host_rm, name: str, idx=None):
    leaf = getattr(host_rm, name)
    if idx is not None:
        leaf = leaf[idx]
    if name == "picked_hist":
        return [int(c) for c in np.asarray(leaf)]
    return float(leaf)


def metrics_to_dict(rm: RoundMetrics) -> Dict[str, Any]:
    """One round's metrics as plain JSON-serializable Python values.

    ONE host transfer (``jax.device_get`` of the whole pytree), not one per
    leaf — the per-round driver calls this once per round.
    """
    host = jax.device_get(rm)
    return {name: _field_to_py(host, name) for name in _present_fields(host)}


def stacked_metrics_to_dicts(
    rm_stacked: RoundMetrics, active: np.ndarray
) -> List[Dict[str, Any]]:
    """Chunk-touchdown conversion: stacked ``[K, ...]`` scan-ys metrics ->
    one plain dict per ACTIVE round (inactive tail steps are discarded work,
    same as their accuracy/picked ys)."""
    host = jax.device_get(rm_stacked)
    fields = _present_fields(host)
    return [
        {name: _field_to_py(host, name, i) for name in fields}
        for i in np.flatnonzero(np.asarray(active))
    ]


def stacked_sweep_metrics_to_dicts(
    rm_stacked: RoundMetrics, active: np.ndarray
) -> List[List[Dict[str, Any]]]:
    """Sweep-touchdown conversion: ``[K, E, ...]`` batched scan-ys metrics ->
    one dict list per EXPERIMENT, each holding that experiment's active rounds
    in order (the batched twin of :func:`stacked_metrics_to_dicts`; one
    ``device_get`` of the whole stacked pytree, then host-side slicing)."""
    host = jax.device_get(rm_stacked)
    active = np.asarray(active)
    fields = _present_fields(host)
    return [
        [
            {name: _field_to_py(host, name, (i, e)) for name in fields}
            for i in np.flatnonzero(active[:, e])
        ]
        for e in range(active.shape[1])
    ]


def metrics_nbytes(rm_stacked: RoundMetrics) -> int:
    """Bytes the stacked metrics add to a chunk touchdown transfer.

    Pure shape*itemsize bookkeeping (``.nbytes`` on the arrays as-is) — no
    host materialization; this feeds the transfer counter, so it must not
    itself add transfers.
    """
    return int(sum(l.nbytes for l in jax.tree_util.tree_leaves(rm_stacked)))


# ---------------------------------------------------------------------------
# Layer 2: trace attribution helpers
# ---------------------------------------------------------------------------


def prepare_profile_dir(log_dir: str) -> str:
    """Validate a ``--profile-dir`` target BEFORE the run starts.

    ``jax.profiler.start_trace`` fails only when the trace is *written* (at
    ``stop_trace``, after the whole experiment ran) — so an unwritable
    directory must be refused up front, not mid-run. Creates the directory
    and probes writability; raises ``ValueError`` with the underlying OS
    error otherwise.
    """
    import tempfile

    try:
        os.makedirs(log_dir, exist_ok=True)
        # mkstemp, not a fixed probe name: under multihost every process
        # probes the same shared directory concurrently, and a shared name
        # races (A removes the probe B just created -> spurious failure).
        fd, probe = tempfile.mkstemp(prefix=".write_probe.", dir=log_dir)
        os.close(fd)
        os.remove(probe)
    except OSError as e:
        raise ValueError(
            f"--profile-dir {log_dir!r} is not a writable directory: {e}"
        ) from e
    return log_dir


@contextlib.contextmanager
def profile_session(log_dir: Optional[str], validate: bool = True):
    """``jax.profiler`` trace over a block, with the writability check done
    eagerly (see :func:`prepare_profile_dir` — ``start_trace`` itself only
    fails when the trace is flushed, after the run). ``None`` = no-op, so
    callers can wrap unconditionally; the actual trace is
    :func:`runtime.debugger.profiler_trace` (dead code from the seed until
    ``run.py --profile-dir`` wired it here). ``validate=False`` skips the
    writability probe for callers that already ran it (run.py pre-checks so
    it can fail as a clean argparse error). Under multihost every process
    traces into the same directory — the profiler namespaces by host."""
    if log_dir is None:
        yield
        return
    from distributed_active_learning_tpu.runtime.debugger import profiler_trace

    if validate:
        prepare_profile_dir(log_dir)
    with profiler_trace(log_dir):
        yield


def jit_cache_size(fn) -> Optional[int]:
    """Compiled-executable count of a jitted callable (None if unknowable).

    Growth between two observations of the SAME function means a recompile —
    a shape/dtype/static-arg changed under the driver, exactly the silent
    perf cliff launch accounting exists to surface.
    """
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def device_memory_gauges(prefix: str = "device") -> Dict[str, int]:
    """HBM watermarks from ``Device.memory_stats()`` when the backend reports
    them (TPU/GPU do; CPU returns None -> empty dict).

    Aggregated as the MAX over this host's local devices: on a multi-device
    host the OOM-binding constraint is the worst single device, and reading
    only device 0 would hide a hot shard on device 3.
    """
    per_dev = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            per_dev.append(stats)
    if not per_dev:
        return {}
    out = {}
    for key, name in (
        ("bytes_in_use", f"{prefix}_bytes_in_use"),
        ("peak_bytes_in_use", f"{prefix}_peak_bytes_in_use"),
    ):
        vals = [int(s[key]) for s in per_dev if key in s]
        if vals:
            out[name] = max(vals)
    return out


# ---------------------------------------------------------------------------
# Layer 3: structured metrics sink
# ---------------------------------------------------------------------------


class MetricsWriter:
    """Rank-tagged JSONL event stream.

    One line per event: ``{"ts": <unix s>, "kind": ..., "rank": <process>,
    ...payload}``. Every process may construct one (the chunked driver calls
    it symmetrically), but only the primary process holds the file handle —
    non-primary writers accumulate counters and participate in the collective
    gauge gather without touching disk.

    The file opens in APPEND mode: a checkpoint-resumed run (`run.py
    --checkpoint-dir` relaunch with the same ``--metrics-out``) must extend
    the crashed run's stream, not truncate the very post-mortem record it
    exists to keep; each resume starts with a fresh ``meta`` event, so
    consumers can segment runs.

    ``flush_every`` batches flushes: the default 1 keeps the original
    flush-per-event post-mortem guarantee (event volume in the batch drivers
    is a handful per touchdown), while the streaming service — which emits
    one ``serve_latency`` event PER QUERY on its hot path — passes a larger
    value and relies on :func:`install_exit_flush` (SIGTERM/atexit) to keep
    the buffered tail on a kill.
    """

    def __init__(
        self, path: str, rank: Optional[int] = None, flush_every: int = 1
    ):
        self.path = path
        self.rank = jax.process_index() if rank is None else rank
        self.flush_every = max(int(flush_every), 1)
        self._since_flush = 0
        self.counters: Dict[str, float] = {}
        self._f = None
        # Serializes line writes: the --stream-rounds path emits events from
        # the jax.debug.callback runtime thread CONCURRENTLY with the main
        # thread's touchdown events, and two interleaved self._f.write calls
        # would corrupt the JSONL stream. REENTRANT: install_exit_flush's
        # SIGTERM handler runs on the main thread and may interrupt an
        # in-progress event() that already holds the lock — a plain Lock
        # would deadlock the shutdown path there; re-entering flush() mid-
        # write is safe (the partial line stays buffered in order).
        self._lock = threading.RLock()
        if self._is_primary():
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a")

    def _is_primary(self) -> bool:
        return self.rank == 0

    @staticmethod
    def _json_safe(v):
        """Strict-JSON floats: ``json.dumps`` would happily emit bare
        ``NaN``/``Infinity`` tokens (allow_nan defaults True), which jq and
        every non-Python consumer reject — map non-finite values to None."""
        if isinstance(v, float) and not np.isfinite(v):
            return None
        if isinstance(v, list):
            return [MetricsWriter._json_safe(x) for x in v]
        if isinstance(v, dict):
            return {k: MetricsWriter._json_safe(x) for k, x in v.items()}
        return v

    def event(self, kind: str, **fields) -> None:
        if self._f is None:
            return
        line = {"ts": round(time.time(), 3), "kind": kind, "rank": self.rank}
        line.update(fields)
        text = json.dumps(self._json_safe(line)) + "\n"
        with self._lock:
            if self._f is None:  # closed between the fast check and here
                return
            self._f.write(text)
            # Flush per event by default: the stream's whole point is
            # post-mortem visibility, and a SIGKILLed/preempted run never
            # reaches close(). High-rate producers (the serve loop's
            # per-query latency events) raise flush_every and install the
            # SIGTERM/atexit flush instead (install_exit_flush).
            self._since_flush += 1
            if self._since_flush >= self.flush_every:
                self._f.flush()
                self._since_flush = 0

    # -- the event vocabulary ------------------------------------------------

    def meta(self, **fields) -> None:
        """Run-identity header (config, backend, mesh) — first line."""
        self.event("meta", **fields)

    def round(self, **fields) -> None:
        """One AL round: counts, accuracy, phase times, RoundMetrics."""
        self.event("round", **fields)

    def counter(self, name: str, value: float) -> None:
        """Monotonic counter increment; the event carries the running total
        so a truncated stream still reads absolutely."""
        self.counters[name] = self.counters.get(name, 0.0) + value
        self.event("counter", name=name, value=value, total=self.counters[name])

    def gauge(self, name: str, value) -> None:
        self.event("gauge", name=name, value=value)

    def gauges(self, values: Dict[str, float], allgather: bool = False) -> None:
        """Emit a dict of gauges. With ``allgather=True`` the values cross a
        ``process_allgather`` first (COLLECTIVE — every process must call),
        and the primary writes one event per gauge carrying the per-host
        vector; single-process runs degrade to plain gauges."""
        if allgather and jax.process_count() > 1:
            from distributed_active_learning_tpu.parallel.multihost import (
                gather_scalar_gauges,
            )

            per_host = gather_scalar_gauges(values)
            for name, vec in per_host.items():
                self.event("gauge", name=name, value=sum(vec), per_host=vec)
            return
        for name, value in values.items():
            self.gauge(name, value)

    def launch(
        self,
        program: str,
        seconds: float,
        first_call: bool,
        cache_size: Optional[int] = None,
        recompiled: bool = False,
        **extra,
    ) -> None:
        """Launch accounting: the first call of a jitted program includes
        tracing + XLA compile, so its wall time is reported separately from
        steady-state executes; ``recompiled`` flags jit-cache growth on a
        non-first call (the silent recompile cliff). ``extra`` carries the
        pipelined driver's overlap accounting (``touchdown_seconds``,
        ``overlap_seconds``, ``touchdown_hidden_fraction`` — how much of the
        chunk's host touchdown ran hidden under another chunk's execution,
        runtime/pipeline.py)."""
        self.event(
            "launch",
            program=program,
            seconds=round(seconds, 6),
            first_call=first_call,
            cache_size=cache_size,
            recompiled=recompiled,
            **{
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in extra.items()
            },
        )

    def roofline(self, program: str, **fields) -> None:
        """Per-program roofline attribution (analysis/roofline.py): static
        flops/bytes joined with measured seconds into achieved FLOP/s,
        bandwidth, MFU, and the compute-vs-bandwidth bound verdict — emitted
        once per program at run end (the cost extraction pays an AOT
        compile, so it never rides the hot path)."""
        self.event("roofline", program=program, **fields)

    # -- lifecycle -----------------------------------------------------------

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._since_flush = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def install_exit_flush(writer: MetricsWriter) -> None:
    """Flush ``writer`` on SIGTERM and at interpreter exit.

    Long-running service runs buffer their JSONL stream (``flush_every`` >
    1), and an orchestrator kill (``timeout``/k8s preemption SIGTERMs before
    SIGKILLing) would otherwise lose the buffered tail — exactly the events
    that explain the kill. The SIGTERM handler flushes and then CHAINS to the
    previously-installed handler (bench.py's JSON-printing unwinder, the
    default terminator, ...), so installing this never changes a process's
    shutdown semantics — it only makes the stream durable first. Idempotent
    per writer; atexit covers clean exits and SIGINT's KeyboardInterrupt
    unwind.
    """
    import atexit
    import signal

    if getattr(writer, "_exit_flush_installed", False):
        return
    writer._exit_flush_installed = True
    atexit.register(writer.flush)

    prev = signal.getsignal(signal.SIGTERM)
    if prev is None:
        # A handler installed from C — unknowable and unchainable. Replacing
        # it would either drop that handler or (worse) leave the process
        # ignoring SIGTERM after our flush; leave it alone and rely on the
        # atexit flush instead.
        return

    def _flush_and_chain(signum, frame):
        try:
            writer.flush()
        except RuntimeError:
            # Signal landed inside the io stack's own C-level write: CPython
            # forbids the reentrant flush. The interrupted write completes
            # (and flushes) when the frame resumes; chaining matters more
            # than this one flush.
            pass
        if callable(prev):
            prev(signum, frame)
        else:
            # SIG_DFL (or SIG_IGN, where flushing was the only work to do):
            # re-deliver with the default disposition so the exit status
            # still reports death-by-SIGTERM.
            if prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _flush_and_chain)
    except ValueError:
        pass  # non-main thread: atexit still covers clean exits


# ---------------------------------------------------------------------------
# Layer 4: the launch flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded in-process ring buffer of runtime events — the post-mortem.

    BENCH_r05 died at rc 124 with ``parsed: null`` and left NOTHING saying
    what it was doing; the JSONL metrics stream only exists when a writer was
    configured, and the bench never configures one. The flight recorder is
    the always-cheap middle ground: every launch / touchdown / veto / refit /
    growth / recompile event (and the bench's mode transitions) appends a
    small dict to a fixed-capacity deque — no I/O, no device reads — and
    :meth:`dump` writes the last N events as one JSON artifact when something
    goes wrong: SIGUSR1 (operator probe of a live run), SIGTERM (an outer
    ``timeout`` unwinding), or an unhandled crash (sys.excepthook).

    Library code records through the module-level :func:`flight_record`
    hook, which is a no-op until :func:`install_flight_recorder` runs — the
    fast paths never pay for a recorder nobody installed.
    """

    def __init__(self, path: Optional[str] = None, capacity: int = 256):
        self.path = path
        self.capacity = int(capacity)
        self._events: collections.deque = collections.deque(maxlen=self.capacity)
        # REENTRANT: dump() runs from signal handlers, which interrupt the
        # main thread between bytecodes — possibly inside record()'s locked
        # block. A plain Lock would deadlock there (the holder is the very
        # frame the handler interrupted); with an RLock the handler's dump
        # proceeds, at worst seeing a half-recorded last event.
        self._lock = threading.RLock()
        self._seq = 0
        self._dumped_reasons: List[str] = []

    def record(self, kind: str, **fields) -> None:
        ev = {"seq": 0, "ts": round(time.time(), 3), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        """Events that aged out of the ring (total recorded - retained)."""
        with self._lock:
            return self._seq - len(self._events)

    def dump(self, reason: str) -> Optional[str]:
        """Write the ring to ``self.path`` as one JSON artifact; returns the
        path (None when the recorder has no path). Safe to call repeatedly —
        each dump rewrites the artifact with the reasons seen so far, so a
        SIGTERM dump followed by the unwind's crash dump keeps both labels.
        Atomic rename so a kill mid-dump never leaves a torn artifact."""
        if not self.path:
            return None
        with self._lock:
            payload = {
                "schema": 1,
                "reason": reason,
                "reasons": self._dumped_reasons + [reason],
                "pid": os.getpid(),
                "dumped_ts": round(time.time(), 3),
                "capacity": self.capacity,
                "recorded_total": self._seq,
                "dropped": self._seq - len(self._events),
                "events": [MetricsWriter._json_safe(e) for e in self._events],
            }
            self._dumped_reasons.append(reason)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)
        return self.path


_FLIGHT_RECORDER: Optional[FlightRecorder] = None


def flight_recorder() -> Optional[FlightRecorder]:
    return _FLIGHT_RECORDER


def flight_record(kind: str, **fields) -> None:
    """Record into the installed flight recorder; no-op without one. The
    library-side hook: LaunchTracker / the pipelined driver / the streaming
    service call this unconditionally."""
    rec = _FLIGHT_RECORDER
    if rec is not None:
        rec.record(kind, **fields)


def flight_dump(reason: str) -> Optional[str]:
    """Dump the installed recorder (no-op None without one)."""
    rec = _FLIGHT_RECORDER
    return rec.dump(reason) if rec is not None else None


#: Default ring capacity when neither the caller nor the environment says
#: otherwise. ``DAL_FLIGHT_RING`` overrides it process-wide — a long-running
#: service whose post-mortem needs more than the last 256 events raises it
#: without a redeploy; the configured capacity rides every dump header.
_DEFAULT_FLIGHT_RING = 256


def flight_ring_capacity(capacity: Optional[int] = None) -> int:
    """Resolve the flight-recorder ring capacity: an explicit argument wins,
    else the ``DAL_FLIGHT_RING`` env var, else 256. Non-positive or
    unparseable values are refused loudly — a zero-capacity ring would
    silently record nothing, which is the exact failure mode the recorder
    exists to prevent."""
    if capacity is None:
        raw = os.environ.get("DAL_FLIGHT_RING", "")
        if raw.strip():
            try:
                capacity = int(raw)
            except ValueError:
                raise ValueError(
                    f"DAL_FLIGHT_RING={raw!r} is not an integer"
                ) from None
        else:
            capacity = _DEFAULT_FLIGHT_RING
    if capacity <= 0:
        raise ValueError(
            f"flight ring capacity must be positive, got {capacity}"
        )
    return int(capacity)


def install_flight_recorder(
    path: Optional[str],
    capacity: Optional[int] = None,
    signals: bool = True,
) -> FlightRecorder:
    """Install the process-wide flight recorder (replacing any previous one).

    ``capacity`` None resolves through :func:`flight_ring_capacity`
    (``DAL_FLIGHT_RING`` env, else 256); whatever wins is recorded in every
    dump header so a post-mortem reader knows how much history the ring
    could have held. With ``signals=True`` (drivers; tests pass False to
    keep the pytest process unhooked) also arms the dump triggers:

    - **SIGUSR1** dumps and keeps running — probe a live run from outside
      (``kill -USR1 <pid>``) without disturbing it;
    - **SIGTERM** dumps, then CHAINS to the previously-installed handler
      (bench.py's JSON-printing unwinder, the default terminator, ...) —
      same discipline as :func:`install_exit_flush`;
    - **sys.excepthook** dumps on an unhandled crash, then chains.
    """
    import signal
    import sys

    global _FLIGHT_RECORDER
    rec = FlightRecorder(path, flight_ring_capacity(capacity))
    _FLIGHT_RECORDER = rec
    if not signals:
        return rec

    def _usr1(_signum, _frame):
        try:
            rec.dump("sigusr1")
        except OSError:
            pass  # a probe of a live run must never kill it

    try:
        signal.signal(signal.SIGUSR1, _usr1)
    except (ValueError, AttributeError):
        pass  # non-main thread / platform without SIGUSR1

    prev_term = signal.getsignal(signal.SIGTERM)

    def _term(signum, frame):
        try:
            rec.dump("sigterm")
        except OSError:
            pass  # an unwritable path must not eat the shutdown
        if callable(prev_term):
            prev_term(signum, frame)
        elif prev_term == signal.SIG_DFL:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            signal.raise_signal(signal.SIGTERM)

    if prev_term is not None:  # None = C-installed, unchainable; leave it
        try:
            signal.signal(signal.SIGTERM, _term)
        except ValueError:
            pass

    prev_hook = sys.excepthook

    def _crash_hook(exc_type, exc, tb):
        try:
            rec.dump(f"crash:{exc_type.__name__}")
        except OSError:
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _crash_hook
    return rec


def uninstall_flight_recorder() -> None:
    """Detach the recorder from :func:`flight_record` (tests). Signal
    handlers armed by a ``signals=True`` install keep a reference to their
    own recorder and would still dump its now-frozen ring — tests wanting
    full isolation install with ``signals=False``."""
    global _FLIGHT_RECORDER
    _FLIGHT_RECORDER = None


def program_obs_feeds(program: str):
    """The three ops-plane children every launch tracker feeds — ONE
    definition of the (family, help) pairs so :class:`LaunchTracker` and the
    serving ``_ProgramTracker`` can never drift on the shared series names
    (``dal_recompiles_after_warmup_total`` is CI-gated by name). Returns
    ``(launches_counter, seconds_histogram, recompiles_counter)``; touching
    the recompile counter here makes the family render 0 from the first
    scrape on, before anything could have recompiled."""
    return (
        obs.counter("launches", "jitted program launches", program=program),
        obs.histogram(
            "launch_seconds", "per-launch wall seconds", program=program
        ),
        obs.counter(
            "recompiles_after_warmup",
            "jit-cache growths past each program's first call",
        ),
    )


class LaunchTracker:
    """Per-program compile-vs-execute split + recompile detection.

    Wraps the touchdown bookkeeping the chunked driver does around its one
    jitted program: remember whether the program has launched before and the
    last observed jit-cache size, and emit one ``launch`` event per call.
    """

    def __init__(self, writer: Optional[MetricsWriter], program: str, fn=None):
        self.writer = writer
        self.program = program
        self.fn = fn
        self.calls = 0
        self.vetoes = 0
        self.seconds_total = 0.0
        self.first_seconds: Optional[float] = None  # the compile call's wall
        self._last_cache = None
        # Live ops plane (runtime/obs.py): children cached at construction —
        # the registry lookup must not sit on the per-launch path.
        self._obs_launches, self._obs_seconds, self._obs_recompiles = (
            program_obs_feeds(program)
        )

    def veto(self, index: int, reason: Optional[str]) -> None:
        """One vetoed speculative launch (runtime/pipeline.py ``on_veto``):
        the driver PROVED chunk ``index`` would be wholly inactive and never
        dispatched it. Emitted as a structured ``launch_veto`` event so veto
        counts are assertable from the JSONL stream — previously a vetoed
        launch was just silence."""
        self.vetoes += 1
        obs.counter(
            "launch_vetoes", "speculative launches proven inactive a priori",
            program=self.program,
        ).inc()
        flight_record(
            "launch_veto", program=self.program, index=index,
            reason=reason or "unknown",
        )
        if self.writer is not None:
            self.writer.event(
                "launch_veto",
                program=self.program,
                index=index,
                reason=reason or "unknown",
            )

    def record(self, seconds: float, **extra) -> None:
        """One launch observation; ``extra`` (e.g. the pipelined driver's
        ``touchdown_seconds``/``overlap_seconds``/``touchdown_hidden_fraction``)
        rides the JSONL event verbatim. Mirrored into the flight recorder
        (when installed) even without a writer — the post-mortem must not
        depend on --metrics-out having been passed."""
        self.calls += 1
        self.seconds_total += seconds
        if self.calls == 1:
            self.first_seconds = seconds
        cache = jit_cache_size(self.fn) if self.fn is not None else None
        recompiled = (
            self.calls > 1
            and cache is not None
            and self._last_cache is not None
            and cache > self._last_cache
        )
        self._last_cache = cache
        self._obs_launches.inc()
        self._obs_seconds.observe(seconds)
        flight_record(
            "launch", program=self.program, call=self.calls,
            seconds=round(seconds, 6), first_call=self.calls == 1,
            recompiled=recompiled,
        )
        if recompiled:
            self._obs_recompiles.inc()
            flight_record(
                "recompile", program=self.program, call=self.calls,
                cache_size=cache,
            )
        if self.writer is None:
            return
        self.writer.launch(
            self.program,
            seconds,
            first_call=self.calls == 1,
            cache_size=cache,
            recompiled=recompiled,
            **extra,
        )

    def steady_seconds_mean(self) -> Optional[float]:
        """Mean wall per launch EXCLUDING the first call (trace + XLA
        compile); the first call itself when it is all we have. None before
        any launch — roofline attribution must not divide by a guess."""
        if self.calls == 0:
            return None
        if self.calls == 1 or self.first_seconds is None:
            return self.seconds_total / self.calls
        return (self.seconds_total - self.first_seconds) / (self.calls - 1)


def emit_roofline(
    writer, tracker: LaunchTracker, fn, args, n_devices: int = 1
) -> Optional[dict]:
    """Join ``fn``'s static cost with ``tracker``'s measured launch seconds
    and emit one ``roofline`` JSONL event (plus a flight-recorder echo).

    Called AFTER a run completes (run.py ``--roofline`` via the chunked
    driver): the cost extraction compiles the program again through the AOT
    path, so it must never sit inside a timed region. ``n_devices`` must be
    the mesh size for sharded programs — MFU divides by the AGGREGATE peak,
    and defaulting a mesh run to one chip would overstate it mesh-fold.
    Failures degrade to an event carrying ``error`` — attribution is
    diagnostics, it must not kill a finished run. Returns the attribution
    dict (or None on failure).
    """
    from distributed_active_learning_tpu.analysis import roofline as roofline_lib

    seconds = tracker.steady_seconds_mean()
    try:
        cost = roofline_lib.program_cost(fn, *args)
    except Exception as e:  # noqa: BLE001 — diagnostics must not raise
        if writer is not None:
            writer.roofline(
                tracker.program, error=f"{type(e).__name__}: {e}"
            )
        return None
    attr = roofline_lib.attribute(cost, seconds, n_devices=n_devices)
    if writer is not None:
        writer.roofline(tracker.program, calls=tracker.calls, **attr)
    flight_record(
        "roofline", program=tracker.program, bound=attr["bound"],
        mfu=attr["mfu"],
    )
    return attr
