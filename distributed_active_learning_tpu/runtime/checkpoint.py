"""Checkpoint/resume of full AL-experiment state.

The reference persists only *models* (``save_regression_model.py:28-34``
try-load-else-train against HDFS; MLlib classifier save observed broken,
``mllib_random_forest_classifer.py:55-58``) — never the AL loop state, so a
crashed run restarts from scratch (SURVEY.md §5.4). Here a checkpoint captures
everything needed to resume mid-experiment: the labeled mask, PRNG key, round
counter, and the accuracy history. Pool features are NOT stored (they are
reproducible from the dataset config); masks + key make the resumed run
bit-identical. Neural experiments additionally persist the network's
parameters and optimizer state (:func:`save_neural`).

Format: step-numbered ``.npz`` files (portable, atomic via rename) + the
records as JSON lines. Masks are stored over *real* pool rows only — mesh
padding is a placement detail, so a checkpoint written under one ``--mesh-data``
resumes under any other (the mesh is deliberately absent from fingerprints).

Chunk-boundary saves: the scan-fused driver (``runtime/loop.py``
``make_chunk_fn``) only touches the host every ``rounds_per_launch`` rounds,
so with ``checkpoint_every = N`` it writes at the first chunk boundary at or
after each multiple of N — step numbers need not land on the multiples
themselves. Nothing else changes: the payload is the same
``alstate_<round>.npz``, the fingerprint excludes ``rounds_per_launch`` (like
the mesh, it is performance-only — chunked and per-round drivers produce
bit-identical state, tests/test_chunked_driver.py), so a checkpoint written
by either driver resumes under the other, at any chunk size.

Bit-identical resume holds for same-mesh resumes on both loops, and for
cross-mesh resumes of the *forest* loop (the sharded round matches the
unsharded one bit-for-bit, tests/test_parallel.py). Cross-mesh resumes of the
*neural* loop are legitimate but may diverge from the original curve when the
pool is not divisible by the data axis: the neural path's per-row RNG draws
(fit minibatch sampling, dropout, deep.random) are shaped by the padded pool
length, so a different padding perturbs the draws even though padded rows are
never selectable.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.parallel import mesh as mesh_lib
from distributed_active_learning_tpu.runtime import state as state_lib
from distributed_active_learning_tpu.runtime.results import ExperimentResult, RoundRecord
from distributed_active_learning_tpu.runtime.state import PoolState

_STEP_RE = re.compile(r"^alstate_(\d+)\.npz$")


def fingerprint_from_ident(ident: dict) -> str:
    """Stable 16-hex-digit hash of an experiment-identity dict."""
    import hashlib

    blob = json.dumps(ident, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _forest_ident(cfg, with_mesh: bool) -> dict:
    forest_ident = dataclasses.asdict(cfg.forest)
    # The evaluation kernel is a pure-performance knob (gather/gemm agree
    # bit-for-bit on votes) — switching it between runs is a legitimate resume.
    # Caveat: the pallas kernel compares features in bfloat16, so for
    # host-fit forests on float features a gemm<->pallas swap across a resume
    # can flip a vote whose feature sits within bf16 rounding of a threshold
    # (~0.4%); device-fit forests compare integer bin codes and are exact
    # (ops/trees_pallas.py numerics note). Kept out of the identity because
    # refusing the resume outright would also refuse the exact cases.
    forest_ident.pop("kernel", None)
    # Unquantized storage ("none", the default) stays out of the identity so
    # checkpoints written before the field existed keep their fingerprint;
    # int8/bf16 storage changes votes (int8) or at least the stored forest
    # and participates.
    if forest_ident.get("quantize", "none") == "none":
        forest_ident.pop("quantize", None)
    ident = {
        "data": dataclasses.asdict(cfg.data),
        "forest": forest_ident,
        "strategy": {
            **dataclasses.asdict(cfg.strategy),
            "options": dict(cfg.strategy.options),
        },
        "n_start": cfg.n_start,
        "seed": cfg.seed,
    }
    # An inactive scenario (kind "none", the default) stays out of the
    # identity — the quantize="none" convention — so every pre-scenario
    # checkpoint keeps its fingerprint and a scenario-disabled run is
    # bit-identical to pre-scenario launches. An ACTIVE scenario changes the
    # oracle/selection/eval semantics, so it participates fully.
    scn = getattr(cfg, "scenario", None)
    if scn is not None and getattr(scn, "kind", "none") != "none":
        ident["scenario"] = dataclasses.asdict(scn)
    if with_mesh:
        ident["mesh"] = dataclasses.asdict(cfg.mesh)
    return ident


def config_fingerprint(cfg) -> str:
    """Hash of the experiment's *identity* fields — dataset, forest, strategy,
    seeding. Loop controls (max_rounds, label_budget, checkpoint/log paths) and
    the mesh (performance-only: the sharded round matches the unsharded one
    bit-for-bit, tests/test_parallel.py) are excluded: resuming with a larger
    round budget or a different device mesh is legitimate; resuming under a
    different strategy or dataset silently continues a mismatched experiment,
    which :func:`restore_latest` refuses.
    """
    return fingerprint_from_ident(_forest_ident(cfg, with_mesh=False))


def accepted_fingerprints(cfg) -> tuple:
    """Current fingerprint plus the legacy (mesh-included) form, so
    checkpoints written before the mesh was dropped from the identity still
    resume when the full config (mesh included) matches."""
    return (
        config_fingerprint(cfg),
        fingerprint_from_ident(_forest_ident(cfg, with_mesh=True)),
    )


def kernel_ident(cfg) -> str:
    """``"<fit>:<kernel>"`` — recorded in the checkpoint *payload* (not the
    fingerprint: kernel swaps are legitimate resumes) so :func:`_restore_base`
    can warn on the one swap that is not vote-exact (host-fit + pallas on
    either side, see the bf16 note at :func:`_forest_ident`)."""
    return f"{cfg.forest.fit}:{cfg.forest.kernel}"


def _kernel_swap_exact(stored: str, current: str) -> bool:
    """Whether resuming ``stored`` under ``current`` preserves votes exactly.

    gather/gemm agree bit-for-bit always; the pallas kernel compares features
    in bfloat16, which is exact for device-fit forests (integer bin codes) but
    can flip a host-fit vote whose float feature sits within bf16 rounding of
    a threshold (ops/trees_pallas.py numerics note).
    """
    (s_fit, s_kern), (c_fit, c_kern) = stored.split(":", 1), current.split(":", 1)
    if s_kern == c_kern:
        return True
    return "pallas" not in (s_kern, c_kern) or "host" not in (s_fit, c_fit)


def _base_payload(
    state: PoolState,
    result: ExperimentResult,
    fingerprint: Optional[str],
    kernel: Optional[str] = None,
) -> dict:
    """The checkpoint fields shared by the forest and neural formats.

    The mask is sliced to real rows so mesh padding never leaks into the file
    (a checkpoint written at ``--mesh-data 8`` must resume at ``--mesh-data 1``).
    """
    from distributed_active_learning_tpu.parallel.multihost import host_np

    payload = {
        # host_np: COLLECTIVE for multi-process data-sharded masks — which is
        # why save()/save_neural() build the payload BEFORE their primary-only
        # gate (every process must reach the allgather).
        "labeled_mask": host_np(state.labeled_mask)[: state.n_valid],
        "key": np.asarray(jax.random.key_data(state.key)),
        "round": np.asarray(int(state.round), dtype=np.int32),
        "records_json": np.frombuffer(
            json.dumps([dataclasses.asdict(r) for r in result.records]).encode(),
            dtype=np.uint8,
        ),
    }
    if fingerprint is not None:
        payload["config_fingerprint"] = np.frombuffer(
            fingerprint.encode(), dtype=np.uint8
        )
    if kernel is not None:
        payload["forest_kernel"] = np.frombuffer(kernel.encode(), dtype=np.uint8)
    return payload


def save(
    ckpt_dir: str,
    state: PoolState,
    result: ExperimentResult,
    fingerprint: Optional[str] = None,
    kernel: Optional[str] = None,
) -> Optional[str]:
    """Write a checkpoint for the state's current round; returns the path.

    Under multi-host SPMD every process runs the loop; only process 0 writes
    (``parallel.multihost.is_primary``) — returns ``None`` elsewhere.
    """
    payload = _base_payload(state, result, fingerprint, kernel)  # collective: all ranks
    if jax.process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    from distributed_active_learning_tpu.utils.io import atomic_savez

    return atomic_savez(
        os.path.join(ckpt_dir, f"alstate_{int(state.round)}.npz"), **payload
    )


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [
        int(m.group(1))
        for fn in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(fn))
    ]
    return max(steps) if steps else None


def _restore_base(
    z,
    step: int,
    state: PoolState,
    result: ExperimentResult,
    fingerprint: Optional[str],
    kernel: Optional[str] = None,
) -> Tuple[PoolState, ExperimentResult]:
    """Rebuild (state, result) from an open npz payload, enforcing the
    fingerprint and pool-size guards and re-applying mesh padding."""
    mask = jnp.asarray(z["labeled_mask"])
    key = jax.random.wrap_key_data(jnp.asarray(z["key"]))
    rnd = jnp.asarray(z["round"])
    records = json.loads(bytes(z["records_json"]).decode())
    stored_fp = (
        bytes(z["config_fingerprint"]).decode()
        if "config_fingerprint" in z.files
        else None
    )
    # ``fingerprint`` may be one hash or a tuple of acceptable hashes (the
    # current form plus legacy spellings, see accepted_fingerprints).
    accepted = (fingerprint,) if isinstance(fingerprint, str) else fingerprint
    if fingerprint is not None and stored_fp is not None and stored_fp not in accepted:
        raise ValueError(
            f"checkpoint config fingerprint {stored_fp} != current experiment "
            f"{accepted[0]}: refusing to resume a different experiment's state"
        )
    if fingerprint is not None and stored_fp is None:
        # Pre-fingerprint checkpoints carry no identity record, so the
        # config-mismatch guard cannot apply — say so instead of silently
        # resuming whatever experiment wrote the file.
        import warnings

        warnings.warn(
            f"resuming unfingerprinted checkpoint alstate_{step}.npz: the "
            "config-mismatch guard did not apply",
            stacklevel=3,
        )
    stored_kernel = (
        bytes(z["forest_kernel"]).decode() if "forest_kernel" in z.files else None
    )
    if (
        kernel is not None
        and stored_kernel is not None
        and stored_kernel != kernel
        and not _kernel_swap_exact(stored_kernel, kernel)
    ):
        import warnings

        warnings.warn(
            f"resuming a '{stored_kernel}' checkpoint under '{kernel}': the "
            "pallas kernel compares host-fit float features in bfloat16, so a "
            "vote whose feature sits within bf16 rounding (~0.4%) of a "
            "threshold can flip across this swap — the resumed curve may "
            "diverge from an uninterrupted run (ops/trees_pallas.py numerics "
            "note)",
            stacklevel=3,
        )
    n_stored = mask.shape[0]
    if n_stored == state.n_valid:
        pad = state.n_pool - n_stored
        if pad:
            # Padding rows read as labeled so selection never picks them
            # (same convention as state.pad_for_sharding).
            mask = jnp.pad(mask, (0, pad), constant_values=True)
    elif n_stored == state.n_pool:
        pass  # legacy format: mask stored over padded rows
    else:
        raise ValueError(
            f"checkpoint pool size ({n_stored},) != experiment pool "
            f"({state.n_valid},)"
        )
    new_state = state.replace(labeled_mask=mask, key=key, round=rnd)
    # Tolerant record rebuild: drop keys this build's RoundRecord doesn't
    # know. Records gained a `metrics` field (the telemetry PR's in-scan
    # RoundMetrics ride the records_json payload); a checkpoint written by a
    # NEWER build with further fields must still resume here — the fields are
    # observability, never loop state, so dropping unknowns is lossless for
    # the resume itself.
    known = {f.name for f in dataclasses.fields(RoundRecord)}
    new_result = ExperimentResult(
        records=[RoundRecord(**{k: v for k, v in r.items() if k in known})
                 for r in records]
    )
    return new_state, new_result


def restore_latest(
    ckpt_dir: str,
    state: PoolState,
    result: ExperimentResult,
    fingerprint: Optional[str] = None,
    kernel: Optional[str] = None,
) -> Optional[Tuple[PoolState, ExperimentResult]]:
    """Load the newest checkpoint into (state, result); None if none exists.

    With ``fingerprint`` set, a stored fingerprint that differs raises — the
    checkpoint belongs to a different experiment (strategy/dataset/forest/seed)
    and silently continuing it would corrupt the run. With ``kernel`` set
    (:func:`kernel_ident` form), a swap that is not vote-exact warns.
    """
    step = latest_step(ckpt_dir)
    if step is None:
        return None
    with np.load(os.path.join(ckpt_dir, f"alstate_{step}.npz")) as z:
        return _restore_base(z, step, state, result, fingerprint, kernel)


_SWEEP_STEP_RE = re.compile(r"^sweepstate_(\d+)\.npz$")


def sweep_fingerprint(cfg, seeds, windows) -> str:
    """Identity hash of a batched sweep (runtime/sweep.py): the base
    experiment identity plus the seed and window vectors — a sweep checkpoint
    must only resume the SAME batch (same seeds in the same order, same
    per-experiment windows), since the file stores all E experiments' state
    positionally."""
    ident = _forest_ident(cfg, with_mesh=False)
    ident["sweep"] = {
        "seeds": [int(s) for s in seeds],
        "windows": [int(w) for w in windows],
    }
    return fingerprint_from_ident(ident)


def _save_batched(
    ckpt_dir: str,
    prefix: str,
    masks,
    key_data,
    rounds,
    results,
    n_cols: int,
    fingerprint: Optional[str],
) -> Optional[str]:
    """Shared body of :func:`save_sweep` / :func:`save_grid`: one npz file
    covering every row (experiment or grid cell) of a batched launch. The
    step number is the MAX round across rows (finished rows' rounds freeze,
    so once every row has stopped, later saves overwrite that same step
    file). Primary-process-only under multi-host, like :func:`save`."""
    from distributed_active_learning_tpu.parallel.multihost import host_np

    masks_np = host_np(masks)[:, :n_cols]  # collective: all ranks
    payload = {
        "labeled_mask": masks_np,
        "key": np.asarray(key_data),
        "round": np.asarray(rounds, dtype=np.int32),
        "records_json": np.frombuffer(
            json.dumps(
                [[dataclasses.asdict(r) for r in res.records] for res in results]
            ).encode(),
            dtype=np.uint8,
        ),
    }
    if fingerprint is not None:
        payload["config_fingerprint"] = np.frombuffer(
            fingerprint.encode(), dtype=np.uint8
        )
    if jax.process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    from distributed_active_learning_tpu.utils.io import atomic_savez

    step = int(np.asarray(rounds).max())
    return atomic_savez(os.path.join(ckpt_dir, f"{prefix}_{step}.npz"), **payload)


def _latest_batched_step(ckpt_dir: str, step_re) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [
        int(m.group(1))
        for fn in os.listdir(ckpt_dir)
        if (m := step_re.match(fn))
    ]
    return max(steps) if steps else None


def _restore_latest_batched(
    ckpt_dir: str,
    prefix: str,
    step_re,
    n_cols: int,
    n_rows: int,
    fingerprint: Optional[str],
    kind: str,
    row_noun: str,
    width_noun: str,
    width_target: str,
):
    """Shared body of :func:`restore_latest_sweep` / :func:`restore_latest_grid`.

    Returns ``(masks [n_rows, n_cols], key_data, rounds [n_rows], results)``
    as host arrays + one :class:`ExperimentResult` per row, or ``None`` if no
    checkpoint exists. A fingerprint or shape mismatch raises — resuming a
    different launch's state positionally would silently cross-wire every
    row. ``kind``/``row_noun``/``width_noun``/``width_target`` keep the
    per-format error wording ("sweep ... experiments" vs "grid ... cells")."""
    step = _latest_batched_step(ckpt_dir, step_re)
    if step is None:
        return None
    with np.load(os.path.join(ckpt_dir, f"{prefix}_{step}.npz")) as z:
        stored_fp = (
            bytes(z["config_fingerprint"]).decode()
            if "config_fingerprint" in z.files
            else None
        )
        if fingerprint is not None and stored_fp is not None and stored_fp != fingerprint:
            raise ValueError(
                f"{kind} checkpoint fingerprint {stored_fp} != current {kind} "
                f"{fingerprint}: refusing to resume a different {kind}'s state"
            )
        masks = z["labeled_mask"]
        key_data = z["key"]
        rounds = z["round"]
        records = json.loads(bytes(z["records_json"]).decode())
    if masks.shape[0] != n_rows:
        raise ValueError(
            f"{kind} checkpoint holds {masks.shape[0]} {row_noun}, the "
            f"current {kind} has {n_rows}"
        )
    if masks.shape[1] != n_cols:
        raise ValueError(
            f"{kind} checkpoint {width_noun} ({masks.shape[1]},) != "
            f"{width_target} ({n_cols},)"
        )
    known = {f.name for f in dataclasses.fields(RoundRecord)}
    results = [
        ExperimentResult(
            records=[RoundRecord(**{k: v for k, v in r.items() if k in known})
                     for r in recs]
        )
        for recs in records
    ]
    return masks, key_data, rounds, results


def save_sweep(
    ckpt_dir: str,
    masks,
    key_data,
    rounds,
    results,
    n_valid: int,
    fingerprint: Optional[str] = None,
) -> Optional[str]:
    """Write one checkpoint covering all E experiments of a batched sweep.

    ``masks [E, n]`` / ``key_data`` / ``rounds [E]`` are the sweep carry's
    donation-safe snapshot (``runtime.loop.ckpt_snapshot`` over the batched
    state); per-experiment records serialize as a list of record lists.
    """
    return _save_batched(
        ckpt_dir, "sweepstate", masks, key_data, rounds, results, n_valid,
        fingerprint,
    )


def latest_sweep_step(ckpt_dir: str) -> Optional[int]:
    return _latest_batched_step(ckpt_dir, _SWEEP_STEP_RE)


def restore_latest_sweep(
    ckpt_dir: str,
    n_valid: int,
    n_experiments: int,
    fingerprint: Optional[str] = None,
):
    """Load the newest sweep checkpoint; ``None`` if none exists.

    Returns ``(masks [E, n_valid], key_data, rounds [E], results)`` as host
    arrays + one :class:`ExperimentResult` per experiment. A fingerprint or
    shape mismatch raises — resuming a different sweep's state positionally
    would silently cross-wire every experiment.
    """
    return _restore_latest_batched(
        ckpt_dir, "sweepstate", _SWEEP_STEP_RE, n_valid, n_experiments,
        fingerprint, kind="sweep", row_noun="experiments",
        width_noun="pool size", width_target="experiment pool",
    )


_GRID_STEP_RE = re.compile(r"^gridstate_(\d+)\.npz$")


def grid_fingerprint(cfg, strategies, seeds, datasets, windows, scenarios=None) -> str:
    """Identity hash of a grid launch (runtime/sweep.py ``run_grid``): the
    sweep fingerprint extended with the strategy and dataset axes. The file
    stores every cell's state positionally in (strategy, dataset, seed)
    order, so a grid checkpoint must only resume the SAME grid — same axes,
    same order. The base identity drops the strategy/data names (they live
    in the axes) but keeps the forest/seeding/loop identity fields."""
    ident = _forest_ident(cfg, with_mesh=False)
    # The anchor cfg carries the FIRST entry of each axis (run.py anchors
    # config-derived identities on a real cell); hashing those copies would
    # refuse a positionally-identical grid anchored on a different cell.
    # Shared identity (beta/options, data path/subsampling, n_start) stays.
    ident["strategy"].pop("name", None)
    ident["strategy"].pop("window_size", None)
    ident["data"].pop("name", None)
    ident.pop("seed", None)
    ident["grid"] = {
        "strategies": [str(s) for s in strategies],
        "seeds": [int(s) for s in seeds],
        "datasets": [str(d) for d in datasets],
        "windows": [int(w) for w in windows],
    }
    # The scenario axis participates only when present (the fingerprint of a
    # scenario-free grid is unchanged — the quantize="none"/_forest_ident
    # convention): cell states are stored positionally in (scenario,
    # strategy, dataset, seed) order, so a scenario grid must only resume
    # the same scenario axis.
    if scenarios:
        ident["grid"]["scenarios"] = [str(s) for s in scenarios]
    return fingerprint_from_ident(ident)


def save_grid(
    ckpt_dir: str,
    masks,
    key_data,
    rounds,
    results,
    n_store: int,
    fingerprint: Optional[str] = None,
) -> Optional[str]:
    """One checkpoint covering every cell of a grid launch.

    ``masks [C, n_slab]`` / ``key_data`` / ``rounds [C]`` are the grid
    carry's donation-safe snapshot; masks are sliced to ``n_store`` (the
    common pad width BEFORE mesh padding) so a grid checkpointed under one
    mesh resumes under another, like every other format here.
    """
    return _save_batched(
        ckpt_dir, "gridstate", masks, key_data, rounds, results, n_store,
        fingerprint,
    )


def latest_grid_step(ckpt_dir: str) -> Optional[int]:
    return _latest_batched_step(ckpt_dir, _GRID_STEP_RE)


def restore_latest_grid(
    ckpt_dir: str,
    n_store: int,
    n_cells: int,
    fingerprint: Optional[str] = None,
):
    """Load the newest grid checkpoint; ``None`` if none exists.

    Returns ``(masks [C, n_store], key_data, rounds [C], results)`` as host
    arrays + one :class:`ExperimentResult` per cell. A fingerprint or shape
    mismatch raises — resuming a different grid's state positionally would
    silently cross-wire every cell (same contract as
    :func:`restore_latest_sweep`).
    """
    return _restore_latest_batched(
        ckpt_dir, "gridstate", _GRID_STEP_RE, n_store, n_cells,
        fingerprint, kind="grid", row_noun="cells",
        width_noun="pool width", width_target="grid slab",
    )


_SERVE_STEP_RE = re.compile(r"^servestate_(\d+)\.npz$")
# The multi-tenant axis: one file series per tenant, the tenant id embedded
# in BOTH the name and the payload (the name routes, the payload verifies).
# Single-tenant files ("servestate_<round>.npz") have no second underscore,
# so the two series cannot collide in one directory.
_SERVE_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def _serve_step_re(tenant: Optional[str]) -> "re.Pattern[str]":
    if tenant is None:
        return _SERVE_STEP_RE
    if not _SERVE_TENANT_RE.fullmatch(tenant):
        raise ValueError(
            f"serve checkpoint tenant id {tenant!r} must match "
            f"{_SERVE_TENANT_RE.pattern} (it names files)"
        )
    return re.compile(rf"^servestate_{re.escape(tenant)}_(\d+)\.npz$")


def save_serve(
    ckpt_dir: str,
    state: PoolState,
    forest,
    result: ExperimentResult,
    fingerprint: Optional[str] = None,
    tenant: Optional[str] = None,
    edges: Optional[np.ndarray] = None,
    edges_epoch: Optional[int] = None,
) -> Optional[str]:
    """Streaming-service checkpoint: slab fill watermark + mask + ingested
    points + the resident fitted forest.

    ``edges``/``edges_epoch`` persist the service's LIVE bin-refresh state
    (serving/tenants.py ``_refresh_bins``): a drifting service re-quantizes
    its slab against refreshed edges at runtime, and a restore that re-binned
    from cold-start edges would hand the restored forest codes it was never
    fitted on. Both ride under the same fingerprint guard as the rest of the
    payload; ``None`` (a pre-refresh service, or an old caller) simply omits
    the leaves and restores report ``(None, 0)`` — old checkpoints stay
    restorable.

    Unlike the batch formats, the pool FEATURES are stored (sliced to the
    fill watermark): a service's pool is not reproducible from the dataset
    config — its tail arrived over the wire, and "resume without replaying
    ingest" is the whole point. The resident forest rides as flattened
    numbered arrays (like :func:`save_neural`'s network pytrees) so a
    restarted service answers its first query from the pre-kill model
    without waiting out a re-fit. Slab capacity is deliberately NOT stored:
    it is an allocation detail, and the restore re-pads to the restoring
    service's own ``slab_rows`` (the slab-growth parity tests prove tail
    content is unobservable).

    ``tenant`` is the multi-tenant axis (serving/tenants.py): each tenant
    writes its own ``servestate_<tenant>_<round>.npz`` series into the
    shared directory, with the id stored in the payload so a restore can
    refuse a cross-wired file even if someone renames it. ``None`` keeps the
    PR-7 single-tenant names — old checkpoints stay restorable, new
    single-tenant services stay byte-compatible.
    """
    from distributed_active_learning_tpu.parallel.multihost import host_np

    _serve_step_re(tenant)  # validates the id before any work
    if state.n_filled is None:
        raise ValueError("save_serve needs a slab-paged state (n_filled set)")
    # Global watermark for either spelling (scalar, or the pod-sharded [S]
    # per-shard leaf). The [:fill] slices below assume contiguous fill — true
    # for the scalar contract and for shard_fill_watermark-split pools; a
    # pool with genuinely independent per-shard ingest has holes a slice
    # cannot express, so refuse rather than silently drop rows.
    fill = int(state_lib.filled_count(state))
    if state.n_filled.ndim and not bool(
        np.asarray(
            state.n_filled
            == mesh_lib.shard_fill_watermark(
                fill, state.n_pool, state.n_filled.shape[0]
            )
        ).all()
    ):
        raise ValueError(
            "save_serve needs a contiguously-filled pool; this per-shard "
            f"watermark {np.asarray(state.n_filled)} has gaps"
        )
    # Like save()/save_neural(), the payload is built BEFORE the primary-only
    # gate: host_np is a collective for multi-process sharded arrays, so
    # every rank must reach it (serving is single-process today, but this
    # module's contract is uniform).
    payload = {
        "x": host_np(state.x)[:fill],
        "oracle_y": host_np(state.oracle_y)[:fill],
        "labeled_mask": host_np(state.labeled_mask)[:fill],
        "n_filled": np.asarray(fill, dtype=np.int32),
        "key": np.asarray(jax.random.key_data(state.key)),
        "round": np.asarray(int(state.round), dtype=np.int32),
        "records_json": np.frombuffer(
            json.dumps([dataclasses.asdict(r) for r in result.records]).encode(),
            dtype=np.uint8,
        ),
    }
    for i, leaf in enumerate(jax.tree_util.tree_leaves(forest)):
        payload[f"forest_leaf_{i}"] = np.asarray(leaf)
    if edges is not None:
        payload["bin_edges"] = np.asarray(edges, dtype=np.float32)
        payload["edges_epoch"] = np.asarray(
            0 if edges_epoch is None else int(edges_epoch), dtype=np.int32
        )
    elif edges_epoch:
        raise ValueError(
            f"save_serve got edges_epoch={edges_epoch} without the edges "
            "array; a restore could not re-code the slab from an epoch alone"
        )
    if fingerprint is not None:
        payload["config_fingerprint"] = np.frombuffer(
            fingerprint.encode(), dtype=np.uint8
        )
    if tenant is not None:
        payload["tenant_id"] = np.frombuffer(tenant.encode(), dtype=np.uint8)
    if jax.process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    from distributed_active_learning_tpu.utils.io import atomic_savez

    stem = (
        f"servestate_{int(state.round)}.npz"
        if tenant is None
        else f"servestate_{tenant}_{int(state.round)}.npz"
    )
    return atomic_savez(os.path.join(ckpt_dir, stem), **payload)


def latest_serve_step(ckpt_dir: str, tenant: Optional[str] = None) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    pat = _serve_step_re(tenant)
    steps = [
        int(m.group(1))
        for fn in os.listdir(ckpt_dir)
        if (m := pat.match(fn))
    ]
    return max(steps) if steps else None


def restore_latest_serve(
    ckpt_dir: str,
    forest_template,
    fingerprint: Optional[str] = None,
    tenant: Optional[str] = None,
):
    """Load the newest service checkpoint; ``None`` if none exists.

    Returns ``(x, y, labeled_mask, n_filled, key_data, round, forest,
    result, edges, edges_epoch)`` — host arrays plus the forest rebuilt
    against
    ``forest_template`` (the pytree ``jax.eval_shape`` of the service's own
    fit program produces; leaf count/shape mismatches mean a differently-
    configured forest and raise rather than resume garbage). A fingerprint
    mismatch raises, as in :func:`restore_latest`. ``tenant`` selects that
    tenant's file series (see :func:`save_serve`); the id stored in the
    payload must match, so a renamed file cannot cross-wire tenants.

    ``edges``/``edges_epoch`` are the persisted bin-refresh state —
    ``(None, 0)`` for checkpoints written before the refresh state rode
    along (or by a service that never refreshed): the restoring service then
    falls back to its cold-start edges, exactly the pre-PR behavior.
    """
    step = latest_serve_step(ckpt_dir, tenant=tenant)
    if step is None:
        return None
    stem = (
        f"servestate_{step}.npz"
        if tenant is None
        else f"servestate_{tenant}_{step}.npz"
    )
    with np.load(os.path.join(ckpt_dir, stem)) as z:
        stored_fp = (
            bytes(z["config_fingerprint"]).decode()
            if "config_fingerprint" in z.files
            else None
        )
        if fingerprint is not None and stored_fp is not None and stored_fp != fingerprint:
            raise ValueError(
                f"serve checkpoint fingerprint {stored_fp} != current service "
                f"{fingerprint}: refusing to resume a different service's pool"
            )
        stored_tenant = (
            bytes(z["tenant_id"]).decode() if "tenant_id" in z.files else None
        )
        if tenant is not None and stored_tenant != tenant:
            raise ValueError(
                f"serve checkpoint {stem} stores tenant "
                f"{stored_tenant!r}, not {tenant!r}: refusing to cross-wire "
                "tenants from a renamed file"
            )
        x = z["x"]
        y = z["oracle_y"]
        mask = z["labeled_mask"]
        n_filled = int(z["n_filled"])
        key_data = z["key"]
        rnd = z["round"]
        edges = z["bin_edges"] if "bin_edges" in z.files else None
        edges_epoch = (
            int(z["edges_epoch"]) if "edges_epoch" in z.files else 0
        )
        records = json.loads(bytes(z["records_json"]).decode())
        leaves, treedef = jax.tree_util.tree_flatten(forest_template)
        stored = sorted(
            int(k[len("forest_leaf_"):])
            for k in z.files
            if k.startswith("forest_leaf_")
        )
        if stored != list(range(len(leaves))):
            raise ValueError(
                f"{stem} holds {len(stored)} forest arrays but "
                f"this configuration's forest has {len(leaves)} — not a "
                "checkpoint of this forest shape"
            )
        new_leaves = []
        for i, tmpl in enumerate(leaves):
            arr = z[f"forest_leaf_{i}"]
            if tuple(arr.shape) != tuple(tmpl.shape):
                raise ValueError(
                    f"serve checkpoint forest leaf {i} shape {arr.shape} != "
                    f"expected {tuple(tmpl.shape)}: different forest "
                    "configuration"
                )
            new_leaves.append(jnp.asarray(arr, dtype=tmpl.dtype))
    forest = jax.tree_util.tree_unflatten(treedef, new_leaves)
    if x.shape[0] != n_filled:
        raise ValueError(
            f"serve checkpoint stores {x.shape[0]} rows but watermark is "
            f"{n_filled}: truncated or corrupt file"
        )
    known = {f.name for f in dataclasses.fields(RoundRecord)}
    result = ExperimentResult(
        records=[RoundRecord(**{k: v for k, v in r.items() if k in known})
                 for r in records]
    )
    return x, y, mask, n_filled, key_data, rnd, forest, result, edges, edges_epoch


def save_neural(
    ckpt_dir: str,
    state: PoolState,
    result: ExperimentResult,
    net_state,
    loop_key: jax.Array,
    fingerprint: Optional[str] = None,
) -> Optional[str]:
    """Neural-experiment checkpoint: AL state + network params/optimizer.

    Extends :func:`save` with what the neural loop additionally needs to
    resume bit-identically: the round-trained network's ``TrainState``
    (params + optimizer state pytrees, flattened to numbered npz entries) and
    the loop's own PRNG key. This closes the round-2 gap where the neural path
    had no persistence at all — a crashed CIFAR run lost every acquired label
    (the reference persists only *models*, never AL state; SURVEY.md §5.4).
    Primary-process-only under multi-host, like :func:`save`.
    """
    payload = _base_payload(state, result, fingerprint)  # collective: all ranks
    payload["loop_key"] = np.asarray(jax.random.key_data(loop_key))
    payload["net_step"] = np.asarray(net_state.step, dtype=np.int32)
    # Network leaves are replicated (DP) — fully-replicated global arrays
    # convert directly even when the mesh spans processes.
    for i, leaf in enumerate(jax.tree_util.tree_leaves(net_state.params)):
        payload[f"net_param_{i}"] = np.asarray(leaf)
    for i, leaf in enumerate(jax.tree_util.tree_leaves(net_state.opt_state)):
        payload[f"net_opt_{i}"] = np.asarray(leaf)
    if jax.process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    from distributed_active_learning_tpu.utils.io import atomic_savez

    return atomic_savez(
        os.path.join(ckpt_dir, f"alstate_{int(state.round)}.npz"), **payload
    )


def _unflatten_like(template, z, prefix: str, step: int):
    """Rebuild a pytree from numbered npz entries using ``template``'s
    structure; leaf count/shape mismatches mean the checkpoint belongs to a
    differently-shaped network and resuming it would be garbage."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    stored = sorted(
        (int(k[len(prefix):]) for k in z.files if k.startswith(prefix))
    )
    if stored != list(range(len(leaves))):
        raise ValueError(
            f"checkpoint alstate_{step}.npz holds {len(stored)} '{prefix}*' "
            f"arrays but the network has {len(leaves)} — not a checkpoint of "
            "this model (or not a neural checkpoint at all)"
        )
    new_leaves = []
    for i, tmpl in enumerate(leaves):
        arr = z[f"{prefix}{i}"]
        if tuple(arr.shape) != tuple(jnp.shape(tmpl)):
            raise ValueError(
                f"checkpoint leaf {prefix}{i} shape {arr.shape} != network "
                f"leaf shape {jnp.shape(tmpl)}: different architecture"
            )
        new_leaves.append(jnp.asarray(arr, dtype=jnp.asarray(tmpl).dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def restore_latest_neural(
    ckpt_dir: str,
    state: PoolState,
    result: ExperimentResult,
    template_net_state,
    fingerprint: Optional[str] = None,
):
    """Load the newest neural checkpoint; ``None`` if the directory is empty.

    Returns ``(state, result, net_state, loop_key)``. The network pytrees are
    rebuilt against ``template_net_state`` (a freshly initialized TrainState),
    so architecture drift is caught by shape/leaf-count checks on top of the
    config-fingerprint guard. One file read covers both the base AL state and
    the network arrays.
    """
    step = latest_step(ckpt_dir)
    if step is None:
        return None
    with np.load(os.path.join(ckpt_dir, f"alstate_{step}.npz")) as z:
        new_state, new_result = _restore_base(z, step, state, result, fingerprint)
        if "loop_key" not in z.files:
            raise ValueError(
                f"alstate_{step}.npz is not a neural checkpoint (no loop_key/"
                "network arrays) — it was written by the forest loop"
            )
        loop_key = jax.random.wrap_key_data(jnp.asarray(z["loop_key"]))
        params = _unflatten_like(template_net_state.params, z, "net_param_", step)
        opt_state = _unflatten_like(template_net_state.opt_state, z, "net_opt_", step)
        net_step = jnp.asarray(z["net_step"])
    net_state = type(template_net_state)(
        params=params, opt_state=opt_state, step=net_step
    )
    return new_state, new_result, net_state, loop_key
