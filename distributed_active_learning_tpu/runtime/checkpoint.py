"""Checkpoint/resume of full AL-experiment state.

The reference persists only *models* (``save_regression_model.py:28-34``
try-load-else-train against HDFS; MLlib classifier save observed broken,
``mllib_random_forest_classifer.py:55-58``) — never the AL loop state, so a
crashed run restarts from scratch (SURVEY.md §5.4). Here a checkpoint captures
everything needed to resume mid-experiment: the labeled mask, PRNG key, round
counter, and the accuracy history. Pool features are NOT stored (they are
reproducible from the dataset config); masks + key make the resumed run
bit-identical.

Format: step-numbered ``.npz`` files (portable, atomic via rename) + the
records as JSON lines.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import tempfile
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.runtime.results import ExperimentResult, RoundRecord
from distributed_active_learning_tpu.runtime.state import PoolState

_STEP_RE = re.compile(r"^alstate_(\d+)\.npz$")


def save(ckpt_dir: str, state: PoolState, result: ExperimentResult) -> str:
    """Write a checkpoint for the state's current round; returns the path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    step = int(state.round)
    payload = {
        "labeled_mask": np.asarray(state.labeled_mask),
        "key": np.asarray(jax.random.key_data(state.key)),
        "round": np.asarray(step, dtype=np.int32),
        "records_json": np.frombuffer(
            json.dumps([dataclasses.asdict(r) for r in result.records]).encode(),
            dtype=np.uint8,
        ),
    }
    final = os.path.join(ckpt_dir, f"alstate_{step}.npz")
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, final)  # atomic publish
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return final


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [
        int(m.group(1))
        for fn in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(fn))
    ]
    return max(steps) if steps else None


def restore_latest(
    ckpt_dir: str, state: PoolState, result: ExperimentResult
) -> Optional[Tuple[PoolState, ExperimentResult]]:
    """Load the newest checkpoint into (state, result); None if none exists."""
    step = latest_step(ckpt_dir)
    if step is None:
        return None
    with np.load(os.path.join(ckpt_dir, f"alstate_{step}.npz")) as z:
        mask = jnp.asarray(z["labeled_mask"])
        key = jax.random.wrap_key_data(jnp.asarray(z["key"]))
        rnd = jnp.asarray(z["round"])
        records = json.loads(bytes(z["records_json"]).decode())
    if mask.shape != state.labeled_mask.shape:
        raise ValueError(
            f"checkpoint pool size {mask.shape} != experiment pool {state.labeled_mask.shape}"
        )
    new_state = state.replace(labeled_mask=mask, key=key, round=rnd)
    new_result = ExperimentResult(records=[RoundRecord(**r) for r in records])
    return new_state, new_result
