"""Checkpoint/resume of full AL-experiment state.

The reference persists only *models* (``save_regression_model.py:28-34``
try-load-else-train against HDFS; MLlib classifier save observed broken,
``mllib_random_forest_classifer.py:55-58``) — never the AL loop state, so a
crashed run restarts from scratch (SURVEY.md §5.4). Here a checkpoint captures
everything needed to resume mid-experiment: the labeled mask, PRNG key, round
counter, and the accuracy history. Pool features are NOT stored (they are
reproducible from the dataset config); masks + key make the resumed run
bit-identical.

Format: step-numbered ``.npz`` files (portable, atomic via rename) + the
records as JSON lines.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.runtime.results import ExperimentResult, RoundRecord
from distributed_active_learning_tpu.runtime.state import PoolState

_STEP_RE = re.compile(r"^alstate_(\d+)\.npz$")


def config_fingerprint(cfg) -> str:
    """Hash of the experiment's *identity* fields — dataset, forest, strategy,
    mesh, seeding. Loop controls (max_rounds, label_budget, checkpoint/log
    paths) are excluded: resuming with a larger round budget is legitimate;
    resuming under a different strategy or dataset silently continues a
    mismatched experiment, which :func:`restore_latest` refuses.
    """
    import hashlib

    forest_ident = dataclasses.asdict(cfg.forest)
    # The evaluation kernel is a pure-performance knob (gather/gemm agree
    # bit-for-bit on votes) — switching it between runs is a legitimate resume.
    forest_ident.pop("kernel", None)
    ident = {
        "data": dataclasses.asdict(cfg.data),
        "forest": forest_ident,
        "strategy": {
            **dataclasses.asdict(cfg.strategy),
            "options": dict(cfg.strategy.options),
        },
        "mesh": dataclasses.asdict(cfg.mesh),
        "n_start": cfg.n_start,
        "seed": cfg.seed,
    }
    blob = json.dumps(ident, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def save(
    ckpt_dir: str,
    state: PoolState,
    result: ExperimentResult,
    fingerprint: Optional[str] = None,
) -> str:
    """Write a checkpoint for the state's current round; returns the path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    step = int(state.round)
    payload = {
        "labeled_mask": np.asarray(state.labeled_mask),
        "key": np.asarray(jax.random.key_data(state.key)),
        "round": np.asarray(step, dtype=np.int32),
        "records_json": np.frombuffer(
            json.dumps([dataclasses.asdict(r) for r in result.records]).encode(),
            dtype=np.uint8,
        ),
    }
    if fingerprint is not None:
        payload["config_fingerprint"] = np.frombuffer(
            fingerprint.encode(), dtype=np.uint8
        )
    from distributed_active_learning_tpu.utils.io import atomic_savez

    return atomic_savez(os.path.join(ckpt_dir, f"alstate_{step}.npz"), **payload)


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [
        int(m.group(1))
        for fn in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(fn))
    ]
    return max(steps) if steps else None


def restore_latest(
    ckpt_dir: str,
    state: PoolState,
    result: ExperimentResult,
    fingerprint: Optional[str] = None,
) -> Optional[Tuple[PoolState, ExperimentResult]]:
    """Load the newest checkpoint into (state, result); None if none exists.

    With ``fingerprint`` set, a stored fingerprint that differs raises — the
    checkpoint belongs to a different experiment (strategy/dataset/forest/seed)
    and silently continuing it would corrupt the run.
    """
    step = latest_step(ckpt_dir)
    if step is None:
        return None
    with np.load(os.path.join(ckpt_dir, f"alstate_{step}.npz")) as z:
        mask = jnp.asarray(z["labeled_mask"])
        key = jax.random.wrap_key_data(jnp.asarray(z["key"]))
        rnd = jnp.asarray(z["round"])
        records = json.loads(bytes(z["records_json"]).decode())
        stored_fp = (
            bytes(z["config_fingerprint"]).decode()
            if "config_fingerprint" in z.files
            else None
        )
    if fingerprint is not None and stored_fp is not None and stored_fp != fingerprint:
        raise ValueError(
            f"checkpoint config fingerprint {stored_fp} != current experiment "
            f"{fingerprint}: refusing to resume a different experiment's state"
        )
    if fingerprint is not None and stored_fp is None:
        # Pre-fingerprint checkpoints carry no identity record, so the
        # config-mismatch guard cannot apply — say so instead of silently
        # resuming whatever experiment wrote the file.
        import warnings

        warnings.warn(
            f"resuming unfingerprinted checkpoint alstate_{step}.npz: the "
            "config-mismatch guard did not apply",
            stacklevel=2,
        )
    if mask.shape != state.labeled_mask.shape:
        raise ValueError(
            f"checkpoint pool size {mask.shape} != experiment pool {state.labeled_mask.shape}"
        )
    new_state = state.replace(labeled_mask=mask, key=key, round=rnd)
    new_result = ExperimentResult(records=[RoundRecord(**r) for r in records])
    return new_state, new_result
