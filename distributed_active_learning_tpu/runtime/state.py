"""Device-resident active-learning pool state.

The reference keeps the labeled/unlabeled split as two index RDDs re-joined to
the data every round (``final_thesis/uncertainty_sampling.py:48-55,62-63``;
``classes/dataset.py:56-130`` ``indicesKnown``/``indicesUnknown``), paying a
Spark shuffle per round and growing RDD lineage forever. The TPU-native design
(SURVEY.md §7): the pool is one dense array pinned in HBM and the split is a
boolean mask updated functionally on device — fixed shapes, no recompiles, no
host round-trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class PoolState:
    """Full state of one AL experiment's pool.

    ``oracle_y`` holds every pool label but strategies may only consume labels
    where ``labeled_mask`` is True — the mask IS the oracle boundary. This
    mirrors the reference, whose train RDD also physically contains all labels
    while strategies only join the known-index RDD against it
    (``active_learner.py:65-67``).
    """

    x: jnp.ndarray             # [n, d] float32 — pool features
    oracle_y: jnp.ndarray      # [n] int32 — all labels (revealed via mask)
    labeled_mask: jnp.ndarray  # [n] bool
    key: jax.Array             # PRNG key threaded through rounds
    round: jnp.ndarray         # scalar int32 round counter

    @property
    def n_pool(self) -> int:
        return self.x.shape[0]

    @property
    def unlabeled_mask(self) -> jnp.ndarray:
        return ~self.labeled_mask

    def visible_y(self, fill: int = -1) -> jnp.ndarray:
        """Labels with unlabeled entries masked to ``fill`` — what a strategy may see."""
        return jnp.where(self.labeled_mask, self.oracle_y, fill)


def labeled_count(state: PoolState) -> jnp.ndarray:
    return jnp.sum(state.labeled_mask.astype(jnp.int32))


def unlabeled_count(state: PoolState) -> jnp.ndarray:
    return jnp.sum((~state.labeled_mask).astype(jnp.int32))


def init_pool_state(x, y, key: jax.Array) -> PoolState:
    """Wrap arrays into a fresh all-unlabeled PoolState."""
    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.int32)
    return PoolState(
        x=x,
        oracle_y=y,
        labeled_mask=jnp.zeros(x.shape[0], dtype=bool),
        key=key,
        round=jnp.asarray(0, dtype=jnp.int32),
    )


def set_start_state(state: PoolState, n_start: int) -> PoolState:
    """Seed the labeled set: one point of each class plus ``n_start - 2`` extras.

    Functional equivalent of ``Dataset.setStartState``
    (``classes/dataset.py:56-130``): the reference shuffles the class-1 and
    class-0 index RDDs by random keys and takes one of each (``:90-106``), then
    shuffles the remainder and adds ``nStart - 2`` more (``:110-124``); the rest
    become ``indicesUnknown`` (``:128-130``). Here the same selection is a pair
    of masked argmaxes over random priorities plus a top-(n_start-2) over the
    remainder — one jittable function, no shuffles.
    """
    n = state.n_pool
    if n_start > n:
        raise ValueError(f"n_start={n_start} exceeds pool size {n}")
    # The class-seed step always labels one point per class, so the effective
    # minimum is 2 (the reference behaves identically: dataset.py:90-106).
    if not isinstance(state.oracle_y, jax.core.Tracer):
        y = np.asarray(state.oracle_y)
        if not ((y == 1).any() and (y == 0).any()):
            raise ValueError(
                "set_start_state needs at least one point of each class in the "
                "pool (the reference's take(1) on an empty class RDD would fail "
                "the same way: dataset.py:90-106)"
            )
    key, k_pos, k_neg, k_rest = jax.random.split(state.key, 4)

    pri_pos = jax.random.uniform(k_pos, (n,))
    pri_neg = jax.random.uniform(k_neg, (n,))
    pos_mask = state.oracle_y == 1
    neg_mask = state.oracle_y == 0
    pos_pick = jnp.argmax(jnp.where(pos_mask, pri_pos, -1.0))
    neg_pick = jnp.argmax(jnp.where(neg_mask, pri_neg, -1.0))

    mask = jnp.zeros(n, dtype=bool).at[pos_pick].set(True).at[neg_pick].set(True)

    n_extra = max(n_start - 2, 0)
    if n_extra > 0:
        pri_rest = jax.random.uniform(k_rest, (n,))
        _, extra_idx = jax.lax.top_k(jnp.where(mask, -1.0, pri_rest), n_extra)
        mask = mask.at[extra_idx].set(True)

    return state.replace(labeled_mask=mask, key=key)


def reveal(state: PoolState, picked_idx: jnp.ndarray) -> PoolState:
    """Label the picked pool indices (the oracle call) and advance the round.

    Replaces the reference's set-algebra pool update
    (``subtractByKey``/``union`` at ``uncertainty_sampling.py:111-112``;
    ``filter`` + ``union`` at ``active_learner.py:209-215``) with one scatter
    into the mask.
    """
    mask = state.labeled_mask.at[picked_idx].set(True)
    return state.replace(labeled_mask=mask, round=state.round + 1)
