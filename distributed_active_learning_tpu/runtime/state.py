"""Device-resident active-learning pool state.

The reference keeps the labeled/unlabeled split as two index RDDs re-joined to
the data every round (``final_thesis/uncertainty_sampling.py:48-55,62-63``;
``classes/dataset.py:56-130`` ``indicesKnown``/``indicesUnknown``), paying a
Spark shuffle per round and growing RDD lineage forever. The TPU-native design
(SURVEY.md §7): the pool is one dense array pinned in HBM and the split is a
boolean mask updated functionally on device — fixed shapes, no recompiles, no
host round-trips.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class PoolState:
    """Full state of one AL experiment's pool.

    ``oracle_y`` holds every pool label but strategies may only consume labels
    where ``labeled_mask`` is True — the mask IS the oracle boundary. This
    mirrors the reference, whose train RDD also physically contains all labels
    while strategies only join the known-index RDD against it
    (``active_learner.py:65-67``).
    """

    x: jnp.ndarray             # [n, d] float32 — pool features
    oracle_y: jnp.ndarray      # [n] int32 — all labels (revealed via mask)
    labeled_mask: jnp.ndarray  # [n] bool
    key: jax.Array             # PRNG key threaded through rounds
    round: jnp.ndarray         # scalar int32 round counter
    # Number of real pool rows; -1 means "all". Rows past this are mesh-
    # divisibility padding (see pad_for_sharding): marked labeled so selection
    # never picks them, and masked out of every real-point statistic via
    # valid_mask. Static (not a pytree leaf) so jitted rounds specialize on it.
    n_valid_static: int = struct.field(pytree_node=False, default=-1)
    # Dynamic fill watermark (slab-paged streaming pools, serving/slab.py):
    # a TRACED int32 leaf — rows past it are allocated-but-unfilled slab
    # capacity, excluded from selection, fit gathers, and every statistic via
    # the dynamic masks below. A leaf (unlike n_valid_static) so ingest can
    # advance it launch-to-launch without changing any program's avals —
    # arrivals never retrigger compilation. None (batch pools) keeps every
    # mask/count on the static fast path, bit-identical to the pre-slab code.
    # Two spellings:
    #   - scalar: one global watermark, rows [0, n_filled) filled (the
    #     single-device slab contract, unchanged);
    #   - [S] per-shard (pod-sharded pools, parallel.mesh.shard_pool_state):
    #     the pool splits into S contiguous row blocks of n_pool // S rows
    #     and n_filled[s] is shard s's OWN watermark — the leaf lives
    #     P(data), so per-shard ingest advances it without a global
    #     renumbering, and the global filled count is the (psum-shaped) sum
    #     over shards (:func:`filled_count`).
    n_filled: Optional[jnp.ndarray] = None

    @property
    def n_pool(self) -> int:
        return self.x.shape[0]

    @property
    def n_valid(self) -> int:
        return self.n_pool if self.n_valid_static < 0 else self.n_valid_static

    @property
    def fill_mask(self) -> jnp.ndarray:
        """Rows below the fill watermark; all-True when no watermark is set.

        Handles both watermark spellings: a scalar compares against the
        global row index; a per-shard ``[S]`` leaf compares each shard's
        block-local row index against that shard's own watermark (block s =
        rows ``[s * rows, (s + 1) * rows)`` with ``rows = n_pool // S`` —
        the contiguous-block layout ``shard_pool_state`` places over
        ``data``).
        """
        if self.n_filled is None:
            return jnp.ones(self.n_pool, dtype=bool)
        if self.n_filled.ndim == 0:
            return jnp.arange(self.n_pool) < self.n_filled
        (n_shards,) = self.n_filled.shape
        rows = self.n_pool // n_shards
        local = jnp.arange(self.n_pool) % rows
        return local < jnp.repeat(self.n_filled, rows)

    @property
    def valid_mask(self) -> jnp.ndarray:
        mask = jnp.arange(self.n_pool) < self.n_valid
        if self.n_filled is not None:
            mask = mask & self.fill_mask
        return mask

    @property
    def unlabeled_mask(self) -> jnp.ndarray:
        # Unfilled slab rows keep labeled_mask=False (ingest never touches the
        # mask) and are excluded here instead, so strategies/selection see
        # exactly the filled unlabeled rows.
        if self.n_filled is not None:
            return ~self.labeled_mask & self.fill_mask
        return ~self.labeled_mask

    def visible_y(self, fill: int = -1) -> jnp.ndarray:
        """Labels with unlabeled entries masked to ``fill`` — what a strategy may see."""
        return jnp.where(self.labeled_mask, self.oracle_y, fill)


def labeled_count(state: PoolState) -> jnp.ndarray:
    """Number of *real* labeled points (padding/unfilled rows never count)."""
    if state.n_filled is None and state.n_valid == state.n_pool:
        return jnp.sum(state.labeled_mask.astype(jnp.int32))
    return jnp.sum((state.labeled_mask & state.valid_mask).astype(jnp.int32))


def unlabeled_count(state: PoolState) -> jnp.ndarray:
    return jnp.sum(state.unlabeled_mask.astype(jnp.int32))


def filled_count(state: PoolState) -> jnp.ndarray:
    """Global filled-row count as one int32 scalar.

    The budget/stop-scalar view of the watermark: for a per-shard ``[S]``
    leaf this is the sum over shards — under GSPMD the jnp.sum of a
    ``P(data)``-placed leaf lowers to the same S-int all-reduce a
    ``lax.psum`` inside a shard_map body spells (``parallel.collectives
    .global_count`` is that explicit twin); for the scalar spelling it is
    the watermark itself, bit-identical to the pre-pod code.
    """
    if state.n_filled is None:
        return jnp.asarray(state.n_valid, jnp.int32)
    if state.n_filled.ndim == 0:
        return state.n_filled.astype(jnp.int32)
    return jnp.sum(state.n_filled).astype(jnp.int32)


def init_pool_state(x, y, key: jax.Array) -> PoolState:
    """Wrap arrays into a fresh all-unlabeled PoolState."""
    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.int32)
    return PoolState(
        x=x,
        oracle_y=y,
        labeled_mask=jnp.zeros(x.shape[0], dtype=bool),
        key=key,
        round=jnp.asarray(0, dtype=jnp.int32),
    )


def set_start_state(state: PoolState, n_start: int, n_classes: int = 2) -> PoolState:
    """Seed the labeled set: one point per (present) class plus random extras.

    Functional equivalent of ``Dataset.setStartState``
    (``classes/dataset.py:56-130``): the reference shuffles the class-1 and
    class-0 index RDDs by random keys and takes one of each (``:90-106``), then
    shuffles the remainder and adds ``nStart - 2`` more (``:110-124``); the rest
    become ``indicesUnknown`` (``:128-130``). Here the same selection is a
    masked argmax over random priorities per class plus a top-k over the
    remainder — one jittable function, no shuffles. ``n_classes > 2``
    generalizes the guarantee to multiclass pools (CIFAR/AG-News configs);
    classes absent from the pool are skipped (AG-News labels start at 1).
    """
    n = state.n_pool
    if n_start > n:
        raise ValueError(f"n_start={n_start} exceeds pool size {n}")
    present = [True] * n_classes
    if not isinstance(state.oracle_y, jax.core.Tracer):
        y = np.asarray(state.oracle_y)
        present = [(y == c).any() for c in range(n_classes)]
        if sum(present) < 2:
            raise ValueError(
                "set_start_state needs at least two classes present in the "
                "pool (the reference's take(1) on an empty class RDD would "
                "fail the same way: dataset.py:90-106)"
            )
    keys = jax.random.split(state.key, n_classes + 2)
    key, k_rest, k_classes = keys[0], keys[1], keys[2:]

    mask = jnp.zeros(n, dtype=bool)
    n_seeded = 0
    for c in range(n_classes):
        if not present[c]:
            continue
        pri = jax.random.uniform(k_classes[c], (n,))
        pick = jnp.argmax(jnp.where(state.oracle_y == c, pri, -1.0))
        mask = mask.at[pick].set(True)
        n_seeded += 1

    n_extra = max(n_start - n_seeded, 0)
    if n_extra > 0:
        pri_rest = jax.random.uniform(k_rest, (n,))
        _, extra_idx = jax.lax.top_k(jnp.where(mask, -1.0, pri_rest), n_extra)
        mask = mask.at[extra_idx].set(True)

    return state.replace(labeled_mask=mask, key=key)


def pad_for_sharding(state: PoolState, multiple: int) -> PoolState:
    """Pad the pool to a row count divisible by ``multiple`` (a mesh data-axis
    size), so ``shard_map``/GSPMD kernels see equal blocks per device.

    Padding rows carry zero features and ``labeled_mask=True``: the masked
    top-k can never select them (selection runs over ``~labeled_mask``), the
    density mass counts only unlabeled rows, and every real-point statistic
    (labeled_count, LAL's f_3/f_8) filters through ``valid_mask``. The real
    row count is recorded statically in ``n_valid_static``.
    """
    n = state.n_pool
    pad = (-n) % multiple
    if pad == 0:
        return state
    return state.replace(
        x=jnp.pad(state.x, ((0, pad), (0, 0))),
        oracle_y=jnp.pad(state.oracle_y, (0, pad)),
        labeled_mask=jnp.pad(state.labeled_mask, (0, pad), constant_values=True),
        n_valid_static=n,
    )


def select_state(pred: jnp.ndarray, on_true: PoolState, on_false: PoolState) -> PoolState:
    """Scalar-predicated state select: ``on_true`` if ``pred`` else ``on_false``.

    The chunked driver's masked no-op reveal (runtime/loop.py
    ``make_chunk_fn``): rounds past the label budget / pool exhaustion inside a
    ``lax.scan`` chunk must leave the carried state EXACTLY unchanged — mask,
    PRNG key, and round counter all frozen — so stopping stays exact rather
    than chunk-quantized, and a resumed or per-round run sees identical state.
    ``lax.cond`` (not ``jnp.where`` per leaf) so typed PRNG keys select
    cleanly; both arguments are already-computed pytrees, so no compute is
    duplicated.
    """
    return jax.lax.cond(pred, lambda: on_true, lambda: on_false)


def reveal(state: PoolState, picked_idx: jnp.ndarray) -> PoolState:
    """Label the picked pool indices (the oracle call) and advance the round.

    Replaces the reference's set-algebra pool update
    (``subtractByKey``/``union`` at ``uncertainty_sampling.py:111-112``;
    ``filter`` + ``union`` at ``active_learner.py:209-215``) with one scatter
    into the mask.
    """
    mask = state.labeled_mask.at[picked_idx].set(True)
    return state.replace(labeled_mask=mask, round=state.round + 1)


def reveal_masked(
    state: PoolState,
    picked_idx: jnp.ndarray,
    keep: jnp.ndarray,
    *,
    abstain_key: Optional[jax.Array] = None,
    abstain_prob: float = 0.0,
) -> PoolState:
    """:func:`reveal` restricted to the picks where ``keep`` is True.

    The batched-sweep round (runtime/sweep.py) pads every experiment's
    selection to the sweep's widest window so the vmapped top-k has one
    static k; picks past an experiment's own window must then be no-ops.
    ``.max(keep)`` writes True only for kept picks and leaves the mask
    untouched elsewhere — with ``keep`` all-True this is bit-identical to
    :func:`reveal` (True max x == True), so the homogeneous-window sweep
    reproduces the serial reveal exactly.

    ``abstain_key``/``abstain_prob`` make the reveal PROBABILISTIC (the
    noisy-oracle scenario, scenarios/engine.py): each kept pick is
    additionally revealed only with probability ``1 - abstain_prob`` — the
    per-pick draw comes from ``abstain_key`` (the round's scenario key fed
    from the scan carry, never from ``state.key``, so the clean PRNG stream
    is untouched). Abstained picks write nothing: the point stays unlabeled
    and re-enters the pool next round, which is exactly why budget
    accounting downstream (``labeled_count``, the chunk's
    ``ChunkExtras.n_labeled_after`` stop scalar) counts REVEALED labels —
    it reduces this mask — and never picks. With ``abstain_prob == 0`` the
    draw is ``uniform >= 0``, identically True, and the mask write matches
    the deterministic reveal bit-for-bit.
    """
    if abstain_key is not None:
        draw = jax.random.uniform(abstain_key, picked_idx.shape)
        keep = keep & (draw >= abstain_prob)
    mask = state.labeled_mask.at[picked_idx].max(keep)
    return state.replace(labeled_mask=mask, round=state.round + 1)


def reveal_masked_local(
    mask_block: jnp.ndarray,
    picked_idx: jnp.ndarray,
    keep: jnp.ndarray,
    shard_index: jnp.ndarray,
    rows: int,
    *,
    abstain_key: Optional[jax.Array] = None,
    abstain_prob: float = 0.0,
) -> jnp.ndarray:
    """Shard-local spelling of :func:`reveal_masked` for the pod-sharded pool.

    Call INSIDE a ``shard_map`` body: ``mask_block [rows]`` is this shard's
    contiguous mask block, ``picked_idx`` the window of GLOBAL indices
    (replicated — the ring-merged selection's ``out_specs=P()`` output), and
    ``shard_index`` the shard's data-axis index. Each shard keeps only the
    picks landing in its own block ``[shard_index * rows, (shard_index + 1)
    * rows)`` and scatters into LOCAL positions — zero collectives, the
    reveal's traffic is the already-replicated window.

    The abstain draw runs on every shard from the same replicated
    ``abstain_key`` over the same window shape, so per-shard draws are
    bit-identical to the global spelling's single draw — concatenating the S
    shard blocks reproduces :func:`reveal_masked`'s mask exactly (pinned by
    the pod-pool parity tests). Foreign picks redirect to local row 0 with
    ``keep=False``; ``.max(False)`` writes nothing.
    """
    if abstain_key is not None:
        draw = jax.random.uniform(abstain_key, picked_idx.shape)
        keep = keep & (draw >= abstain_prob)
    local = picked_idx - shard_index * rows
    mine = (local >= 0) & (local < rows)
    safe = jnp.clip(local, 0, rows - 1)
    return mask_block.at[safe].max(keep & mine)
