"""Runtime: AL pool state, driver loop, checkpointing, tracing, results logging.

Replaces the reference's L5 experiment-driver layer (module-level while-loops in
``final_thesis/*.py`` and the driver tail of ``classes/active_learner.py:369-384``)
plus the auxiliary subsystems it lacked (SURVEY.md §5): structured tracing,
checkpoint/resume of full AL state, and a results logger.
"""

from distributed_active_learning_tpu.runtime.state import (
    PoolState,
    init_pool_state,
    set_start_state,
    labeled_count,
    unlabeled_count,
    reveal,
)
