"""Live ops plane: in-process metrics registry + pull-based HTTP exporter.

PR 8's observatory (roofline, flight recorder, JSONL metrics stream) is
post-hoc by construction: every signal lands in a file or a signal-triggered
dump, readable only after the run — exactly how BENCH_r05 died with
``parsed: null`` and nothing watchable in flight. The stack has since become
a long-running system (multi-tenant serving, multi-hour scenario grids), and
a long-running system needs what every production training/inference stack
has: a live, pull-based metrics surface. This module is that surface, in
three stdlib-only layers (no jax import — the exporter must work from any
process, including the bench's own scraper thread and future sidecars):

1. **Registry** — named :class:`Counter` / :class:`Gauge` /
   :class:`Histogram` families with Prometheus-style labels
   (``registry().counter("serve_queries", tenant="t0").inc()``). Histograms
   use FIXED log-scale buckets (:data:`LATENCY_BUCKETS`, 5 per decade from
   10us to 100s): bounded memory per series, counts merge exactly across
   threads/tenants/shards (integer adds — the MLPerf logging discipline),
   and p50/p99 come from the bucket counts, never from stored samples.
   Everything renders two ways: :meth:`Registry.render_prometheus` (the
   ``/metrics`` text format) and :meth:`Registry.snapshot` (the ``/varz``
   JSON). Heartbeats (:meth:`Registry.heartbeat`) are timestamps with an
   optional staleness bound — the ``/healthz`` liveness source.

2. **SLO accounting** — :class:`SLOTracker`: a latency/availability
   objective (queries answering successfully within ``objective_seconds``
   count as good), lifetime compliance ratio, and multi-window burn rates
   (``bad_fraction / error_budget`` — the Google SRE workbook's
   burn-rate alerting form: burn 1.0 spends the budget exactly at the
   target rate; 14.4 spends a 30-day budget in 2 days). Windowed counts
   live in coarse time slots (bounded memory, no per-query timestamps).

3. **Ops endpoint** — :class:`OpsServer`, a ``ThreadingHTTPServer`` bound
   to localhost (``ServeConfig.ops_port`` / ``--ops-port``, off by
   default):

   - ``/metrics``  Prometheus text format (scrape me);
   - ``/healthz``  event-loop liveness + last-touchdown age (200/503);
   - ``/varz``     the full registry snapshot as JSON;
   - ``/flightz``  trigger + return a flight-recorder dump — the SIGUSR1
     probe over HTTP (lazy import of runtime.telemetry; 404 when no
     recorder is installed).

The registry is fed by the existing instrumentation points —
``runtime.telemetry.LaunchTracker`` (launches, recompiles, vetoes),
``runtime.pipeline.run_pipelined`` (in-flight depth, touchdown-hidden
fraction), ``serving/tenants.py`` + ``frontend.py`` (per-tenant query/
ingest/refit counters, cause-tagged latency histograms, queue depth,
admission rejects, slab growths, AOT-precompile hits, SLO gauges), and
``runtime.sweep.run_grid`` (cell rounds, frozen cells, ETA) — so one
``curl localhost:PORT/metrics`` answers "what is this process doing RIGHT
NOW" for every subsystem. Recording is host-side dict/int work only: no
traced program changes, no device reads — the disabled-by-default ops
*endpoint* gates the HTTP listener, never the (cheap, bounded) counting.
"""

from __future__ import annotations

import bisect
import collections
import json
import math
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LATENCY_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "SLOTracker",
    "OpsServer",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "heartbeat",
]

#: Metric-name prefix on every exported series — one namespace to scrape-
#: filter on (``dal`` = distributed active learning).
PROM_PREFIX = "dal_"

#: Fixed log-scale latency bucket upper bounds (seconds): 5 per decade from
#: 10 microseconds to 100 seconds (36 edges; one-bucket width = a factor of
#: 10^(1/5) ~= 1.58x). Fixed — never adapted to the data — so two histograms
#: of the same family ALWAYS merge exactly, across threads, tenants, and
#: processes; the MLPerf-logging/Prometheus discipline.
LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    round(10.0 ** (e / 5.0), 12) for e in range(-25, 11)
)

_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise ValueError(
            f"metric name {name!r} must match [a-zA-Z_][a-zA-Z0-9_]* "
            "(it becomes a Prometheus series name)"
        )
    return name


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r"\"")
    )


def _fmt_value(v: float) -> str:
    """Prometheus sample values: integers render bare, floats via repr."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


class Counter:
    """Monotonic counter. ``inc`` only — a counter that goes down is a gauge."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        if n < 0:
            raise ValueError(f"counters only go up (inc by {n})")
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self.value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self.value -= n


class Histogram:
    """Fixed-bucket histogram: bounded memory, exactly mergeable, percentiles
    from bucket counts.

    ``edges`` are ascending upper bounds; counts hold ``len(edges) + 1``
    integer cells (cell i covers ``(edges[i-1], edges[i]]``, the last cell is
    the ``+Inf`` overflow). ``observe`` is a bisect + two adds — cheap enough
    for a per-query hot path. Merging two histograms of identical edges adds
    their integer counts, which is why shard-merged percentiles are
    bit-identical to single-shard ingestion (pinned in tests/test_obs.py).
    """

    __slots__ = ("edges", "counts", "sum", "_lock")

    def __init__(self, edges: Tuple[float, ...] = LATENCY_BUCKETS):
        edges = tuple(float(e) for e in edges)
        if len(edges) < 2 or any(b <= a for a, b in zip(edges, edges[1:])):
            # >= 2 edges: the first bucket's interpolation width is inferred
            # from the edge RATIO, which a single edge cannot supply
            raise ValueError("histogram edges must be >= 2 and ascending")
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.edges, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v

    @property
    def count(self) -> int:
        return sum(self.counts)

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other``'s counts into this histogram (identical edges
        required — fixed buckets exist so this can never be a re-binning)."""
        if other.edges != self.edges:
            raise ValueError("cannot merge histograms with different edges")
        with self._lock:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.sum += other.sum
        return self

    def percentile(self, q: float) -> Optional[float]:
        """Bucket-derived percentile (``q`` in [0, 1]): find the bucket the
        rank falls in, interpolate geometrically inside it (linear in log
        space — the buckets are log-spaced). The estimate is within one
        bucket width (a factor of ``edges[i+1]/edges[i]``) of the exact
        sample percentile by construction; None on an empty histogram."""
        with self._lock:
            counts = list(self.counts)
        return self._percentile_from(counts, q)

    def _percentile_from(self, counts: List[int], q: float) -> Optional[float]:
        """Percentile over an already-copied counts list — so a snapshot's
        derived percentiles describe the SAME observation set as its
        count/sum fields, not whatever concurrent observes added since."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"percentile q must be in [0, 1], got {q}")
        total = sum(counts)
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                if i == len(self.edges):
                    # overflow bucket: no upper bound to interpolate toward
                    return self.edges[-1]
                hi = self.edges[i]
                if i == 0:
                    lo = hi / (self.edges[1] / self.edges[0])
                else:
                    lo = self.edges[i - 1]
                frac = (rank - (cum - c)) / c
                frac = min(max(frac, 0.0), 1.0)
                if lo <= 0.0:
                    return hi * frac
                return lo * (hi / lo) ** frac
        return self.edges[-1]

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self.counts)
            total_sum = self.sum
        total = sum(counts)
        out = {"count": total, "sum": round(total_sum, 9), "counts": counts}
        if total:
            out["p50"] = self._percentile_from(counts, 0.50)
            out["p90"] = self._percentile_from(counts, 0.90)
            out["p99"] = self._percentile_from(counts, 0.99)
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family: kind + labeled children."""

    __slots__ = ("name", "kind", "help", "children", "buckets")

    def __init__(self, name, kind, help_text, buckets=None):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.buckets = buckets
        self.children: Dict[Tuple[Tuple[str, str], ...], object] = {}


class Registry:
    """Thread-safe registry of metric families, heartbeats, and health.

    ``counter``/``gauge``/``histogram`` are get-or-create: the first call
    fixes the family's kind (a name re-used across kinds is refused loudly),
    later calls with the same labels return the SAME child, so callers may
    cache children on hot paths or just re-look-them-up on cold ones.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        # name -> (wall_ts, monotonic_ts, max_age_seconds)
        self._heartbeats: Dict[str, Tuple[float, float, Optional[float]]] = {}
        self._created = time.time()
        self._created_mono = time.monotonic()

    # -- metric creation -----------------------------------------------------

    def _child(self, kind: str, name: str, help_text: str, labels: dict,
               buckets=None):
        _check_name(name)
        for k in labels:
            _check_name(k)
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help_text, buckets)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} is a {fam.kind}, not a {kind}"
                )
            child = fam.children.get(key)
            if child is None:
                if kind == "histogram":
                    child = Histogram(fam.buckets or LATENCY_BUCKETS)
                else:
                    child = _KINDS[kind]()
                fam.children[key] = child
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._child("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._child("gauge", name, help, labels)

    def histogram(
        self, name: str, help: str = "", buckets=None, **labels
    ) -> Histogram:
        return self._child("histogram", name, help, labels, buckets=buckets)

    # -- heartbeats / health -------------------------------------------------

    def heartbeat(self, name: str, max_age_seconds: Optional[float] = None) -> None:
        """Mark ``name`` alive now. A heartbeat with ``max_age_seconds`` set
        participates in the ``/healthz`` verdict: staler than its bound =>
        the whole process reports unhealthy (503)."""
        _check_name(name)
        with self._lock:
            if max_age_seconds is None and name in self._heartbeats:
                max_age_seconds = self._heartbeats[name][2]
            self._heartbeats[name] = (
                time.time(), time.monotonic(), max_age_seconds
            )

    def clear_heartbeat(self, name: str) -> None:
        """Forget a heartbeat (a cleanly-stopped loop must not read as a
        liveness failure forever after)."""
        with self._lock:
            self._heartbeats.pop(name, None)

    def health(self) -> dict:
        """The ``/healthz`` document: per-heartbeat ages, the minimum
        touchdown age (how long since ANY event loop last completed a unit
        of work), and the overall verdict."""
        now_mono = time.monotonic()
        with self._lock:
            beats = dict(self._heartbeats)
        ok = True
        out_beats = {}
        touchdown_ages = []
        for name, (_wall, mono, max_age) in sorted(beats.items()):
            age = now_mono - mono
            fresh = max_age is None or age <= max_age
            ok = ok and fresh
            out_beats[name] = {
                "age_seconds": round(age, 3),
                "max_age_seconds": max_age,
                "fresh": fresh,
            }
            if name.endswith("touchdown"):
                touchdown_ages.append(age)
        return {
            "ok": ok,
            "uptime_seconds": round(now_mono - self._created_mono, 3),
            "last_touchdown_age_seconds": (
                round(min(touchdown_ages), 3) if touchdown_ages else None
            ),
            "heartbeats": out_beats,
        }

    # -- rendering -----------------------------------------------------------

    @staticmethod
    def _labels_text(key, extra: str = "") -> str:
        parts = [f'{k}="{_escape_label(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render_prometheus(self) -> str:
        """The ``/metrics`` payload (Prometheus text exposition format
        0.0.4). Counters gain the conventional ``_total`` suffix; histograms
        render cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``
        — exactly the shape promtool and every scraper expect."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.items())
        for name, fam in families:
            prom = PROM_PREFIX + name
            if fam.kind == "counter" and not prom.endswith("_total"):
                prom += "_total"
            if fam.help:
                lines.append(f"# HELP {prom} {fam.help}")
            lines.append(f"# TYPE {prom} {fam.kind}")
            with self._lock:
                children = sorted(fam.children.items())
            for key, child in children:
                if fam.kind == "histogram":
                    with child._lock:
                        counts = list(child.counts)
                        h_sum = child.sum
                    cum = 0
                    for i, edge in enumerate(child.edges):
                        cum += counts[i]
                        le = self._labels_text(key, f'le="{_fmt_value(edge)}"')
                        lines.append(f"{prom}_bucket{le} {cum}")
                    cum += counts[-1]
                    le = self._labels_text(key, 'le="+Inf"')
                    lines.append(f"{prom}_bucket{le} {cum}")
                    lt = self._labels_text(key)
                    lines.append(f"{prom}_sum{lt} {_fmt_value(h_sum)}")
                    lines.append(f"{prom}_count{lt} {cum}")
                else:
                    lt = self._labels_text(key)
                    lines.append(f"{prom}{lt} {_fmt_value(child.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """The ``/varz`` document: every family/child as plain JSON values
        (histograms include their bucket counts and derived percentiles)."""
        out: Dict[str, dict] = {}
        with self._lock:
            families = sorted(self._families.items())
        for name, fam in families:
            fam_out = {"kind": fam.kind, "series": []}
            with self._lock:
                children = sorted(fam.children.items())
            for key, child in children:
                entry: dict = {"labels": dict(key)}
                if fam.kind == "histogram":
                    entry.update(child.snapshot())
                else:
                    entry["value"] = child.value
                fam_out["series"].append(entry)
            out[name] = fam_out
        return {"metrics": out, "health": self.health()}


# ---------------------------------------------------------------------------
# The process-wide default registry (the flight-recorder discipline: library
# code feeds the module-level hooks unconditionally; they are cheap host-side
# dict/int work whether or not anything ever scrapes).
# ---------------------------------------------------------------------------

_DEFAULT = Registry()


def registry() -> Registry:
    return _DEFAULT


def counter(name: str, help: str = "", **labels) -> Counter:
    return _DEFAULT.counter(name, help, **labels)


def gauge(name: str, help: str = "", **labels) -> Gauge:
    return _DEFAULT.gauge(name, help, **labels)


def histogram(name: str, help: str = "", buckets=None, **labels) -> Histogram:
    return _DEFAULT.histogram(name, help, buckets=buckets, **labels)


def heartbeat(name: str, max_age_seconds: Optional[float] = None) -> None:
    _DEFAULT.heartbeat(name, max_age_seconds)


# ---------------------------------------------------------------------------
# SLO accounting
# ---------------------------------------------------------------------------

#: Burn-rate windows (seconds) and their display names — the SRE-workbook
#: short/medium/long alerting trio, bounded at one hour so the windowed
#: state stays a few hundred slots per tenant.
SLO_WINDOWS: Tuple[Tuple[str, float], ...] = (
    ("1m", 60.0), ("5m", 300.0), ("1h", 3600.0),
)


class SLOTracker:
    """One tenant's latency/availability objective and its burn accounting.

    A query is GOOD when it succeeded AND answered within
    ``objective_seconds`` (the combined latency+availability SLI — a failed
    query can never be good, however fast it failed). Tracked two ways:

    - lifetime ``good/total`` -> :meth:`compliance` (the ratio the service
      summary and the bench's ``slo_compliance`` key report);
    - time-sloted window counts -> :meth:`burn_rate`: the window's bad
      fraction divided by the error budget ``1 - target``. Burn 1.0 means
      the budget is being spent exactly at the sustainable rate; >> 1 is the
      page. Slots are ``slot_seconds`` wide and pruned past the longest
      window, so memory is bounded regardless of query rate.
    """

    def __init__(
        self,
        objective_seconds: float,
        target: float = 0.99,
        windows: Tuple[Tuple[str, float], ...] = SLO_WINDOWS,
        slot_seconds: float = 5.0,
        clock=time.monotonic,
    ):
        if objective_seconds <= 0:
            raise ValueError(
                f"SLO objective must be > 0 seconds, got {objective_seconds}"
            )
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"SLO target must be a fraction in (0, 1), got {target} — "
                "1.0 leaves no error budget to burn"
            )
        self.objective_seconds = float(objective_seconds)
        self.target = float(target)
        self.windows = tuple(windows)
        self.slot_seconds = float(slot_seconds)
        self._clock = clock
        self._lock = threading.Lock()
        self.good = 0
        self.total = 0
        # (slot_index, good, total) triples, oldest first
        self._slots: collections.deque = collections.deque()
        self._horizon_slots = int(
            math.ceil(max(w for _, w in self.windows) / self.slot_seconds)
        ) + 1

    def observe(self, seconds: Optional[float], ok: bool = True) -> bool:
        """Record one query; returns whether it counted as good. ``seconds``
        None means the query never produced a latency (it failed before
        completing) — always bad."""
        good = bool(ok) and seconds is not None and seconds <= self.objective_seconds
        slot = int(self._clock() / self.slot_seconds)
        with self._lock:
            self.total += 1
            self.good += int(good)
            if self._slots and self._slots[-1][0] == slot:
                _s, g, t = self._slots[-1]
                self._slots[-1] = (slot, g + int(good), t + 1)
            else:
                self._slots.append((slot, int(good), 1))
            while self._slots and self._slots[0][0] < slot - self._horizon_slots:
                self._slots.popleft()
        return good

    def compliance(self) -> Optional[float]:
        with self._lock:
            return self.good / self.total if self.total else None

    def window_counts(self, window_seconds: float) -> Tuple[int, int]:
        now_slot = int(self._clock() / self.slot_seconds)
        first = now_slot - int(math.ceil(window_seconds / self.slot_seconds))
        g = t = 0
        with self._lock:
            for slot, sg, st in self._slots:
                if slot > first:
                    g += sg
                    t += st
        return g, t

    def burn_rate(self, window_seconds: float) -> Optional[float]:
        """``bad_fraction / (1 - target)`` over the window; None when the
        window holds no queries (no data is not the same as no burn)."""
        g, t = self.window_counts(window_seconds)
        if t == 0:
            return None
        return ((t - g) / t) / (1.0 - self.target)

    def burn_rates(self) -> Dict[str, Optional[float]]:
        return {name: self.burn_rate(w) for name, w in self.windows}

    def snapshot(self) -> dict:
        comp = self.compliance()
        return {
            "objective_ms": round(self.objective_seconds * 1e3, 3),
            "target": self.target,
            "good": self.good,
            "total": self.total,
            "compliance": round(comp, 6) if comp is not None else None,
            "burn": {
                name: (round(b, 4) if b is not None else None)
                for name, b in self.burn_rates().items()
            },
        }


# ---------------------------------------------------------------------------
# The ops endpoint
# ---------------------------------------------------------------------------


class OpsServer:
    """``ThreadingHTTPServer`` serving the registry on localhost.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` — the
    bench's self-scrape route); ``start()`` spawns a daemon serve thread so
    a dying process never hangs on its own exporter. Every successful GET of
    a known endpoint increments ``dal_ops_scrapes_total`` — the bench's
    ``ops_scrapes`` key and the proof in its own ``/metrics`` output that
    something is actually watching.
    """

    def __init__(
        self,
        registry: Optional[Registry] = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self._registry = registry if registry is not None else _DEFAULT
        self._host = host
        self._want_port = int(port)
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def start(self) -> "OpsServer":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        if self._httpd is not None:
            return self
        reg = self._registry

        class _Handler(BaseHTTPRequestHandler):
            server_version = "dal-ops/1"

            def log_message(self, *_args):  # quiet: stderr is the run's log
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server's naming
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        reg.counter("ops_scrapes").inc()
                        body = reg.render_prometheus().encode()
                        self._send(
                            200, body,
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        health = reg.health()
                        reg.counter("ops_scrapes").inc()
                        self._send(
                            200 if health["ok"] else 503,
                            (json.dumps(health) + "\n").encode(),
                            "application/json",
                        )
                    elif path == "/varz":
                        reg.counter("ops_scrapes").inc()
                        self._send(
                            200,
                            (json.dumps(reg.snapshot()) + "\n").encode(),
                            "application/json",
                        )
                    elif path == "/flightz":
                        # the SIGUSR1 probe over HTTP: dump the installed
                        # flight recorder (writes its artifact when it has a
                        # path) and return the ring in the response
                        from distributed_active_learning_tpu.runtime import (
                            telemetry,
                        )

                        rec = telemetry.flight_recorder()
                        if rec is None:
                            self._send(
                                404,
                                b'{"error": "no flight recorder installed"}\n',
                                "application/json",
                            )
                            return
                        try:
                            artifact = rec.dump("flightz")
                        except OSError:
                            artifact = None  # a probe must not kill the run
                        reg.counter("ops_scrapes").inc()
                        body = json.dumps({
                            "artifact": artifact,
                            "capacity": rec.capacity,
                            "dropped": rec.dropped,
                            "events": rec.snapshot(),
                        }) + "\n"
                        self._send(200, body.encode(), "application/json")
                    else:
                        self._send(
                            404,
                            b"not found; endpoints: /metrics /healthz /varz"
                            b" /flightz\n",
                            "text/plain",
                        )
                except BrokenPipeError:
                    pass  # scraper hung up mid-response; its problem

        httpd = ThreadingHTTPServer((self._host, self._want_port), _Handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = int(httpd.server_address[1])
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="dal-ops-server",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "OpsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
