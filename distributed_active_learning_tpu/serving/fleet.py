"""Shared-nothing serve fleet: N worker processes behind a consistent-hash
router (the process axis of the serve scale-out, on top of the signature
-group axis inside each worker's :class:`~serving.tenants.TenantManager`).

Topology — one host, N + 1 processes, no shared state:

- **Workers** (:func:`_worker_main`, spawned): each is a FULL serving stack
  — its own ``TenantManager`` (with signature-grouped resident stacked
  scoring), its own :class:`~serving.frontend.ServiceFrontend` (one
  dispatcher thread owning that process's device work), its own ops plane
  (``/metrics`` + ``/healthz`` via :class:`~runtime.obs.OpsServer`), and a
  small HTTP score endpoint. A worker owns its tenants outright: slabs,
  forests, and compiled executables never cross a process boundary, so
  adding a worker adds compute without adding coordination.

- **Router** (:class:`RouterServer`, its own process under :class:`Fleet`):
  consistent hashing on tenant id (:class:`HashRing`, SHA-1, virtual nodes)
  picks the owning worker; forwarding is health-gated by the worker's OWN
  ``/healthz`` (TTL-cached probe) and walks the ring past unhealthy workers
  (``nodes_for`` order), so a wedged worker is routed around instead of
  timing every client out. The router re-exports the whole fleet as ONE
  service: its ``/metrics`` is every worker's registry with a
  ``worker="wN"`` label injected per series plus the router's own routing
  counters, and its ``/healthz`` is up while ANY worker is.

- **Placement = routing.** :class:`Fleet` assigns tenants to workers with
  the SAME ring the router routes by, so the first hop is the owner; the
  ring walk only matters when health gating skips it. Consistent hashing
  keeps the assignment stable under fleet resizing — adding or removing a
  worker remaps ~1/N of tenants (pinned by ``tests/test_fleet.py``), not
  all of them.

The multiprocessing context is ALWAYS ``spawn``: a worker initializes its
own JAX backend, and forking a process that already touched a backend is
undefined behavior; spawn also makes the shared-nothing claim literal.

The data plane is keep-alive HTTP/1.1 on both hops with two wire forms for
``POST /score``: JSON (curl-able) and a raw-float32 binary form (tenant in
the query string, so the router forwards the payload without ever parsing
it). At smoke shapes, per-request TCP connects and JSON float text cost
more CPU than the score launch itself — the binary keep-alive path is what
lets the scaling leg measure launches instead of plumbing, and it
round-trips scores bit-exactly.

Entry point: ``bench.py --mode serve-fleet`` (the 1 -> 4 worker scaling
leg; headline ``serve_fleet_qps``, ``fleet_qps_scaling_ratio``, and the
hard-zero per-worker ``recompiles_after_warmup`` gate).
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import multiprocessing as mp
import threading
import time
import http.client
import socket
import struct
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

#: Forwarded score calls may cover several width-rounds of a cold CPU rig;
#: the router's per-attempt budget must sit above the worker's worst case.
_FORWARD_TIMEOUT = 120.0
#: Health probes are cheap but not free — one per worker per TTL window.
_HEALTH_TTL = 1.0
_HEALTH_TIMEOUT = 3.0


# ---------------------------------------------------------------------------
# Consistent hashing
# ---------------------------------------------------------------------------


class HashRing:
    """SHA-1 consistent-hash ring with virtual nodes.

    ``vnodes`` points per node smooth the arc lengths so small fleets still
    split keys roughly evenly; SHA-1 (not :func:`hash`) makes the mapping
    stable across processes and Python runs — the router process and the
    placement logic in :class:`Fleet` MUST agree on it byte-for-byte.
    Adding/removing a node moves only the keys on the arcs it owned
    (~1/N of them), which is the whole point of the structure.
    """

    def __init__(self, nodes=(), vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []  # sorted (hash, node)
        self._nodes: List[str] = []
        for node in nodes:
            self.add(node)

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key.encode()).digest()[:8], "big"
        )

    @property
    def nodes(self) -> List[str]:
        return sorted(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.append(node)
        for v in range(self.vnodes):
            bisect.insort(self._points, (self._hash(f"{node}#{v}"), node))

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.remove(node)
        self._points = [(h, n) for h, n in self._points if n != node]

    def lookup(self, key: str) -> Optional[str]:
        """The node owning ``key``: first ring point clockwise of its hash."""
        owners = self.nodes_for(key, n=1)
        return owners[0] if owners else None

    def nodes_for(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct nodes in ring order from ``key``'s position — index 0 is
        the owner, the rest is the failover walk order."""
        if not self._points:
            return []
        want = len(self._nodes) if n is None else min(int(n), len(self._nodes))
        start = bisect.bisect(self._points, (self._hash(key), ""))
        out: List[str] = []
        for i in range(len(self._points)):
            node = self._points[(start + i) % len(self._points)][1]
            if node not in out:
                out.append(node)
                if len(out) >= want:
                    break
        return out


# ---------------------------------------------------------------------------
# Tenant specs (the picklable worker boot payload)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """Everything a worker needs to cold-start one tenant, as plain data —
    the spawn boundary pickles these, never live arrays or managers. The
    worker synthesizes the tenant's pool/test data from (seed, shift), the
    same shifted-gaussian convention the serve benches use."""

    tenant_id: str
    features: int = 16
    pool_rows: int = 256
    shift: float = 0.0
    seed: int = 0
    n_trees: int = 6
    max_depth: int = 3
    kernel: str = "gemm"
    slab_rows: int = 256
    score_width: int = 32
    ingest_block: int = 32


def _spec_data(spec: TenantSpec):
    r = np.random.default_rng(spec.seed)
    x = r.normal(size=(spec.pool_rows, spec.features)).astype(np.float32)
    x += spec.shift
    y = (x[:, 0] + 0.3 * x[:, 1] > spec.shift).astype(np.int32)
    n_test = min(spec.pool_rows, 512)
    tx = r.normal(size=(n_test, spec.features)).astype(np.float32) + spec.shift
    ty = (tx[:, 0] + 0.3 * tx[:, 1] > spec.shift).astype(np.int32)
    return x, y, tx, ty


# ---------------------------------------------------------------------------
# The worker process
# ---------------------------------------------------------------------------


def _worker_main(worker_id: str, specs: List[TenantSpec], conn) -> None:
    """A whole serving stack in one spawned process.

    Boot: build the manager from ``specs``, warm up (one fused score launch
    per signature group — ALL compile cost lands here), mark warmup
    complete, then bring up the frontend, the ops plane, and the score
    endpoint and report the bound ports over ``conn``. Serve until the
    parent sends ``"stop"``, then ship a JSON-safe final summary (the
    per-worker recompile/fallback/group evidence the bench gates on) back
    over the pipe.

    The serve traffic contract is score-only by construction of the specs
    (no drift re-fits, no slab growth), so every post-warmup launch must
    hit a warm jit cache: ``recompiles_after_warmup`` is a hard 0 or the
    worker's process is broken.
    """
    from distributed_active_learning_tpu.config import (
        ExperimentConfig,
        ForestConfig,
        ServeConfig,
        StrategyConfig,
    )
    from distributed_active_learning_tpu.runtime import obs
    from distributed_active_learning_tpu.serving.frontend import (
        AdmissionError,
        ServiceFrontend,
    )
    from distributed_active_learning_tpu.serving.tenants import TenantManager

    manager = TenantManager()
    for i, spec in enumerate(specs):
        serve = ServeConfig(
            slab_rows=spec.slab_rows,
            ingest_block=spec.ingest_block,
            score_width=spec.score_width,
            refit_rounds=2,
            # score-only traffic: drift can never fire and staleness never
            # forces a re-fit, so the resident forest (and its compiled
            # executables) are immutable after warmup
            drift_entropy_shift=99.0,
            max_staleness=0,
            precompile_ahead=False,
            max_pending=4096,
            slo_latency_ms=60_000.0,
            slo_target=0.9,
        )
        cfg = ExperimentConfig(
            forest=ForestConfig(
                n_trees=spec.n_trees,
                max_depth=spec.max_depth,
                kernel=spec.kernel,
                fit="device",
                fit_budget=spec.slab_rows,
            ),
            strategy=StrategyConfig(name="uncertainty", window_size=16),
            n_start=max(spec.pool_rows // 8, 4),
            log_every=0,
            seed=spec.seed + i,
        )
        x, y, tx, ty = _spec_data(spec)
        manager.add_tenant(spec.tenant_id, cfg, serve, x, y, tx, ty)

    warm = {
        spec.tenant_id: _spec_data(spec)[2][: spec.score_width]
        for spec in specs
    }
    if warm:
        manager.score_many(warm)
    manager.mark_warmup_complete()

    frontend = ServiceFrontend(manager).start()
    ops = obs.OpsServer(port=0).start()
    obs.gauge(
        "fleet_worker_tenants", "tenants resident on this fleet worker",
        worker=worker_id,
    ).set(len(specs))

    lat_lock = threading.Lock()
    latencies: List[float] = []

    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _ScoreHandler(BaseHTTPRequestHandler):
        server_version = "dal-fleet-worker/1"
        # Keep-alive (every response carries Content-Length): the router's
        # pooled forwarding connections each pin one handler thread here
        # instead of a connect + thread spawn per forwarded score call.
        protocol_version = "HTTP/1.1"
        # Nagle + delayed ACK would add ~40ms to every response on these
        # persistent connections.
        disable_nagle_algorithm = True

        def log_message(self, *_args):
            pass

        def _send(self, code: int, payload: dict) -> None:
            body = (json.dumps(payload) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802 — http.server's naming
            path, _, query = self.path.partition("?")
            if path.rstrip("/") != "/score":
                self._send(404, {"error": "POST /score only"})
                return
            # Two wire forms. JSON: {"tenant", "queries"} — debuggable with
            # curl. Binary (Content-Type application/octet-stream, tenant in
            # the query string): an <II> (rows, features) header + raw
            # float32 rows — JSON float text costs more CPU per request
            # than the score launch it carries, and the binary form also
            # round-trips bit-exactly.
            binary = (
                self.headers.get("Content-Type", "")
                == "application/octet-stream"
            )
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if binary:
                    tid = str(urllib.parse.parse_qs(query)["tenant"][0])
                    w, d = struct.unpack("<II", body[:8])
                    queries = np.frombuffer(
                        body, np.float32, offset=8
                    ).reshape(w, d)
                else:
                    req = json.loads(body)
                    tid = str(req["tenant"])
                    queries = np.asarray(req["queries"], np.float32)
            except (ValueError, KeyError, TypeError, struct.error) as e:
                self._send(400, {"error": f"bad request: {e!r}"})
                return
            if tid not in manager.tenant_ids:
                self._send(
                    404, {"error": f"tenant {tid!r} not on worker {worker_id}"}
                )
                return
            t0 = time.perf_counter()
            try:
                scores = frontend.score(tid, queries)
            except AdmissionError as e:
                self._send(429, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 — the error belongs to
                # this request's client; the worker keeps serving
                self._send(500, {"error": repr(e)[:200]})
                return
            dt = time.perf_counter() - t0
            with lat_lock:
                latencies.append(dt)
            if binary:
                out = np.ascontiguousarray(
                    np.asarray(scores, np.float32)
                ).tobytes()
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)
                return
            self._send(
                200,
                {
                    "tenant": tid,
                    "worker": worker_id,
                    "scores": np.asarray(scores).tolist(),
                },
            )

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _ScoreHandler)
    httpd.daemon_threads = True
    score_port = int(httpd.server_address[1])
    serve_thread = threading.Thread(
        target=httpd.serve_forever, kwargs={"poll_interval": 0.25},
        name=f"fleet-{worker_id}-score", daemon=True,
    )
    serve_thread.start()

    conn.send({
        "worker": worker_id,
        "ops_port": ops.port,
        "score_port": score_port,
        "tenants": [spec.tenant_id for spec in specs],
    })

    try:
        while True:
            if conn.poll(0.25):
                msg = conn.recv()
                if msg == "stop":
                    break
            manager.poll()
    except (EOFError, KeyboardInterrupt):
        pass

    frontend.stop(drain=True, timeout=30.0)
    with lat_lock:
        lat = sorted(latencies)

    def _pct(q: float) -> Optional[float]:
        if not lat:
            return None
        return round(lat[min(int(q * len(lat)), len(lat) - 1)] * 1e3, 3)

    final = {
        "worker": worker_id,
        "tenants": [spec.tenant_id for spec in specs],
        "queries": len(lat),
        "p50_ms": _pct(0.50),
        "p99_ms": _pct(0.99),
        "recompiles_after_warmup": int(manager.recompiles_after_warmup()),
        "batched_score_launches": int(manager.batched_score_launches),
        "score_fallback_reasons": {
            k: int(v) for k, v in manager.score_fallback_reasons.items()
        },
        "score_groups": manager.score_groups(),
    }
    try:
        conn.send(final)
    except (BrokenPipeError, OSError):
        pass
    httpd.shutdown()
    httpd.server_close()
    ops.stop()
    conn.close()


# ---------------------------------------------------------------------------
# Keep-alive HTTP client (the fleet data plane)
# ---------------------------------------------------------------------------


class _KeepAliveClient:
    """Thread-local persistent HTTP/1.1 connections, keyed by endpoint.

    A fresh TCP connect plus a fresh server handler thread per request
    costs more CPU than the score launch the request carries at smoke
    shapes — and both hops of the data plane (client -> router -> worker)
    paid it. Persistent connections pin one server handler thread per
    (client thread, endpoint) instead.

    A pooled connection can go stale (peer restarted, socket reaped): one
    transparent fresh-connection retry distinguishes "my cached socket
    died" from "the peer is down". Safe here because ``POST /score`` is a
    pure read — a retry can never double-apply anything.
    """

    def __init__(self, timeout: float):
        self._timeout = float(timeout)
        self._local = threading.local()

    def _conn(
        self, host: str, port: int, fresh: bool = False
    ) -> http.client.HTTPConnection:
        pool = getattr(self._local, "pool", None)
        if pool is None:
            pool = self._local.pool = {}
        key = (host, int(port))
        conn = pool.get(key)
        if fresh and conn is not None:
            conn.close()
            conn = None
        if conn is None:
            conn = http.client.HTTPConnection(
                host, int(port), timeout=self._timeout
            )
            conn.connect()
            # Nagle + delayed ACK on a keep-alive connection turns every
            # small request into a ~40ms stall; the whole point of the
            # persistent data plane is sub-launch-latency hops.
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            pool[key] = conn
        return conn

    def request(
        self,
        host: str,
        port: int,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        ctype: str = "application/json",
    ) -> Tuple[int, bytes, str]:
        """``(status, body, content_type)``; raises ``OSError``/
        ``HTTPException`` only when the endpoint is unreachable on a FRESH
        connection too."""
        for attempt in (0, 1):
            conn = self._conn(host, port, fresh=attempt > 0)
            try:
                headers = {"Content-Type": ctype} if body is not None else {}
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                out_ctype = resp.headers.get(
                    "Content-Type", "application/json"
                )
                return resp.status, resp.read(), out_ctype
            except (http.client.HTTPException, OSError, ValueError):
                conn.close()
                if attempt:
                    raise
        raise OSError("unreachable")  # pragma: no cover — loop always exits


# ---------------------------------------------------------------------------
# The router
# ---------------------------------------------------------------------------


class RouterServer:
    """The fleet's single front door: consistent-hash forwarding with
    health gating, plus the aggregated ops plane.

    ``workers`` maps worker id -> ``{"host", "score_port", "ops_port"}``.
    Endpoints:

    - ``POST /score`` — JSON ``{"tenant": ..., "queries": [[...]]}``, or
      the binary form (``?tenant=...`` + ``application/octet-stream`` body:
      ``<II`` rows/features header + raw float32 rows, relayed without
      parsing) — forwarded to the ring owner; an unhealthy (TTL-cached
      ``/healthz`` probe) or unreachable worker is walked past in ring
      order. A worker's 400/404/429 is relayed as-is — the worker
      answered and the verdict is the client's; 5xx and connection errors
      advance the walk. 503 when no healthy worker remains.
    - ``GET /metrics`` — every worker's registry concatenated with a
      ``worker="wN"`` label injected into each series, plus the router's
      own ``dal_fleet_router_*`` counters: one scrape covers the fleet.
    - ``GET /healthz`` — 200 while ANY worker is healthy (per-worker
      verdicts in the body); the fleet is up if someone can serve.
    - ``GET /workers`` — the endpoint map (CI uses it to scrape each
      worker's own ``/metrics`` for the per-worker recompile gate).
    - ``GET /summary`` — routing counters as JSON.

    Instantiable in-process (tests run it against stub workers on local
    threads); :class:`Fleet` runs it in its own process via
    :func:`_router_main`.
    """

    def __init__(
        self,
        workers: Dict[str, Dict],
        port: int = 0,
        host: str = "127.0.0.1",
        vnodes: int = 64,
        health_ttl: float = _HEALTH_TTL,
        forward_timeout: float = _FORWARD_TIMEOUT,
    ):
        self.workers = {str(w): dict(ep) for w, ep in workers.items()}
        self.ring = HashRing(sorted(self.workers), vnodes=vnodes)
        self._host = host
        self._want_port = int(port)
        self._health_ttl = float(health_ttl)
        self._forward_timeout = float(forward_timeout)
        self._health_cache: Dict[str, Tuple[float, bool]] = {}
        self._probing: set = set()
        self._fwd = _KeepAliveClient(self._forward_timeout)
        self._lock = threading.Lock()
        self.routed: Dict[str, int] = {}
        self.rerouted = 0
        self.unhealthy_skips = 0
        self.unroutable = 0
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def _url(self, wid: str, which: str) -> str:
        ep = self.workers[wid]
        return f"http://{ep.get('host', '127.0.0.1')}:{ep[which]}"

    def _mark_unhealthy(self, wid: str) -> None:
        with self._lock:
            self._health_cache[wid] = (
                time.monotonic() + self._health_ttl, False
            )

    def healthy(self, wid: str) -> bool:
        """TTL-cached ``/healthz`` probe of one worker's own ops plane.

        Single-flight with stale-while-revalidate: when the TTL lapses
        under concurrent traffic, exactly ONE request re-probes while the
        rest keep the stale verdict — N in-flight requests herding N
        simultaneous probes at a worker whose threads are already busy
        stalls every one of them behind the probe timeout.
        """
        now = time.monotonic()
        with self._lock:
            cached = self._health_cache.get(wid)
            if cached is not None and cached[0] > now:
                return cached[1]
            if wid in self._probing and cached is not None:
                return cached[1]
            self._probing.add(wid)
        try:
            try:
                with urllib.request.urlopen(
                    self._url(wid, "ops_port") + "/healthz",
                    timeout=_HEALTH_TIMEOUT,
                ) as r:
                    ok = r.status == 200
            except (urllib.error.URLError, OSError, ValueError):
                ok = False
        finally:
            with self._lock:
                self._probing.discard(wid)
        with self._lock:
            # The TTL test and this install are deliberately separate lock
            # scopes — the probe itself ran unlocked — and the single-flight
            # `_probing` set guarantees one installer per worker, so the
            # check-then-install overwrite race cannot happen here.
            self._health_cache[wid] = (  # audit: ok[DAL203]
                time.monotonic() + self._health_ttl, ok
            )
        return ok

    def route(self, tenant: str) -> List[str]:
        """The forwarding walk for a tenant: owner first, then failovers."""
        return self.ring.nodes_for(str(tenant))

    def summary(self) -> Dict:
        with self._lock:
            return {
                "workers": sorted(self.workers),
                "routed": dict(self.routed),
                "rerouted": self.rerouted,
                "unhealthy_skips": self.unhealthy_skips,
                "unroutable": self.unroutable,
            }

    def _aggregate_metrics(self) -> str:
        """One Prometheus payload for the fleet: each worker's series with a
        ``worker`` label injected (comment lines dropped — N workers would
        repeat every HELP/TYPE header), then the router's own counters."""
        lines: List[str] = []
        for wid in sorted(self.workers):
            try:
                with urllib.request.urlopen(
                    self._url(wid, "ops_port") + "/metrics",
                    timeout=_HEALTH_TIMEOUT,
                ) as r:
                    text = r.read().decode()
            except (urllib.error.URLError, OSError, ValueError):
                lines.append(f'dal_fleet_worker_up{{worker="{wid}"}} 0')
                continue
            lines.append(f'dal_fleet_worker_up{{worker="{wid}"}} 1')
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                head, sep, val = line.rpartition(" ")
                if not sep:
                    continue
                if head.endswith("}"):
                    head = head[:-1] + f',worker="{wid}"}}'
                else:
                    head = head + f'{{worker="{wid}"}}'
                lines.append(head + " " + val)
        with self._lock:
            for wid in sorted(self.workers):
                lines.append(
                    f'dal_fleet_router_requests_total{{worker="{wid}"}} '
                    f"{self.routed.get(wid, 0)}"
                )
            lines.append(f"dal_fleet_router_rerouted_total {self.rerouted}")
            lines.append(
                f"dal_fleet_router_unhealthy_skips_total "
                f"{self.unhealthy_skips}"
            )
            lines.append(
                f"dal_fleet_router_unroutable_total {self.unroutable}"
            )
        return "\n".join(lines) + "\n"

    def start(self) -> "RouterServer":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        if self._httpd is not None:
            return self
        router = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "dal-fleet-router/1"
            # Keep-alive: every response carries Content-Length, so the
            # connection (and this handler thread) survives across requests
            # instead of paying connect + thread spawn per score call.
            protocol_version = "HTTP/1.1"
            # Nagle + delayed ACK would add ~40ms to every response on
            # these persistent connections.
            disable_nagle_algorithm = True

            def log_message(self, *_args):
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, payload: dict) -> None:
                self._send(
                    code, (json.dumps(payload) + "\n").encode(),
                    "application/json",
                )

            def do_GET(self):  # noqa: N802
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    self._send(
                        200, router._aggregate_metrics().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/healthz":
                    verdicts = {
                        wid: router.healthy(wid)
                        for wid in sorted(router.workers)
                    }
                    ok = any(verdicts.values())
                    self._send_json(
                        200 if ok else 503,
                        {"ok": ok, "workers": verdicts},
                    )
                elif path == "/workers":
                    self._send_json(200, router.workers)
                elif path == "/summary":
                    self._send_json(200, router.summary())
                else:
                    self._send(
                        404,
                        b"not found; endpoints: /score (POST) /metrics"
                        b" /healthz /workers /summary\n",
                        "text/plain",
                    )

            def do_POST(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                if path.rstrip("/") != "/score":
                    self._send_json(404, {"error": "POST /score only"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                req_ctype = self.headers.get(
                    "Content-Type", "application/json"
                )
                # Routing key: ?tenant=... when present (the binary form —
                # the router then never touches the payload), else parsed
                # from the JSON body.
                tenant = urllib.parse.parse_qs(query).get(
                    "tenant", [None]
                )[0]
                if tenant is None:
                    try:
                        tenant = str(json.loads(body)["tenant"])
                    except (ValueError, KeyError, TypeError) as e:
                        self._send_json(
                            400, {"error": f"bad request: {e!r}"}
                        )
                        return
                walk = router.route(tenant)
                for hop, wid in enumerate(walk):
                    if not router.healthy(wid):
                        with router._lock:
                            router.unhealthy_skips += 1
                        continue
                    ep = router.workers[wid]
                    try:
                        status, out, out_ctype = router._fwd.request(
                            ep.get("host", "127.0.0.1"), ep["score_port"],
                            "POST", self.path, body=body, ctype=req_ctype,
                        )
                    except (http.client.HTTPException, OSError, ValueError):
                        router._mark_unhealthy(wid)
                        continue
                    if status in (400, 404, 429):
                        # the worker answered; the verdict is the client's
                        # problem, not a routing problem
                        self._send(status, out, out_ctype)
                        return
                    if status != 200:
                        router._mark_unhealthy(wid)
                        continue
                    with router._lock:
                        router.routed[wid] = router.routed.get(wid, 0) + 1
                        if hop > 0:
                            router.rerouted += 1
                    self._send(200, out, out_ctype)
                    return
                with router._lock:
                    router.unroutable += 1
                self._send_json(
                    503,
                    {"error": f"no healthy worker for tenant {tenant!r}"},
                )

        httpd = ThreadingHTTPServer((self._host, self._want_port), _Handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = int(httpd.server_address[1])
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="dal-fleet-router", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def _router_main(workers: Dict[str, Dict], port: int, conn) -> None:
    """The router as its own process (the :class:`Fleet` wiring): start,
    report the bound port, serve until "stop", ship the routing summary
    back."""
    router = RouterServer(workers, port=port).start()
    conn.send({"router_port": router.port})
    try:
        while True:
            if conn.poll(0.25):
                if conn.recv() == "stop":
                    break
    except (EOFError, KeyboardInterrupt):
        pass
    summary = router.summary()
    router.stop()
    try:
        conn.send(summary)
    except (BrokenPipeError, OSError):
        pass
    conn.close()


# ---------------------------------------------------------------------------
# The fleet orchestrator
# ---------------------------------------------------------------------------


class Fleet:
    """Spawn the workers, place the tenants, front them with the router.

    Placement uses the same :class:`HashRing` (same worker ids, same
    ``vnodes``) the router routes by, so the router's first hop is always
    the owner. ``start()`` blocks until every worker reports its ports
    (workers warm up — compile their signature groups' stacked programs —
    before reporting, so the fleet is serve-ready when this returns);
    ``stop()`` collects each worker's final summary (the per-worker
    recompile/fallback evidence) and the router's routing counters.
    """

    def __init__(
        self,
        specs: List[TenantSpec],
        n_workers: int,
        router_port: int = 0,
        vnodes: int = 64,
        start_timeout: float = 600.0,
    ):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.specs = list(specs)
        self.n_workers = int(n_workers)
        self._router_port = int(router_port)
        self._vnodes = int(vnodes)
        self._start_timeout = float(start_timeout)
        self.worker_ids = [f"w{i}" for i in range(self.n_workers)]
        ring = HashRing(self.worker_ids, vnodes=self._vnodes)
        self.assignment: Dict[str, str] = {
            spec.tenant_id: ring.lookup(spec.tenant_id)
            for spec in self.specs
        }
        self._procs: Dict[str, mp.process.BaseProcess] = {}
        self._conns: Dict[str, object] = {}
        self._client = _KeepAliveClient(_FORWARD_TIMEOUT)
        self.endpoints: Dict[str, Dict] = {}
        self._router_proc: Optional[mp.process.BaseProcess] = None
        self._router_conn = None
        self.router_port: Optional[int] = None

    def specs_for(self, worker_id: str) -> List[TenantSpec]:
        return [
            spec for spec in self.specs
            if self.assignment[spec.tenant_id] == worker_id
        ]

    def start(self) -> "Fleet":
        ctx = mp.get_context("spawn")
        deadline = time.monotonic() + self._start_timeout
        try:
            for wid in self.worker_ids:
                parent, child = ctx.Pipe()
                proc = ctx.Process(
                    target=_worker_main,
                    args=(wid, self.specs_for(wid), child),
                    name=f"dal-fleet-{wid}",
                    daemon=True,
                )
                proc.start()
                child.close()
                self._procs[wid] = proc
                self._conns[wid] = parent
            for wid in self.worker_ids:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._conns[wid].poll(remaining):
                    raise RuntimeError(
                        f"fleet worker {wid} did not report ready within "
                        f"{self._start_timeout:.0f}s"
                    )
                ready = self._conns[wid].recv()
                self.endpoints[wid] = {
                    "host": "127.0.0.1",
                    "score_port": ready["score_port"],
                    "ops_port": ready["ops_port"],
                    "tenants": ready["tenants"],
                }
            parent, child = ctx.Pipe()
            self._router_proc = ctx.Process(
                target=_router_main,
                args=(self.endpoints, self._router_port, child),
                name="dal-fleet-router",
                daemon=True,
            )
            self._router_proc.start()
            child.close()
            self._router_conn = parent
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not parent.poll(remaining):
                raise RuntimeError("fleet router did not report ready")
            self.router_port = parent.recv()["router_port"]
        except BaseException:
            self._kill_all()
            raise
        return self

    def _kill_all(self) -> None:
        procs = list(self._procs.values())
        if self._router_proc is not None:
            procs.append(self._router_proc)
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=5)

    def score(self, tenant: str, queries) -> np.ndarray:
        """Score through the router — the fleet's one client surface.

        Uses the binary wire form (raw float32 rows, tenant in the query
        string) over a thread-local keep-alive connection: no float-text
        encode/decode on either hop, and the scores round-trip bit-exactly.
        A non-200 status raises ``urllib.error.HTTPError`` (same exception
        a urllib client would surface, so callers keep their handling).
        """
        q = np.ascontiguousarray(np.asarray(queries, np.float32))
        if q.ndim == 1:
            q = q[None, :]
        body = struct.pack("<II", q.shape[0], q.shape[1]) + q.tobytes()
        path = "/score?tenant=" + urllib.parse.quote(str(tenant), safe="")
        status, out, _ = self._client.request(
            "127.0.0.1", self.router_port, "POST", path,
            body=body, ctype="application/octet-stream",
        )
        if status != 200:
            raise urllib.error.HTTPError(
                f"http://127.0.0.1:{self.router_port}{path}", status,
                out.decode(errors="replace")[:200], hdrs=None, fp=None,
            )
        return np.frombuffer(out, np.float32).copy()

    def worker_metrics(self, worker_id: str) -> str:
        """One worker's OWN ``/metrics`` payload (the per-worker hard-zero
        recompile gate scrapes this, not the router aggregate)."""
        url = (
            f"http://127.0.0.1:{self.endpoints[worker_id]['ops_port']}"
            "/metrics"
        )
        with urllib.request.urlopen(url, timeout=_HEALTH_TIMEOUT) as r:
            return r.read().decode()

    def stop(self) -> Dict:
        """Stop everything; returns ``{"workers": {...}, "router": {...}}``
        with each worker's final summary and the router's counters."""
        finals: Dict[str, Dict] = {}
        for wid, conn in self._conns.items():
            try:
                conn.send("stop")
            except (BrokenPipeError, OSError):
                continue
        for wid, conn in self._conns.items():
            try:
                if conn.poll(60.0):
                    finals[wid] = conn.recv()
            except (EOFError, OSError):
                pass
        router_summary = None
        if self._router_conn is not None:
            try:
                self._router_conn.send("stop")
                if self._router_conn.poll(30.0):
                    router_summary = self._router_conn.recv()
            except (BrokenPipeError, EOFError, OSError):
                pass
        self._kill_all()
        return {"workers": finals, "router": router_summary}

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
