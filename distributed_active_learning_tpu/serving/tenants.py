"""Multi-tenant AL-as-a-service: N resident forests on one process/mesh.

PR 7's :class:`~serving.service.ALService` runs ONE dataset x model per
process — fine for a demo, wrong for the north star ("heavy traffic from
millions of users" means many tenants resident simultaneously). This module
generalizes the single-tenant event loop into three load-bearing pieces:

- **Tenant** — everything one resident (dataset x model) owns: its slab-paged
  pool (serving/slab.py), drift monitor (serving/drift.py), per-capacity
  program cache, resident fitted forest, stats/result/telemetry. The body is
  the single-tenant service verbatim — :class:`~serving.service.ALService`
  is now a thin wrapper over a 1-tenant manager, so there is exactly one
  event-loop implementation.

- **TenantManager** — N tenants on one device/mesh, plus the two cross-tenant
  fused paths:

  * **Batched scoring** (:meth:`TenantManager.score_many`): concurrent score
    requests from different tenants coalesce into fused launches —
    :func:`make_batched_score_fn` vmaps the shared
    :func:`~serving.slab.score_body` over a leading tenant axis. Resident
    tenants are partitioned into SAME-SIGNATURE GROUPS (forest structure x
    score width x feature width); each group keeps a RESIDENT stacked
    forest and its own stacked score program (:class:`_ScoreGroup` —
    restacked only on a member's re-fit touchdown or a membership change,
    never per dispatch) and dispatches one vmapped launch per width-round.
    Each group's tenant axis is PADDED to its full membership (absent
    tenants ride as zero-row no-ops, per-tenant ``n_valid`` watermarks mask
    them out at unstack), so request-subset churn never changes a program's
    avals — the same discipline the slab pool applies to arrivals. Only
    tenants no group can hold — a signature shared with NO other resident,
    an unbatchable kernel, a single-tenant manager — fall back to
    per-tenant launches, each with a NAMED reason in the summary.

  * **Batched re-fit** (tenant-axis chunk): when several same-configuration
    tenants' drift monitors fire together, their re-fit chunks launch as ONE
    program — the PR-9 grid chunk (``runtime/sweep.py make_grid_chunk_fn``)
    with tenants riding the dataset axis (G=1 strategy group, D=T tenants,
    E=1 seeds): per-tenant pools stack padded to the group's max capacity,
    unequal fills ride the dynamic ``n_filled`` watermark, per-tenant
    edges/test sets/budgets ride the per-cell inputs, and non-candidate
    group members ride as masked no-ops (``end_round == round``) so the
    program's tenant axis stays aval-stable. Outputs unstack per tenant at
    touchdown. The grid chunk is bit-identical to serial cells (PR-9), so
    batched tenants produce the SAME selections as independent services —
    pinned by tests/test_serving_multi.py.

- **AOT capacity precompile** — the known p99 spike: slab growth and the
  first re-fit at a new capacity paid XLA compile on the triggering request
  (the cause-tagged ``slab_growth_compile`` ``serve_latency`` events from
  PR 8). A background worker thread now ``lower().compile()``s the NEXT
  capacity's ingest/chunk/fit programs (and the tenant-axis chunk at the
  group's next max capacity) before the watermark reaches the growth
  threshold, so growth becomes an executable swap. An AOT executable also
  CANNOT silently recompile — a mismatched aval raises — which is a strictly
  stronger form of the ``recompiles_after_warmup == 0`` contract.

Threading model: device work (score/ingest/chunk dispatch + touchdown) is
assumed to run on ONE thread — the frontend (serving/frontend.py) funnels
concurrent clients through its dispatcher; direct TenantManager calls from
multiple threads must hold their own discipline. The precompile worker only
builds executables and installs them under the manager lock; it never
launches anything.
"""

from __future__ import annotations

import atexit
import dataclasses
import queue as queue_lib
import re
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.config import ExperimentConfig, ServeConfig
from distributed_active_learning_tpu.runtime import obs
from distributed_active_learning_tpu.runtime import state as state_lib
from distributed_active_learning_tpu.runtime import telemetry
from distributed_active_learning_tpu.serving import drift as drift_lib
from distributed_active_learning_tpu.serving import slab as slab_lib

_TENANT_ID_RE = re.compile(r"[A-Za-z0-9._-]+")

# Killing a thread that is INSIDE an XLA compile at interpreter teardown
# aborts the process ("terminate called without an active exception"), so
# every manager's precompile worker registers here and atexit drains them
# before the interpreter starts dying. WeakSet: a collected manager must not
# be kept alive by its own shutdown hook.
_LIVE_MANAGERS: "weakref.WeakSet[TenantManager]" = weakref.WeakSet()


@atexit.register
def _shutdown_precompile_workers() -> None:
    for manager in list(_LIVE_MANAGERS):
        manager.close()

#: Eval kernels whose fitted forests stack/vmap cleanly over a tenant axis.
#: "pallas" wraps the forest in a mesh-bound shard_map evaluator — per-tenant
#: fallback with a named reason instead of a cryptic trace error.
_BATCHABLE_KERNELS = ("gemm", "gather")


class _ProgramTracker:
    """Per-program-instance launch accounting with a recompile COUNT.

    Like :class:`~runtime.telemetry.LaunchTracker` (and it emits the same
    ``launch`` JSONL events through the writer), but the recompile detection
    runs with or without a writer and accumulates — the service's headline
    ``recompiles_after_warmup`` is the sum over every program instance, and a
    bench must be able to assert it at zero without a metrics file. For an
    AOT-compiled program ``jit_cache_size`` is unknowable (None) and the
    count stays 0 — structurally true: an AOT executable cannot recompile,
    a mismatched aval raises instead.
    """

    def __init__(self, writer, program: str, fn):
        self.writer = writer
        self.program = program
        self.fn = fn
        self.calls = 0
        self.recompiles = 0
        self._last_cache = None
        # Live ops plane: the same three series LaunchTracker feeds, from
        # the one shared definition (telemetry.program_obs_feeds) so the
        # CI-gated family names cannot drift between the two trackers.
        self._obs_launches, self._obs_seconds, self._obs_recompiles = (
            telemetry.program_obs_feeds(program)
        )

    def record(self, seconds: float, **extra) -> None:
        self.calls += 1
        cache = telemetry.jit_cache_size(self.fn)
        recompiled = (
            self.calls > 1
            and cache is not None
            and self._last_cache is not None
            and cache > self._last_cache
        )
        self._obs_launches.inc()
        self._obs_seconds.observe(seconds)
        if recompiled:
            self.recompiles += 1
            self._obs_recompiles.inc()
            # A silent recompile is exactly the event a dead run's post-
            # mortem needs; the score path's per-query launches stay out of
            # the ring (they'd flush everything else) — recompiles don't.
            telemetry.flight_record(
                "recompile", program=self.program, call=self.calls,
                cache_size=cache,
            )
        self._last_cache = cache
        if self.writer is not None:
            self.writer.launch(
                self.program, seconds,
                first_call=self.calls == 1,
                cache_size=cache,
                recompiled=recompiled,
                **extra,
            )


@dataclasses.dataclass
class _CapacityPrograms:
    """The programs specialized on one slab capacity — jitted closures when
    built lazily on the request path, AOT ``Compiled`` executables when the
    precompile worker built them ahead of the growth threshold."""

    ingest: object
    chunk: object
    fit: object
    ingest_tracker: _ProgramTracker
    chunk_tracker: _ProgramTracker
    fit_tracker: _ProgramTracker
    aot: bool = False
    # The bin-edge epoch these programs were built against (the fit/chunk
    # closures capture the edges): a drift-triggered bin refresh bumps the
    # tenant's epoch, and _install_programs rejects stale-epoch sets — an
    # AOT precompile racing a refresh must never install old-edge programs.
    edges_epoch: int = 0


@dataclasses.dataclass
class ServeStats:
    """Host-side per-tenant service counters (all plain ints — no device
    reads)."""

    queries: int = 0
    scored_points: int = 0
    # Score requests that raised before producing a result (frontend
    # dispatch errors routed back here): the availability half of the SLO.
    query_failures: int = 0
    ingest_blocks: int = 0
    ingested_points: int = 0
    refits: int = 0
    refit_rounds: int = 0
    refits_skipped_fit_budget: int = 0
    slab_growths: int = 0
    # Growths whose new-capacity programs were already resident (the AOT
    # precompile landed in time) — the executable-swap fast path.
    growths_precompiled: int = 0
    # Drift-triggered bin-edge refreshes (serving scenario follow-up): the
    # stream drifted past the cold-start quantiles, the binning was
    # re-quantiled from the live slab, and the forest fingerprint bumped.
    bin_refreshes: int = 0


def _sds(shape, dtype) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _aval(tree):
    """Abstract twin of a concrete pytree (key arrays keep their extended
    dtype) — what ``jit(...).lower`` consumes for AOT compilation."""
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(tuple(l.shape), l.dtype), tree
    )


def make_batched_score_fn():
    """Build the cross-tenant fused scoring program.

    ``score(forests, queries[T, W, d]) -> (scores[T, W], entropy[T, W])`` —
    :func:`~serving.slab.score_body` vmapped over a leading tenant axis, so
    T tenants' concurrent queries cost ONE launch. ``forests`` is the
    resident stacked forest (every leaf gains a leading ``[T]`` axis); the
    tenant axis is static, so the program compiles once per resident-set
    size, and per-call participation differences ride as padded rows the
    caller masks at unstack (never as aval changes).
    """

    @jax.jit
    def score(forests, queries: jnp.ndarray):
        with jax.named_scope("serve/batched_score"):
            return jax.vmap(slab_lib.score_body)(forests, queries)

    return score


class _ScoreGroup:
    """One same-signature resident group of the fused score path.

    The group key is everything the stacked program's avals depend on —
    forest signature, ``score_width``, feature width — so every member can
    ride one ``[G, W, d]`` vmapped launch. The stacked forest is RESIDENT:
    it is rebuilt only when a member's re-fit touches down (``dirty``) or
    when group membership changes (the manager then builds a fresh group),
    never per dispatch. Each group owns its score program instance, so the
    jit cache (and the recompile count) is per group: a stable group
    compiles exactly once.
    """

    def __init__(self, key: tuple, tids: List[str], metrics):
        self.key = key
        self.tids = list(tids)  # registration order — the stable tenant axis
        self.fn = make_batched_score_fn()
        # Same program name as the pre-grouping single stacked program: the
        # obs series (launches/seconds/recompiles tagged
        # program="serve_batched_score") keep their CI-gated family names;
        # per-group attribution rides the launch events' ``tenants`` extra.
        self.tracker = _ProgramTracker(metrics, "serve_batched_score", self.fn)
        self.stacked = None
        self.dirty = True
        self.launches = 0

    @property
    def width(self) -> int:
        return self.key[1]

    @property
    def features(self) -> int:
        return self.key[2]


class Tenant:
    """One resident dataset x model: slab pool, drift monitor, per-capacity
    programs, resident forest — the single-tenant event loop's whole state.

    ``cfg`` supplies the model/strategy/seeding half (the same
    :class:`ExperimentConfig` the batch drivers take — ``forest.fit`` must be
    ``"device"``; the whole point is a resident device loop); ``serve``
    supplies the streaming knobs. ``train_x/train_y`` seed the pool (the
    tenant's cold-start corpus), ``test_x/test_y`` feed the chunk's accuracy
    eval exactly as in the batch loop. ``ckpt_name`` is the tenant axis of
    the serve checkpoint format (None keeps the single-tenant file names, so
    pre-multi-tenant checkpoints keep resuming).
    """

    def __init__(
        self,
        tenant_id: str,
        cfg: ExperimentConfig,
        serve: ServeConfig,
        train_x,
        train_y,
        test_x,
        test_y,
        metrics=None,
        checkpoint_dir: Optional[str] = None,
        ckpt_name: Optional[str] = None,
        manager: Optional["TenantManager"] = None,
    ):
        from distributed_active_learning_tpu.ops import trees_train
        from distributed_active_learning_tpu.runtime.loop import build_aux
        from distributed_active_learning_tpu.runtime.results import ExperimentResult
        from distributed_active_learning_tpu.strategies import get_strategy

        if cfg.forest.fit != "device":
            raise ValueError(
                "the streaming service needs ForestConfig.fit='device' — a "
                "host sklearn fit cannot live inside the resident loop"
            )
        self.tenant_id = tenant_id
        self.cfg = cfg
        self.serve = serve
        self.metrics = metrics
        self.checkpoint_dir = checkpoint_dir
        self._ckpt_name = ckpt_name
        self._manager = manager
        self.stats = ServeStats()
        self.refit_reasons: Dict[str, int] = {}
        self.result = ExperimentResult()
        # Post-warmup latency-cause table: how many serve_latency events each
        # concurrent cause was tagged with. mark_warmup_complete() zeroes it;
        # the serve-multi bench gate asserts slab_growth_compile stays absent
        # afterwards (the AOT precompile's acceptance criterion).
        self.cause_counts: Dict[str, int] = {}
        # Live ops plane (runtime/obs.py): per-tenant counters + the cause-
        # tagged latency histogram, tenant-labeled with the SAME tag the
        # JSONL events carry so a /metrics series and a summarize_metrics row
        # name the same tenant. Children cached — the registry lookup stays
        # off the per-query path; the per-cause histogram children fill
        # lazily (causes are a tiny closed set).
        self._obs_queries = obs.counter(
            "serve_queries", "score queries served", tenant=tenant_id
        )
        self._obs_points = obs.counter(
            "serve_scored_points", "points scored", tenant=tenant_id
        )
        self._obs_lat: Dict[str, obs.Histogram] = {}
        # Per-tenant SLO accounting (ServeConfig.slo_latency_ms > 0): the
        # combined latency+availability SLI — compliance ratio + multi-
        # window burn-rate gauges, a periodic `slo` JSONL event, and the
        # summary/bench `slo_compliance` surface. Off by default.
        self.slo: Optional[obs.SLOTracker] = None
        self._slo_gauge_ts = 0.0  # last gauge refresh (monotonic)
        self._obs_slo_comp: Optional[obs.Gauge] = None
        self._obs_slo_burn: Dict[str, obs.Gauge] = {}
        if getattr(serve, "slo_latency_ms", 0.0) > 0.0:
            self.slo = obs.SLOTracker(
                serve.slo_latency_ms / 1e3,
                target=getattr(serve, "slo_target", 0.99),
            )

        host_y = np.asarray(train_y, np.int32)
        self.n_classes = max(int(host_y.max()) + 1, 2) if host_y.size else 2
        self._strategy = get_strategy(cfg.strategy)

        state0 = state_lib.init_pool_state(train_x, train_y, jax.random.key(cfg.seed))
        state0 = state_lib.set_start_state(state0, cfg.n_start, n_classes=self.n_classes)
        binned = trees_train.make_bins(jnp.asarray(state0.x), cfg.forest.max_bins)
        self._edges = binned.edges
        # Bin-edge drift tracking: the binning is frozen at cold start until
        # the ingested stream's out-of-range EMA crosses the refresh
        # threshold (ServeConfig.bin_refresh_out_frac); the epoch versions
        # every program set built against the edges.
        self._edges_epoch = 0
        self._oob_ema: Optional[float] = None
        self._fresh_since_refresh = 0
        self._set_edge_bounds()
        self._slab = slab_lib.init_slab_pool(
            state0.x, state0.oracle_y, state0.labeled_mask,
            self._edges, serve.slab_rows,
        )
        self._key = state0.key
        self._round = state0.round
        self._round_host = 0
        self._fill = int(state0.x.shape[0])
        self._labeled = int(state_lib.labeled_count(state0))
        aux = build_aux(cfg, state0)
        # The seed mask must track the SLAB arrays' capacity (strategies that
        # consume it — density's non-seed mass, random's seed exclusion — dot
        # it against capacity-sized pool vectors), and padding it here also
        # makes it a fresh buffer the chunk's carry donation cannot alias
        # (the same copy the batch driver does). Re-padded on every growth.
        if aux.seed_mask is not None:
            aux = aux.replace(seed_mask=self._pad_seed_mask(aux.seed_mask))
        self._aux = aux
        self._fit_key = jax.random.key(cfg.seed + 0x5EED)
        self._test_x = jnp.asarray(test_x)
        self._test_y = jnp.asarray(test_y)

        # Labeled-window capacity of the device fit, FIXED across capacities
        # so a grown pool reuses the same gather/fit shapes. Labels grow
        # without bound in a service; the dispatch guard below refuses a
        # chunk that could outgrow the window instead of silently truncating.
        self._fit_budget = (
            min(cfg.forest.fit_budget, self._slab.capacity)
            if cfg.forest.fit_budget is not None
            else serve.slab_rows
        )
        self._fit_budget_exhausted = False

        self.drift = drift_lib.DriftMonitor(
            entropy_shift=serve.drift_entropy_shift,
            margin_shift=serve.drift_margin_shift,
            min_fresh=serve.drift_min_fresh,
            max_staleness=serve.max_staleness,
        )

        self._programs: Dict[int, _CapacityPrograms] = {}
        self._programs_lock = threading.Lock()
        self._score_fn = slab_lib.make_score_fn()
        self._score_tracker = _ProgramTracker(
            metrics, f"serve_score@{tenant_id}", self._score_fn
        )
        self._ingest_buf_x: list = []
        self._ingest_buf_y: list = []
        # A single-tenant in-flight re-fit is the (extras, ys, t0, reason,
        # progs) tuple; a tenant-axis batched re-fit is the shared
        # _BatchedRefit whose touchdown updates every participant.
        self._inflight = None
        self._inflight_polls = 0
        # Concurrent-cause tags for the NEXT serve_latency event: slab
        # growths and refit dispatches queue device work (and one-off
        # compiles) that the following score query pays for as a latency
        # spike — tagging the query with what ran beside it makes the serve
        # bench's p99 attributable (summarize_metrics groups by cause).
        self._latency_causes: set = set()

        restored = False
        if checkpoint_dir:
            restored = self._try_restore(checkpoint_dir)
        if not restored:
            self._refresh_forest()
        # The batched score path needs structurally identical forests across
        # tenants; the signature is capacity-independent (the fit window is
        # fixed), so computing it once here is safe across growths.
        self._forest_sig = (
            str(jax.tree_util.tree_structure(self._forest)),
            tuple(
                (tuple(l.shape), str(l.dtype))
                for l in jax.tree_util.tree_leaves(self._forest)
            ),
        )

    # -- identity ------------------------------------------------------------

    def _pad_seed_mask(self, mask) -> jnp.ndarray:
        """Seed mask padded (False) to the current slab capacity — slab rows
        past the cold-start pool were never seeded."""
        pad = self._slab.capacity - mask.shape[0]
        return jnp.pad(jnp.asarray(mask, bool), (0, pad))

    def _set_edge_bounds(self) -> None:
        """Host copies of the outermost quantile edges per feature — what
        the ingest path's out-of-range check compares blocks against
        without touching the device."""
        e = np.asarray(self._edges)
        self._edges_lo = e[:, 0]
        self._edges_hi = e[:, -1]

    @property
    def forest_fingerprint(self) -> str:
        """Identity of the resident forest's FEATURE SPACE: the bin edges +
        their epoch. Scores are only comparable across queries while this
        holds still; a drift-triggered bin refresh bumps it (the 'forest
        fingerprint bump' consumers key cache invalidation on)."""
        import hashlib

        h = hashlib.sha256()
        h.update(np.asarray(self._edges).tobytes())
        h.update(str(self._edges_epoch).encode())
        return h.hexdigest()[:16]

    # -- drift-triggered bin-edge refresh ------------------------------------

    def _observe_block_range(self, bx: np.ndarray, count: int) -> None:
        """Fold one ingest block's out-of-cold-start-range fraction into the
        EMA. In-distribution streams sit near 2/max_bins by construction
        (the outermost quantile edges), far under the refresh threshold; a
        mean-shifted/rotated stream climbs toward 1."""
        if getattr(self.serve, "bin_refresh_out_frac", 0.0) <= 0.0 or count == 0:
            return
        real = bx[:count]
        # The MOST-drifted feature's out-of-range fraction (not the mean
        # over features — a one-axis mean shift would be diluted by d-1
        # stationary features): in-distribution it sits near 2/max_bins,
        # a drifted axis climbs toward 1.
        oob = float(
            np.max(
                np.mean(
                    (real < self._edges_lo) | (real > self._edges_hi), axis=0
                )
            )
        )
        if self._oob_ema is None:
            self._oob_ema = oob
        else:
            self._oob_ema += 0.2 * (oob - self._oob_ema)
        self._fresh_since_refresh += count

    def _maybe_refresh_bins(self) -> None:
        thr = getattr(self.serve, "bin_refresh_out_frac", 0.0)
        if thr <= 0.0 or self._oob_ema is None:
            return
        if self._inflight is not None:
            return  # the slab is donation-bound to a running chunk; defer
        if self._fresh_since_refresh < self.serve.drift_min_fresh:
            return
        if self._oob_ema > thr:
            self._refresh_bins()

    def _refresh_bins(self) -> None:
        """Re-quantile the bin edges from the LIVE slab and rebuild against
        them — the serving half of the drift scenario (the cold-start
        binning was documented as frozen until this landed).

        The whole filled slab re-bins: edges from the current points'
        quantiles, codes re-coded in one off-path launch, the per-capacity
        program cache dropped (fit/chunk closures captured the old edges),
        the forest re-fit, and the forest fingerprint bumped. The rebuilt
        programs are FRESH instances, so their first compiles are warmup by
        definition — ``recompiles_after_warmup`` stays 0 on the
        non-drifting path AND across a refresh (pinned in
        tests/test_scenarios.py); the one-off cost is tagged onto the next
        query as the ``bin_refresh_compile`` latency cause instead.
        """
        from distributed_active_learning_tpu.ops import trees_train

        fill = self._fill
        x_host = np.asarray(self._slab.x)[:fill]
        binned = trees_train.make_bins(
            jnp.asarray(x_host), self.cfg.forest.max_bins
        )
        self._edges = binned.edges
        self._edges_epoch += 1
        self._set_edge_bounds()
        # Re-code the whole slab against the new edges; rows past the
        # watermark are unobservable junk either way (the slab contract).
        self._slab = self._slab.replace(
            codes=trees_train.code_features(self._slab.x, self._edges)
        )
        with self._programs_lock:
            self._programs = {}
        self.stats.bin_refreshes += 1
        obs.counter(
            "bin_refreshes", "drift-triggered bin-edge re-quantiles",
            tenant=self.tenant_id,
        ).inc()
        self._oob_ema = None
        self._fresh_since_refresh = 0
        self._latency_causes.add("bin_refresh_compile")
        telemetry.flight_record(
            "bin_refresh", tenant=self.tenant_id,
            epoch=self._edges_epoch, fill=fill,
            capacity=self._slab.capacity,
        )
        if self.metrics is not None:
            self.metrics.event(
                "bin_refresh", tenant=self.tenant_id,
                epoch=self._edges_epoch, fill=fill,
                capacity=self._slab.capacity,
                forest_fingerprint=self.forest_fingerprint,
            )
        self._refresh_forest()
        self._schedule_precompile()

    def _chunk_signature(self) -> tuple:
        """The program-shape identity a tenant-axis batched re-fit groups on:
        tenants whose chunks would trace to the same per-cell body (strategy,
        window, forest dims, fused round count, fit window, class count,
        feature width) may share one grid-chunk launch."""
        fc = self.cfg.forest
        return (
            self.cfg.strategy.name,
            self.cfg.strategy.window_size,
            tuple(sorted((k, str(v)) for k, v in self.cfg.strategy.options.items())),
            fc.n_trees, fc.max_depth, fc.max_bins, fc.kernel, fc.quantize,
            self.serve.refit_rounds,
            self.n_classes,
            self._fit_budget,
            int(self._slab.x.shape[1]),
            self.serve.slab_rows,
        )

    def _batchable_refit_reason(self) -> Optional[str]:
        """None if this tenant's re-fit chunk may ride a tenant-axis batched
        launch; a named reason otherwise (per-tenant dispatch fallback)."""
        if self._aux.lal_forest is not None:
            return "lal_forest"  # the grid takes ONE regressor per group
        if self.cfg.forest.kernel not in _BATCHABLE_KERNELS:
            return f"kernel:{self.cfg.forest.kernel}"
        return None

    @property
    def refit_inflight(self) -> bool:
        return self._inflight is not None

    # -- program cache -------------------------------------------------------

    def _build_programs(self, capacity: int, aot: bool = False) -> _CapacityPrograms:
        """Assemble (and for ``aot`` compile) one capacity's program set.

        The lazy request path builds jitted closures exactly as PR 7 did; the
        precompile worker calls with ``aot=True`` to ``lower().compile()``
        against the capacity's avals — same traced bodies, so the two paths
        cannot diverge (pinned bit-identical in tests/test_serving_multi.py).
        """
        from distributed_active_learning_tpu.runtime.loop import (
            make_chunk_fn,
            make_device_fit,
        )

        # One coherent (edges, epoch) read: a bin refresh racing this build
        # bumps the epoch, and _install_programs rejects the stale set.
        edges_epoch = self._edges_epoch
        fit = make_device_fit(self.cfg, self._edges, self._fit_budget, self.n_classes)
        chunk = make_chunk_fn(
            self._strategy,
            self.cfg.strategy.window_size,
            self.serve.refit_rounds,
            fit,
            label_cap=capacity,
            with_metrics=True,
            n_classes=self.n_classes,
        )
        ingest = slab_lib.make_ingest_fn()
        if aot:
            d = int(self._slab.x.shape[1])
            key_aval = _aval(self._key)
            slab_aval = slab_lib.SlabPool(
                x=_sds((capacity, d), jnp.float32),
                oracle_y=_sds((capacity,), jnp.int32),
                labeled_mask=_sds((capacity,), jnp.bool_),
                codes=_sds((capacity, d), jnp.int32),
                n_filled=_sds((), jnp.int32),
                slab_rows=self.serve.slab_rows,
            )
            state_aval = state_lib.PoolState(
                x=_sds((capacity, d), jnp.float32),
                oracle_y=_sds((capacity,), jnp.int32),
                labeled_mask=_sds((capacity,), jnp.bool_),
                key=key_aval,
                round=_sds((), jnp.int32),
                n_filled=_sds((), jnp.int32),
            )
            aux_aval = _aval(self._aux)
            if self._aux.seed_mask is not None:
                aux_aval = aux_aval.replace(
                    seed_mask=_sds((capacity,), jnp.bool_)
                )
            edges_aval = _aval(self._edges)
            ingest = ingest.lower(
                slab_aval, edges_aval,
                _sds((self.serve.ingest_block, d), jnp.float32),
                _sds((self.serve.ingest_block,), jnp.int32),
                _sds((), jnp.int32),
            ).compile()
            chunk = chunk.lower(
                _sds((capacity, d), jnp.int32), state_aval, aux_aval,
                _aval(self._fit_key), _aval(self._test_x), _aval(self._test_y),
                _sds((), jnp.int32),
            ).compile()
            fit = fit.lower(
                _sds((capacity, d), jnp.int32), state_aval, _aval(self._fit_key)
            ).compile()
        m = self.metrics
        tid = self.tenant_id
        return _CapacityPrograms(
            ingest=ingest,
            chunk=chunk,
            fit=fit,
            ingest_tracker=_ProgramTracker(m, f"serve_ingest@{tid}@{capacity}", ingest),
            chunk_tracker=_ProgramTracker(m, f"serve_chunk@{tid}@{capacity}", chunk),
            fit_tracker=_ProgramTracker(m, f"serve_fit@{tid}@{capacity}", fit),
            aot=aot,
            edges_epoch=edges_epoch,
        )

    def _programs_for(self, capacity: int) -> _CapacityPrograms:
        with self._programs_lock:
            progs = self._programs.get(capacity)
        if progs is not None:
            return progs
        progs = self._build_programs(capacity)
        with self._programs_lock:
            # the precompile worker may have landed meanwhile: its AOT set wins
            return self._programs.setdefault(capacity, progs)

    def _install_programs(self, capacity: int, progs: _CapacityPrograms) -> bool:
        with self._programs_lock:
            if progs.edges_epoch != self._edges_epoch:
                # built against pre-refresh bin edges: installing it would
                # silently serve a forest fit on the stale feature coding
                return False
            if capacity in self._programs:
                return False
            self._programs[capacity] = progs
            return True

    def _schedule_precompile(self) -> None:
        """Hand the NEXT capacity to the precompile worker once the watermark
        is within the headroom threshold of the current capacity."""
        if self._manager is None or not self.serve.precompile_ahead:
            return
        headroom = int(self.serve.precompile_headroom_slabs * self.serve.slab_rows)
        if self._slab.capacity - self._fill <= headroom:
            self._manager.schedule_precompile(
                self, self._slab.capacity + self.serve.slab_rows
            )

    # -- the three work sources ---------------------------------------------

    def score(self, queries) -> np.ndarray:
        """Score query points against the resident forest (the endpoint).

        Blocks only on ITS OWN batch's result — an in-flight re-fit chunk is
        polled non-blockingly, so p99 scoring latency stays decoupled from
        chunk wall time. Batches wider than the static ``score_width`` are
        served in width-sized sub-batches.
        """
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if q.shape[0] == 0:
            return np.zeros((0,), np.float32)
        width = self.serve.score_width
        out = []
        for lo in range(0, q.shape[0], width):
            out.append(self._score_block(q[lo : lo + width]))
        return np.concatenate(out) if len(out) > 1 else out[0]

    def _score_block(self, q: np.ndarray) -> np.ndarray:
        self._poll_refit()
        n = q.shape[0]
        pad = self.serve.score_width - n
        qpad = np.pad(q, ((0, pad), (0, 0))) if pad else q
        t0 = time.perf_counter()
        scores, ent = self._score_fn(self._forest, jnp.asarray(qpad))
        scores_np = np.asarray(scores)[:n]  # the one blocking fetch = latency
        dt = time.perf_counter() - t0
        self._score_tracker.record(dt, batch=n)
        self._finish_query(dt, n, float(np.mean(np.asarray(ent)[:n])))
        self._maybe_refit()
        return scores_np

    def _finish_query(
        self, dt: float, n: int, mean_entropy: float, batched: bool = False
    ) -> None:
        """Post-launch per-query bookkeeping shared by the single-tenant and
        cross-tenant-batched score paths: drift observation, stats, and the
        cause-tagged ``serve_latency`` event."""
        self.drift.observe_serve(mean_entropy)
        self.stats.queries += 1
        self.stats.scored_points += n
        # The concurrent cause this query's latency is attributable to:
        # a slab growth's one-per-new-capacity compile outranks an ordinary
        # refit dispatch (both can be pending; the compile is the spike).
        if "slab_growth_compile" in self._latency_causes:
            cause = "slab_growth_compile"
        elif "bin_refresh_compile" in self._latency_causes:
            cause = "bin_refresh_compile"
        elif "refit_dispatch" in self._latency_causes or self._inflight is not None:
            cause = "refit_dispatch"
        else:
            cause = "none"
        self._latency_causes.clear()
        self.cause_counts[cause] = self.cause_counts.get(cause, 0) + 1
        self._obs_queries.inc()
        self._obs_points.inc(n)
        hist = self._obs_lat.get(cause)
        if hist is None:
            hist = self._obs_lat[cause] = obs.histogram(
                "serve_latency_seconds",
                "per-query scoring latency by concurrent cause",
                tenant=self.tenant_id, cause=cause,
            )
        hist.observe(dt)
        obs.heartbeat("serve_query")
        if self.slo is not None:
            self.slo.observe(dt, ok=True)
            self._update_slo_gauges()
            if self.metrics is not None and self.stats.queries % 100 == 0:
                self._emit_slo_event()
        if self.metrics is not None:
            self.metrics.event(
                "serve_latency", tenant=self.tenant_id,
                seconds=round(dt, 6), batch=n,
                inflight_refit=self._inflight is not None,
                cause=cause,
                batched=batched,
            )

    def note_query_failure(self, error: Exception) -> None:
        """One score block that FAILED before producing a result
        (``score_many``'s failure paths charge it completion-aware — only
        blocks that did not finish): availability accounting — a failed
        query can never meet the SLO, however fast it failed."""
        self.stats.query_failures += 1
        obs.counter(
            "serve_query_failures", "score requests that raised",
            tenant=self.tenant_id,
        ).inc()
        if self.slo is not None:
            self.slo.observe(None, ok=False)
            self._update_slo_gauges(force=True)
        if self.metrics is not None:
            self.metrics.event(
                "serve_error", tenant=self.tenant_id, error=repr(error)[:200],
            )

    def _update_slo_gauges(self, force: bool = False) -> None:
        """Refresh the compliance/burn gauges — throttled to ~1/s (burn
        windows only move at slot granularity, and walking three window
        deques per QUERY would put real work on the scoring hot path; a
        scrape reads at most one second of staleness). Failures force an
        immediate refresh — they are rare and exactly the news."""
        now = time.monotonic()
        if not force and now - self._slo_gauge_ts < 1.0:
            return
        self._slo_gauge_ts = now
        comp = self.slo.compliance()
        if comp is not None:
            if self._obs_slo_comp is None:
                self._obs_slo_comp = obs.gauge(
                    "slo_compliance_ratio",
                    "lifetime fraction of queries meeting the tenant's SLO",
                    tenant=self.tenant_id,
                )
            self._obs_slo_comp.set(round(comp, 6))
        for name, rate in self.slo.burn_rates().items():
            if rate is None:
                continue
            g = self._obs_slo_burn.get(name)
            if g is None:
                g = self._obs_slo_burn[name] = obs.gauge(
                    "slo_burn_rate",
                    "windowed error-budget burn rate (1.0 = sustainable)",
                    tenant=self.tenant_id, window=name,
                )
            g.set(round(rate, 4))

    def _emit_slo_event(self) -> None:
        if self.metrics is None or self.slo is None:
            return
        snap = self.slo.snapshot()
        burn = snap.pop("burn")
        self.metrics.event(
            "slo", tenant=self.tenant_id, **snap,
            **{f"burn_{name}": rate for name, rate in burn.items()},
        )

    def submit(self, x, y) -> None:
        """Queue arriving points (with their eventual oracle labels — the
        simulation convention the whole repo uses: labels exist but are
        hidden until an AL round reveals them)."""
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            x = x[None, :]
        y = np.asarray(y, np.int32).reshape(-1)
        # The class count is frozen at cold start (it sizes the fit's static
        # shapes and the metrics histogram); a label past it would silently
        # fall out of the histogram fit — refuse loudly instead.
        if y.size and int(y.max()) >= self.n_classes:
            raise ValueError(
                f"ingested label {int(y.max())} is out of range for the "
                f"service's {self.n_classes} classes (fixed by the cold-start "
                "corpus); restart the service with a corpus covering every "
                "class"
            )
        self._ingest_buf_x.append(x)
        self._ingest_buf_y.append(y)
        self._poll_refit()
        self._drain_ingest()
        self._maybe_refit()

    def flush(self) -> None:
        """Drain any partial ingest block and force an in-flight re-fit's
        touchdown — the quiesce point (checkpoint, shutdown, test barriers)."""
        self._drain_ingest(force=True)
        self._poll_refit(force=True)
        if self.slo is not None and self.stats.queries:
            self._emit_slo_event()  # the stream's final compliance word

    # -- ingest --------------------------------------------------------------

    def _drain_ingest(self, force: bool = False) -> None:
        if not self._ingest_buf_x:
            return
        bx = np.concatenate(self._ingest_buf_x)
        by = np.concatenate(self._ingest_buf_y)
        block = self.serve.ingest_block
        lo = 0
        while bx.shape[0] - lo >= block:
            self._ingest_block(bx[lo : lo + block], by[lo : lo + block], block)
            lo += block
        if force and lo < bx.shape[0]:
            px, py, count = slab_lib.pad_block(bx[lo:], by[lo:], block)
            self._ingest_block(px, py, count)
            lo = bx.shape[0]
        self._ingest_buf_x = [bx[lo:]] if lo < bx.shape[0] else []
        self._ingest_buf_y = [by[lo:]] if lo < bx.shape[0] else []

    def _ingest_block(self, bx: np.ndarray, by: np.ndarray, count: int) -> None:
        block = self.serve.ingest_block
        while self._fill + block > self._slab.capacity:
            self._grow()
        progs = self._programs_for(self._slab.capacity)
        t0 = time.perf_counter()
        self._slab, _fill_out = progs.ingest(
            self._slab, self._edges,
            jnp.asarray(bx), jnp.asarray(by), jnp.asarray(count, jnp.int32),
        )
        dt = time.perf_counter() - t0  # dispatch wall: the write is async
        progs.ingest_tracker.record(dt, points=count)
        self._fill += count
        self.stats.ingest_blocks += 1
        self.stats.ingested_points += count
        obs.counter(
            "ingest_points", "points ingested", tenant=self.tenant_id
        ).inc(count)
        obs.gauge(
            "slab_fill", "slab fill watermark (rows)", tenant=self.tenant_id
        ).set(self._fill)
        obs.gauge(
            "slab_capacity", "slab capacity (rows)", tenant=self.tenant_id
        ).set(self._slab.capacity)
        self.drift.observe_ingest(count)
        self._observe_block_range(bx, count)
        self._maybe_refresh_bins()
        if self.metrics is not None:
            self.metrics.event(
                "ingest", tenant=self.tenant_id,
                points=count, seconds=round(dt, 6),
                fill=self._fill, capacity=self._slab.capacity,
            )
        self._schedule_precompile()

    def _grow(self) -> None:
        self._slab = slab_lib.grow_slab(self._slab)
        if self._aux.seed_mask is not None:
            self._aux = self._aux.replace(
                seed_mask=self._pad_seed_mask(self._aux.seed_mask)
            )
        self.stats.slab_growths += 1
        cap = self._slab.capacity
        with self._programs_lock:
            ready = cap in self._programs
        if not ready and self._manager is not None:
            # A precompile may be mid-flight: wait for it rather than racing
            # a second compile of the same programs on the request thread.
            # The wait is still a growth stall, so the cause tag stands
            # (ready stays False for the accounting below).
            self._manager.wait_precompile(self, cap)
        obs.counter(
            "slab_growths", "slab capacity growths", tenant=self.tenant_id
        ).inc()
        if ready:
            self.stats.growths_precompiled += 1
            obs.counter(
                "slab_growths_precompiled",
                "growths that swapped in AOT-precompiled executables",
                tenant=self.tenant_id,
            ).inc()
        else:
            self._latency_causes.add("slab_growth_compile")
        telemetry.flight_record(
            "slab_grow", tenant=self.tenant_id,
            capacity=cap, fill=self._fill,
            buffered=sum(len(b) for b in self._ingest_buf_x),
            precompiled=ready,
        )
        if self.metrics is not None:
            self.metrics.event(
                "slab_grow", tenant=self.tenant_id,
                capacity=cap, fill=self._fill, precompiled=ready,
            )
        self._schedule_precompile()

    # -- re-fit --------------------------------------------------------------

    def _refit_candidate(self) -> Optional[str]:
        """The drift decision plus every dispatch guard, WITHOUT dispatching:
        the manager collects candidates across tenants so coinciding re-fits
        batch into one tenant-axis launch. Returns the reason, or None."""
        if self._inflight is not None or self._fit_budget_exhausted:
            return None
        reason = self.drift.should_refit()
        if reason is None:
            return None
        return self._check_refit_guards(reason)

    def _check_refit_guards(self, reason: str) -> Optional[str]:
        if self._fill - self._labeled <= 0:
            return None  # nothing left to label; a chunk would be all sentinels
        K, window = self.serve.refit_rounds, self.cfg.strategy.window_size
        if self._labeled + K * window > self._fit_budget:
            # The device fit's labeled window is static; overrunning it would
            # silently truncate the gather and corrupt the forest. Refuse
            # loudly, once.
            self._fit_budget_exhausted = True
            self.stats.refits_skipped_fit_budget += 1
            if self.metrics is not None:
                self.metrics.event(
                    "refit_skipped", tenant=self.tenant_id, reason="fit_budget",
                    labeled=self._labeled, fit_budget=self._fit_budget,
                )
            return None
        return reason

    def _maybe_refit(self) -> None:
        if self._manager is not None:
            self._manager._maybe_refit_group()
            return
        reason = self._refit_candidate()
        if reason is not None:
            self._dispatch_refit(reason)

    def refit_now(self, reason: str = "manual") -> bool:
        """Dispatch a re-fit chunk immediately (warmup, operator request),
        bypassing the drift decision but not the safety guards; returns
        whether a chunk actually launched."""
        if self._inflight is not None or self._fit_budget_exhausted:
            return False
        if self._check_refit_guards(reason) is None:
            return False
        self._dispatch_refit(reason)
        return True

    def _record_refit_dispatch(self, reason: str) -> None:
        self.stats.refits += 1
        self.refit_reasons[reason] = self.refit_reasons.get(reason, 0) + 1
        obs.counter(
            "refits", "re-fit chunk dispatches by drift reason",
            tenant=self.tenant_id, reason=reason,
        ).inc()
        obs.gauge(
            "refit_inflight", "1 while a re-fit chunk is in flight",
            tenant=self.tenant_id,
        ).set(1)
        self._latency_causes.add("refit_dispatch")
        telemetry.flight_record(
            "refit", tenant=self.tenant_id,
            reason=reason, rounds=self.serve.refit_rounds,
            labeled=self._labeled, fill=self._fill,
            capacity=self._slab.capacity,
            buffered=sum(len(b) for b in self._ingest_buf_x),
        )
        if self.metrics is not None:
            self.metrics.event(
                "refit", tenant=self.tenant_id,
                reason=reason, rounds=self.serve.refit_rounds,
                labeled=self._labeled, fill=self._fill,
                capacity=self._slab.capacity,
            )

    def _dispatch_refit(self, reason: str) -> None:
        progs = self._programs_for(self._slab.capacity)
        state = slab_lib.flat_state(self._slab, self._key, self._round)
        end_round = self._round_host + self.serve.refit_rounds
        t0 = time.perf_counter()
        out_state, extras, ys = progs.chunk(
            self._slab.codes, state, self._aux, self._fit_key,
            self._test_x, self._test_y, jnp.asarray(end_round, jnp.int32),
        )
        # The chunk donated the carried state: rebind the slab to the output
        # arrays NOW — every later ingest/score consumes these futures and
        # sequences behind the running chunk on device.
        self._slab = self._slab.replace(
            x=out_state.x,
            oracle_y=out_state.oracle_y,
            labeled_mask=out_state.labeled_mask,
            n_filled=out_state.n_filled,
        )
        self._key = out_state.key
        self._round = out_state.round
        self._inflight = (extras, ys, t0, reason, progs)
        self._inflight_polls = 0
        self._record_refit_dispatch(reason)

    def _poll_refit(self, force: bool = False) -> None:
        if self._inflight is None:
            return
        if isinstance(self._inflight, _BatchedRefit):
            self._inflight.poll(force=force)
            return
        extras = self._inflight[0]
        self._inflight_polls += 1
        ready = True
        probe = getattr(extras.n_labeled_after, "is_ready", None)
        if probe is not None and not force:
            ready = bool(probe())
        if force or ready or self._inflight_polls >= self.serve.refit_poll_events:
            self._touchdown()

    def _touchdown(self) -> None:
        extras, ys, t0, reason, progs = self._inflight
        self._inflight = None
        n_labeled_after = int(extras.n_labeled_after)  # blocks if still running
        n_active = int(extras.n_active)
        dt = time.perf_counter() - t0
        telemetry.flight_record(
            "touchdown", tenant=self.tenant_id,
            program=progs.chunk_tracker.program, reason=reason,
            n_active=n_active, n_labeled_after=n_labeled_after,
            seconds=round(dt, 6), polls=self._inflight_polls,
        )
        progs.chunk_tracker.record(dt, reason=reason)
        self._labeled = n_labeled_after
        self._round_host += n_active
        self.stats.refit_rounds += n_active
        self._obs_refit_touchdown(n_active)
        if n_active:
            rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
            active_np = np.asarray(active_y)
            rounds_np = np.asarray(rounds_y)[active_np]
            labeled_np = np.asarray(labeled_y)[active_np]
            acc_np = np.asarray(acc_y)[active_np]
            round_dicts = telemetry.stacked_metrics_to_dicts(ys[5], active_np)
            self._absorb_rounds(rounds_np, labeled_np, acc_np, round_dicts, dt / n_active)

    def _obs_refit_touchdown(self, n_active: int) -> None:
        """Ops-plane echo of one re-fit touchdown (single-tenant and
        tenant-axis batched paths): the in-flight gauge drops, the round
        counter advances, and /healthz's last-touchdown age resets."""
        obs.gauge(
            "refit_inflight", "1 while a re-fit chunk is in flight",
            tenant=self.tenant_id,
        ).set(0)
        obs.counter(
            "refit_rounds", "AL rounds completed by re-fit chunks",
            tenant=self.tenant_id,
        ).inc(n_active)
        obs.heartbeat("serve_touchdown")

    def _absorb_rounds(
        self, rounds_np, labeled_np, acc_np, round_dicts, per_round_seconds
    ) -> None:
        """Fold one touchdown's active rounds into records/drift/metrics and
        refresh the resident forest — shared by the single-tenant and the
        tenant-axis batched touchdown paths."""
        self.result.extend_from_arrays(
            rounds_np, labeled_np,
            np.maximum(self._fill - labeled_np, 0), acc_np,
            total_time=per_round_seconds,
            metrics=round_dicts,
        )
        self.drift.observe_chunk(round_dicts)
        if self.metrics is not None:
            for i in range(len(rounds_np)):
                self.metrics.round(
                    tenant=self.tenant_id,
                    round=int(rounds_np[i]),
                    n_labeled=int(labeled_np[i]),
                    accuracy=float(acc_np[i]),
                    **round_dicts[i],
                )
        self._refresh_forest()

    def _refresh_forest(self) -> None:
        """Re-fit the RESIDENT forest from the current labeled set — the
        async launch whose output every subsequent score serves from."""
        progs = self._programs_for(self._slab.capacity)
        state = slab_lib.flat_state(self._slab, self._key, self._round)
        t0 = time.perf_counter()
        self._forest = progs.fit(
            self._slab.codes, state,
            jax.random.fold_in(self._fit_key, self._round_host),
        )
        progs.fit_tracker.record(time.perf_counter() - t0)
        if self._manager is not None:
            self._manager._mark_forest_dirty(self.tenant_id)

    # -- persistence ---------------------------------------------------------

    def save_checkpoint(self) -> Optional[str]:
        """Persist the slab watermark + mask + ingested points + resident
        forest so a killed service resumes WITHOUT replaying ingest
        (runtime/checkpoint.py ``save_serve``, tenant-axis file names when
        this tenant rides a multi-tenant manager)."""
        if not self.checkpoint_dir:
            return None
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        self.flush()
        state = slab_lib.flat_state(self._slab, self._key, self._round)
        return ckpt_lib.save_serve(
            self.checkpoint_dir, state, self._forest, self.result,
            fingerprint=ckpt_lib.config_fingerprint(self.cfg),
            tenant=self._ckpt_name,
            # Live bin-refresh state: a drift-refreshed service re-coded its
            # slab against these edges, and the resident forest was fitted
            # on those codes — a restore must re-code from the SAME edges,
            # not the cold-start ones (_try_restore).
            edges=self._edges,
            edges_epoch=self._edges_epoch,
        )

    def _try_restore(self, ckpt_dir: str) -> bool:
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        progs = self._programs_for(self._slab.capacity)
        # The forest's pytree structure is whatever this configuration's fit
        # program produces — eval_shape gives the template without running it.
        template = jax.eval_shape(
            progs.fit,
            self._slab.codes,
            slab_lib.flat_state(self._slab, self._key, self._round),
            self._fit_key,
        )
        restored = ckpt_lib.restore_latest_serve(
            ckpt_dir, template,
            fingerprint=ckpt_lib.config_fingerprint(self.cfg),
            tenant=self._ckpt_name,
        )
        if restored is None:
            return False
        (
            x, y, mask, n_filled, key_data, rnd, forest, result,
            edges, edges_epoch,
        ) = restored
        if edges is not None and int(edges_epoch) > self._edges_epoch:
            # The checkpointed service had drift-refreshed its bin edges:
            # the restored forest was fitted on codes quantized against
            # THOSE edges, so adopt them before re-coding the slab below —
            # re-binning from the cold-start edges would pair the restored
            # forest with codes it never saw. The cold-start program set
            # (built above for the restore template) captured the old
            # edges; drop it so the next use rebuilds at this epoch
            # (_install_programs rejects stale-epoch sets the same way a
            # live refresh does).
            self._edges = jnp.asarray(edges)
            self._edges_epoch = int(edges_epoch)
            self._set_edge_bounds()
            with self._programs_lock:
                self._programs = {}
        self._slab = slab_lib.init_slab_pool(
            x, y, mask, self._edges, self.serve.slab_rows
        )
        if self._aux.seed_mask is not None:
            self._aux = self._aux.replace(
                seed_mask=self._pad_seed_mask(self._aux.seed_mask)
            )
        self._fill = int(n_filled)
        self._key = jax.random.wrap_key_data(
            jnp.asarray(key_data), impl=jax.random.key_impl(self._key)
        )
        self._round = jnp.asarray(rnd)
        self._round_host = int(rnd)
        self._forest = forest
        self.result = result
        self._labeled = int(np.asarray(mask).sum())
        return True

    # -- reporting -----------------------------------------------------------

    def recompiles_after_warmup(self) -> int:
        """Total jit-cache growths beyond each program instance's first call
        — the no-silent-recompile guarantee the serve bench asserts at 0."""
        total = self._score_tracker.recompiles
        with self._programs_lock:
            progs_list = list(self._programs.values())
        for progs in progs_list:
            total += (
                progs.ingest_tracker.recompiles
                + progs.chunk_tracker.recompiles
                + progs.fit_tracker.recompiles
            )
        return total

    def summary(self) -> Dict:
        out = {
            "tenant": self.tenant_id,
            "queries": self.stats.queries,
            "query_failures": self.stats.query_failures,
            "scored_points": self.stats.scored_points,
            "ingest_blocks": self.stats.ingest_blocks,
            "ingested_points": self.stats.ingested_points,
            "refits": self.stats.refits,
            "refit_rounds": self.stats.refit_rounds,
            "refit_reasons": dict(self.refit_reasons),
            "refits_skipped_fit_budget": self.stats.refits_skipped_fit_budget,
            "slab_growths": self.stats.slab_growths,
            "growths_precompiled": self.stats.growths_precompiled,
            "bin_refreshes": self.stats.bin_refreshes,
            "bin_epoch": self._edges_epoch,
            "forest_fingerprint": self.forest_fingerprint,
            "capacity": self._slab.capacity,
            "fill": self._fill,
            "labeled": self._labeled,
            "latency_causes": dict(self.cause_counts),
            "recompiles_after_warmup": self.recompiles_after_warmup(),
        }
        if self.slo is not None:
            # the SLO block only exists when an objective is configured, so
            # SLO-less summaries stay key-for-key what they always were
            out["slo"] = self.slo.snapshot()
        return out


class _BatchedRefit:
    """One in-flight tenant-axis re-fit launch: the shared handle every
    participating tenant's ``_inflight`` points at. Touchdown unstacks the
    grid chunk's ``[K, T, ...]`` ys and ``[T, ...]`` carry back onto each
    participant — non-candidate group members rode as masked no-ops and are
    skipped (their carry passed through untouched; outputs are discards)."""

    def __init__(
        self,
        manager: "TenantManager",
        members: List[Tenant],
        participants: Dict[str, Tuple[int, str]],  # tid -> (cell index, reason)
        caps_at_dispatch: List[int],
        out_grid,
        extras,
        ys,
        t0: float,
        tracker: _ProgramTracker,
    ):
        self.manager = manager
        self.members = members
        self.participants = participants
        self.caps_at_dispatch = caps_at_dispatch
        self.out_grid = out_grid
        self.extras = extras
        self.ys = ys
        self.t0 = t0
        self.tracker = tracker
        self.polls = 0
        self.done = False
        self._poll_limit = min(t.serve.refit_poll_events for t in members)

    def poll(self, force: bool = False) -> None:
        if self.done:
            return
        self.polls += 1
        ready = True
        probe = getattr(self.extras.n_labeled_after, "is_ready", None)
        if probe is not None and not force:
            ready = bool(probe())
        if force or ready or self.polls >= self._poll_limit:
            self.touchdown()

    def touchdown(self) -> None:
        if self.done:
            return
        self.done = True
        ys = self.ys
        active_all = np.asarray(ys[4])          # [K, T] bool
        dt = time.perf_counter() - self.t0
        n_parts = len(self.participants)
        # One fetch of the whole stacked metrics pytree, host-sliced per cell
        # (the sweep-touchdown discipline — never one transfer per tenant).
        dicts_by_cell = telemetry.stacked_sweep_metrics_to_dicts(ys[5], active_all)
        rounds_all = np.asarray(ys[0])
        labeled_all = np.asarray(ys[1])
        acc_all = np.asarray(ys[2])
        by_id = {t.tenant_id: (i, t) for i, t in enumerate(self.members)}
        for tid, (cell, reason) in self.participants.items():
            i, t = by_id[tid]
            assert i == cell
            t._inflight = None
            active_np = active_all[:, i]
            n_active = int(active_np.sum())
            cap_i = self.caps_at_dispatch[i]
            mask_out = self.out_grid.labeled_mask[i, :cap_i]
            if t._slab.capacity > cap_i:  # the tenant grew mid-flight
                mask_out = jnp.pad(mask_out, (0, t._slab.capacity - cap_i))
            host_mask = np.asarray(mask_out)
            t._slab = t._slab.replace(labeled_mask=jnp.asarray(host_mask))
            t._key = self.out_grid.key[i]
            t._round = self.out_grid.round[i]
            t._labeled = int(host_mask[:cap_i].sum())
            t._round_host += n_active
            t.stats.refit_rounds += n_active
            t._obs_refit_touchdown(n_active)
            telemetry.flight_record(
                "touchdown", tenant=tid, program=self.tracker.program,
                reason=reason, n_active=n_active,
                n_labeled_after=t._labeled,
                seconds=round(dt, 6), polls=self.polls, batched=True,
            )
            if n_active:
                sel = np.flatnonzero(active_np)
                t._absorb_rounds(
                    rounds_all[sel, i], labeled_all[sel, i], acc_all[sel, i],
                    dicts_by_cell[i], dt / n_active,
                )
        self.tracker.record(dt, tenants=n_parts)


@dataclasses.dataclass
class _PrecompileJob:
    kind: str                       # "capacity" | "batched_chunk"
    tenant: Optional[Tenant]
    capacity: int
    group_key: Optional[tuple] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    ok: bool = False


class TenantManager:
    """N resident tenants, the cross-tenant fused paths, and the AOT
    capacity-precompile worker. See the module docstring for the design;
    the short form:

    - ``add_tenant`` makes a dataset x model resident (restoring from the
      tenant-axis serve checkpoint when one exists);
    - ``score_many`` fuses concurrent score requests into one vmapped launch
      per same-signature group (per-tenant fallback with a named reason only
      for tenants no group can hold);
    - drift-triggered re-fits from same-configuration tenants coalesce into
      one tenant-axis grid-chunk launch;
    - slab growth swaps in background-AOT-compiled executables instead of
      paying XLA compile on the triggering request.
    """

    def __init__(self, metrics=None, checkpoint_dir: Optional[str] = None):
        self.metrics = metrics
        self.checkpoint_dir = checkpoint_dir
        self._tenants: Dict[str, Tenant] = {}
        self._lock = threading.RLock()
        # batched scoring: same-signature groups, each with a RESIDENT
        # stacked forest and its own stacked score program (rebuilt only on
        # membership change; restacked only on re-fit touchdown).
        self._score_groups: Optional[Dict[tuple, _ScoreGroup]] = None
        self._prev_score_groups: Dict[tuple, _ScoreGroup] = {}
        self._score_fallback_by_tid: Dict[str, str] = {}
        # recompiles counted by groups a membership change retired — the
        # headline recompiles_after_warmup must never forget a recompile
        # just because its program instance was replaced
        self._retired_group_recompiles = 0
        self.batched_score_launches = 0
        self.score_fallback_reasons: Dict[str, int] = {}
        # tenant-axis batched re-fit
        self._grid_fits: Dict[tuple, object] = {}
        self._batched_chunks: Dict[tuple, Tuple[object, _ProgramTracker]] = {}
        self.batched_refit_launches = 0
        # AOT precompile worker (lazily started)
        self._queue: "queue_lib.Queue[Optional[_PrecompileJob]]" = queue_lib.Queue()
        self._pending: Dict[tuple, _PrecompileJob] = {}
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.precompiles = 0
        self.precompile_errors = 0
        _LIVE_MANAGERS.add(self)

    # -- tenancy -------------------------------------------------------------

    def add_tenant(
        self,
        tenant_id: str,
        cfg: ExperimentConfig,
        serve: ServeConfig,
        train_x,
        train_y,
        test_x,
        test_y,
        ckpt_name: str = "__tenant_id__",
    ) -> Tenant:
        """Make a tenant resident (cold start, or resumed from its tenant-axis
        serve checkpoint when ``checkpoint_dir`` holds one). ``ckpt_name``
        defaults to the tenant id; ``None`` keeps the PR-7 single-tenant file
        names (the :class:`~serving.service.ALService` compatibility route).
        """
        if not _TENANT_ID_RE.fullmatch(tenant_id):
            raise ValueError(
                f"tenant id {tenant_id!r} must match {_TENANT_ID_RE.pattern} "
                "(it names checkpoint files and telemetry streams)"
            )
        # SLO classes (serving/frontend.py): a non-positive weight would
        # starve the tenant FOREVER under deficit round-robin (its credits
        # never reach a slot's cost and its Futures never resolve) — refuse
        # at residency time, where the operator can see it, not in the
        # shared dispatcher loop.
        if getattr(serve, "slo_weight", 1.0) <= 0.0:
            raise ValueError(
                f"tenant {tenant_id!r} has slo_weight="
                f"{serve.slo_weight}; weights must be > 0 (1.0 = served "
                "every contended cycle, 0.5 = every other one) — to pause "
                "a tenant, stop submitting to it"
            )
        if getattr(serve, "slo_priority", 0) < 0:
            raise ValueError(
                f"tenant {tenant_id!r} has slo_priority="
                f"{serve.slo_priority}; priorities are >= 0 (admission cap "
                "scales by 1 + priority)"
            )
        with self._lock:
            if tenant_id in self._tenants:
                raise ValueError(f"tenant {tenant_id!r} is already resident")
            tenant = Tenant(
                tenant_id, cfg, serve, train_x, train_y, test_x, test_y,
                metrics=self.metrics,
                checkpoint_dir=self.checkpoint_dir,
                ckpt_name=tenant_id if ckpt_name == "__tenant_id__" else ckpt_name,
                manager=self,
            )
            self._tenants[tenant_id] = tenant
            # membership changed: repartition the fused score path (groups
            # whose membership survives keep their program + resident stack)
            self._score_groups = None
        if self.metrics is not None:
            self.metrics.event(
                "tenant_added", tenant=tenant_id,
                capacity=tenant._slab.capacity, fill=tenant._fill,
                n_classes=tenant.n_classes,
                strategy=cfg.strategy.name,
            )
        tenant._schedule_precompile()
        return tenant

    def tenant(self, tenant_id: str) -> Tenant:
        return self._tenants[tenant_id]

    @property
    def tenant_ids(self) -> List[str]:
        return list(self._tenants)

    def __len__(self) -> int:
        return len(self._tenants)

    # -- scoring -------------------------------------------------------------

    def score(self, tenant_id: str, queries) -> np.ndarray:
        """Single-tenant scoring path (the PR-7 endpoint, byte-compatible)."""
        return self._tenants[tenant_id].score(queries)

    def submit(self, tenant_id: str, x, y) -> None:
        self._tenants[tenant_id].submit(x, y)

    def _tenant_group_key(self, t: Tenant) -> tuple:
        """Everything the stacked score program's avals depend on: tenants
        agreeing on this tuple can share one vmapped launch."""
        return (t._forest_sig, t.serve.score_width, int(t._slab.x.shape[1]))

    def _score_grouping(
        self,
    ) -> Tuple[Dict[tuple, "_ScoreGroup"], Dict[str, str]]:
        """The resident partition of the fused score path: same-signature
        groups of >= 2 (each with its resident stacked forest + program) and
        a per-tenant NAMED fallback reason for everyone else. Rebuilt only
        when the tenant set changes (``add_tenant`` invalidates); a rebuild
        reuses any group whose key AND membership survived, so stable groups
        keep their compiled program and resident stack. The rebuild runs on
        the dispatcher thread while ``add_tenant`` invalidates under the
        manager lock from a client thread — same lock here, or a stale
        partition serves the wrong path (flagged by DAL201)."""
        with self._lock:
            if self._score_groups is not None:
                return self._score_groups, self._score_fallback_by_tid
            members: Dict[tuple, List[str]] = {}
            fallback: Dict[str, str] = {}
            single = len(self._tenants) < 2
            for tid, t in self._tenants.items():
                if t.cfg.forest.kernel not in _BATCHABLE_KERNELS:
                    fallback[tid] = "kernel"
                    continue
                members.setdefault(self._tenant_group_key(t), []).append(tid)
            prev = self._prev_score_groups
            groups: Dict[tuple, _ScoreGroup] = {}
            for key, tids in members.items():
                if single:
                    fallback[tids[0]] = "single_tenant"
                elif len(tids) < 2:
                    # structurally alone among the residents: sharing would
                    # need another tenant with this signature
                    fallback[tids[0]] = "singleton_signature"
                else:
                    old = prev.get(key)
                    if old is not None and old.tids == tids:
                        groups[key] = old  # program + resident stack survive
                    else:
                        groups[key] = _ScoreGroup(key, tids, self.metrics)
            for key, old in prev.items():
                if groups.get(key) is not old:
                    self._retired_group_recompiles += old.tracker.recompiles
            self._prev_score_groups = groups
            self._score_groups = groups
            self._score_fallback_by_tid = fallback
            return groups, fallback

    def score_groups(self) -> List[List[str]]:
        """The current same-signature groups riding the fused path (tenant
        ids in registration order) — the observable the fleet bench and the
        summary report."""
        groups, _ = self._score_grouping()
        return [list(g.tids) for g in groups.values()]

    def _mark_forest_dirty(self, tenant_id: Optional[str] = None) -> None:
        """A re-fit touchdown moved ``tenant_id``'s resident forest: restack
        that tenant's group before its next fused launch (None = all groups;
        the conservative path for callers that predate per-group dirt)."""
        with self._lock:
            if self._score_groups is None:
                return  # next _score_grouping() stacks fresh anyway
            for g in self._score_groups.values():
                if tenant_id is None or tenant_id in g.tids:
                    g.dirty = True

    def _stacked_for(self, group: "_ScoreGroup"):
        # The re-stack must be ATOMIC with the dirty flag (a touchdown
        # marking dirty mid-stack would be lost); the stack itself is a
        # dispatch under the manager lock, which is the accepted cost here —
        # one dispatcher thread by design, and RLock re-entry keeps the
        # score path cheap when the cache is warm.
        with self._lock:
            if group.dirty or group.stacked is None:
                forests = [self._tenants[tid]._forest for tid in group.tids]
                group.stacked = jax.tree_util.tree_map(  # audit: ok[DAL202]
                    lambda *ls: jnp.stack(ls), *forests
                )
                group.dirty = False
            return group.stacked

    def score_many(self, requests: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Serve concurrent score requests from several tenants as fused
        cross-tenant launches (ONE program execution per group per
        width-round).

        Resident tenants are partitioned into same-signature GROUPS
        (:meth:`_score_grouping`); each group keeps a resident stacked
        forest and its own stacked score program, and its tenant axis spans
        every member (absent ones ride as zero-valid padding — the
        aval-stability discipline), so each program compiles once per group
        membership. Requests wider than ``score_width`` are served in
        width-rounds: each round launches one batch holding every group
        member's next sub-block. Only tenants the partition could NOT group
        (singleton signature, unbatchable kernel, single resident tenant)
        fall back to the per-tenant endpoint, each with a named reason.
        """
        order = [tid for tid in self._tenants if tid in requests]
        unknown = set(requests) - set(order)
        if unknown:
            raise KeyError(f"unknown tenants in score_many: {sorted(unknown)}")
        if not order:
            return {}
        groups, fallback_by_tid = self._score_grouping()
        arrays: Dict[str, np.ndarray] = {}
        for tid in order:
            q = np.asarray(requests[tid], np.float32)
            if q.ndim == 1:
                q = q[None, :]
            arrays[tid] = q
        outs: Dict[str, list] = {tid: [] for tid in order}
        pos = {tid: 0 for tid in order}

        def charge_failure(e: Exception, attempted) -> None:
            # Availability accounting, completion-aware (SLO observations
            # are per width-round/block): the tenants in the failed attempt
            # plus every tenant with blocks never attempted count one
            # failure each; blocks that already completed keep their (real)
            # good observations — charging everyone would double-count
            # requests that completed (frontend callers still see the whole
            # call fail; SLO counts what actually ran).
            for tid in order:
                if tid in attempted or pos[tid] < arrays[tid].shape[0]:
                    self._tenants[tid].note_query_failure(e)

        # One vmapped launch per GROUP per width-round: the group axis spans
        # every member (absent ones ride as zero-valid padding — the
        # aval-stability discipline), so each group's program compiles once
        # per membership.
        for group in groups.values():
            in_play = [tid for tid in group.tids if tid in arrays]
            if not in_play:
                continue
            width, d = group.width, group.features
            while any(pos[tid] < arrays[tid].shape[0] for tid in in_play):
                self.poll()  # once per distinct in-flight launch per round
                qpad = np.zeros((len(group.tids), width, d), np.float32)
                n_valid = [0] * len(group.tids)
                round_tids = set()
                for i, tid in enumerate(group.tids):
                    if tid not in arrays or pos[tid] >= arrays[tid].shape[0]:
                        continue
                    block = arrays[tid][pos[tid] : pos[tid] + width]
                    pos[tid] += block.shape[0]
                    qpad[i, : block.shape[0]] = block
                    n_valid[i] = block.shape[0]
                    round_tids.add(tid)
                try:
                    t0 = time.perf_counter()
                    scores, ents = group.fn(
                        self._stacked_for(group), jnp.asarray(qpad)
                    )
                    scores_np = np.asarray(scores)  # the blocking fetch = latency
                    dt = time.perf_counter() - t0
                    ents_np = np.asarray(ents)
                except Exception as e:
                    charge_failure(e, round_tids)
                    raise
                group.tracker.record(
                    dt, tenants=sum(1 for n in n_valid if n),
                    group_size=len(group.tids),
                )
                group.launches += 1
                self.batched_score_launches += 1
                for i, tid in enumerate(group.tids):
                    n = n_valid[i]
                    if not n:
                        continue
                    outs[tid].append(scores_np[i, :n])
                    self._tenants[tid]._finish_query(
                        dt, n, float(np.mean(ents_np[i, :n])), batched=True
                    )
                self._maybe_refit_group()
        # Per-tenant fallback for everyone the partition could not group —
        # with a NAMED reason (singleton_signature / kernel / single_tenant),
        # never silent. A tenant sharing its signature with at least one
        # other resident never lands here.
        for tid in order:
            reason = fallback_by_tid.get(tid)
            if reason is None:
                continue
            self.score_fallback_reasons[reason] = (
                self.score_fallback_reasons.get(reason, 0) + 1
            )
            try:
                outs[tid].append(self._tenants[tid].score(arrays[tid]))
                pos[tid] = arrays[tid].shape[0]
            except Exception as e:
                charge_failure(e, {tid})
                raise
        return {
            tid: (
                np.concatenate(outs[tid]) if len(outs[tid]) > 1
                else outs[tid][0] if outs[tid]
                else np.zeros((0,), np.float32)  # empty request: empty result
            )
            for tid in order
        }

    # -- re-fit grouping -------------------------------------------------------

    def _maybe_refit_group(self) -> None:
        """Collect drift-triggered re-fit candidates across tenants; dispatch
        same-signature groups of >= 2 as ONE tenant-axis chunk launch, the
        rest through the single-tenant path."""
        candidates: List[Tuple[Tenant, str]] = []
        for t in self._tenants.values():
            reason = t._refit_candidate()
            if reason is not None:
                candidates.append((t, reason))
        if not candidates:
            return
        self._dispatch_refits(candidates)

    def refit_now(self, reason: str = "manual") -> int:
        """Dispatch re-fits for every eligible tenant immediately (warmup,
        operator request) — batched per signature group; returns how many
        tenants actually launched."""
        candidates = []
        for t in self._tenants.values():
            if t._inflight is not None or t._fit_budget_exhausted:
                continue
            if t._check_refit_guards(reason) is None:
                continue
            candidates.append((t, reason))
        self._dispatch_refits(candidates)
        return len(candidates)

    def _dispatch_refits(self, candidates: List[Tuple[Tenant, str]]) -> None:
        groups: Dict[tuple, List[Tuple[Tenant, str]]] = {}
        singles: List[Tuple[Tenant, str]] = []
        for t, reason in candidates:
            if t._batchable_refit_reason() is None:
                groups.setdefault(t._chunk_signature(), []).append((t, reason))
            else:
                singles.append((t, reason))
        for sig, members in groups.items():
            if len(members) >= 2:
                self._dispatch_batched_refit(sig, members)
            else:
                singles.extend(members)
        for t, reason in singles:
            t._dispatch_refit(reason)

    def _group_members(self, sig: tuple) -> List[Tenant]:
        """Every resident tenant sharing a chunk signature, in registration
        order — the STABLE tenant axis a batched re-fit launches over
        (non-candidates ride as masked no-ops, so varying candidate subsets
        never change the program's avals)."""
        return [
            t for t in self._tenants.values()
            if t._batchable_refit_reason() is None and t._chunk_signature() == sig
        ]

    def _batched_chunk_for(
        self, sig: tuple, members: List[Tenant], cap_max: int, aot: bool = False
    ):
        """The tenant-axis chunk program for one signature group at one padded
        capacity: the PR-9 grid chunk with tenants as the dataset axis
        (G=1, D=T, E=1), per-tenant edges/fills/test sets riding the per-cell
        inputs. Cached per (signature, T, cap_max, test shape)."""
        from distributed_active_learning_tpu.runtime.loop import make_grid_device_fit
        from distributed_active_learning_tpu.runtime.sweep import (
            SweepState,
            make_grid_chunk_fn,
        )

        rep = members[0]
        t_max = max(int(t._test_x.shape[0]) for t in members)
        use_test_fill = len({int(t._test_x.shape[0]) for t in members}) > 1
        key = (sig, len(members), cap_max, t_max, use_test_fill)
        with self._lock:
            cached = self._batched_chunks.get(key)
        if cached is not None:
            return cached
        grid_fit = self._grid_fits.get(sig)
        if grid_fit is None:
            grid_fit = make_grid_device_fit(rep.cfg, rep._fit_budget, rep.n_classes)
            self._grid_fits[sig] = grid_fit
        chunk = make_grid_chunk_fn(
            [rep._strategy],
            rep.cfg.strategy.window_size,
            rep.serve.refit_rounds,
            grid_fit,
            n_datasets=len(members),
            n_seeds=1,
            use_fill=True,
            use_test_fill=use_test_fill,
            with_metrics=True,
            n_classes=rep.n_classes,
        )
        if aot:
            T = len(members)
            d = int(rep._slab.x.shape[1])
            bins = int(rep._edges.shape[1])
            keys_aval = _aval(
                jax.eval_shape(lambda: jax.random.split(jax.random.key(0), T))
            )
            grid_aval = SweepState(
                labeled_mask=_sds((T, cap_max), jnp.bool_),
                key=keys_aval,
                round=_sds((T,), jnp.int32),
            )
            chunk = chunk.lower(
                _sds((T, cap_max, d), jnp.int32),    # codes
                _sds((T, cap_max, d), jnp.float32),  # x
                _sds((T, cap_max), jnp.int32),       # oracle_y
                grid_aval,                           # donated carry
                _sds((T, cap_max), jnp.bool_),       # seed_masks
                (None,),                             # lal_forests (refused above)
                keys_aval,                           # fit_keys
                _sds((T,), jnp.int32),               # windows
                _sds((T, t_max, d), jnp.float32),    # test_x
                _sds((T, t_max), jnp.int32),         # test_y
                _sds((T,), jnp.int32),               # end_rounds
                _sds((T,), jnp.int32),               # label_caps
                _sds((T, d, bins), jnp.float32),     # edges
                _sds((T,), jnp.int32),               # n_valids
                _sds((T,), jnp.int32),               # test_ns
            ).compile()
        tracker = _ProgramTracker(
            self.metrics, f"serve_chunk_multi@{len(members)}x{cap_max}", chunk
        )
        with self._lock:
            return self._batched_chunks.setdefault(key, (chunk, tracker))

    def _dispatch_batched_refit(
        self, sig: tuple, candidates: List[Tuple[Tenant, str]]
    ) -> None:
        members = self._group_members(sig)
        # Members already mid-refit may ride as no-ops (their inputs are
        # device futures that simply queue behind their own chunk); their
        # outputs are discarded. Candidates are never inflight (guarded).
        want = {t.tenant_id: reason for t, reason in candidates}
        cap_max = max(t._slab.capacity for t in members)
        chunk, tracker = self._batched_chunk_for(sig, members, cap_max)
        T = len(members)
        t_max = max(int(t._test_x.shape[0]) for t in members)
        K = members[0].serve.refit_rounds

        def pad_rows(arr, rows):
            pad = rows - arr.shape[0]
            if pad == 0:
                return arr
            widths = ((0, pad),) + ((0, 0),) * (arr.ndim - 1)
            return jnp.pad(arr, widths)

        caps = [t._slab.capacity for t in members]
        codes = jnp.stack([pad_rows(t._slab.codes, cap_max) for t in members])
        x = jnp.stack([pad_rows(t._slab.x, cap_max) for t in members])
        oy = jnp.stack([pad_rows(t._slab.oracle_y, cap_max) for t in members])
        # Padding rows beyond a tenant's own capacity are labeled=True
        # sentinels (the grid convention: never selectable, excluded from
        # real-row counts by the per-cell n_valids watermark below).
        masks = jnp.stack([
            jnp.pad(t._slab.labeled_mask, (0, cap_max - c), constant_values=True)
            for t, c in zip(members, caps)
        ])
        seed_masks = jnp.stack([
            pad_rows(
                t._aux.seed_mask
                if t._aux.seed_mask is not None
                else jnp.zeros((c,), bool),
                cap_max,
            )
            for t, c in zip(members, caps)
        ])
        from distributed_active_learning_tpu.runtime.sweep import SweepState

        grid = SweepState(
            labeled_mask=masks,
            key=jnp.stack([t._key for t in members]),
            round=jnp.stack([jnp.asarray(t._round, jnp.int32) for t in members]),
        )
        fit_keys = jnp.stack([t._fit_key for t in members])
        windows = jnp.asarray(
            [t.cfg.strategy.window_size for t in members], jnp.int32
        )
        test_x = jnp.stack([pad_rows(t._test_x, t_max) for t in members])
        test_y = jnp.stack(
            [pad_rows(jnp.asarray(t._test_y, jnp.int32), t_max) for t in members]
        )
        # Non-candidates no-op from step one: end_round == their current
        # round, so active is False and select_state passes their carry
        # through untouched — the aval-stable tenant axis.
        end_rounds = jnp.asarray(
            [
                t._round_host + (K if t.tenant_id in want else 0)
                for t in members
            ],
            jnp.int32,
        )
        label_caps = jnp.asarray(caps, jnp.int32)
        edges = jnp.stack([t._edges for t in members])
        n_valids = jnp.stack(
            [jnp.asarray(t._slab.n_filled, jnp.int32) for t in members]
        )
        test_ns = jnp.asarray(
            [int(t._test_x.shape[0]) for t in members], jnp.int32
        )
        t0 = time.perf_counter()
        out_grid, extras, ys = chunk(
            codes, x, oy, grid, seed_masks, (None,), fit_keys, windows,
            test_x, test_y, end_rounds, label_caps, edges, n_valids, test_ns,
        )
        participants = {
            t.tenant_id: (i, want[t.tenant_id])
            for i, t in enumerate(members)
            if t.tenant_id in want
        }
        br = _BatchedRefit(
            self, members, participants, caps, out_grid, extras, ys, t0, tracker
        )
        self.batched_refit_launches += 1
        for t, reason in candidates:
            t._inflight = br
            t._record_refit_dispatch(reason)

    # -- lifecycle / shared ops ----------------------------------------------

    def poll(self, force: bool = False) -> None:
        """Non-blocking touchdown check for every tenant's in-flight re-fit
        (``force=True`` blocks — the flush/quiesce path). One poll per
        distinct launch per call: a tenant-axis batched re-fit is shared by
        its participants, and counting it once per TENANT would hit the
        forced-touchdown limit (``ServeConfig.refit_poll_events`` — pending
        score EVENTS tolerated) P times too early."""
        seen: set = set()
        for t in self._tenants.values():
            inflight = t._inflight
            if isinstance(inflight, _BatchedRefit):
                if id(inflight) in seen:
                    continue
                seen.add(id(inflight))
            t._poll_refit(force=force)

    def flush(self) -> None:
        for t in self._tenants.values():
            t.flush()

    def save_checkpoints(self) -> Dict[str, Optional[str]]:
        """Persist every tenant's serve checkpoint (tenant-axis file names);
        a restarted manager re-adding the same tenants resumes all of them
        bit-identically (round-trip pinned in tests/test_serving_multi.py)."""
        return {tid: t.save_checkpoint() for tid, t in self._tenants.items()}

    def mark_warmup_complete(self) -> None:
        """Zero the per-tenant latency-cause tables: every cause counted
        after this call is a POST-warmup event — the serve-multi bench's
        ``slab_growth_compile`` acceptance gate reads exactly this."""
        for t in self._tenants.values():
            t.cause_counts.clear()

    def recompiles_after_warmup(self) -> int:
        total = self._retired_group_recompiles
        for g in (self._score_groups or {}).values():
            total += g.tracker.recompiles
        for _, tracker in self._batched_chunks.values():
            total += tracker.recompiles
        for t in self._tenants.values():
            total += t.recompiles_after_warmup()
        return total

    def post_warmup_growth_compile_events(self) -> int:
        """serve_latency events tagged ``slab_growth_compile`` since
        :meth:`mark_warmup_complete` — the p99 spike the AOT precompile
        exists to kill; the serve-multi bench asserts 0."""
        return sum(
            t.cause_counts.get("slab_growth_compile", 0)
            for t in self._tenants.values()
        )

    def slo_summary(self) -> Optional[Dict]:
        """Aggregate + per-tenant SLO accounting, or None when no resident
        tenant has an objective configured (the summary key then stays
        absent — SLO-less deployments keep their exact key set)."""
        with_slo = [t for t in self._tenants.values() if t.slo is not None]
        if not with_slo:
            return None
        good = sum(t.slo.good for t in with_slo)
        total = sum(t.slo.total for t in with_slo)
        return {
            "good": good,
            "total": total,
            "compliance": round(good / total, 6) if total else None,
            "per_tenant": {t.tenant_id: t.slo.snapshot() for t in with_slo},
        }

    def summary(self) -> Dict:
        per_tenant = {tid: t.summary() for tid, t in self._tenants.items()}
        agg = {
            k: sum(s[k] for s in per_tenant.values())
            for k in (
                "queries", "scored_points", "ingest_blocks", "ingested_points",
                "refits", "refit_rounds", "slab_growths", "growths_precompiled",
            )
        }
        slo = self.slo_summary()
        if slo is not None:
            agg["slo"] = slo
        return {
            "tenants": len(self._tenants),
            **agg,
            "batched_score_launches": self.batched_score_launches,
            "batched_refit_launches": self.batched_refit_launches,
            "score_fallback_reasons": dict(self.score_fallback_reasons),
            "score_groups": self.score_groups(),
            "precompiles": self.precompiles,
            "precompile_errors": self.precompile_errors,
            "post_warmup_growth_compile_events":
                self.post_warmup_growth_compile_events(),
            "recompiles_after_warmup": self.recompiles_after_warmup(),
            "per_tenant": per_tenant,
        }

    # -- AOT precompile worker -------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._stop.clear()
                self._worker = threading.Thread(
                    target=self._worker_loop,
                    name="serve-precompile",
                    daemon=True,
                )
                self._worker.start()

    def schedule_precompile(self, tenant: Tenant, capacity: int) -> bool:
        """Queue AOT builds of ``tenant``'s next-capacity programs (and the
        tenant-axis chunk at the group's resulting max capacity). Dedups
        against pending jobs and already-resident programs; returns whether
        anything new was queued."""
        queued = False
        with tenant._programs_lock:
            have = capacity in tenant._programs
        key = ("capacity", tenant.tenant_id, capacity)
        with self._lock:
            if not have and key not in self._pending:
                job = _PrecompileJob("capacity", tenant, capacity)
                self._pending[key] = job
                self._queue.put(job)
                queued = True
        if tenant._batchable_refit_reason() is None:
            sig = tenant._chunk_signature()
            members = self._group_members(sig)
            if len(members) >= 2:
                cap_max = max(
                    [capacity] + [t._slab.capacity for t in members]
                )
                t_max = max(int(t._test_x.shape[0]) for t in members)
                use_tf = len({int(t._test_x.shape[0]) for t in members}) > 1
                ck = (sig, len(members), cap_max, t_max, use_tf)
                with self._lock:
                    if (
                        ck not in self._batched_chunks
                        and ("batched", ck) not in self._pending
                    ):
                        job = _PrecompileJob(
                            "batched_chunk", tenant, cap_max, group_key=ck
                        )
                        self._pending[("batched", ck)] = job
                        self._queue.put(job)
                        queued = True
        if queued:
            self._ensure_worker()
        return queued

    def wait_precompile(
        self, tenant: Tenant, capacity: int, timeout: Optional[float] = None
    ) -> bool:
        """Block until a pending precompile of ``tenant``'s ``capacity``
        lands (True) — the growth path uses this instead of racing a second
        compile of the same programs; False when no such job is pending."""
        with self._lock:
            job = self._pending.get(("capacity", tenant.tenant_id, capacity))
        if job is None:
            return False
        job.done.wait(timeout)
        return job.ok

    def wait_precompiles(self, timeout: Optional[float] = None) -> bool:
        """Test/bench barrier: wait for every queued precompile to land."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                jobs = list(self._pending.values())
            if not jobs:
                return True
            for job in jobs:
                remaining = (
                    None if deadline is None else max(deadline - time.monotonic(), 0)
                )
                if not job.done.wait(remaining):
                    return False

    def _worker_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None or self._stop.is_set():
                # release anything still waiting on abandoned jobs
                with self._lock:
                    pending = list(self._pending.values())
                    self._pending.clear()
                for p in pending:
                    p.done.set()
                if job is not None:
                    job.done.set()
                return
            t0 = time.perf_counter()
            try:
                if job.kind == "capacity":
                    progs = job.tenant._build_programs(job.capacity, aot=True)
                    job.ok = job.tenant._install_programs(job.capacity, progs)
                else:
                    sig, T, cap_max, _t_max, _use_tf = job.group_key
                    members = self._group_members(sig)
                    if len(members) == T:
                        self._batched_chunk_for(sig, members, cap_max, aot=True)
                        job.ok = True
                self.precompiles += 1
                obs.counter(
                    "precompiles", "background AOT capacity precompiles"
                ).inc()
                seconds = round(time.perf_counter() - t0, 3)
                telemetry.flight_record(
                    "precompile", target=job.kind,
                    tenant=job.tenant.tenant_id, capacity=job.capacity,
                    seconds=seconds, installed=job.ok,
                )
                if self.metrics is not None:
                    self.metrics.event(
                        "precompile", target=job.kind,
                        tenant=job.tenant.tenant_id, capacity=job.capacity,
                        seconds=seconds, installed=job.ok,
                    )
            except Exception as e:  # noqa: BLE001 — a failed AOT build must
                # never kill the worker: the lazy request path still compiles,
                # the failure is just a (named) lost optimization.
                self.precompile_errors += 1
                obs.counter(
                    "precompile_errors", "failed background AOT builds"
                ).inc()
                telemetry.flight_record(
                    "precompile_error", target=job.kind,
                    tenant=job.tenant.tenant_id, capacity=job.capacity,
                    error=repr(e)[:200],
                )
                if self.metrics is not None:
                    self.metrics.event(
                        "precompile_error", target=job.kind,
                        tenant=job.tenant.tenant_id, capacity=job.capacity,
                        error=repr(e)[:200],
                    )
            finally:
                with self._lock:
                    # a snapshot for safe in-loop deletion, not a jit key
                    for k, v in list(self._pending.items()):  # audit: ok[DAL104]
                        if v is job:
                            del self._pending[k]
                job.done.set()
                self._queue.task_done()

    def close(self) -> None:
        """Stop the precompile worker (idempotent). Called by atexit for
        every live manager — a worker aborted MID-compile at interpreter
        teardown takes the whole process down, so shutdown waits out the
        in-flight build (bounded) instead."""
        with self._lock:
            worker = self._worker
            self._worker = None
        self._stop.set()
        if worker is not None and worker.is_alive():
            self._queue.put(None)
            worker.join(timeout=30)
