"""Thread-safe front queue for the multi-tenant service: admission,
fairness, backpressure.

The PR-7 event loop was single-threaded BY DESIGN — one caller interleaving
score/submit on one thread. Production clients are concurrent, so something
must make them actually contend: this module is that something, and it keeps
the one-device-thread discipline the whole serving stack assumes by funneling
every device-touching operation through ONE dispatcher thread.

- **Clients enqueue, the dispatcher executes.** ``submit_score`` /
  ``submit_ingest`` append to a per-tenant FIFO and return a
  ``concurrent.futures.Future``; blocking (``score``) and asyncio
  (``ascore``) wrappers ride the same futures. Request payloads never touch
  the device on the client thread.

- **Admission control.** A tenant whose queue already holds ``max_pending``
  requests (ServeConfig.max_pending) has new submissions refused with
  :class:`AdmissionError` — bounded memory, and the backpressure signal a
  client can act on.

- **Per-tenant fairness, weighted by SLO class.** Each dispatch cycle
  drains AT MOST one score request per tenant, rotating the starting tenant
  round-robin — a noisy tenant cannot occupy more than its slot in any
  fused launch while others wait. On top of the rotation, deficit weighted
  round-robin (``ServeConfig.slo_weight``): a tenant accrues ``weight``
  credits per contended cycle and a score slot costs 1, so weight 1.0 (the
  default) is served every cycle — exactly the pre-SLO fair rotation —
  while weight 0.5 is served every OTHER cycle its queue is nonempty. The
  collected slots coalesce into ONE cross-tenant batched launch
  (:meth:`~serving.tenants.TenantManager.score_many`).

- **Priority admission.** ``ServeConfig.slo_priority`` scales the admission
  cap: a priority-``p`` tenant tolerates ``max_pending * (1 + p)`` queued
  requests before :class:`AdmissionError`, so under global load the lower
  classes shed first and the gold class keeps enqueueing.

- **Burn-rate-driven admission.** The first consumer that ACTS on the
  PR-15 burn gauges: a tenant whose 5-minute SLO burn rate
  (:meth:`~runtime.obs.SLOTracker.burn_rate`) reaches 1.0 has its effective
  WRR weight scaled by ``1 / (1 + burn)`` (dispatch deprioritization,
  always on), and once the burn crosses ``ServeConfig.burn_shed_threshold``
  (> 0 to enable) new SCORE submissions are refused with
  :class:`AdmissionError` before they queue — the SLO is already lost for
  the window, so shedding early keeps healthy tenants from waiting behind a
  doomed queue. Ingest is never burn-shed: fresh data is how a burning
  tenant recovers.

- **Re-fit backpressure.** While a tenant's re-fit chunk is in flight its
  INGEST requests are held (the slab arrays are donation-bound to the
  running chunk's output futures; piling more device writes behind a
  long chunk just hides queueing in the device stream) — held requests stay
  queued, the queue fills, and admission pushes back on the producer.
  Scoring is deliberately NOT held: the resident forest stays hot through a
  re-fit (that asymmetry is the service's core latency guarantee), so score
  requests may overtake held ingests of the same tenant.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Deque, Dict, Optional

import numpy as np

from distributed_active_learning_tpu.runtime import obs, telemetry
from distributed_active_learning_tpu.serving.tenants import TenantManager

#: /healthz staleness bound for the dispatcher-loop heartbeat: the loop
#: beats at least every 0.1s when idle, but a fused launch (or a first-time
#: XLA compile a cold tenant sneaks onto the dispatch path) can hold it for
#: seconds — the bound must catch a DEAD loop, not a busy one.
_LOOP_HEARTBEAT_MAX_AGE = 60.0


class AdmissionError(RuntimeError):
    """A tenant's front queue is full — the caller-visible backpressure
    signal (retry later, shed load, or slow the producer)."""


@dataclasses.dataclass
class _Request:
    kind: str            # "score" | "ingest"
    tenant: str
    x: np.ndarray
    y: Optional[np.ndarray]
    future: Future
    enqueued: float


class ServiceFrontend:
    """The concurrent front of a :class:`~serving.tenants.TenantManager`.

    Use as a context manager (or ``start()``/``stop()``); clients then call
    ``score``/``submit_score``/``submit_ingest``/``ascore`` from any thread
    or event loop. One dispatcher thread owns all device work.
    """

    def __init__(
        self,
        manager: TenantManager,
        max_pending: Optional[int] = None,
        idle_poll_seconds: float = 0.002,
    ):
        self.manager = manager
        self._max_pending = max_pending
        self._idle_poll = idle_poll_seconds
        self._queues: Dict[str, Deque[_Request]] = {}
        self._cond = threading.Condition()
        self._rr = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.dispatch_cycles = 0
        self.fused_launch_cycles = 0
        self.held_ingest_cycles = 0
        self.rejected: Dict[str, int] = {}
        # SLO accounting (deficit weighted round-robin; see _credit_ok):
        # score slots granted / deferred per tenant, and the running credit.
        self._credits: Dict[str, float] = {}
        self.slo_served: Dict[str, int] = {}
        self.slo_deferred: Dict[str, int] = {}
        # Burn-rate-driven admission/dispatch (the first consumer that ACTS
        # on the PR-15 burn gauges): score submissions shed at admission
        # while the 5m burn says the SLO is already lost, and dispatch
        # cycles where the deficit WRR deprioritized a burning tenant.
        self.burn_shed: Dict[str, int] = {}
        self.burn_deprioritized: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServiceFrontend":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="serve-frontend", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop the dispatcher; ``drain=True`` first serves everything still
        queued (a held ingest drains once its tenant's re-fit touches down)."""
        if drain:
            deadline = None if timeout is None else time.monotonic() + timeout
            while self.pending() and (
                deadline is None or time.monotonic() < deadline
            ):
                time.sleep(self._idle_poll)
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # A cleanly-stopped dispatcher must not read as a liveness failure
        # on a scrape that arrives after shutdown.
        obs.registry().clear_heartbeat("frontend_loop")

    def __enter__(self) -> "ServiceFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface ------------------------------------------------------

    def _cap_for(self, tenant: str) -> int:
        serve = self.manager.tenant(tenant).serve
        base = (
            self._max_pending
            if self._max_pending is not None
            else serve.max_pending
        )
        # Priority admission: higher SLO classes tolerate deeper queues, so
        # under shared load the lower classes hit AdmissionError first.
        prio = max(int(getattr(serve, "slo_priority", 0)), 0)
        return base * (1 + prio)

    def _burn5(self, tenant: str) -> Optional[float]:
        """The tenant's 5-minute SLO burn rate, or None when the tracker has
        no observations in the window (a fresh or idle tenant is NOT
        burning)."""
        slo = getattr(self.manager.tenant(tenant), "slo", None)
        if slo is None:
            return None
        return slo.burn_rate(300.0)

    def _credit_ok(self, tenant: str) -> bool:
        """Deficit weighted round-robin: accrue ``slo_weight`` credits per
        contended cycle, spend 1 per score slot. Called at most once per
        tenant per dispatch cycle (and only when a score is actually
        queued), so the accrual rate IS the cycle rate. Weight >= 1 is
        always served (the pre-SLO behavior for the default 1.0); weight w
        in (0, 1) is served a w fraction of its contended cycles.

        Burn deprioritization: once the 5m burn rate reaches 1.0 (the error
        budget is being spent faster than sustainable), the tenant's
        effective weight is scaled by ``1 / (1 + burn)`` — a tenant burning
        at 2x accrues a third of its configured credits, so healthy tenants'
        slots stop queueing behind one that is already missing its SLO. The
        scale is continuous in the burn rate (no cliff at the threshold) and
        recovers automatically as good observations re-enter the window."""
        serve = self.manager.tenant(tenant).serve
        w = max(float(getattr(serve, "slo_weight", 1.0)), 0.0)
        burn = self._burn5(tenant)
        if burn is not None and burn >= 1.0:
            w = w / (1.0 + min(burn, 100.0))
            self.burn_deprioritized[tenant] = (
                self.burn_deprioritized.get(tenant, 0) + 1
            )
        c = min(self._credits.get(tenant, 0.0) + w, max(1.0, w))
        if c >= 1.0:
            self._credits[tenant] = c - 1.0
            self.slo_served[tenant] = self.slo_served.get(tenant, 0) + 1
            return True
        self._credits[tenant] = c
        self.slo_deferred[tenant] = self.slo_deferred.get(tenant, 0) + 1
        return False

    def _enqueue(self, req: _Request) -> Future:
        cap = self._cap_for(req.tenant)
        serve = self.manager.tenant(req.tenant).serve
        shed_at = float(getattr(serve, "burn_shed_threshold", 0.0))
        if req.kind == "score" and shed_at > 0.0:
            # Burn shedding: past the configured 5m burn rate the SLO is
            # already lost for this window — refusing new SCORE work early
            # keeps the doomed tenant's queue from delaying healthy ones.
            # Ingest is never shed: fresh data is how a burning tenant
            # recovers.
            burn = self._burn5(req.tenant)
            if burn is not None and burn >= shed_at:
                self.burn_shed[req.tenant] = (
                    self.burn_shed.get(req.tenant, 0) + 1
                )
                obs.counter(
                    "admission_burn_sheds",
                    "score submissions shed because the 5m SLO burn rate "
                    "crossed burn_shed_threshold",
                    tenant=req.tenant,
                ).inc()
                raise AdmissionError(
                    f"tenant {req.tenant!r} shed at admission: 5m burn rate "
                    f"{burn:.2f} >= burn_shed_threshold {shed_at:.2f}; the "
                    f"SLO budget is exhausted — retry after the window cools"
                )
        with self._cond:
            if not self._running:
                raise RuntimeError("frontend is not running (call start())")
            q = self._queues.setdefault(req.tenant, collections.deque())
            if len(q) >= cap:
                self.rejected[req.tenant] = self.rejected.get(req.tenant, 0) + 1
                obs.counter(
                    "admission_rejects", "requests refused by admission control",
                    tenant=req.tenant,
                ).inc()
                raise AdmissionError(
                    f"tenant {req.tenant!r} has {len(q)} pending requests "
                    f"(max_pending={cap}); backpressure — retry later"
                )
            q.append(req)
            obs.gauge(
                "frontend_queue_depth", "queued requests per tenant",
                tenant=req.tenant,
            ).set(len(q))
            self._cond.notify()
        return req.future

    def submit_score(self, tenant: str, queries) -> Future:
        """Enqueue a score request; the Future resolves to the scores array."""
        self.manager.tenant(tenant)  # KeyError now, not on the dispatcher
        q = np.asarray(queries, np.float32)
        return self._enqueue(
            _Request("score", tenant, q, None, Future(), time.perf_counter())
        )

    def submit_ingest(self, tenant: str, x, y) -> Future:
        """Enqueue an ingest block; the Future resolves to an ack dict."""
        self.manager.tenant(tenant)
        return self._enqueue(
            _Request(
                "ingest", tenant,
                np.asarray(x, np.float32), np.asarray(y, np.int32),
                Future(), time.perf_counter(),
            )
        )

    def score(self, tenant: str, queries, timeout: Optional[float] = None):
        """Blocking convenience wrapper: enqueue + wait."""
        return self.submit_score(tenant, queries).result(timeout)

    async def ascore(self, tenant: str, queries):
        """asyncio client surface over the same queue/futures."""
        return await asyncio.wrap_future(self.submit_score(tenant, queries))

    async def asubmit(self, tenant: str, x, y):
        return await asyncio.wrap_future(self.submit_ingest(tenant, x, y))

    def pending(self, tenant: Optional[str] = None) -> int:
        with self._cond:
            if tenant is not None:
                return len(self._queues.get(tenant, ()))
            return sum(len(q) for q in self._queues.values())

    # -- the dispatcher ------------------------------------------------------

    def _collect(self):
        """One fairness cycle under the lock: at most one score request per
        tenant (rotating start), ingest heads for tenants whose re-fit is
        NOT in flight. Returns (scores, ingests, held_any)."""
        scores: Dict[str, _Request] = {}
        ingests = []
        held = False
        tids = list(self._queues)
        n = len(tids)
        for k in range(n):
            tid = tids[(self._rr + k) % n]
            q = self._queues[tid]
            if not q:
                continue
            head = q[0]
            if head.kind == "ingest":
                if self.manager.tenant(tid).refit_inflight:
                    # backpressure: hold the ingest, but let a queued score
                    # overtake it — the resident forest stays hot
                    held = True
                    if any(r.kind == "score" for r in q) and self._credit_ok(tid):
                        for i, req in enumerate(q):
                            if req.kind == "score":
                                del q[i]
                                scores[tid] = req
                                break
                    continue
                ingests.append(q.popleft())
                # an ingest and a score from one tenant may share a cycle
                if q and q[0].kind == "score" and self._credit_ok(tid):
                    scores[tid] = q.popleft()
            elif self._credit_ok(tid):
                scores[tid] = q.popleft()
        for tid in tids:
            obs.gauge(
                "frontend_queue_depth", "queued requests per tenant",
                tenant=tid,
            ).set(len(self._queues[tid]))
        if n:
            self._rr = (self._rr + 1) % n
        return scores, ingests, held

    def _dispatch_loop(self) -> None:
        while True:
            # /healthz liveness: one beat per loop pass. The registered
            # staleness bound means a wedged dispatcher (deadlock, dead
            # thread) flips the health endpoint to 503 within a minute —
            # the "event-loop liveness" half of the ops plane.
            obs.heartbeat("frontend_loop", max_age_seconds=_LOOP_HEARTBEAT_MAX_AGE)
            with self._cond:
                while self._running and not any(self._queues.values()):
                    self._cond.wait(timeout=0.1)
                    obs.heartbeat("frontend_loop")
                if not self._running:
                    return
                scores, ingests, held = self._collect()
            self.dispatch_cycles += 1
            # A client may have cancelled a still-queued Future (asyncio
            # timeouts do); claiming it via set_running_or_notify_cancel
            # drops cancelled requests AND makes the set_result/set_exception
            # below safe — an unguarded InvalidStateError here would kill the
            # one thread serving everybody.
            ingests = [r for r in ingests if r.future.set_running_or_notify_cancel()]
            scores = {
                tid: r for tid, r in scores.items()
                if r.future.set_running_or_notify_cancel()
            }
            for req in ingests:
                try:
                    self.manager.submit(req.tenant, req.x, req.y)
                    req.future.set_result(
                        {"tenant": req.tenant, "points": int(req.x.shape[0])}
                    )
                except Exception as e:  # noqa: BLE001 — the error belongs to
                    # the submitting client, not the shared dispatcher
                    req.future.set_exception(e)
            if scores:
                self.fused_launch_cycles += 1
                try:
                    results = self.manager.score_many(
                        {tid: req.x for tid, req in scores.items()}
                    )
                    for tid, req in scores.items():
                        req.future.set_result(results[tid])
                except Exception as e:  # noqa: BLE001
                    # availability accounting happens INSIDE score_many
                    # (completion-aware: only tenants whose blocks did not
                    # finish are charged — see tenants.py); here the error
                    # just routes to the waiting callers
                    for req in scores.values():
                        if not req.future.done():
                            req.future.set_exception(e)
                    telemetry.flight_record(
                        "frontend_error", error=repr(e)[:200],
                        tenants=sorted(scores),
                    )
            if held:
                self.held_ingest_cycles += 1
            if not scores and not ingests:
                # everything queued is held behind in-flight re-fits: poll
                # for their touchdowns so held ingests eventually release
                self.manager.poll()
                time.sleep(self._idle_poll)
