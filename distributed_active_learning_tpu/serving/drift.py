"""Drift-aware re-fit triggers for the streaming AL service.

A batch AL loop re-fits on a fixed cadence because nothing else changes; a
service should re-fit when the WORLD changes — the incoming traffic no longer
looks like what the resident forest was trained on, or the last labeling
round's selection boundary collapsed. Both signals already exist in the
codebase, free:

- **Serve-time entropy** — the scoring endpoint returns per-query predictive
  entropy alongside the scores (serving/slab.py ``make_score_fn``); an EMA of
  the served batches is the live view of the traffic.
- **Chunk RoundMetrics** — every fused re-fit chunk ships per-round
  device-computed metrics in its scan ys (runtime/telemetry.py); the final
  round's pool entropy is the baseline the live EMA drifts against, and the
  chunk-mean selection margin shifting between consecutive chunks flags a
  crowding/thinning boundary.

:class:`DriftMonitor` folds these into one host-side decision,
``should_refit``, with a staleness backstop so a quiet-but-drifting stream
still re-fits eventually. It is pure host arithmetic — no device work, no
syncs — and deterministic (pinned unit tests in tests/test_serving.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional

_EPS = 1e-6


class DriftMonitor:
    """Entropy/margin drift thresholds deciding when a re-fit chunk launch
    is worth dispatching (instead of a fixed round cadence).

    ``entropy_shift``/``margin_shift`` are RELATIVE thresholds: a trigger
    fires when the live signal departs its baseline by more than that
    fraction. Entropy and margin triggers additionally require at least
    ``min_fresh`` points ingested since the last re-fit — drift with nothing
    new to label is not actionable by a labeling round. ``max_staleness``
    (serve observations since the last re-fit; 0 disables) is the backstop
    cadence of last resort.
    """

    def __init__(
        self,
        entropy_shift: float = 0.25,
        margin_shift: float = 0.5,
        min_fresh: int = 32,
        max_staleness: int = 512,
        ema: float = 0.2,
    ):
        self.entropy_shift = entropy_shift
        self.margin_shift = margin_shift
        self.min_fresh = min_fresh
        self.max_staleness = max_staleness
        self.ema = ema
        self.baseline_entropy: Optional[float] = None
        self.baseline_margin: Optional[float] = None
        self.serve_entropy: Optional[float] = None
        self.fresh_points = 0
        self.serves_since_refit = 0
        self._margin_shifted = False

    # -- observations --------------------------------------------------------

    def observe_serve(self, mean_entropy: float) -> None:
        """One served batch's mean predictive entropy (EMA-folded)."""
        self.serves_since_refit += 1
        if self.serve_entropy is None:
            self.serve_entropy = float(mean_entropy)
        else:
            self.serve_entropy += self.ema * (
                float(mean_entropy) - self.serve_entropy
            )

    def observe_ingest(self, n_points: int) -> None:
        self.fresh_points += int(n_points)

    def observe_chunk(self, round_metrics: List[Dict]) -> None:
        """Fold one re-fit chunk's in-scan RoundMetrics stream.

        The final active round's ``pool_entropy`` becomes the new entropy
        baseline (and re-seeds the live EMA — the forest just re-fit, so the
        served traffic SHOULD look like the pool again); the chunk-mean
        ``score_margin`` is compared against the previous chunk's to detect a
        boundary shift, then replaces it.
        """
        self.fresh_points = 0
        self.serves_since_refit = 0
        self._margin_shifted = False
        ents = [m["pool_entropy"] for m in round_metrics if m.get("pool_entropy") is not None]
        margins = [m["score_margin"] for m in round_metrics if m.get("score_margin") is not None]
        if ents:
            self.baseline_entropy = float(ents[-1])
            self.serve_entropy = float(ents[-1])
        if margins:
            mean_margin = float(sum(margins) / len(margins))
            if self.baseline_margin is not None:
                denom = max(abs(self.baseline_margin), _EPS)
                if abs(mean_margin - self.baseline_margin) / denom > self.margin_shift:
                    self._margin_shifted = True
            self.baseline_margin = mean_margin

    # -- the decision --------------------------------------------------------

    def entropy_drift(self) -> Optional[float]:
        """Relative departure of the live serve-entropy EMA from the last
        chunk's pool-entropy baseline (None until both exist)."""
        if self.baseline_entropy is None or self.serve_entropy is None:
            return None
        denom = max(abs(self.baseline_entropy), _EPS)
        return abs(self.serve_entropy - self.baseline_entropy) / denom

    def should_refit(self) -> Optional[str]:
        """The re-fit decision: a reason string, or None to keep serving.

        Checked cheapest-signal-first; the reason rides the service's
        ``refit`` JSONL event so a metrics stream explains every chunk
        launch.
        """
        if self.fresh_points >= self.min_fresh:
            drift = self.entropy_drift()
            if drift is not None and drift > self.entropy_shift:
                return "entropy_shift"
            if self._margin_shifted:
                return "margin_shift"
        if self.max_staleness and self.serves_since_refit >= self.max_staleness:
            return "staleness"
        return None
