"""Streaming AL service: ingest-drain + resident scoring + drift-gated re-fit.

The batch drivers (runtime/loop.py, runtime/pipeline.py) already separate
*dispatch* from *touchdown*: a fused chunk launches, the host keeps working,
and the bookkeeping runs when the chunk's two stop scalars arrive. This
module generalizes that discipline from "a fixed sequence of chunks" into a
long-running event loop interleaving three work sources:

- **Ingest.** Arrivals buffer host-side and drain into the slab-paged pool
  (serving/slab.py) in fixed-width donation writes — the watermark advances,
  no program recompiles, capacity grows slab-at-a-time when headroom runs
  out.

- **Scoring.** ``score(points)`` serves from the RESIDENT fitted forest
  through a fixed-width jitted program — the low-latency path. It never
  touches the pool, so it stays hot while a re-fit chunk is in flight: the
  old forest answers queries until the new one lands.

- **Re-fit.** A drift monitor (serving/drift.py) watches the serve-time
  entropy stream against the last chunk's in-scan RoundMetrics baseline and
  dispatches a fused AL chunk (the SAME ``make_chunk_fn`` program the batch
  driver runs, with the watermark riding as the dynamic ``n_filled`` leaf)
  when the traffic drifts — not on a fixed cadence. The chunk's touchdown is
  polled non-blockingly (``jax.Array.is_ready``) so scoring latency never
  eats a chunk's device time.

Donation choreography (the part that must not be improvised): the chunk
donates its carried state, so the instant a re-fit dispatches, the slab
rebinds to the chunk's OUTPUT arrays — ingest launched while the chunk is in
flight consumes those futures and simply queues behind it on device. The
binned ``codes`` ride outside the donated carry, so they survive the chunk
and only ingest ever rewrites them.

Single-process by design: multihost serving is the pod-sharding ROADMAP item;
this module is the continuous-operation substrate it will serve through.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.config import ExperimentConfig, ServeConfig
from distributed_active_learning_tpu.runtime import state as state_lib
from distributed_active_learning_tpu.runtime import telemetry
from distributed_active_learning_tpu.serving import drift as drift_lib
from distributed_active_learning_tpu.serving import slab as slab_lib


class _ProgramTracker:
    """Per-program-instance launch accounting with a recompile COUNT.

    Like :class:`~runtime.telemetry.LaunchTracker` (and it emits the same
    ``launch`` JSONL events through the writer), but the recompile detection
    runs with or without a writer and accumulates — the service's headline
    ``recompiles_after_warmup`` is the sum over every program instance, and a
    bench must be able to assert it at zero without a metrics file.
    """

    def __init__(self, writer, program: str, fn):
        self.writer = writer
        self.program = program
        self.fn = fn
        self.calls = 0
        self.recompiles = 0
        self._last_cache = None

    def record(self, seconds: float, **extra) -> None:
        self.calls += 1
        cache = telemetry.jit_cache_size(self.fn)
        recompiled = (
            self.calls > 1
            and cache is not None
            and self._last_cache is not None
            and cache > self._last_cache
        )
        if recompiled:
            self.recompiles += 1
            # A silent recompile is exactly the event a dead run's post-
            # mortem needs; the score path's per-query launches stay out of
            # the ring (they'd flush everything else) — recompiles don't.
            telemetry.flight_record(
                "recompile", program=self.program, call=self.calls,
                cache_size=cache,
            )
        self._last_cache = cache
        if self.writer is not None:
            self.writer.launch(
                self.program, seconds,
                first_call=self.calls == 1,
                cache_size=cache,
                recompiled=recompiled,
                **extra,
            )


@dataclasses.dataclass
class _CapacityPrograms:
    """The jitted programs specialized on one slab capacity."""

    ingest: object
    chunk: object
    fit: object
    ingest_tracker: _ProgramTracker
    chunk_tracker: _ProgramTracker
    fit_tracker: _ProgramTracker


@dataclasses.dataclass
class ServeStats:
    """Host-side service counters (all plain ints — no device reads)."""

    queries: int = 0
    scored_points: int = 0
    ingest_blocks: int = 0
    ingested_points: int = 0
    refits: int = 0
    refit_rounds: int = 0
    refits_skipped_fit_budget: int = 0
    slab_growths: int = 0


class ALService:
    """The long-running service driver.

    ``cfg`` supplies the model/strategy/seeding half (the same
    :class:`ExperimentConfig` the batch drivers take — ``forest.fit`` must be
    ``"device"``; the whole point is a resident device loop); ``serve``
    supplies the streaming knobs. ``train_x/train_y`` seed the pool (the
    service's cold-start corpus), ``test_x/test_y`` feed the chunk's accuracy
    eval exactly as in the batch loop.
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        serve: ServeConfig,
        train_x,
        train_y,
        test_x,
        test_y,
        metrics=None,
        checkpoint_dir: Optional[str] = None,
    ):
        from distributed_active_learning_tpu.ops import trees_train
        from distributed_active_learning_tpu.runtime.loop import build_aux
        from distributed_active_learning_tpu.runtime.results import ExperimentResult
        from distributed_active_learning_tpu.strategies import get_strategy

        if cfg.forest.fit != "device":
            raise ValueError(
                "the streaming service needs ForestConfig.fit='device' — a "
                "host sklearn fit cannot live inside the resident loop"
            )
        self.cfg = cfg
        self.serve = serve
        self.metrics = metrics
        self.checkpoint_dir = checkpoint_dir
        self.stats = ServeStats()
        self.refit_reasons: Dict[str, int] = {}
        self.result = ExperimentResult()

        host_y = np.asarray(train_y, np.int32)
        self.n_classes = max(int(host_y.max()) + 1, 2) if host_y.size else 2
        self._strategy = get_strategy(cfg.strategy)

        state0 = state_lib.init_pool_state(train_x, train_y, jax.random.key(cfg.seed))
        state0 = state_lib.set_start_state(state0, cfg.n_start, n_classes=self.n_classes)
        binned = trees_train.make_bins(jnp.asarray(state0.x), cfg.forest.max_bins)
        self._edges = binned.edges
        self._slab = slab_lib.init_slab_pool(
            state0.x, state0.oracle_y, state0.labeled_mask,
            self._edges, serve.slab_rows,
        )
        self._key = state0.key
        self._round = state0.round
        self._round_host = 0
        self._fill = int(state0.x.shape[0])
        self._labeled = int(state_lib.labeled_count(state0))
        aux = build_aux(cfg, state0)
        # The seed mask must track the SLAB arrays' capacity (strategies that
        # consume it — density's non-seed mass, random's seed exclusion — dot
        # it against capacity-sized pool vectors), and padding it here also
        # makes it a fresh buffer the chunk's carry donation cannot alias
        # (the same copy the batch driver does). Re-padded on every growth.
        if aux.seed_mask is not None:
            aux = aux.replace(seed_mask=self._pad_seed_mask(aux.seed_mask))
        self._aux = aux
        self._fit_key = jax.random.key(cfg.seed + 0x5EED)
        self._test_x = jnp.asarray(test_x)
        self._test_y = jnp.asarray(test_y)

        # Labeled-window capacity of the device fit, FIXED across capacities
        # so a grown pool reuses the same gather/fit shapes. Labels grow
        # without bound in a service; the dispatch guard below refuses a
        # chunk that could outgrow the window instead of silently truncating.
        self._fit_budget = (
            min(cfg.forest.fit_budget, self._slab.capacity)
            if cfg.forest.fit_budget is not None
            else serve.slab_rows
        )
        self._fit_budget_exhausted = False

        self.drift = drift_lib.DriftMonitor(
            entropy_shift=serve.drift_entropy_shift,
            margin_shift=serve.drift_margin_shift,
            min_fresh=serve.drift_min_fresh,
            max_staleness=serve.max_staleness,
        )

        self._programs: Dict[int, _CapacityPrograms] = {}
        self._score_fn = slab_lib.make_score_fn()
        self._score_tracker = _ProgramTracker(metrics, "serve_score", self._score_fn)
        self._ingest_buf_x: list = []
        self._ingest_buf_y: list = []
        self._inflight = None
        self._inflight_polls = 0
        # Concurrent-cause tags for the NEXT serve_latency event: slab
        # growths and refit dispatches queue device work (and one-off
        # compiles) that the following score query pays for as a latency
        # spike — tagging the query with what ran beside it makes the serve
        # bench's p99 attributable (summarize_metrics groups by cause).
        self._latency_causes: set = set()

        if metrics is not None:
            from distributed_active_learning_tpu.config import asdict as cfg_asdict

            metrics.meta(
                config=cfg_asdict(cfg),
                serve=cfg_asdict(serve),
                backend=jax.default_backend(),
                loop="serve",
            )

        restored = False
        if checkpoint_dir:
            restored = self._try_restore(checkpoint_dir)
        if not restored:
            self._refresh_forest()

    def _pad_seed_mask(self, mask) -> jnp.ndarray:
        """Seed mask padded (False) to the current slab capacity — slab rows
        past the cold-start pool were never seeded."""
        pad = self._slab.capacity - mask.shape[0]
        return jnp.pad(jnp.asarray(mask, bool), (0, pad))

    # -- program cache -------------------------------------------------------

    def _programs_for(self, capacity: int) -> _CapacityPrograms:
        progs = self._programs.get(capacity)
        if progs is not None:
            return progs
        from distributed_active_learning_tpu.runtime.loop import (
            make_chunk_fn,
            make_device_fit,
        )

        fit = make_device_fit(self.cfg, self._edges, self._fit_budget, self.n_classes)
        chunk = make_chunk_fn(
            self._strategy,
            self.cfg.strategy.window_size,
            self.serve.refit_rounds,
            fit,
            label_cap=capacity,
            with_metrics=True,
            n_classes=self.n_classes,
        )
        ingest = slab_lib.make_ingest_fn()
        m = self.metrics
        progs = _CapacityPrograms(
            ingest=ingest,
            chunk=chunk,
            fit=fit,
            ingest_tracker=_ProgramTracker(m, f"serve_ingest@{capacity}", ingest),
            chunk_tracker=_ProgramTracker(m, f"serve_chunk@{capacity}", chunk),
            fit_tracker=_ProgramTracker(m, f"serve_fit@{capacity}", fit),
        )
        self._programs[capacity] = progs
        return progs

    # -- the three work sources ---------------------------------------------

    def score(self, queries) -> np.ndarray:
        """Score query points against the resident forest (the endpoint).

        Blocks only on ITS OWN batch's result — an in-flight re-fit chunk is
        polled non-blockingly, so p99 scoring latency stays decoupled from
        chunk wall time. Batches wider than the static ``score_width`` are
        served in width-sized sub-batches.
        """
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if q.shape[0] == 0:
            return np.zeros((0,), np.float32)
        width = self.serve.score_width
        out = []
        for lo in range(0, q.shape[0], width):
            out.append(self._score_block(q[lo : lo + width]))
        return np.concatenate(out) if len(out) > 1 else out[0]

    def _score_block(self, q: np.ndarray) -> np.ndarray:
        self._poll_refit()
        n = q.shape[0]
        pad = self.serve.score_width - n
        qpad = np.pad(q, ((0, pad), (0, 0))) if pad else q
        t0 = time.perf_counter()
        scores, ent = self._score_fn(self._forest, jnp.asarray(qpad))
        scores_np = np.asarray(scores)[:n]  # the one blocking fetch = latency
        dt = time.perf_counter() - t0
        self._score_tracker.record(dt, batch=n)
        self.drift.observe_serve(float(np.mean(np.asarray(ent)[:n])))
        self.stats.queries += 1
        self.stats.scored_points += n
        # The concurrent cause this query's latency is attributable to:
        # a slab growth's one-per-new-capacity compile outranks an ordinary
        # refit dispatch (both can be pending; the compile is the spike).
        if "slab_growth_compile" in self._latency_causes:
            cause = "slab_growth_compile"
        elif "refit_dispatch" in self._latency_causes or self._inflight is not None:
            cause = "refit_dispatch"
        else:
            cause = "none"
        self._latency_causes.clear()
        if self.metrics is not None:
            self.metrics.event(
                "serve_latency", seconds=round(dt, 6), batch=n,
                inflight_refit=self._inflight is not None,
                cause=cause,
            )
        self._maybe_refit()
        return scores_np

    def submit(self, x, y) -> None:
        """Queue arriving points (with their eventual oracle labels — the
        simulation convention the whole repo uses: labels exist but are
        hidden until an AL round reveals them)."""
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            x = x[None, :]
        y = np.asarray(y, np.int32).reshape(-1)
        # The class count is frozen at cold start (it sizes the fit's static
        # shapes and the metrics histogram); a label past it would silently
        # fall out of the histogram fit — refuse loudly instead.
        if y.size and int(y.max()) >= self.n_classes:
            raise ValueError(
                f"ingested label {int(y.max())} is out of range for the "
                f"service's {self.n_classes} classes (fixed by the cold-start "
                "corpus); restart the service with a corpus covering every "
                "class"
            )
        self._ingest_buf_x.append(x)
        self._ingest_buf_y.append(y)
        self._poll_refit()
        self._drain_ingest()
        self._maybe_refit()

    def flush(self) -> None:
        """Drain any partial ingest block and force an in-flight re-fit's
        touchdown — the quiesce point (checkpoint, shutdown, test barriers)."""
        self._drain_ingest(force=True)
        self._poll_refit(force=True)

    # -- ingest --------------------------------------------------------------

    def _drain_ingest(self, force: bool = False) -> None:
        if not self._ingest_buf_x:
            return
        bx = np.concatenate(self._ingest_buf_x)
        by = np.concatenate(self._ingest_buf_y)
        block = self.serve.ingest_block
        lo = 0
        while bx.shape[0] - lo >= block:
            self._ingest_block(bx[lo : lo + block], by[lo : lo + block], block)
            lo += block
        if force and lo < bx.shape[0]:
            px, py, count = slab_lib.pad_block(bx[lo:], by[lo:], block)
            self._ingest_block(px, py, count)
            lo = bx.shape[0]
        self._ingest_buf_x = [bx[lo:]] if lo < bx.shape[0] else []
        self._ingest_buf_y = [by[lo:]] if lo < bx.shape[0] else []

    def _ingest_block(self, bx: np.ndarray, by: np.ndarray, count: int) -> None:
        block = self.serve.ingest_block
        while self._fill + block > self._slab.capacity:
            self._grow()
        progs = self._programs_for(self._slab.capacity)
        t0 = time.perf_counter()
        self._slab, _fill_out = progs.ingest(
            self._slab, self._edges,
            jnp.asarray(bx), jnp.asarray(by), np.int32(count),
        )
        dt = time.perf_counter() - t0  # dispatch wall: the write is async
        progs.ingest_tracker.record(dt, points=count)
        self._fill += count
        self.stats.ingest_blocks += 1
        self.stats.ingested_points += count
        self.drift.observe_ingest(count)
        if self.metrics is not None:
            self.metrics.event(
                "ingest", points=count, seconds=round(dt, 6),
                fill=self._fill, capacity=self._slab.capacity,
            )

    def _grow(self) -> None:
        self._slab = slab_lib.grow_slab(self._slab)
        if self._aux.seed_mask is not None:
            self._aux = self._aux.replace(
                seed_mask=self._pad_seed_mask(self._aux.seed_mask)
            )
        self.stats.slab_growths += 1
        self._latency_causes.add("slab_growth_compile")
        telemetry.flight_record(
            "slab_grow", capacity=self._slab.capacity, fill=self._fill,
            buffered=sum(len(b) for b in self._ingest_buf_x),
        )
        if self.metrics is not None:
            self.metrics.event(
                "slab_grow", capacity=self._slab.capacity, fill=self._fill
            )

    # -- re-fit --------------------------------------------------------------

    def _maybe_refit(self) -> None:
        if self._inflight is not None or self._fit_budget_exhausted:
            return
        reason = self.drift.should_refit()
        if reason is None:
            return
        if self._fill - self._labeled <= 0:
            return  # nothing left to label; a chunk would be all sentinels
        K, window = self.serve.refit_rounds, self.cfg.strategy.window_size
        if self._labeled + K * window > self._fit_budget:
            # The device fit's labeled window is static; overrunning it would
            # silently truncate the gather and corrupt the forest. Refuse
            # loudly, once.
            self._fit_budget_exhausted = True
            self.stats.refits_skipped_fit_budget += 1
            if self.metrics is not None:
                self.metrics.event(
                    "refit_skipped", reason="fit_budget",
                    labeled=self._labeled, fit_budget=self._fit_budget,
                )
            return
        self._dispatch_refit(reason)

    def refit_now(self, reason: str = "manual") -> bool:
        """Dispatch a re-fit chunk immediately (warmup, operator request),
        bypassing the drift decision but not the safety guards; returns
        whether a chunk actually launched."""
        if (
            self._inflight is not None
            or self._fit_budget_exhausted
            or self._fill - self._labeled <= 0
        ):
            return False
        K, window = self.serve.refit_rounds, self.cfg.strategy.window_size
        if self._labeled + K * window > self._fit_budget:
            return False
        self._dispatch_refit(reason)
        return True

    def _dispatch_refit(self, reason: str) -> None:
        progs = self._programs_for(self._slab.capacity)
        state = slab_lib.flat_state(self._slab, self._key, self._round)
        end_round = self._round_host + self.serve.refit_rounds
        t0 = time.perf_counter()
        out_state, extras, ys = progs.chunk(
            self._slab.codes, state, self._aux, self._fit_key,
            self._test_x, self._test_y, end_round,
        )
        # The chunk donated the carried state: rebind the slab to the output
        # arrays NOW — every later ingest/score consumes these futures and
        # sequences behind the running chunk on device.
        self._slab = self._slab.replace(
            x=out_state.x,
            oracle_y=out_state.oracle_y,
            labeled_mask=out_state.labeled_mask,
            n_filled=out_state.n_filled,
        )
        self._key = out_state.key
        self._round = out_state.round
        self._inflight = (extras, ys, t0, reason, progs)
        self._inflight_polls = 0
        self.stats.refits += 1
        self.refit_reasons[reason] = self.refit_reasons.get(reason, 0) + 1
        self._latency_causes.add("refit_dispatch")
        telemetry.flight_record(
            "refit", reason=reason, rounds=self.serve.refit_rounds,
            labeled=self._labeled, fill=self._fill,
            capacity=self._slab.capacity,
            buffered=sum(len(b) for b in self._ingest_buf_x),
        )
        if self.metrics is not None:
            self.metrics.event(
                "refit", reason=reason, rounds=self.serve.refit_rounds,
                labeled=self._labeled, fill=self._fill,
                capacity=self._slab.capacity,
            )

    def _poll_refit(self, force: bool = False) -> None:
        if self._inflight is None:
            return
        extras = self._inflight[0]
        self._inflight_polls += 1
        ready = True
        probe = getattr(extras.n_labeled_after, "is_ready", None)
        if probe is not None and not force:
            ready = bool(probe())
        if force or ready or self._inflight_polls >= self.serve.refit_poll_events:
            self._touchdown()

    def _touchdown(self) -> None:
        extras, ys, t0, reason, progs = self._inflight
        self._inflight = None
        n_labeled_after = int(extras.n_labeled_after)  # blocks if still running
        n_active = int(extras.n_active)
        dt = time.perf_counter() - t0
        telemetry.flight_record(
            "touchdown", program=progs.chunk_tracker.program, reason=reason,
            n_active=n_active, n_labeled_after=n_labeled_after,
            seconds=round(dt, 6), polls=self._inflight_polls,
        )
        progs.chunk_tracker.record(dt, reason=reason)
        self._labeled = n_labeled_after
        self._round_host += n_active
        self.stats.refit_rounds += n_active
        if n_active:
            rounds_y, labeled_y, acc_y, _picked_y, active_y = ys[:5]
            active_np = np.asarray(active_y)
            rounds_np = np.asarray(rounds_y)[active_np]
            labeled_np = np.asarray(labeled_y)[active_np]
            acc_np = np.asarray(acc_y)[active_np]
            round_dicts = telemetry.stacked_metrics_to_dicts(ys[5], active_np)
            self.result.extend_from_arrays(
                rounds_np, labeled_np,
                np.maximum(self._fill - labeled_np, 0), acc_np,
                total_time=dt / n_active,
                metrics=round_dicts,
            )
            self.drift.observe_chunk(round_dicts)
            if self.metrics is not None:
                for i in range(n_active):
                    self.metrics.round(
                        round=int(rounds_np[i]),
                        n_labeled=int(labeled_np[i]),
                        accuracy=float(acc_np[i]),
                        **round_dicts[i],
                    )
            self._refresh_forest()

    def _refresh_forest(self) -> None:
        """Re-fit the RESIDENT forest from the current labeled set — the
        async launch whose output every subsequent score serves from."""
        progs = self._programs_for(self._slab.capacity)
        state = slab_lib.flat_state(self._slab, self._key, self._round)
        t0 = time.perf_counter()
        self._forest = progs.fit(
            self._slab.codes, state,
            jax.random.fold_in(self._fit_key, self._round_host),
        )
        progs.fit_tracker.record(time.perf_counter() - t0)

    # -- persistence ---------------------------------------------------------

    def save_checkpoint(self) -> Optional[str]:
        """Persist the slab watermark + mask + ingested points + resident
        forest so a killed service resumes WITHOUT replaying ingest
        (runtime/checkpoint.py ``save_serve``)."""
        if not self.checkpoint_dir:
            return None
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        self.flush()
        state = slab_lib.flat_state(self._slab, self._key, self._round)
        return ckpt_lib.save_serve(
            self.checkpoint_dir, state, self._forest, self.result,
            fingerprint=ckpt_lib.config_fingerprint(self.cfg),
        )

    def _try_restore(self, ckpt_dir: str) -> bool:
        from distributed_active_learning_tpu.runtime import checkpoint as ckpt_lib

        progs = self._programs_for(self._slab.capacity)
        # The forest's pytree structure is whatever this configuration's fit
        # program produces — eval_shape gives the template without running it.
        template = jax.eval_shape(
            progs.fit,
            self._slab.codes,
            slab_lib.flat_state(self._slab, self._key, self._round),
            self._fit_key,
        )
        restored = ckpt_lib.restore_latest_serve(
            ckpt_dir, template,
            fingerprint=ckpt_lib.config_fingerprint(self.cfg),
        )
        if restored is None:
            return False
        x, y, mask, n_filled, key_data, rnd, forest, result = restored
        self._slab = slab_lib.init_slab_pool(
            x, y, mask, self._edges, self.serve.slab_rows
        )
        if self._aux.seed_mask is not None:
            self._aux = self._aux.replace(
                seed_mask=self._pad_seed_mask(self._aux.seed_mask)
            )
        self._fill = int(n_filled)
        self._key = jax.random.wrap_key_data(
            jnp.asarray(key_data), impl=jax.random.key_impl(self._key)
        )
        self._round = jnp.asarray(rnd)
        self._round_host = int(rnd)
        self._forest = forest
        self.result = result
        self._labeled = int(np.asarray(mask).sum())
        return True

    # -- reporting -----------------------------------------------------------

    def recompiles_after_warmup(self) -> int:
        """Total jit-cache growths beyond each program instance's first call
        — the no-silent-recompile guarantee the serve bench asserts at 0."""
        total = self._score_tracker.recompiles
        for progs in self._programs.values():
            total += (
                progs.ingest_tracker.recompiles
                + progs.chunk_tracker.recompiles
                + progs.fit_tracker.recompiles
            )
        return total

    def summary(self) -> Dict:
        return {
            "queries": self.stats.queries,
            "scored_points": self.stats.scored_points,
            "ingest_blocks": self.stats.ingest_blocks,
            "ingested_points": self.stats.ingested_points,
            "refits": self.stats.refits,
            "refit_rounds": self.stats.refit_rounds,
            "refit_reasons": dict(self.refit_reasons),
            "refits_skipped_fit_budget": self.stats.refits_skipped_fit_budget,
            "slab_growths": self.stats.slab_growths,
            "capacity": self._slab.capacity,
            "fill": self._fill,
            "labeled": self._labeled,
            "recompiles_after_warmup": self.recompiles_after_warmup(),
        }
