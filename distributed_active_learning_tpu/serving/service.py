"""Streaming AL service: the single-tenant front of the tenant manager.

PR 7 built this module as a self-contained event loop (ingest-drain +
resident scoring + drift-gated re-fit over a slab-paged pool). PR 12 moved
that loop VERBATIM into :class:`~serving.tenants.Tenant` so a multi-tenant
manager (:class:`~serving.tenants.TenantManager`) can hold N of them —
:class:`ALService` is now a thin compatibility wrapper routing through a
1-tenant manager. There is exactly ONE event-loop implementation; this
module only preserves the public single-tenant surface:

- the constructor signature, ``score``/``submit``/``flush``/``refit_now``/
  ``save_checkpoint``/``summary``/``recompiles_after_warmup``;
- the ``bench.py --mode serve`` key set (byte-compatible — the committed
  ``benches/baselines/cpu_smoke_serve.json`` baseline and its CI gate
  survive unchanged);
- pre-multi-tenant serve checkpoints (the wrapper keeps the tenant-less
  ``servestate_<round>.npz`` file names).

What the wrapper ALSO inherits from the tenant core, for free: the AOT
capacity precompile (slab growth swaps in background-compiled executables
instead of paying XLA compile on the triggering request — the
``slab_growth_compile`` p99 cause from PR 8 disappears post-warmup) and the
tenant-tagged telemetry stream. See serving/tenants.py for the design and
serving/frontend.py for the concurrent front queue.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from distributed_active_learning_tpu.config import ExperimentConfig, ServeConfig
from distributed_active_learning_tpu.serving.tenants import (  # noqa: F401
    ServeStats,
    Tenant,
    TenantManager,
    _CapacityPrograms,
    _ProgramTracker,
)


class ALService:
    """The long-running single-tenant service driver (compatibility front).

    ``cfg`` supplies the model/strategy/seeding half (the same
    :class:`ExperimentConfig` the batch drivers take — ``forest.fit`` must be
    ``"device"``; the whole point is a resident device loop); ``serve``
    supplies the streaming knobs. ``train_x/train_y`` seed the pool (the
    service's cold-start corpus), ``test_x/test_y`` feed the chunk's accuracy
    eval exactly as in the batch loop. Internally this is a
    :class:`~serving.tenants.TenantManager` holding one tenant named
    ``default`` — no duplicated event loop.
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        serve: ServeConfig,
        train_x,
        train_y,
        test_x,
        test_y,
        metrics=None,
        checkpoint_dir: Optional[str] = None,
    ):
        if metrics is not None:
            from distributed_active_learning_tpu.config import asdict as cfg_asdict
            import jax

            metrics.meta(
                config=cfg_asdict(cfg),
                serve=cfg_asdict(serve),
                backend=jax.default_backend(),
                loop="serve",
            )
        self.manager = TenantManager(metrics=metrics, checkpoint_dir=checkpoint_dir)
        # ckpt_name=None keeps the PR-7 single-tenant checkpoint file names,
        # so services started before the tenant axis existed keep resuming.
        self._tenant = self.manager.add_tenant(
            "default", cfg, serve, train_x, train_y, test_x, test_y,
            ckpt_name=None,
        )

    # -- the public endpoints (delegation, not reimplementation) -------------

    def score(self, queries) -> np.ndarray:
        return self._tenant.score(queries)

    def submit(self, x, y) -> None:
        self._tenant.submit(x, y)

    def flush(self) -> None:
        self._tenant.flush()

    def refit_now(self, reason: str = "manual") -> bool:
        return self._tenant.refit_now(reason)

    def save_checkpoint(self) -> Optional[str]:
        return self._tenant.save_checkpoint()

    def recompiles_after_warmup(self) -> int:
        return self.manager.recompiles_after_warmup()

    def summary(self) -> Dict:
        """The PR-7 key set, byte-compatible (bench.py --mode serve and its
        committed baseline read these names)."""
        t = self._tenant
        out = {
            "queries": t.stats.queries,
            "scored_points": t.stats.scored_points,
            "ingest_blocks": t.stats.ingest_blocks,
            "ingested_points": t.stats.ingested_points,
            "refits": t.stats.refits,
            "refit_rounds": t.stats.refit_rounds,
            "refit_reasons": dict(t.refit_reasons),
            "refits_skipped_fit_budget": t.stats.refits_skipped_fit_budget,
            "slab_growths": t.stats.slab_growths,
            "capacity": t._slab.capacity,
            "fill": t._fill,
            "labeled": t._labeled,
            "recompiles_after_warmup": self.recompiles_after_warmup(),
        }
        if t.slo is not None:
            # present ONLY when ServeConfig configures an objective, so the
            # PR-7 key set (and the committed serve baseline) is untouched
            # for SLO-less services
            out["slo"] = t.slo.snapshot()
        return out

    # -- state passthroughs (tests, __main__, and benches read these) --------

    @property
    def cfg(self) -> ExperimentConfig:
        return self._tenant.cfg

    @property
    def serve(self) -> ServeConfig:
        return self._tenant.serve

    @property
    def metrics(self):
        return self._tenant.metrics

    @property
    def checkpoint_dir(self) -> Optional[str]:
        return self._tenant.checkpoint_dir

    @property
    def stats(self) -> ServeStats:
        return self._tenant.stats

    @property
    def refit_reasons(self) -> Dict[str, int]:
        return self._tenant.refit_reasons

    @property
    def result(self):
        return self._tenant.result

    @property
    def n_classes(self) -> int:
        return self._tenant.n_classes

    @property
    def drift(self):
        return self._tenant.drift

    @property
    def _slab(self):
        return self._tenant._slab

    @property
    def _aux(self):
        return self._tenant._aux

    @property
    def _fill(self) -> int:
        return self._tenant._fill

    @property
    def _labeled(self) -> int:
        return self._tenant._labeled

    @property
    def _forest(self):
        return self._tenant._forest
