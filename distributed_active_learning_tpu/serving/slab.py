"""Slab-paged streaming pool: fixed-capacity slabs + donation ingest.

The batch pipeline's :class:`~runtime.state.PoolState` is sized once per
experiment — fine for a thesis reproduction, fatal for a service where points
arrive continuously: naively appending a row changes every array's shape and
recompiles every program on every arrival. The slab design splits "how much
memory is allocated" from "how much of it is real":

- **Capacity is slab-quantized and static.** Pool arrays are allocated in
  fixed ``slab_rows``-row slabs; every program specializes on the capacity,
  and growth (rare, slab-at-a-time) is the ONLY shape change — one compile
  per capacity ever reached, never one per arrival.

- **The fill is a dynamic watermark.** ``PoolState.n_filled`` is a traced
  int32 leaf: rows at/past it are allocated-but-unfilled tail, excluded from
  selection/fit/metrics by the dynamic masks in ``runtime/state.py``. Ingest
  advances the watermark launch-to-launch with identical avals — arrivals
  never retrigger compilation (pinned by tests/test_serving.py's jit-cache
  assertions).

- **Ingest is an in-place donation write.** :func:`make_ingest_fn` builds a
  jitted program that donates the slab arrays and writes a fixed-width block
  at the watermark via ``dynamic_update_slice`` — the service's hot append
  path costs one aliased launch, no host round-trip of the pool. Arrivals
  smaller than the block width are padded; the pad rows land past the
  advanced watermark and are overwritten by the next block.

- **Scoring is capacity-independent.** :func:`make_score_fn` evaluates the
  resident fitted forest over a fixed-width query batch — its program never
  depends on the pool at all, so it compiles exactly once for the service's
  lifetime.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from distributed_active_learning_tpu.runtime import state as state_lib


@struct.dataclass
class SlabPool:
    """Device-resident slab-paged pool.

    ``labeled_mask`` rows past the watermark stay False (ingest never touches
    the mask — fresh points arrive unlabeled); consumers exclude the unfilled
    tail through ``PoolState``'s dynamic masks instead. ``codes`` holds the
    binned features the device trainer consumes, kept in lockstep with ``x``
    by the ingest program so a re-fit launch needs no re-binning pass.
    """

    x: jnp.ndarray             # [capacity, d] float32
    oracle_y: jnp.ndarray      # [capacity] int32
    labeled_mask: jnp.ndarray  # [capacity] bool
    codes: jnp.ndarray         # [capacity, d] int32 — binned features
    n_filled: jnp.ndarray      # scalar int32 — dynamic fill watermark
    slab_rows: int = struct.field(pytree_node=False, default=1024)

    @property
    def capacity(self) -> int:
        return self.x.shape[0]

    @property
    def n_slabs(self) -> int:
        return self.capacity // self.slab_rows


def slab_capacity(n_rows: int, slab_rows: int) -> int:
    """Smallest slab-multiple capacity holding ``n_rows`` (at least 1 slab)."""
    return max(-(-n_rows // slab_rows), 1) * slab_rows


def init_slab_pool(
    x,
    y,
    labeled_mask,
    edges: jnp.ndarray,
    slab_rows: int,
) -> SlabPool:
    """Allocate a slab pool holding the initial points.

    The unfilled tail is zero content with ``labeled_mask=False`` — the
    watermark, not the stored values, is what keeps it out of every program
    (the slab-growth parity tests prove the discipline: tail content is
    unobservable).
    """
    from distributed_active_learning_tpu.ops import trees_train

    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.int32)
    mask = jnp.asarray(labeled_mask, bool)
    n = x.shape[0]
    cap = slab_capacity(n, slab_rows)
    codes = trees_train.code_features(x, edges)
    pad = cap - n
    return SlabPool(
        x=jnp.pad(x, ((0, pad), (0, 0))),
        oracle_y=jnp.pad(y, (0, pad)),
        labeled_mask=jnp.pad(mask, (0, pad)),
        codes=jnp.pad(codes, ((0, pad), (0, 0))),
        n_filled=jnp.asarray(n, jnp.int32),
        slab_rows=slab_rows,
    )


def grow_slab(pool: SlabPool, n_slabs: int = 1) -> SlabPool:
    """Extend capacity by ``n_slabs`` fresh (unfilled) slabs.

    The one legitimate shape change of a service's lifetime: programs for the
    new capacity compile once when first used; the watermark and all filled
    content carry over untouched.
    """
    pad = n_slabs * pool.slab_rows
    return pool.replace(
        x=jnp.pad(pool.x, ((0, pad), (0, 0))),
        oracle_y=jnp.pad(pool.oracle_y, (0, pad)),
        labeled_mask=jnp.pad(pool.labeled_mask, (0, pad)),
        codes=jnp.pad(pool.codes, ((0, pad), (0, 0))),
    )


def flat_state(
    pool: SlabPool, key: jax.Array, round_: jnp.ndarray
) -> state_lib.PoolState:
    """The :class:`PoolState` view a fused AL chunk consumes — the SAME
    arrays (no copies), with the watermark riding as the dynamic
    ``n_filled`` leaf so the chunk's selection/fit/metrics mask the unfilled
    tail."""
    return state_lib.PoolState(
        x=pool.x,
        oracle_y=pool.oracle_y,
        labeled_mask=pool.labeled_mask,
        key=key,
        round=round_,
        n_filled=pool.n_filled,
    )


def make_ingest_fn():
    """Build the jitted donation-append program.

    ``ingest(pool, edges, block_x, block_y, count) -> (pool, n_filled)``
    writes a fixed-width block at the watermark (donating the slab arrays —
    the write is in place, no pool copy), bins the block's features against
    the service's frozen edges inside the same program, and advances the
    watermark by ``count`` (the block's REAL rows; pad rows land past the new
    watermark and are overwritten by the next block). The post-ingest
    watermark also returns as a separate scalar — the one value host
    accounting may fetch without touching the slab arrays (the ingest twin of
    the chunk's :class:`~runtime.pipeline.ChunkExtras`).

    Each factory call returns a FRESH jit closure: the service builds one per
    capacity, so a program instance's jit cache holds exactly one executable
    and any growth past it is a loud recompile signal rather than silent
    cache churn (the ``recompiles_after_warmup`` accounting in
    serving/service.py keys on this).

    The caller must guarantee ``n_filled + block_rows <= capacity`` (grow
    first); ``dynamic_update_slice`` would otherwise clamp the start index
    and silently overwrite the newest filled rows.
    """
    from distributed_active_learning_tpu.ops import trees_train

    @functools.partial(jax.jit, donate_argnums=(0,))
    def ingest(
        pool: SlabPool,
        edges: jnp.ndarray,
        block_x: jnp.ndarray,
        block_y: jnp.ndarray,
        count: jnp.ndarray,
    ) -> Tuple[SlabPool, jnp.ndarray]:
        with jax.named_scope("serve/ingest"):
            fill = pool.n_filled
            block_codes = trees_train.code_features(block_x, edges)
            new_pool = pool.replace(
                x=jax.lax.dynamic_update_slice(pool.x, block_x, (fill, 0)),
                oracle_y=jax.lax.dynamic_update_slice(
                    pool.oracle_y, block_y, (fill,)
                ),
                codes=jax.lax.dynamic_update_slice(
                    pool.codes, block_codes, (fill, 0)
                ),
                n_filled=fill + count,
            )
        return new_pool, new_pool.n_filled

    return ingest


# --------------------------------------------------------------------------
# Pod-sharded slab pool: shard-local ingest + rebalancing epochs.
#
# The single-slab spelling above funnels every arrival through one host's
# slab. At pod scale the pool lives as S contiguous row blocks on the mesh's
# ``data`` axis (parallel/mesh.py), ``n_filled`` is the per-shard ``[S]``
# watermark leaf, and the data path stays shard-local:
#
# - **Ingest** writes each arriving block at ONE shard's own watermark inside
#   a single shard_map — the non-addressed shards run the same program as a
#   window-sized identity rewrite, so there is one executable per capacity
#   and zero collectives beyond the psum'd global-fill scalar. A host-side
#   router (:func:`route_to_shard`) points arrivals at the least-filled
#   shard.
#
# - **Rebalance** restores fill balance after skewed labeling/ingest with ONE
#   window-sized ``all_to_all`` per epoch (never pool-scale — the PR-13
#   ``collective-bytes-over-budget`` auditor is the contract, enforced on the
#   registered ``pod_ingest`` programs). Donors ship their topmost filled
#   rows; receivers append at their watermark; the permutation returns as a
#   small global-index map so selection indices remain recoverable
#   (``ops/ring_topk.remap_indices``).
#
# Global row identity is positional: ``global_idx = shard * rows + local``
# with ``rows = capacity // S``. Growth (:func:`grow_sharded_slab`) pads each
# shard's block in place, so it RENUMBERS global indices — callers treat
# indices as valid only between shape changes (the single-slab pool has the
# same property: its indices are stable only because it never re-chunks).
# --------------------------------------------------------------------------

#: Invalid-slot marker in rebalance index maps (valid global indices are >= 0).
MOVED_SENTINEL = -1


def shard_slab_pool(pool: SlabPool, mesh) -> SlabPool:
    """Place a slab pool over ``mesh``'s data axis with a per-shard watermark.

    A scalar ``n_filled`` is split with
    :func:`parallel.mesh.shard_fill_watermark` (a single-slab pool fills
    contiguously, so the split is exact); an already per-shard ``[S]`` leaf is
    validated and re-placed as-is. Capacity must divide by the data axis —
    each shard owns the contiguous block ``[s * rows, (s + 1) * rows)``.
    """
    from distributed_active_learning_tpu.parallel import mesh as mesh_lib
    from jax.sharding import PartitionSpec as P

    n_shards = mesh.shape[mesh_lib.AXIS_DATA]
    if pool.capacity % n_shards:
        raise ValueError(
            f"slab capacity {pool.capacity} not divisible by data axis "
            f"{n_shards}"
        )
    nf = jnp.asarray(pool.n_filled)
    if nf.ndim == 0:
        nf = mesh_lib.shard_fill_watermark(nf, pool.capacity, n_shards)
    elif nf.shape != (n_shards,):
        raise ValueError(
            f"per-shard n_filled leaf {nf.shape} does not match the data "
            f"axis ({n_shards} shards)"
        )
    # Every leaf rides the ONE canonical spec P("data") — rank-2 leaves
    # shard dim 0 and replicate the rest, exactly pool_spec()'s meaning.
    # The ingest/rebalance factories pin their outputs to the same spec
    # (out_shardings), so the donated pool round-trips with an identical
    # cache key on every mesh width; a spelling mismatch (P("data", None)
    # in, P("data") out) would cost one silent recompile per closure.
    spec = P(mesh_lib.AXIS_DATA)
    return pool.replace(
        x=mesh_lib.global_put(pool.x, mesh, spec),
        oracle_y=mesh_lib.global_put(pool.oracle_y, mesh, spec),
        labeled_mask=mesh_lib.global_put(pool.labeled_mask, mesh, spec),
        codes=mesh_lib.global_put(pool.codes, mesh, spec),
        n_filled=mesh_lib.global_put(nf, mesh, spec),
    )


def route_to_shard(fills) -> int:
    """The ingest router: the least-filled shard's index (ties to the lowest).

    Host-side and O(S) — routing consults only the ``[S]`` watermark vector
    (S ints fetched per arrival batch at most), never the pool.
    """
    return int(np.argmin(np.asarray(fills)))


def make_sharded_ingest_fn(mesh):
    """Build the jitted per-shard donation-append program.

    ``ingest(pool, edges, block_x, block_y, count, shard) -> (pool, global_fill)``
    is the sharded spelling of :func:`make_ingest_fn`: one shard_map over the
    mesh in which the shard addressed by ``shard`` (a traced scalar — the
    router's pick) writes the block at its OWN watermark and advances it by
    ``count``; every other shard executes the identical program as a
    window-sized read-modify-write of rows it already owns (a slice re-write
    of unchanged content), so the pool never materializes on one host and the
    executable is shard-choice-independent. ``global_fill`` is the psum'd
    post-ingest total (``parallel.collectives.global_count`` discipline) —
    budget/stop bookkeeping stays exact without fetching the ``[S]`` leaf.

    Same per-capacity compile contract as the single-slab factory: each call
    returns a FRESH closure, one executable per capacity ever reached, growth
    is the only loud recompile. The caller must guarantee the addressed shard
    has room (``fills[shard] + block_rows <= capacity // S`` — grow first);
    ``dynamic_update_slice`` would otherwise clamp and overwrite the newest
    rows, exactly like the single-slab contract.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_active_learning_tpu.ops import trees_train
    from distributed_active_learning_tpu.parallel import mesh as mesh_lib
    from distributed_active_learning_tpu.utils.compat import shard_map

    data = mesh_lib.AXIS_DATA

    def _body(x_blk, y_blk, c_blk, nf, edges, block_x, block_y, count, shard):
        me = jax.lax.axis_index(data)
        fill = nf[0]
        mine = me == shard
        block_codes = trees_train.code_features(block_x, edges)
        b, d = block_x.shape
        # Window-sized conditional write: non-addressed shards slice their
        # own rows at the watermark and write them back unchanged — same
        # program on every shard, no gather of the pool anywhere. Full
        # shards clamp the slice start; the write-back is then an identity
        # on existing rows, still content-preserving.
        cur_x = jax.lax.dynamic_slice(x_blk, (fill, 0), (b, d))
        cur_y = jax.lax.dynamic_slice(y_blk, (fill,), (b,))
        cur_c = jax.lax.dynamic_slice(c_blk, (fill, 0), (b, c_blk.shape[1]))
        x_out = jax.lax.dynamic_update_slice(
            x_blk, jnp.where(mine, block_x, cur_x), (fill, 0)
        )
        y_out = jax.lax.dynamic_update_slice(
            y_blk, jnp.where(mine, block_y, cur_y), (fill,)
        )
        c_out = jax.lax.dynamic_update_slice(
            c_blk, jnp.where(mine, block_codes, cur_c), (fill, 0)
        )
        nf_out = nf + jnp.where(mine, count, 0).astype(nf.dtype)
        global_fill = jax.lax.psum(nf_out[0], data)
        return x_out, y_out, c_out, nf_out, global_fill

    sharded = shard_map(
        _body,
        mesh=mesh,
        in_specs=(
            P(data, None), P(data), P(data, None), P(data),
            P(), P(), P(), P(), P(),
        ),
        out_specs=(P(data, None), P(data), P(data, None), P(data), P()),
        check_vma=False,
    )

    # Pin the output pool to the input's named placement. On a 1-wide data
    # axis GSPMD normalizes P("data") to P() (they are equivalent), so the
    # returned watermark leaf would otherwise come back replicated and the
    # NEXT donation-append call would miss the executable cache — one silent
    # extra compile per 1-device-mesh closure, the exact cliff the hard-zero
    # recompile gates exist to catch.
    out_shardings = (
        jax.sharding.NamedSharding(mesh, P(data)),
        jax.sharding.NamedSharding(mesh, P()),
    )

    @functools.partial(
        jax.jit, donate_argnums=(0,), out_shardings=out_shardings
    )
    def ingest(
        pool: SlabPool,
        edges: jnp.ndarray,
        block_x: jnp.ndarray,
        block_y: jnp.ndarray,
        count: jnp.ndarray,
        shard: jnp.ndarray,
    ) -> Tuple[SlabPool, jnp.ndarray]:
        with jax.named_scope("serve/pod_ingest"):
            x, y, codes, nf, global_fill = sharded(
                pool.x, pool.oracle_y, pool.codes, pool.n_filled,
                edges, block_x, block_y,
                jnp.asarray(count, jnp.int32), jnp.asarray(shard, jnp.int32),
            )
            new_pool = pool.replace(x=x, oracle_y=y, codes=codes, n_filled=nf)
        return new_pool, global_fill

    return ingest


def grow_sharded_slab(pool: SlabPool, mesh, n_slabs: int = 1) -> SlabPool:
    """Extend EVERY shard's block by ``n_slabs`` fresh slabs, shard-locally.

    Each shard pads its own contiguous block in place (one shard_map, zero
    collectives); global capacity grows by ``S * n_slabs * slab_rows`` and
    the per-shard watermark leaf carries over untouched (local fills are
    positions within the shard's block, which only grew at the tail). Global
    row indices RENUMBER (``shard * rows`` strides widen) — the same
    shape-change boundary at which programs recompile, so no live program
    ever sees indices across a growth.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_active_learning_tpu.parallel import mesh as mesh_lib
    from distributed_active_learning_tpu.utils.compat import shard_map

    data = mesh_lib.AXIS_DATA
    pad = n_slabs * pool.slab_rows

    def _body(x, y, m, c):
        return (
            jnp.pad(x, ((0, pad), (0, 0))),
            jnp.pad(y, (0, pad)),
            jnp.pad(m, (0, pad)),
            jnp.pad(c, ((0, pad), (0, 0))),
        )

    x, y, m, c = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P(data, None), P(data), P(data), P(data, None)),
        out_specs=(P(data, None), P(data), P(data), P(data, None)),
        check_vma=False,
    )(pool.x, pool.oracle_y, pool.labeled_mask, pool.codes)
    # Re-place on the canonical P("data") spec (see shard_slab_pool): the
    # grown pool must present the same cache key to the NEXT capacity's
    # fresh ingest closure as a freshly sharded pool would, so growth pays
    # exactly one compile — the per-capacity contract.
    spec = P(data)
    return pool.replace(
        x=mesh_lib.global_put(x, mesh, spec),
        oracle_y=mesh_lib.global_put(y, mesh, spec),
        labeled_mask=mesh_lib.global_put(m, mesh, spec),
        codes=mesh_lib.global_put(c, mesh, spec),
    )


def rebalance_plan(fills: jnp.ndarray, block_rows: int) -> jnp.ndarray:
    """The epoch's move matrix ``[S, S] int32``: ``plan[i, j]`` rows go i→j.

    Pure and replicated: every shard computes the identical plan from the
    all-gathered ``[S]`` fill vector. Donors are shards above the floor
    target ``total // S``, receivers below it; per-shard movement is capped
    at ``block_rows`` (the epoch's window-sized budget — a badly skewed pool
    converges over a few epochs rather than paying one pool-scale shuffle).
    The matching is the interval overlap of donor/receiver cumulative runs,
    so it is exact, order-stable, and never moves more than the smaller of
    total excess/deficit.
    """
    n_shards = fills.shape[0]
    fills = jnp.asarray(fills, jnp.int32)
    target = jnp.sum(fills) // n_shards
    excess = jnp.clip(fills - target, 0, block_rows)
    deficit = jnp.clip(target - fills, 0, block_rows)
    dc = jnp.cumsum(excess)
    rc = jnp.cumsum(deficit)
    dlo = dc - excess
    rlo = rc - deficit
    overlap = (
        jnp.minimum(dc[:, None], rc[None, :])
        - jnp.maximum(dlo[:, None], rlo[None, :])
    )
    return jnp.clip(overlap, 0, block_rows).astype(jnp.int32)


def rebalance_trigger(fills, ratio: float = 2.0) -> bool:
    """Host-side epoch trigger: fire when max/min shard fill exceeds
    ``ratio`` (an empty shard next to a non-empty one always fires). O(S)
    on the watermark vector only."""
    f = np.asarray(fills)
    if f.size <= 1 or f.max() == 0:
        return False
    if f.min() == 0:
        return True
    return float(f.max()) / float(f.min()) > ratio


def _fill_ratio(fills) -> Optional[float]:
    """max/min shard fill as a float (inf when an empty shard sits next to
    a non-empty one), or None when the vector can't be imbalanced."""
    f = np.asarray(fills)
    if f.size <= 1 or f.max() == 0:
        return None
    if f.min() == 0:
        return float("inf")
    return float(f.max()) / float(f.min())


class RebalanceHysteresis:
    """Thrash-proof epoch trigger wrapping :func:`rebalance_trigger`.

    The bare fill-ratio threshold is instantaneous: an adversarial arrival
    pattern that keeps the ratio oscillating around the threshold fires an
    epoch on every check, and each window-sized epoch only partially
    corrects the skew it was fired for — the classic rebalance thrash. This
    stateful trigger fixes both failure modes:

    - **Enter/exit band.** The trigger becomes ACTIVE when the ratio
      exceeds ``enter_ratio`` and stays active until the ratio drops to
      ``exit_ratio`` or below — so once a skew is being worked, epochs keep
      firing until the pool is genuinely balanced (not merely back under
      the entry threshold), and a ratio hovering just below ``enter_ratio``
      after recovery fires nothing.

    - **Minimum inter-epoch interval.** While active, at most one fire per
      ``min_interval`` calls to :meth:`update` — callers check once per
      ingest step, so this is a step-denominated rate limit that gives each
      epoch's moves time to land before the next is cut.

    Call :meth:`update` with the current fill vector once per step; it
    returns True exactly when an epoch should run now. ``fired`` /
    ``suppressed_interval`` / ``suppressed_band`` count decisions for
    observability and tests.
    """

    def __init__(
        self,
        enter_ratio: float = 2.0,
        exit_ratio: float = 1.5,
        min_interval: int = 4,
    ):
        if exit_ratio > enter_ratio:
            raise ValueError(
                f"exit_ratio ({exit_ratio}) must not exceed enter_ratio "
                f"({enter_ratio}) — the band would invert"
            )
        self.enter_ratio = float(enter_ratio)
        self.exit_ratio = float(exit_ratio)
        self.min_interval = int(min_interval)
        self._active = False
        # Primed so the FIRST excursion past enter_ratio fires immediately;
        # the interval gates consecutive fires, not the initial response.
        self._since_fire = self.min_interval
        self.fired = 0
        self.suppressed_interval = 0
        self.suppressed_band = 0

    @property
    def active(self) -> bool:
        """True while the trigger is between enter and exit — epochs fire
        (subject to the interval) until the ratio drops to ``exit_ratio``."""
        return self._active

    def update(self, fills) -> bool:
        """Advance one step with the current ``[S]`` fill vector; True means
        run a rebalance epoch now."""
        self._since_fire += 1
        ratio = _fill_ratio(fills)
        if ratio is None:
            self._active = False
            return False
        if self._active and ratio <= self.exit_ratio:
            self._active = False
        if not self._active and ratio > self.enter_ratio:
            self._active = True
        if not self._active:
            if ratio > self.exit_ratio:
                # inside the band but not entered from above — the
                # hysteresis is doing its job
                self.suppressed_band += 1
            return False
        if self._since_fire < self.min_interval:
            self.suppressed_interval += 1
            return False
        self._since_fire = 0
        self.fired += 1
        return True


def make_rebalance_fn(mesh, block_rows: int):
    """Build the jitted donated rebalance-epoch program.

    ``rebalance(pool) -> (pool, moved_src, moved_dst)`` runs one epoch: all
    shards agree on a :func:`rebalance_plan` from the all-gathered fills,
    donors pack their TOPMOST filled rows (content, labels, codes — labeled
    rows move with their labels, and nothing re-bins) into a per-target
    ``[S, block_rows]`` buffer, ONE window-sized ``all_to_all``
    (:func:`parallel.collectives.exchange_blocks`) swaps the buffers, and
    receivers append the valid rows at their own watermark. Donor rows past
    the shrunk watermark get their labeled bits cleared — the slab tail
    contract (tail content is unobservable, tail mask is False) holds on
    every shard after the epoch.

    ``moved_src``/``moved_dst`` ``[S, S * block_rows] int32`` are the
    epoch's global-index map (``MOVED_SENTINEL`` pads unused slots): row
    ``s`` lists the rows shard ``s`` RECEIVED as ``old global idx -> new
    global idx``. Selection over the rebalanced pool recovers
    pre-rebalance identities through ``ops/ring_topk.remap_indices`` — the
    ring-top-k exactness argument needs only this contiguous-block index
    recovery, which is why the permutation can ride a window-sized map
    instead of forcing a pool-scale renumbering.

    A balanced pool yields an all-zero plan and the epoch is a pure no-op
    (identical watermarks, empty map) at unchanged per-launch bytes — safe
    to run on a timer. Same per-capacity fresh-closure compile contract as
    the ingest factories.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_active_learning_tpu.parallel import collectives, mesh as mesh_lib
    from distributed_active_learning_tpu.utils.compat import shard_map

    data = mesh_lib.AXIS_DATA
    n_shards = mesh.shape[data]

    def _body(x, y, m, c, nf):
        rows, d = x.shape
        me = jax.lax.axis_index(data)
        fill = nf[0]
        fills = collectives.gather_fills(fill, data)
        plan = rebalance_plan(fills, block_rows)
        send_counts = plan[me]                       # [S] rows I send per target
        sent = jnp.sum(send_counts)
        recv_total = jnp.sum(plan[:, me])
        # Pack: my topmost `sent` filled rows, partitioned per target in
        # target order. Slot (j, b) holds my row fill - sent + off[j] + b.
        off = jnp.cumsum(send_counts) - send_counts
        slot = jnp.arange(block_rows, dtype=jnp.int32)
        slot_valid = slot[None, :] < send_counts[:, None]      # [S, block]
        src_local = fill - sent + off[:, None] + slot[None, :]
        src_safe = jnp.clip(src_local, 0, rows - 1)
        send_g = jnp.where(
            slot_valid, (me * rows + src_safe).astype(jnp.int32), MOVED_SENTINEL
        )
        exch = lambda t: collectives.exchange_blocks(t, data)
        rx = exch(x[src_safe])
        ry = exch(y[src_safe])
        rm = exch(m[src_safe])
        rcodes = exch(c[src_safe])
        rg = exch(send_g)
        rvalid = exch(slot_valid)
        # Compact received rows (valid first, stable in sender order) and
        # append at my watermark. Invalid slots scatter out of bounds and
        # drop — never a clamped overwrite of real rows. Receivers have room
        # by construction: fill + recv_total <= target <= rows.
        flat = n_shards * block_rows
        rvalid_f = rvalid.reshape(flat)
        order = jnp.argsort(jnp.logical_not(rvalid_f), stable=True)
        taken = rvalid_f[order]
        dst_local = jnp.where(
            taken, fill + jnp.arange(flat, dtype=jnp.int32), rows
        )
        x_out = x.at[dst_local].set(rx.reshape(flat, d)[order], mode="drop")
        y_out = y.at[dst_local].set(ry.reshape(flat)[order], mode="drop")
        m_out = m.at[dst_local].set(rm.reshape(flat)[order], mode="drop")
        c_out = c.at[dst_local].set(
            rcodes.reshape(flat, c.shape[1])[order], mode="drop"
        )
        new_fill = fill - sent + recv_total
        # Donor tail contract: rows shipped away fall past the shrunk
        # watermark; their labeled bits must not linger.
        m_out = m_out & (jnp.arange(rows) < new_fill)
        moved_src = jnp.where(taken, rg.reshape(flat)[order], MOVED_SENTINEL)
        moved_dst = jnp.where(taken, me * rows + dst_local, MOVED_SENTINEL)
        return (
            x_out, y_out, m_out, c_out,
            new_fill.astype(nf.dtype)[None],
            moved_src[None], moved_dst[None],
        )

    sharded = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P(data, None), P(data), P(data), P(data, None), P(data)),
        out_specs=(
            P(data, None), P(data), P(data), P(data, None), P(data),
            P(data), P(data),
        ),
        check_vma=False,
    )

    # Same 1-wide-axis placement pin as the ingest factory: the donated
    # pool must round-trip with its P("data") shardings intact or the next
    # epoch recompiles.
    out_shardings = (
        jax.sharding.NamedSharding(mesh, P(data)),
        jax.sharding.NamedSharding(mesh, P(data)),
        jax.sharding.NamedSharding(mesh, P(data)),
    )

    @functools.partial(
        jax.jit, donate_argnums=(0,), out_shardings=out_shardings
    )
    def rebalance(
        pool: SlabPool,
    ) -> Tuple[SlabPool, jnp.ndarray, jnp.ndarray]:
        with jax.named_scope("serve/pod_rebalance"):
            x, y, m, c, nf, moved_src, moved_dst = sharded(
                pool.x, pool.oracle_y, pool.labeled_mask, pool.codes,
                pool.n_filled,
            )
            new_pool = pool.replace(
                x=x, oracle_y=y, labeled_mask=m, codes=c, n_filled=nf
            )
        return new_pool, moved_src, moved_dst

    return rebalance


def score_body(forest, queries: jnp.ndarray):
    """The resident-forest scoring computation, shared by the single-tenant
    endpoint (:func:`make_score_fn`) and the cross-tenant batched endpoint
    (``serving/tenants.py make_batched_score_fn`` vmaps this over a leading
    tenant axis). One traced body so the two paths cannot drift — the
    batched-vs-independent bit-identity tests lean on it."""
    from distributed_active_learning_tpu.ops import forest_eval, scoring, trees_multi

    if trees_multi.is_multi(forest):
        probs = trees_multi.proba_multi(forest, queries)
        scores = jnp.max(probs, axis=-1)
        ent = trees_multi.entropy_multi(probs)
    else:
        p = forest_eval.proba(forest, queries)
        scores = p
        ent = scoring.full_entropy(p)
    return scores.astype(jnp.float32), ent.astype(jnp.float32)


def make_score_fn():
    """Build the resident-forest scoring endpoint program.

    ``score(forest, queries[B, d]) -> (scores[B], entropy[B])`` — the
    model's confidence per query (P(class 1) for binary forests, the
    predicted class's probability for multiclass) plus the predictive
    entropy the drift monitor consumes. Fixed query width ``B`` (callers
    pad), no pool dependence: one compile for the service's lifetime, and
    re-fitted forests of the same configuration reuse the executable.
    """

    @jax.jit
    def score(forest, queries: jnp.ndarray):
        with jax.named_scope("serve/score"):
            return score_body(forest, queries)

    return score


def pad_block(
    x: np.ndarray, y: np.ndarray, block_rows: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-side pad of an arrival to the static ingest width; returns
    ``(block_x, block_y, count)`` with ``count`` the real rows."""
    n = x.shape[0]
    if n > block_rows:
        raise ValueError(f"arrival of {n} rows exceeds ingest block {block_rows}")
    pad = block_rows - n
    bx = np.zeros((block_rows, x.shape[1]), np.float32)
    bx[:n] = x
    by = np.zeros((block_rows,), np.int32)
    by[:n] = y
    return bx, by, n
