"""Slab-paged streaming pool: fixed-capacity slabs + donation ingest.

The batch pipeline's :class:`~runtime.state.PoolState` is sized once per
experiment — fine for a thesis reproduction, fatal for a service where points
arrive continuously: naively appending a row changes every array's shape and
recompiles every program on every arrival. The slab design splits "how much
memory is allocated" from "how much of it is real":

- **Capacity is slab-quantized and static.** Pool arrays are allocated in
  fixed ``slab_rows``-row slabs; every program specializes on the capacity,
  and growth (rare, slab-at-a-time) is the ONLY shape change — one compile
  per capacity ever reached, never one per arrival.

- **The fill is a dynamic watermark.** ``PoolState.n_filled`` is a traced
  int32 leaf: rows at/past it are allocated-but-unfilled tail, excluded from
  selection/fit/metrics by the dynamic masks in ``runtime/state.py``. Ingest
  advances the watermark launch-to-launch with identical avals — arrivals
  never retrigger compilation (pinned by tests/test_serving.py's jit-cache
  assertions).

- **Ingest is an in-place donation write.** :func:`make_ingest_fn` builds a
  jitted program that donates the slab arrays and writes a fixed-width block
  at the watermark via ``dynamic_update_slice`` — the service's hot append
  path costs one aliased launch, no host round-trip of the pool. Arrivals
  smaller than the block width are padded; the pad rows land past the
  advanced watermark and are overwritten by the next block.

- **Scoring is capacity-independent.** :func:`make_score_fn` evaluates the
  resident fitted forest over a fixed-width query batch — its program never
  depends on the pool at all, so it compiles exactly once for the service's
  lifetime.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from distributed_active_learning_tpu.runtime import state as state_lib


@struct.dataclass
class SlabPool:
    """Device-resident slab-paged pool.

    ``labeled_mask`` rows past the watermark stay False (ingest never touches
    the mask — fresh points arrive unlabeled); consumers exclude the unfilled
    tail through ``PoolState``'s dynamic masks instead. ``codes`` holds the
    binned features the device trainer consumes, kept in lockstep with ``x``
    by the ingest program so a re-fit launch needs no re-binning pass.
    """

    x: jnp.ndarray             # [capacity, d] float32
    oracle_y: jnp.ndarray      # [capacity] int32
    labeled_mask: jnp.ndarray  # [capacity] bool
    codes: jnp.ndarray         # [capacity, d] int32 — binned features
    n_filled: jnp.ndarray      # scalar int32 — dynamic fill watermark
    slab_rows: int = struct.field(pytree_node=False, default=1024)

    @property
    def capacity(self) -> int:
        return self.x.shape[0]

    @property
    def n_slabs(self) -> int:
        return self.capacity // self.slab_rows


def slab_capacity(n_rows: int, slab_rows: int) -> int:
    """Smallest slab-multiple capacity holding ``n_rows`` (at least 1 slab)."""
    return max(-(-n_rows // slab_rows), 1) * slab_rows


def init_slab_pool(
    x,
    y,
    labeled_mask,
    edges: jnp.ndarray,
    slab_rows: int,
) -> SlabPool:
    """Allocate a slab pool holding the initial points.

    The unfilled tail is zero content with ``labeled_mask=False`` — the
    watermark, not the stored values, is what keeps it out of every program
    (the slab-growth parity tests prove the discipline: tail content is
    unobservable).
    """
    from distributed_active_learning_tpu.ops import trees_train

    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.int32)
    mask = jnp.asarray(labeled_mask, bool)
    n = x.shape[0]
    cap = slab_capacity(n, slab_rows)
    codes = trees_train.code_features(x, edges)
    pad = cap - n
    return SlabPool(
        x=jnp.pad(x, ((0, pad), (0, 0))),
        oracle_y=jnp.pad(y, (0, pad)),
        labeled_mask=jnp.pad(mask, (0, pad)),
        codes=jnp.pad(codes, ((0, pad), (0, 0))),
        n_filled=jnp.asarray(n, jnp.int32),
        slab_rows=slab_rows,
    )


def grow_slab(pool: SlabPool, n_slabs: int = 1) -> SlabPool:
    """Extend capacity by ``n_slabs`` fresh (unfilled) slabs.

    The one legitimate shape change of a service's lifetime: programs for the
    new capacity compile once when first used; the watermark and all filled
    content carry over untouched.
    """
    pad = n_slabs * pool.slab_rows
    return pool.replace(
        x=jnp.pad(pool.x, ((0, pad), (0, 0))),
        oracle_y=jnp.pad(pool.oracle_y, (0, pad)),
        labeled_mask=jnp.pad(pool.labeled_mask, (0, pad)),
        codes=jnp.pad(pool.codes, ((0, pad), (0, 0))),
    )


def flat_state(
    pool: SlabPool, key: jax.Array, round_: jnp.ndarray
) -> state_lib.PoolState:
    """The :class:`PoolState` view a fused AL chunk consumes — the SAME
    arrays (no copies), with the watermark riding as the dynamic
    ``n_filled`` leaf so the chunk's selection/fit/metrics mask the unfilled
    tail."""
    return state_lib.PoolState(
        x=pool.x,
        oracle_y=pool.oracle_y,
        labeled_mask=pool.labeled_mask,
        key=key,
        round=round_,
        n_filled=pool.n_filled,
    )


def make_ingest_fn():
    """Build the jitted donation-append program.

    ``ingest(pool, edges, block_x, block_y, count) -> (pool, n_filled)``
    writes a fixed-width block at the watermark (donating the slab arrays —
    the write is in place, no pool copy), bins the block's features against
    the service's frozen edges inside the same program, and advances the
    watermark by ``count`` (the block's REAL rows; pad rows land past the new
    watermark and are overwritten by the next block). The post-ingest
    watermark also returns as a separate scalar — the one value host
    accounting may fetch without touching the slab arrays (the ingest twin of
    the chunk's :class:`~runtime.pipeline.ChunkExtras`).

    Each factory call returns a FRESH jit closure: the service builds one per
    capacity, so a program instance's jit cache holds exactly one executable
    and any growth past it is a loud recompile signal rather than silent
    cache churn (the ``recompiles_after_warmup`` accounting in
    serving/service.py keys on this).

    The caller must guarantee ``n_filled + block_rows <= capacity`` (grow
    first); ``dynamic_update_slice`` would otherwise clamp the start index
    and silently overwrite the newest filled rows.
    """
    from distributed_active_learning_tpu.ops import trees_train

    @functools.partial(jax.jit, donate_argnums=(0,))
    def ingest(
        pool: SlabPool,
        edges: jnp.ndarray,
        block_x: jnp.ndarray,
        block_y: jnp.ndarray,
        count: jnp.ndarray,
    ) -> Tuple[SlabPool, jnp.ndarray]:
        with jax.named_scope("serve/ingest"):
            fill = pool.n_filled
            block_codes = trees_train.code_features(block_x, edges)
            new_pool = pool.replace(
                x=jax.lax.dynamic_update_slice(pool.x, block_x, (fill, 0)),
                oracle_y=jax.lax.dynamic_update_slice(
                    pool.oracle_y, block_y, (fill,)
                ),
                codes=jax.lax.dynamic_update_slice(
                    pool.codes, block_codes, (fill, 0)
                ),
                n_filled=fill + count,
            )
        return new_pool, new_pool.n_filled

    return ingest


def score_body(forest, queries: jnp.ndarray):
    """The resident-forest scoring computation, shared by the single-tenant
    endpoint (:func:`make_score_fn`) and the cross-tenant batched endpoint
    (``serving/tenants.py make_batched_score_fn`` vmaps this over a leading
    tenant axis). One traced body so the two paths cannot drift — the
    batched-vs-independent bit-identity tests lean on it."""
    from distributed_active_learning_tpu.ops import forest_eval, scoring, trees_multi

    if trees_multi.is_multi(forest):
        probs = trees_multi.proba_multi(forest, queries)
        scores = jnp.max(probs, axis=-1)
        ent = trees_multi.entropy_multi(probs)
    else:
        p = forest_eval.proba(forest, queries)
        scores = p
        ent = scoring.full_entropy(p)
    return scores.astype(jnp.float32), ent.astype(jnp.float32)


def make_score_fn():
    """Build the resident-forest scoring endpoint program.

    ``score(forest, queries[B, d]) -> (scores[B], entropy[B])`` — the
    model's confidence per query (P(class 1) for binary forests, the
    predicted class's probability for multiclass) plus the predictive
    entropy the drift monitor consumes. Fixed query width ``B`` (callers
    pad), no pool dependence: one compile for the service's lifetime, and
    re-fitted forests of the same configuration reuse the executable.
    """

    @jax.jit
    def score(forest, queries: jnp.ndarray):
        with jax.named_scope("serve/score"):
            return score_body(forest, queries)

    return score


def pad_block(
    x: np.ndarray, y: np.ndarray, block_rows: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-side pad of an arrival to the static ingest width; returns
    ``(block_x, block_y, count)`` with ``count`` the real rows."""
    n = x.shape[0]
    if n > block_rows:
        raise ValueError(f"arrival of {n} rows exceeds ingest block {block_rows}")
    pad = block_rows - n
    bx = np.zeros((block_rows, x.shape[1]), np.float32)
    bx[:n] = x
    by = np.zeros((block_rows,), np.int32)
    by[:n] = y
    return bx, by, n
