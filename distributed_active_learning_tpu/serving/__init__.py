"""Streaming AL as an online service (ROADMAP "heavy traffic" direction).

- :mod:`serving.slab` — the slab-paged pool: static slab-quantized capacity,
  dynamic fill watermark, donation ingest, fixed-width resident scoring;
- :mod:`serving.drift` — entropy/margin drift triggers deciding when a
  re-fit chunk launch is worth dispatching;
- :mod:`serving.tenants` — the multi-tenant core: N resident tenants per
  process, cross-tenant fused scoring (one vmapped launch over a tenant
  axis), tenant-axis batched re-fits (the PR-9 grid chunk with tenants as
  the dataset axis), and the background AOT capacity precompile that turns
  slab growth into an executable swap;
- :mod:`serving.frontend` — the thread-safe/asyncio front queue: admission
  control, per-tenant fairness, re-fit backpressure, one dispatcher thread
  owning all device work;
- :mod:`serving.service` — the single-tenant compatibility front
  (:class:`ALService` routes through a 1-tenant manager);
- :mod:`serving.fleet` — the shared-nothing multi-process fleet: N worker
  processes (each a full manager + frontend + ops plane) behind a
  consistent-hash router with health-gated forwarding.

Entry points: ``python -m distributed_active_learning_tpu.serving`` (a
simulated stream over a registry dataset), ``bench.py --mode serve`` (the
single-tenant sustained-qps / p99-latency benchmark), ``bench.py --mode
serve-multi`` (>= 4 tenants under mixed ingest + re-fit load, per-tenant
p50/p99, the zero-growth-compile gate) and ``bench.py --mode serve-fleet``
(the 1 -> 4 worker scaling leg behind the router).
"""

from distributed_active_learning_tpu.serving.drift import DriftMonitor  # noqa: F401
from distributed_active_learning_tpu.serving.fleet import (  # noqa: F401
    Fleet,
    HashRing,
    RouterServer,
    TenantSpec,
)
from distributed_active_learning_tpu.serving.frontend import (  # noqa: F401
    AdmissionError,
    ServiceFrontend,
)
from distributed_active_learning_tpu.serving.service import ALService  # noqa: F401
from distributed_active_learning_tpu.serving.slab import (  # noqa: F401
    RebalanceHysteresis,
    SlabPool,
    flat_state,
    grow_slab,
    init_slab_pool,
    make_ingest_fn,
    make_score_fn,
)
from distributed_active_learning_tpu.serving.tenants import (  # noqa: F401
    ServeStats,
    Tenant,
    TenantManager,
    make_batched_score_fn,
)
