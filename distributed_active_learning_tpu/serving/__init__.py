"""Streaming AL as an online service (ROADMAP "heavy traffic" direction).

- :mod:`serving.slab` — the slab-paged pool: static slab-quantized capacity,
  dynamic fill watermark, donation ingest, fixed-width resident scoring;
- :mod:`serving.drift` — entropy/margin drift triggers deciding when a
  re-fit chunk launch is worth dispatching;
- :mod:`serving.service` — the event loop interleaving ingest drains, the
  ``score(points)`` endpoint, and drift-gated fused AL chunk launches.

Entry points: ``python -m distributed_active_learning_tpu.serving`` (a
simulated stream over a registry dataset) and ``bench.py --mode serve`` (the
sustained-qps / p99-latency benchmark).
"""

from distributed_active_learning_tpu.serving.drift import DriftMonitor  # noqa: F401
from distributed_active_learning_tpu.serving.service import (  # noqa: F401
    ALService,
    ServeStats,
)
from distributed_active_learning_tpu.serving.slab import (  # noqa: F401
    SlabPool,
    flat_state,
    grow_slab,
    init_slab_pool,
    make_ingest_fn,
    make_score_fn,
)
