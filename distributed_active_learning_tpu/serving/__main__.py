"""CLI for the streaming AL service: a simulated stream over a dataset.

    python -m distributed_active_learning_tpu.serving \
        --dataset checkerboard2x2 --queries 500 --ingest-every 4 \
        --metrics-out results/serve.jsonl

Splits the registry dataset into a cold-start pool and a held-back arrival
stream, then drives the service with interleaved score queries (drawn from
the test split) and ingest blocks (the held-back stream), printing one JSON
summary line: sustained queries/sec, p50/p99 scoring latency, ingest
throughput, re-fit counts by drift reason, and the no-silent-recompile
counter. ``--checkpoint-dir`` saves the slab + resident forest at shutdown
and resumes from it at startup (no ingest replay).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="distributed_active_learning_tpu.serving",
        description="streaming AL service over a simulated arrival stream",
    )
    ap.add_argument("--dataset", default="checkerboard2x2")
    ap.add_argument("--data-path", default=None)
    ap.add_argument("--strategy", default="uncertainty")
    ap.add_argument("--window", type=int, default=10)
    ap.add_argument("--trees", type=int, default=10)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--kernel", choices=["gemm", "pallas", "gather"], default="gemm")
    ap.add_argument("--n-start", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--initial-frac", type=float, default=0.5,
        help="fraction of the train split seeding the pool; the rest arrives "
        "as the ingest stream",
    )
    ap.add_argument("--queries", type=int, default=500)
    ap.add_argument(
        "--ingest-every", type=int, default=4,
        help="submit one ingest block every N queries (0 = no ingest)",
    )
    ap.add_argument("--slab-rows", type=int, default=None)
    ap.add_argument("--ingest-block", type=int, default=None)
    ap.add_argument("--score-width", type=int, default=None)
    ap.add_argument("--refit-rounds", type=int, default=None)
    ap.add_argument("--drift-entropy-shift", type=float, default=None)
    ap.add_argument("--drift-margin-shift", type=float, default=None)
    ap.add_argument("--max-staleness", type=int, default=None)
    ap.add_argument("--fit-budget", type=int, default=None)
    ap.add_argument("--metrics-out", default=None, metavar="PATH")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument(
        "--ops-port", type=int, default=None, metavar="PORT",
        help="serve the live ops plane (runtime/obs.py) on localhost:PORT — "
        "/metrics (Prometheus text), /healthz, /varz, /flightz; 0/absent = "
        "off (ServeConfig.ops_port)",
    )
    ap.add_argument(
        "--slo-latency-ms", type=float, default=None, metavar="MS",
        help="per-query latency objective: queries answering within MS count "
        "toward the SLO; enables compliance + burn-rate gauges and the "
        "summary's slo block (ServeConfig.slo_latency_ms; absent = no SLO)",
    )
    ap.add_argument(
        "--slo-target", type=float, default=None, metavar="FRAC",
        help="SLO compliance target in (0, 1), e.g. 0.99 "
        "(ServeConfig.slo_target)",
    )
    return ap


def _serve_config(args):
    import dataclasses

    from distributed_active_learning_tpu.config import ServeConfig

    overrides = {
        name: getattr(args, flag)
        for name, flag in (
            ("slab_rows", "slab_rows"),
            ("ingest_block", "ingest_block"),
            ("score_width", "score_width"),
            ("refit_rounds", "refit_rounds"),
            ("drift_entropy_shift", "drift_entropy_shift"),
            ("drift_margin_shift", "drift_margin_shift"),
            ("max_staleness", "max_staleness"),
            ("ops_port", "ops_port"),
            ("slo_latency_ms", "slo_latency_ms"),
            ("slo_target", "slo_target"),
        )
        if getattr(args, flag) is not None
    }
    return dataclasses.replace(ServeConfig(), **overrides)


def drive_stream(service, stream_x, stream_y, test_x, *,
                 queries: int, ingest_every: int, block: int, rng):
    """Interleave score queries with ingest blocks; returns per-query
    latencies (seconds). ``bench.py --mode serve`` drives the same shape but
    with its own loop (it shifts the QUERY distribution mid-run to exercise
    the entropy trigger, which this dataset-backed drive cannot); a latency
    here is one ``service.score`` call wall — including any re-fit dispatch
    (and its compile) that call performs — matching the bench's definition."""
    latencies = []
    stream_pos = 0
    for i in range(queries):
        if (
            ingest_every
            and i % ingest_every == 0
            and stream_pos < stream_x.shape[0]
        ):
            hi = min(stream_pos + block, stream_x.shape[0])
            service.submit(stream_x[stream_pos:hi], stream_y[stream_pos:hi])
            stream_pos = hi
        idx = rng.integers(0, test_x.shape[0], size=min(service.serve.score_width, test_x.shape[0]))
        t0 = time.perf_counter()
        service.score(test_x[idx])
        latencies.append(time.perf_counter() - t0)
    return latencies


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from distributed_active_learning_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ForestConfig,
        StrategyConfig,
    )
    from distributed_active_learning_tpu.data.datasets import get_dataset
    from distributed_active_learning_tpu.serving.service import ALService

    bundle = get_dataset(
        DataConfig(name=args.dataset, path=args.data_path, seed=args.seed)
    )
    x = np.asarray(bundle.train_x, np.float32)
    y = np.asarray(bundle.train_y, np.int32)
    n0 = max(int(x.shape[0] * args.initial_frac), args.n_start + 2)
    serve = _serve_config(args)
    cfg = ExperimentConfig(
        data=DataConfig(name=args.dataset, path=args.data_path, seed=args.seed),
        forest=ForestConfig(
            n_trees=args.trees, max_depth=args.depth, kernel=args.kernel,
            fit="device", fit_budget=args.fit_budget,
        ),
        strategy=StrategyConfig(name=args.strategy, window_size=args.window),
        n_start=args.n_start,
        seed=args.seed,
    )

    writer = None
    if args.metrics_out:
        from distributed_active_learning_tpu.runtime.telemetry import (
            MetricsWriter,
            install_exit_flush,
        )

        # Buffered writes (serve_latency is per-query — hot path), with the
        # SIGTERM/atexit flush so a killed service keeps its tail events.
        writer = MetricsWriter(args.metrics_out, flush_every=64)
        install_exit_flush(writer)

    # Live ops plane: bind BEFORE the service builds so /healthz answers
    # during cold-start compiles (a 503-until-warm endpoint is still an
    # endpoint; a connection refused is "is it even running?").
    ops_server = None
    if serve.ops_port > 0:
        from distributed_active_learning_tpu.runtime.obs import OpsServer

        ops_server = OpsServer(port=serve.ops_port).start()
        print(
            f"# ops plane: http://127.0.0.1:{ops_server.port}/metrics "
            "(/healthz /varz /flightz)",
            flush=True,
        )

    service = ALService(
        cfg, serve, x[:n0], y[:n0], bundle.test_x, bundle.test_y,
        metrics=writer, checkpoint_dir=args.checkpoint_dir,
    )
    rng = np.random.default_rng(args.seed)
    test_x = np.asarray(bundle.test_x, np.float32)

    t0 = time.perf_counter()
    latencies = drive_stream(
        service, x[n0:], y[n0:], test_x,
        queries=args.queries, ingest_every=args.ingest_every,
        block=serve.ingest_block, rng=rng,
    )
    service.flush()
    wall = time.perf_counter() - t0

    if args.checkpoint_dir:
        service.save_checkpoint()
    if writer is not None:
        writer.close()
    if ops_server is not None:
        ops_server.stop()

    lat = np.asarray(latencies)
    payload = {
        "serve_qps": round(len(latencies) / wall, 2) if wall > 0 else None,
        "serve_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "serve_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "ingest_points_per_sec": round(service.stats.ingested_points / wall, 1)
        if wall > 0
        else None,
        **service.summary(),
    }
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
