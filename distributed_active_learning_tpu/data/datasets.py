"""Dataset registry producing device-ready train/test bundles.

Replaces the reference's ``Dataset`` class hierarchy
(``classes/dataset.py:48-273``: DatasetCheckerboard2x2 / 4x4 / Rotated /
StriatumMini) and the inlined loading in ``final_thesis/*.py:37-42``. Each entry
returns a :class:`DataBundle` of dense float32/int32 arrays, already
standardized when the config asks for it (the reference scales with MLlib
StandardScaler at ``dataset.py:163-165``; note it fits a *separate* scaler on
the test set — ``dataset.py:268-271`` flags this as a known inconsistency; we
default to the statistically-correct train-fitted scaler and expose
``scale_test_independently`` to reproduce the reference exactly).
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Optional

import jax
import numpy as np

from distributed_active_learning_tpu.config import DataConfig
from distributed_active_learning_tpu.data import formats, scaler, synthetic


class DataBundle(NamedTuple):
    """Dense train/test arrays for one AL experiment."""

    train_x: np.ndarray  # [n, d] float32
    train_y: np.ndarray  # [n] int32 — the oracle's labels, revealed via the mask
    test_x: np.ndarray   # [m, d] float32
    test_y: np.ndarray   # [m] int32
    name: str = ""

    @property
    def n_pool(self) -> int:
        return self.train_x.shape[0]

    @property
    def n_features(self) -> int:
        return self.train_x.shape[1]


_REGISTRY: Dict[str, Callable[[DataConfig], DataBundle]] = {}


def register_dataset(name: str):
    def deco(fn: Callable[[DataConfig], DataBundle]):
        _REGISTRY[name] = fn
        return fn
    return deco


def available_datasets():
    return sorted(_REGISTRY)


def get_dataset(cfg: DataConfig) -> DataBundle:
    if cfg.name not in _REGISTRY:
        raise KeyError(f"unknown dataset {cfg.name!r}; available: {available_datasets()}")
    bundle = _REGISTRY[cfg.name](cfg)
    if cfg.n_samples is not None and cfg.n_samples < bundle.n_pool:
        # Pool subsampling, as density_weighting.py:30 (n_samples=5000) does.
        rng = np.random.default_rng(cfg.seed)
        idx = rng.permutation(bundle.n_pool)[: cfg.n_samples]
        bundle = bundle._replace(
            train_x=bundle.train_x[idx], train_y=bundle.train_y[idx]
        )
    return bundle


def _standardize(bundle: DataBundle, cfg: DataConfig, independent_test: bool = False) -> DataBundle:
    if not cfg.standardize:
        return bundle
    if cfg.scale_test_independently is not None:
        independent_test = cfg.scale_test_independently
    st = scaler.fit_standard_scaler(bundle.train_x)
    train_x = np.asarray(scaler.transform(st, bundle.train_x), dtype=np.float32)
    if independent_test:
        # Reference behavior: separate scaler fit on test (dataset.py:268-271).
        test_x = np.asarray(scaler.fit_transform(bundle.test_x), dtype=np.float32)
    else:
        test_x = np.asarray(scaler.transform(st, bundle.test_x), dtype=np.float32)
    return bundle._replace(train_x=train_x, test_x=test_x)


def _synth(cfg: DataConfig, gen, n_train: int, n_test: int, name: str, **kw) -> DataBundle:
    k_tr, k_te = jax.random.split(jax.random.key(cfg.seed))
    train_x, train_y = gen(k_tr, n_train, **kw)
    test_x, test_y = gen(k_te, n_test, **kw)
    bundle = DataBundle(
        train_x=np.asarray(train_x), train_y=np.asarray(train_y),
        test_x=np.asarray(test_x), test_y=np.asarray(test_y), name=name,
    )
    return _standardize(bundle, cfg)


@register_dataset("checkerboard2x2")
def _checkerboard2x2(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_checkerboard, 1000, 1000, "checkerboard2x2", grid=2)


@register_dataset("checkerboard4x4")
def _checkerboard4x4(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_checkerboard, 1000, 1000, "checkerboard4x4", grid=4)


@register_dataset("rotated_checkerboard2x2")
def _rotated(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_rotated_checkerboard, 1000, 1000, "rotated_checkerboard2x2")


@register_dataset("xor")
def _xor(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_xor, 10000, 2000, "xor", d=10)


@register_dataset("striatum")
def _striatum(cfg: DataConfig) -> DataBundle:
    """Label-last whitespace text files, -1 remapped to 0 (dataset.py:245-273).

    ``cfg.path`` must point at a directory holding ``striatum_train_mini.txt``
    and ``striatum_test_mini.txt`` (the reference reads them from HDFS at
    ``dataset.py:253`` — there is no HDFS here, plain files instead).
    """
    import os
    if cfg.path is None:
        raise ValueError("striatum dataset needs cfg.path")
    train_x, train_y = formats.load_labeled_text(os.path.join(cfg.path, "striatum_train_mini.txt"))
    test_x, test_y = formats.load_labeled_text(os.path.join(cfg.path, "striatum_test_mini.txt"))
    bundle = DataBundle(train_x, train_y, test_x, test_y, "striatum")
    return _standardize(bundle, cfg, independent_test=True)


@register_dataset("credit_card_fraud")
def _credit_card(cfg: DataConfig) -> DataBundle:
    """Kaggle fraud CSV with a 70/30 split (mllib/credit_card_fraud.py:28)."""
    if cfg.path is None:
        raise ValueError("credit_card_fraud dataset needs cfg.path (the CSV file)")
    x, y = formats.load_credit_card_csv(cfg.path)
    rng = np.random.default_rng(cfg.seed)
    perm = rng.permutation(len(x))
    split = int(0.7 * len(x))
    tr, te = perm[:split], perm[split:]
    bundle = DataBundle(x[tr], y[tr], x[te], y[te], "credit_card_fraud")
    return _standardize(bundle, cfg)


@register_dataset("gaussian_unbalanced")
def _gaussian_unbalanced(cfg: DataConfig) -> DataBundle:
    """Simulated unbalanced clouds (classes/test.py:150-187)."""
    key = jax.random.key(cfg.seed)
    train_x, train_y, test_x, test_y = synthetic.make_gaussian_unbalanced(key, 1000)
    bundle = DataBundle(
        np.asarray(train_x), np.asarray(train_y),
        np.asarray(test_x), np.asarray(test_y), "gaussian_unbalanced",
    )
    return _standardize(bundle, cfg)
