"""Dataset registry producing device-ready train/test bundles.

Replaces the reference's ``Dataset`` class hierarchy
(``classes/dataset.py:48-273``: DatasetCheckerboard2x2 / 4x4 / Rotated /
StriatumMini) and the inlined loading in ``final_thesis/*.py:37-42``. Each entry
returns a :class:`DataBundle` of dense float32/int32 arrays, already
standardized when the config asks for it (the reference scales with MLlib
StandardScaler at ``dataset.py:163-165``; note it fits a *separate* scaler on
the test set — ``dataset.py:268-271`` flags this as a known inconsistency; we
default to the statistically-correct train-fitted scaler and expose
``scale_test_independently`` to reproduce the reference exactly).
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Optional, Tuple

import jax
import numpy as np

from distributed_active_learning_tpu.config import DataConfig
from distributed_active_learning_tpu.data import formats, scaler, synthetic


class DataBundle(NamedTuple):
    """Dense train/test arrays for one AL experiment.

    ``train_x`` is ``[n, d] float32`` for tabular pools, ``[n, H, W, C]``
    float32 for image pools (cifar10), or ``[n, T] int32`` token ids for text
    pools (agnews; ``vocab_size`` set).
    """

    train_x: np.ndarray
    train_y: np.ndarray  # [n] int32 — the oracle's labels, revealed via the mask
    test_x: np.ndarray
    test_y: np.ndarray   # [m] int32
    name: str = ""
    vocab_size: Optional[int] = None  # token pools only

    @property
    def n_pool(self) -> int:
        return self.train_x.shape[0]

    @property
    def n_features(self) -> int:
        return self.train_x.shape[1]


_REGISTRY: Dict[str, Callable[[DataConfig], DataBundle]] = {}


def register_dataset(name: str):
    def deco(fn: Callable[[DataConfig], DataBundle]):
        _REGISTRY[name] = fn
        return fn
    return deco


def available_datasets():
    return sorted(_REGISTRY)


def get_dataset(cfg: DataConfig) -> DataBundle:
    if cfg.name not in _REGISTRY:
        raise KeyError(f"unknown dataset {cfg.name!r}; available: {available_datasets()}")
    bundle = _REGISTRY[cfg.name](cfg)
    if cfg.n_samples is not None and cfg.n_samples < bundle.n_pool:
        # Pool subsampling, as density_weighting.py:30 (n_samples=5000) does.
        rng = np.random.default_rng(cfg.seed)
        idx = rng.permutation(bundle.n_pool)[: cfg.n_samples]
        bundle = bundle._replace(
            train_x=bundle.train_x[idx], train_y=bundle.train_y[idx]
        )
    return bundle


def _standardize(bundle: DataBundle, cfg: DataConfig, independent_test: bool = False) -> DataBundle:
    if not cfg.standardize:
        return bundle
    if cfg.scale_test_independently is not None:
        independent_test = cfg.scale_test_independently
    st = scaler.fit_standard_scaler(bundle.train_x)
    train_x = np.asarray(scaler.transform(st, bundle.train_x), dtype=np.float32)
    if independent_test:
        # Reference behavior: separate scaler fit on test (dataset.py:268-271).
        test_x = np.asarray(scaler.fit_transform(bundle.test_x), dtype=np.float32)
    else:
        test_x = np.asarray(scaler.transform(st, bundle.test_x), dtype=np.float32)
    return bundle._replace(train_x=train_x, test_x=test_x)


def _synth(cfg: DataConfig, gen, n_train: int, n_test: int, name: str, **kw) -> DataBundle:
    if cfg.n_samples is not None:
        # Synthetic pools are generated, not read: honor the requested size in
        # BOTH directions (10k-pool scale runs were silently capped at the
        # 1000-row default before; labels here are key-independent functions
        # of x, so larger draws stay consistent with the test split).
        n_train = cfg.n_samples
    k_tr, k_te = jax.random.split(jax.random.key(cfg.seed))
    train_x, train_y = gen(k_tr, n_train, **kw)
    test_x, test_y = gen(k_te, n_test, **kw)
    bundle = DataBundle(
        train_x=np.asarray(train_x), train_y=np.asarray(train_y),
        test_x=np.asarray(test_x), test_y=np.asarray(test_y), name=name,
    )
    return _standardize(bundle, cfg)


def _standin_sizes(cfg: DataConfig, default_train: int = 2000) -> Tuple[int, int]:
    """Pool sizing for the generated deep-AL stand-ins (cifar10/agnews without
    ``cfg.path``): ``--n-samples`` sets the POOL size (generation, not
    subsampling); the test set rides on top at 1/5 of the pool, floored at
    500 so small probe pools still get a stable accuracy estimate."""
    n_train = cfg.n_samples or default_train
    return n_train, max(500, n_train // 5)


@register_dataset("checkerboard2x2")
def _checkerboard2x2(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_checkerboard, 1000, 1000, "checkerboard2x2", grid=2)


@register_dataset("checkerboard4x4")
def _checkerboard4x4(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_checkerboard, 1000, 1000, "checkerboard4x4", grid=4)


@register_dataset("rotated_checkerboard2x2")
def _rotated(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_rotated_checkerboard, 1000, 1000, "rotated_checkerboard2x2")


@register_dataset("blobs4")
def _blobs4(cfg: DataConfig) -> DataBundle:
    """4-class Gaussian-blob tabular pool (multiclass forest-loop dataset)."""
    return _synth(cfg, synthetic.make_blobs, 2000, 2000, "blobs4", n_classes=4)


@register_dataset("xor")
def _xor(cfg: DataConfig) -> DataBundle:
    return _synth(cfg, synthetic.make_xor, 10000, 2000, "xor", d=10)


@register_dataset("striatum_like")
def _striatum_like(cfg: DataConfig) -> DataBundle:
    """10k-pool striatum stand-in (d=50 oblique boundary, minority positives)
    — the scale-run dataset for BASELINE.md's window-10/50/100 US-vs-RAND
    rows; see :func:`synthetic.make_striatum_like` for why this geometry and
    not a checkerboard."""
    return _synth(cfg, synthetic.make_striatum_like, 10000, 10000, "striatum_like")


def _register_file_checkerboard(base: str) -> None:
    """Registry entries for the reference's committed fixture files
    (``lal_direct_mllib_implementation/data/<base>_{train,test}.txt``, loaded
    by the reference at ``classes/dataset.py:149-238``). ``cfg.path`` is the
    directory holding them; parsing is byte-compatible ``load_labeled_text``.
    These run curve-for-curve parity against the reference's own data, vs the
    synthetic twins above."""

    @register_dataset(f"{base}_file")
    def _loader(cfg: DataConfig, base: str = base) -> DataBundle:
        import os

        if cfg.path is None:
            raise ValueError(f"{base}_file dataset needs cfg.path (fixture directory)")
        train_x, train_y = formats.load_labeled_text(
            os.path.join(cfg.path, f"{base}_train.txt")
        )
        test_x, test_y = formats.load_labeled_text(
            os.path.join(cfg.path, f"{base}_test.txt")
        )
        bundle = DataBundle(train_x, train_y, test_x, test_y, f"{base}_file")
        return _standardize(bundle, cfg)


for _base in ("checkerboard2x2", "checkerboard4x4", "rotated_checkerboard2x2"):
    _register_file_checkerboard(_base)


@register_dataset("striatum")
def _striatum(cfg: DataConfig) -> DataBundle:
    """Label-last whitespace text files, -1 remapped to 0 (dataset.py:245-273).

    ``cfg.path`` must point at a directory holding ``striatum_train_mini.txt``
    and ``striatum_test_mini.txt`` (the reference reads them from HDFS at
    ``dataset.py:253`` — there is no HDFS here, plain files instead).
    """
    import os
    if cfg.path is None:
        raise ValueError("striatum dataset needs cfg.path")
    train_x, train_y = formats.load_labeled_text(os.path.join(cfg.path, "striatum_train_mini.txt"))
    test_x, test_y = formats.load_labeled_text(os.path.join(cfg.path, "striatum_test_mini.txt"))
    bundle = DataBundle(train_x, train_y, test_x, test_y, "striatum")
    return _standardize(bundle, cfg, independent_test=True)


@register_dataset("credit_card_fraud")
def _credit_card(cfg: DataConfig) -> DataBundle:
    """Kaggle fraud CSV with a 70/30 split (mllib/credit_card_fraud.py:28)."""
    if cfg.path is None:
        raise ValueError("credit_card_fraud dataset needs cfg.path (the CSV file)")
    x, y = formats.load_credit_card_csv(cfg.path)
    rng = np.random.default_rng(cfg.seed)
    perm = rng.permutation(len(x))
    split = int(0.7 * len(x))
    tr, te = perm[:split], perm[split:]
    bundle = DataBundle(x[tr], y[tr], x[te], y[te], "credit_card_fraud")
    return _standardize(bundle, cfg)


@register_dataset("cifar10")
def _cifar10(cfg: DataConfig) -> DataBundle:
    """CIFAR-10 image pool (BASELINE.json config 4: CIFAR-10, small CNN).

    With ``cfg.path``: loads the standard python-pickle batches directory
    (``cifar-10-batches-py`` with data_batch_1..5 + test_batch), scaled to
    zero-mean unit-ish range. Without a path: a synthetic stand-in at the
    exact shape/dtype (32x32x3 float32, 10 classes) so the CNN pipeline is
    exercisable anywhere — documented stand-in, not real CIFAR.
    """
    if cfg.path is not None:
        import os
        import pickle

        def load_batch(fn):
            with open(os.path.join(cfg.path, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return x.astype(np.float32) / 127.5 - 1.0, np.asarray(
                d[b"labels"], dtype=np.int32
            )
        xs, ys = zip(*[load_batch(f"data_batch_{i}") for i in range(1, 6)])
        train_x, train_y = np.concatenate(xs), np.concatenate(ys)
        test_x, test_y = load_batch("test_batch")
        return DataBundle(train_x, train_y, test_x, test_y, "cifar10")
    from distributed_active_learning_tpu.data.synthetic import make_synthetic_images

    # One draw, then split: the class prototypes are sampled from the key, so
    # separate train/test draws would define two unrelated labelings (test
    # accuracy pinned at chance no matter the learner).
    n_train, n_test = _standin_sizes(cfg)
    # Difficulty (r4 recalibration, v5e sweep): multi-mode shifted prototypes
    # + geometric class imbalance so a SmallCNN's accuracy-vs-labels curve
    # rises across >=20 window-100 rounds instead of saturating by round 8.
    # The difficulty must come from STRUCTURE (mode coverage, shift orbits,
    # rare classes), not additive noise: at noise=3.0 the pool is
    # noise-dominated and entropy acquisition chases the noisiest points —
    # every strategy loses to random (the classic noise-seeking pathology).
    # At 2.2 the uncertainty signal tracks boundaries/rare modes instead:
    # BADGE/entropy beat random by ~7 points final accuracy while the curve
    # still rises at 2020 labels (benches/standin_calibration.py — "passive"
    # and "ordering" modes reproduce both halves of this tuning).
    x, y = make_synthetic_images(
        jax.random.key(cfg.seed), n_train + n_test,
        noise=2.2, modes_per_class=4, max_shift=8, imbalance=0.30,
    )
    return DataBundle(
        np.asarray(x[:n_train]), np.asarray(y[:n_train]),
        np.asarray(x[n_train:]), np.asarray(y[n_train:]), "cifar10",
    )


@register_dataset("agnews")
def _agnews(cfg: DataConfig) -> DataBundle:
    """AG-News token pool (BASELINE.json config 5: AG-News, encoder, BatchBALD).

    With ``cfg.path``: a directory holding ``train.csv``/``test.csv`` in the
    AG-News format ('"class","title","description"', class 1..4), hashed to
    token ids (data/text.py). Without a path: a synthetic topic pool at the
    exact shape ([n, 64] int32 ids, 4 classes).
    """
    vocab, max_len = 4096, 64
    if cfg.path is not None:
        import os

        from distributed_active_learning_tpu.data.text import load_agnews_csv

        train_x, train_y = load_agnews_csv(
            os.path.join(cfg.path, "train.csv"), vocab, max_len
        )
        test_x, test_y = load_agnews_csv(
            os.path.join(cfg.path, "test.csv"), vocab, max_len
        )
        return DataBundle(train_x, train_y, test_x, test_y, "agnews", vocab_size=vocab)
    from distributed_active_learning_tpu.data.synthetic import make_synthetic_tokens

    # Difficulty (r4 recalibration): thinner topical evidence, neighbouring
    # topics share vocabulary, geometric class imbalance — so the encoder's
    # curve rises across >=20 window-50 rounds instead of saturating early.
    # Same structure-over-noise principle as the cifar10 stand-in: at
    # topic_frac=0.35/overlap=0.5 the pool was token-noise-dominated and
    # BatchBALD tied random; at these settings it leads (+5 points at the
    # curve midpoint — benches/standin_calibration.py "ordering" mode).
    hard = dict(topic_frac=0.4, overlap=0.25, imbalance=0.35)
    n_train, n_test = _standin_sizes(cfg)
    k_tr, k_te = jax.random.split(jax.random.key(cfg.seed))
    tx, ty = make_synthetic_tokens(
        k_tr, n_train, vocab_size=vocab, max_len=max_len, **hard
    )
    ex, ey = make_synthetic_tokens(
        k_te, n_test, vocab_size=vocab, max_len=max_len, **hard
    )
    return DataBundle(
        np.asarray(tx), np.asarray(ty), np.asarray(ex), np.asarray(ey),
        "agnews", vocab_size=vocab,
    )


@register_dataset("gaussian_unbalanced")
def _gaussian_unbalanced(cfg: DataConfig) -> DataBundle:
    """Simulated unbalanced clouds (classes/test.py:150-187): two random
    Gaussian clouds, class-1 prior uniform in [10%, 90%], test set 10x the
    pool. Each seed draws a fresh geometry — the distribution the LAL
    regressor's Monte-Carlo training data comes from, i.e. LAL's home turf
    (Konyushkova et al. build LAL for exactly these unbalanced problems)."""
    key = jax.random.key(cfg.seed)
    n = cfg.n_samples or 1000
    train_x, train_y, test_x, test_y = synthetic.make_gaussian_unbalanced(key, n)
    bundle = DataBundle(
        np.asarray(train_x), np.asarray(train_y),
        np.asarray(test_x), np.asarray(test_y), "gaussian_unbalanced",
    )
    return _standardize(bundle, cfg)
