"""ctypes binding for the native C++ data loader (``cpp/loader.cpp``).

The reference's IO substrate is HDFS text reads executed by JVM workers
(``sc.textFile``, ``classes/dataset.py:254``); here the equivalent native layer
is a small C++ parser compiled to a shared library and reached via ctypes. All
entry points return ``None`` when the library is unavailable so callers fall
back to the pure-numpy path (which doubles as the correctness oracle in tests).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

_LIB = None
_LIB_TRIED = False


def _find_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates = [
        os.path.join(here, "..", "cpp", "build", "libdal_loader.so"),
        os.path.join(here, "cpp", "libdal_loader.so"),
    ]
    env = os.environ.get("DAL_TPU_LOADER_LIB")
    if env:
        candidates.insert(0, env)
    for cand in candidates:
        cand = os.path.abspath(cand)
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
            except OSError:
                continue
            # int dal_parse_matrix(const char* path, int is_csv, float* out,
            #                      long capacity, long* n_rows, long* n_cols)
            lib.dal_parse_matrix.restype = ctypes.c_int
            lib.dal_parse_matrix.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
            ]
            lib.dal_count_dims.restype = ctypes.c_int
            lib.dal_count_dims.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
            ]
            _LIB = lib
            return _LIB
    return None


def _parse(path: str, is_csv: bool) -> Optional[np.ndarray]:
    lib = _find_lib()
    if lib is None or not os.path.exists(path):
        return None
    n_rows = ctypes.c_long(0)
    n_cols = ctypes.c_long(0)
    rc = lib.dal_count_dims(path.encode(), int(is_csv), ctypes.byref(n_rows), ctypes.byref(n_cols))
    if rc != 0 or n_rows.value <= 0 or n_cols.value <= 0:
        return None
    expect = (n_rows.value, n_cols.value)
    out = np.empty(expect, dtype=np.float32)
    rc = lib.dal_parse_matrix(
        path.encode(),
        int(is_csv),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size,
        ctypes.byref(n_rows),
        ctypes.byref(n_cols),
    )
    if rc != 0 or (n_rows.value, n_cols.value) != expect:
        # dims changed between the count and parse passes (file mutated
        # mid-read): the packed buffer would not match the array strides.
        return None
    return out


def try_load_matrix(path: str, sep: Optional[str]) -> Optional[np.ndarray]:
    """Native parse of a whitespace-separated dense matrix; None if unavailable.

    Only ``sep=None`` (any-whitespace) is handled natively: an explicit
    ``sep=" "`` means numpy's strict single-space semantics, which the C
    tokenizer does not reproduce — let the fallback handle it so accepted
    inputs don't depend on whether the .so is built.
    """
    if sep is not None:
        return None
    return _parse(path, is_csv=False)


def try_load_csv_label_last(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native parse of a header+quoted-label CSV; None if unavailable."""
    mat = _parse(path, is_csv=True)
    if mat is None:
        return None
    return np.ascontiguousarray(mat[:, :-1]), mat[:, -1].astype(np.int32)
