"""Text tokenization and the AG-News CSV format.

The reference has no text pipeline at all (its pools are tabular floats);
BASELINE.json config 5 ("AG-News, BERT encoder, BatchBALD") introduces one.
TPU-first constraints shape the design: token-id pools must be dense, fixed-
length ``int32 [n, max_len]`` arrays (static shapes for the jitted learner),
so tokenization is a *hashing* tokenizer — no vocabulary file, no OOV path,
every token maps to ``1 + (hash(token) % (vocab_size - 1))`` with 0 reserved
for padding. Hash collisions trade a little accuracy for a pipeline with zero
host-side state, the standard feature-hashing trick.
"""

from __future__ import annotations

import csv
import hashlib
import re
from typing import List, Sequence, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"[a-z0-9']+")


def tokenize(text: str) -> List[str]:
    """Lowercase word tokens (alnum + apostrophe runs)."""
    return _TOKEN_RE.findall(text.lower())


def _hash_token(token: str, vocab_size: int) -> int:
    # blake2b for a stable cross-process hash (Python's hash() is salted).
    h = int.from_bytes(hashlib.blake2b(token.encode(), digest_size=8).digest(), "little")
    return 1 + h % (vocab_size - 1)


def hash_encode(
    texts: Sequence[str], vocab_size: int = 4096, max_len: int = 64
) -> np.ndarray:
    """Encode texts to ``int32 [n, max_len]`` token ids (0 = padding)."""
    out = np.zeros((len(texts), max_len), dtype=np.int32)
    for i, t in enumerate(texts):
        toks = tokenize(t)[:max_len]
        for j, tok in enumerate(toks):
            out[i, j] = _hash_token(tok, vocab_size)
    return out


def load_agnews_csv(
    path: str, vocab_size: int = 4096, max_len: int = 64
) -> Tuple[np.ndarray, np.ndarray]:
    """Load the AG-News CSV format: ``"class","title","description"`` rows,
    class in 1..4. Returns ``(ids [n, max_len] int32, labels [n] int32)``
    with labels remapped to 0..3 (like the striatum −1→0 remap,
    ``classes/dataset.py:259``)."""
    ids_texts: List[str] = []
    labels: List[int] = []
    with open(path, newline="", encoding="utf-8") as f:
        for row in csv.reader(f):
            if not row:
                continue
            cls = int(row[0])
            if not 1 <= cls <= 4:
                raise ValueError(f"AG-News class out of range: {cls}")
            labels.append(cls - 1)
            ids_texts.append(" ".join(row[1:]))
    return hash_encode(ids_texts, vocab_size, max_len), np.asarray(labels, np.int32)
