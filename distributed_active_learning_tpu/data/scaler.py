"""Feature standardization as pure functions.

Replaces MLlib's ``StandardScaler(withMean=True, withStd=True)``
(``classes/dataset.py:163-165``, ``:257``) with a stateless fit/transform pair.
MLlib computes the *sample* standard deviation (ddof=1); we match that so
accuracy parity against the reference's preprocessed features holds. Zero-variance
columns divide by 1 instead of 0 (MLlib leaves them at 0 after centering; same
net effect).

Works on numpy or jax arrays (pure jnp ops) so it can live inside a jitted
pipeline when the pool is device-resident.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax.numpy as jnp
import numpy as np

Array = Union[np.ndarray, jnp.ndarray]


class StandardScalerState(NamedTuple):
    mean: Array  # [d]
    std: Array   # [d], sample std (ddof=1), zeros replaced by 1


def fit_standard_scaler(x: Array, with_mean: bool = True, with_std: bool = True) -> StandardScalerState:
    """Fit mean/std over rows of ``x`` [n, d]."""
    xp = jnp if isinstance(x, jnp.ndarray) else np
    mean = xp.mean(x, axis=0)
    n = x.shape[0]
    if n > 1:
        std = xp.std(x, axis=0, ddof=1)
    else:
        std = xp.zeros_like(mean)
    std = xp.where(std == 0, xp.ones_like(std), std)
    if not with_mean:
        mean = xp.zeros_like(mean)
    if not with_std:
        std = xp.ones_like(std)
    return StandardScalerState(mean=mean, std=std)


def transform(state: StandardScalerState, x: Array) -> Array:
    return (x - state.mean) / state.std


def fit_transform(x: Array, with_mean: bool = True, with_std: bool = True) -> Array:
    return transform(fit_standard_scaler(x, with_mean, with_std), x)
