"""Synthetic dataset generators.

TPU-native (jax.random, pure-functional) equivalents of the reference's
generators:

- XOR / checkerboard-parity data: ``final_thesis/dataset/xor_generator.py:3-8``
  (d-dimensional two-class XOR over quadrant parity; the reference writes
  N=100000, D=100 to ``xor.txt`` at ``:21-23``).
- Checkerboard 2x2 / 4x4 / rotated fixtures: the 1000-row files under
  ``lal_direct_mllib_implementation/data/`` (2 features in [0,1], binary label by
  cell parity; rotated variant is the same board rotated 45 degrees).
- Simulated unbalanced Gaussians: ``classes/test.py:150-187`` — two Gaussian
  clouds with random means/covariances, class-1 prior drawn from [10%, 90%],
  test set 10x the train size. Used to synthesize LAL-regressor training data.
- Dense random matrices for similarity benchmarks: ``final_thesis/sqgen.py``.

All generators take an explicit PRNG key and return numpy-compatible jnp arrays.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _class_labels(
    key: jax.Array, n: int, n_classes: int, imbalance: float
) -> jnp.ndarray:
    """Labels with a geometric class prior ``p_k \\propto (1-imbalance)^k``
    (``imbalance=0`` = balanced uniform). Rare classes dominate late-curve
    error, which is where uncertainty-aware acquisition separates from random
    — the shared difficulty knob of the deep-AL stand-in pools."""
    if imbalance > 0.0:
        logp = jnp.arange(n_classes) * jnp.log1p(-imbalance)
        return jax.random.categorical(key, logp, shape=(n,))
    return jax.random.randint(key, (n,), 0, n_classes)


def make_xor(key: jax.Array, n: int, d: int = 2) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """d-dimensional XOR data: x ~ U[0,1]^d, label = parity of per-dim half-space bits.

    Behavioral twin of ``xor_generator.get_xor_data`` (xor_generator.py:3-8).
    Returns (features [n, d] float32, labels [n] int32 in {0, 1}).
    """
    x = jax.random.uniform(key, (n, d), dtype=jnp.float32)
    bits = (x > 0.5).astype(jnp.int32)
    labels = jnp.sum(bits, axis=1) % 2
    return x, labels.astype(jnp.int32)


def make_checkerboard(
    key: jax.Array, n: int, grid: int = 2
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """2-D checkerboard data on a ``grid x grid`` board over [0,1]^2.

    Fixture-equivalent of ``data/checkerboard{2x2,4x4}_train.txt`` (2 features +
    binary label by cell parity, loaded at ``classes/dataset.py:149-210``).
    """
    x = jax.random.uniform(key, (n, 2), dtype=jnp.float32)
    cells = jnp.floor(x * grid).astype(jnp.int32)
    labels = (cells[:, 0] + cells[:, 1]) % 2
    return x, labels.astype(jnp.int32)


def make_rotated_checkerboard(
    key: jax.Array, n: int, grid: int = 2, angle: float = 0.7853981633974483
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Checkerboard rotated by ``angle`` (default 45deg) about the board center.

    Fixture-equivalent of ``DatasetRotatedCheckerboard2x2``
    (``classes/dataset.py:217-238``).
    """
    x = jax.random.uniform(key, (n, 2), dtype=jnp.float32)
    c, s = jnp.cos(angle), jnp.sin(angle)
    centered = x - 0.5
    un_rot = jnp.stack(
        [c * centered[:, 0] + s * centered[:, 1], -s * centered[:, 0] + c * centered[:, 1]],
        axis=1,
    ) + 0.5
    cells = jnp.floor(un_rot * grid).astype(jnp.int32)
    labels = (cells[:, 0] + cells[:, 1]) % 2
    return x, labels.astype(jnp.int32)


def make_gaussian_unbalanced(
    key: jax.Array, n_train: int, dim: int = 2, test_factor: int = 10
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Two random Gaussian clouds with a random class imbalance in [10%, 90%].

    Behavioral twin of ``DatasetSimulatedUnbalanced`` (``classes/test.py:150-187``):
    random means/covariances per class, class-1 prior uniform in [0.1, 0.9], test
    set ``test_factor``x the train size drawn from the same mixture. This is the
    generator the reference uses to synthesize LAL-regressor training data.

    Returns (train_x, train_y, test_x, test_y).
    """
    k_prior, k_mean0, k_mean1, k_cov0, k_cov1, k_tr, k_te = jax.random.split(key, 7)
    p1 = jax.random.uniform(k_prior, (), minval=0.1, maxval=0.9)
    mean0 = jax.random.uniform(k_mean0, (dim,), minval=-1.0, maxval=1.0)
    mean1 = jax.random.uniform(k_mean1, (dim,), minval=-1.0, maxval=1.0)

    def _rand_cov(k):
        a = jax.random.uniform(k, (dim, dim), minval=-1.0, maxval=1.0)
        return a @ a.T + 0.1 * jnp.eye(dim)

    cov0, cov1 = _rand_cov(k_cov0), _rand_cov(k_cov1)
    chol0, chol1 = jnp.linalg.cholesky(cov0), jnp.linalg.cholesky(cov1)

    def _sample(k, n):
        k_lab, k_pts = jax.random.split(k)
        y = (jax.random.uniform(k_lab, (n,)) < p1).astype(jnp.int32)
        z = jax.random.normal(k_pts, (n, dim), dtype=jnp.float32)
        x0 = z @ chol0.T + mean0
        x1 = z @ chol1.T + mean1
        x = jnp.where(y[:, None] == 1, x1, x0)
        return x.astype(jnp.float32), y

    train_x, train_y = _sample(k_tr, n_train)
    test_x, test_y = _sample(k_te, n_train * test_factor)
    return train_x, train_y, test_x, test_y


def make_blobs(
    key: jax.Array, n: int, d: int = 4, n_classes: int = 4, spread: float = 2.2
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """C-class Gaussian blobs — the multiclass tabular pool the forest loop
    shares with the neural loop (the reference is binary-only; this serves
    the C-class generalization of its strategies). Class means are a FIXED
    ``spread``-scaled lattice (axis-aligned for c <= d, deterministic
    otherwise) so independently-keyed train/test draws come from the same
    mixture (the ``_synth`` split contract); unit-variance clouds, balanced
    labels.
    """
    k_lab, k_pts = jax.random.split(key)
    if n_classes <= d:
        means = spread * jnp.eye(n_classes, d, dtype=jnp.float32)
    else:
        means = spread * jax.random.normal(
            jax.random.key(0), (n_classes, d), dtype=jnp.float32
        )
    y = jax.random.randint(k_lab, (n,), 0, n_classes)
    z = jax.random.normal(k_pts, (n, d), dtype=jnp.float32)
    x = z + means[y]
    return x.astype(jnp.float32), y.astype(jnp.int32)


def make_striatum_like(
    key: jax.Array,
    n: int,
    d: int = 50,
    pos_frac: float = 0.25,
    decay: float = 0.5,
    label_noise: float = 0.01,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Striatum-shaped tabular pool: high-dim features, an oblique boundary
    with a decaying feature-importance spectrum, minority positive class.

    The reference's headline curves (BASELINE.md rows 1-6) are on its
    striatum EM dataset — 10k pool, high-dim image statistics, membrane
    (minority) vs non-membrane — which lives only on its HDFS cluster
    (``final_thesis/uncertainty_sampling.py:37-40``). This generator mirrors
    that *task shape* without the checkerboard geometry whose batch-US
    pathology inverted the window-50/100 curves in the r3/r4 10k runs:

    - ``x ~ N(0, I_d)`` with labels from one fixed oblique hyperplane
      ``x . w > t`` — axis-aligned tree splits can only approximate it, so
      accuracy rises gradually over hundreds of labels (no early saturation);
    - ``w_j ∝ decay^j`` — a few strong features and a long informative tail,
      like image-statistic spectra. ``decay=0.5`` puts the forest's curve in
      the reference's striatum range (≈86% at 100 labels → 90% at full
      budget; the reference logs 85% at 10 → 91.5%): the head features make
      the base task easy fast, the tail is boundary refinement — exactly the
      regime where its US runs beat random at every window;
    - ``t`` set analytically so positives are a ``pos_frac`` minority
      (score is ``N(0, ||w||²)``, membranes are the rare class);
    - ``label_noise`` symmetric flips bound attainable accuracy below 100%.

    Calibration protocol (r5, guarding against the r4 tuned-on-chip
    critique): decay/noise/tree-count were selected on probe seeds 0-2 only;
    the committed ``results/striatum_like_10k_*`` sextet runs on HELD-OUT
    seed 3, with seed 4 as a second unseen check (results/README.md). The
    scale runs use 20 trees: with 10 the vote granularity is 11 levels, so
    window-10 top-k selects among mass score-ties and the US margin is seed
    noise; 20 trees doubles the granularity and the margin is stable.

    Labels are a key-independent function of x up to the per-draw noise
    flips, satisfying the ``_synth`` train/test split contract. Structure
    (w, t) is deterministic across keys — one fixed dataset distribution,
    like striatum itself.
    """
    w = decay ** jnp.arange(d, dtype=jnp.float32)
    # Fixed sign pattern so the boundary is oblique in every coordinate,
    # not monotone in all features at once.
    w = w * jnp.where(jnp.arange(d) % 3 == 1, -1.0, 1.0)
    from jax.scipy.stats import norm

    t = jnp.linalg.norm(w) * norm.ppf(1.0 - pos_frac)
    k_x, k_flip = jax.random.split(key)
    x = jax.random.normal(k_x, (n, d), dtype=jnp.float32)
    y = (x @ w > t).astype(jnp.int32)
    if label_noise > 0.0:
        flip = jax.random.uniform(k_flip, (n,)) < label_noise
        y = jnp.where(flip, 1 - y, y)
    return x, y


def drift_transform(
    x: jnp.ndarray,
    step,
    kind: str = "mean_shift",
    rate: float = 0.1,
    direction: jnp.ndarray = None,
) -> jnp.ndarray:
    """Apply ``step`` units of distribution drift to a feature batch.

    The shared drift schedule of the scenario engine and the serving drift
    stream: ``mean_shift`` translates along ``direction`` (unit vector;
    defaults to the first axis) by ``rate`` per step; ``rotation`` rotates
    the first two feature coordinates by ``rate`` radians per step about
    the origin. ``step`` may be a traced scalar (the AL round counter) or a
    host int (the stream's block index) — one formula either way.
    """
    t = jnp.asarray(step, jnp.float32)
    if kind == "rotation":
        theta = rate * t
        c, s = jnp.cos(theta), jnp.sin(theta)
        x0, x1 = x[..., 0], x[..., 1]
        return x.at[..., 0].set(c * x0 - s * x1).at[..., 1].set(s * x0 + c * x1)
    if kind != "mean_shift":
        raise ValueError(f"unknown drift kind {kind!r}; 'mean_shift' or 'rotation'")
    d = x.shape[-1]
    if direction is None:
        direction = jnp.zeros((d,), jnp.float32).at[0].set(1.0)
    return x + (rate * t) * direction


def make_drifting_stream(
    key: jax.Array,
    n_blocks: int,
    block_rows: int,
    d: int = 4,
    kind: str = "mean_shift",
    rate: float = 0.25,
    warm_blocks: int = 0,
):
    """A drifting ingest stream for the serving scenario tests/benches.

    Yields ``n_blocks`` blocks of ``(x [block_rows, d], y [block_rows])``
    drawn from the :func:`make_blobs`-style two-class mixture, where block
    ``i`` past the first ``warm_blocks`` is drifted by ``i - warm_blocks``
    steps of :func:`drift_transform` — the synthetic stream that pushes a
    service's traffic past its cold-start quantile edges (the bin-edge
    refresh trigger in serving/tenants.py). Labels stay a function of the
    PRE-drift coordinates: the world moves under the model, exactly the
    covariate-shift regime the refresh exists for.
    """
    blocks = []
    for i in range(n_blocks):
        k_i = jax.random.fold_in(key, i)
        k_lab, k_pts = jax.random.split(k_i)
        y = jax.random.randint(k_lab, (block_rows,), 0, 2)
        z = jax.random.normal(k_pts, (block_rows, d), dtype=jnp.float32)
        x = z + 2.0 * y[:, None].astype(jnp.float32)
        step = max(i - warm_blocks, 0)
        if step > 0:
            x = drift_transform(x, step, kind=kind, rate=rate)
        blocks.append((x.astype(jnp.float32), y.astype(jnp.int32)))
    return blocks


def make_random_matrix(key: jax.Array, n: int, d: int) -> jnp.ndarray:
    """Dense random matrix like ``sqgen.py`` (vectors_50000x1000.txt) /
    ``cosine_similarity.py:26`` (3000x500 random vectors)."""
    return jax.random.uniform(key, (n, d), dtype=jnp.float32)


def make_synthetic_images(
    key: jax.Array,
    n: int,
    n_classes: int = 10,
    hw: int = 32,
    channels: int = 3,
    noise: float = 6.0,
    modes_per_class: int = 1,
    max_shift: int = 0,
    imbalance: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """CIFAR-shaped stand-in pool: ``[n, hw, hw, c] float32`` + labels.

    Each class is a smooth random "prototype" image (low-frequency pattern per
    class) plus per-sample noise, so a small CNN can genuinely learn the task
    while shapes/dtypes match CIFAR-10 exactly (BASELINE.json config 4). Used
    when no local CIFAR files are supplied — the real batches load via
    data/datasets.py:cifar10 with cfg.path.

    The prototypes are drawn from ``key``: train/test splits must come from
    ONE call (slice the result), or their labelings are unrelated.

    Difficulty knobs (defaults reproduce the single-prototype pool):

    - ``modes_per_class``: each class is a *mixture* of this many independent
      prototypes. A learner must see samples from every mode of every class,
      so the learning curve stretches over thousands of labels instead of
      saturating once the single matched filter is found, and batch-diverse
      acquisition (BADGE/coreset) has genuine mode-coverage work to do.
    - ``max_shift``: each sample's prototype is circularly rolled by a random
      per-sample offset in [-max_shift, max_shift]^2 before noise. The class
      manifold becomes a shift orbit rather than a point — a stride-conv CNN
      has to learn the invariance from data, like real image classes.
    - ``imbalance``: geometric class prior ``p_k \\propto (1-imbalance)^k``
      (0 = balanced). Rare classes dominate late-curve error, which is where
      uncertainty-aware acquisition separates from random.
    """
    k_proto, k_noise, k_lab, k_mode, k_shift = jax.random.split(key, 5)
    # low-frequency prototypes: upsampled 4x4 random patterns, one per mode
    coarse = jax.random.normal(k_proto, (n_classes, modes_per_class, 4, 4, channels))
    protos = jax.image.resize(
        coarse, (n_classes, modes_per_class, hw, hw, channels), "bilinear"
    )
    y = _class_labels(k_lab, n, n_classes, imbalance)
    mode = jax.random.randint(k_mode, (n,), 0, modes_per_class)
    base = protos[y, mode]
    if max_shift > 0:
        shifts = jax.random.randint(k_shift, (n, 2), -max_shift, max_shift + 1)
        base = jax.vmap(lambda img, s: jnp.roll(img, s, axis=(0, 1)))(base, shifts)
    x = base + noise * jax.random.normal(k_noise, (n, hw, hw, channels))
    return x.astype(jnp.float32), y.astype(jnp.int32)


def make_synthetic_tokens(
    key: jax.Array,
    n: int,
    n_classes: int = 4,
    vocab_size: int = 4096,
    max_len: int = 64,
    topic_frac: float = 0.7,
    overlap: float = 0.0,
    imbalance: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """AG-News-shaped stand-in pool: ``[n, max_len] int32`` token ids + labels.

    Each class draws tokens from its own slice of the vocabulary (plus shared
    "stopword" ids), giving a learnable topic-classification signal at the
    exact shape of the hashed AG-News pipeline (data/text.py).

    Difficulty knobs (defaults reproduce the original pool):

    - ``topic_frac``: fraction of positions carrying topical tokens (the rest
      are uniform "stopwords"). Lowering it thins the per-document evidence.
    - ``overlap``: each class's token span is widened to spill this fraction
      into its neighbours' spans, so adjacent topics share vocabulary and the
      decision needs distributional rather than single-token evidence.
    - ``imbalance``: geometric class prior ``p_k \\propto (1-imbalance)^k``
      (0 = balanced); rare topics dominate late-curve error.
    """
    k_lab, k_tok, k_stop, k_mix = jax.random.split(key, 4)
    y = _class_labels(k_lab, n, n_classes, imbalance)
    span = (vocab_size - 1) // n_classes
    # Cap the widened span at the whole vocabulary: past that point (large
    # overlap at small n_classes) the classes just share all tokens, and an
    # uncapped width would push the clip's upper bound below its lower bound
    # — emitting the reserved padding id 0 and negative ids.
    wide = min(int(span * (1.0 + 2.0 * overlap)), vocab_size - 1)
    # Clip the *window start* so every class keeps a full-width span inside
    # the vocabulary; clamping the drawn ids instead would pile the edge
    # classes' spillover onto a single boundary token — a one-token class
    # giveaway that defeats the overlap knob.
    lo = jnp.clip(1 + y[:, None] * span - int(span * overlap), 1, vocab_size - wide)
    topic = lo + jax.random.randint(k_tok, (n, max_len), 0, max(wide, 1))
    stop = 1 + jax.random.randint(k_stop, (n, max_len), 0, vocab_size - 1)
    is_topic = jax.random.uniform(k_mix, (n, max_len)) < topic_frac
    ids = jnp.where(is_topic, topic, stop)
    return ids.astype(jnp.int32), y.astype(jnp.int32)
