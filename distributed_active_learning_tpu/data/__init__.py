"""Data layer: file-format parsers, standardization, synthetic generators, registry.

Replaces the reference's L0 (HDFS text files) + L3 (Dataset classes / index
bookkeeping) layers (SURVEY.md §1) with host-side array loading feeding dense
device-resident pools.
"""

from distributed_active_learning_tpu.data.formats import (
    load_labeled_text,
    load_credit_card_csv,
    load_triplet_text,
    write_triplet_text,
)
from distributed_active_learning_tpu.data.scaler import (
    StandardScalerState,
    fit_standard_scaler,
    transform,
    fit_transform,
)
from distributed_active_learning_tpu.data.synthetic import (
    make_xor,
    make_checkerboard,
    make_rotated_checkerboard,
    make_gaussian_unbalanced,
    make_random_matrix,
)
from distributed_active_learning_tpu.data.datasets import (
    DataBundle,
    get_dataset,
    register_dataset,
    available_datasets,
)

__all__ = [
    "load_labeled_text",
    "load_credit_card_csv",
    "load_triplet_text",
    "write_triplet_text",
    "StandardScalerState",
    "fit_standard_scaler",
    "transform",
    "fit_transform",
    "make_xor",
    "make_checkerboard",
    "make_rotated_checkerboard",
    "make_gaussian_unbalanced",
    "make_random_matrix",
    "DataBundle",
    "get_dataset",
    "register_dataset",
    "available_datasets",
]
