"""Scenario-engine primitives: the deterministic transforms every driver
shares.

Design rules (what keeps scenario cells comparable across drivers):

- **Scenario randomness is keyed separately from the experiment.** Flip
  masks, cost vectors, and the drift direction derive from
  ``ScenarioConfig.seed`` (plus the cell's experiment seed / dataset name
  where per-cell variation is wanted) — never from ``PoolState.key`` — so a
  scenario=none cell's PRNG stream is untouched and stays bit-identical to
  the pre-scenario code.

- **Transforms are pure functions of (config, static identity).** The same
  formula runs host-side (serial setup) and in-trace (the grid chunk), so a
  grid cell and its serial twin see identical flips/costs/drift — the
  serial-vs-grid bit-identity tests lean on this.

- **Inactive means absent.** Every helper returns the identity (all-False
  masks, unit costs, untransformed arrays) for an inactive scenario, and
  the drivers skip the scenario plumbing entirely when no scenario is
  active, so the clean path's traced programs never change.
"""

from __future__ import annotations

import zlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_active_learning_tpu.config import ScenarioConfig

SCENARIO_KINDS = ("none", "noisy_oracle", "cost_budget", "rare_event", "drift")

#: Domain separator for scenario keys so a scenario seed equal to an
#: experiment seed still draws an unrelated stream.
_SALT = 0x5CE7A410


def scenario_from_name(name: str, base: Optional[ScenarioConfig] = None) -> ScenarioConfig:
    """A :class:`ScenarioConfig` of kind ``name``, carrying ``base``'s knobs.

    The CLI's ``--scenarios a,b,c`` axis shares one knob set (--flip-prob,
    --cost-budget, ...) across entries; this swaps only the kind.
    """
    import dataclasses

    if name not in SCENARIO_KINDS:
        raise ValueError(f"unknown scenario {name!r}; one of {SCENARIO_KINDS}")
    base = base if base is not None else ScenarioConfig()
    return dataclasses.replace(base, kind=name)


def validate_scenario(scn: ScenarioConfig, *, strategy=None, max_rounds=None) -> None:
    """Refuse unservable scenario configurations LOUDLY at run start.

    ``strategy`` (a :class:`~strategies.base.Strategy`, optional) gates the
    knapsack's score-direction assumption; ``max_rounds`` gates the
    abstaining oracle's termination (an all-abstain oracle never reaches a
    label budget, so an unbounded run would never stop — by design it never
    terminates EARLY, so the round quota is the only stop it has).
    """
    if scn.kind not in SCENARIO_KINDS:
        raise ValueError(f"unknown scenario kind {scn.kind!r}; one of {SCENARIO_KINDS}")
    if not scn.active:
        return
    if scn.kind == "noisy_oracle":
        if not (0.0 <= scn.flip_prob <= 1.0 and 0.0 <= scn.abstain_prob <= 1.0):
            raise ValueError(
                f"noisy_oracle needs flip_prob/abstain_prob in [0, 1], got "
                f"{scn.flip_prob}/{scn.abstain_prob}"
            )
        if scn.flip_prob == 0.0 and scn.abstain_prob == 0.0:
            raise ValueError(
                "noisy_oracle with flip_prob=0 and abstain_prob=0 is the "
                "clean oracle; use scenario 'none' or set a probability"
            )
        if scn.abstain_prob > 0.0 and max_rounds is None:
            raise ValueError(
                "an abstaining oracle may never reach the label budget "
                "(abstained picks re-enter the pool), so the run needs "
                "max_rounds as its stop; set --rounds"
            )
    elif scn.kind == "cost_budget":
        if scn.cost_budget <= 0.0:
            raise ValueError("cost_budget scenario needs cost_budget > 0")
        if scn.cost_spread < 0.0:
            raise ValueError(f"cost_spread must be >= 0, got {scn.cost_spread}")
        if strategy is not None and not strategy.higher_is_better:
            raise ValueError(
                f"knapsack selection ranks by score-per-cost and assumes "
                f"nonnegative higher-is-better scores; strategy "
                f"{strategy.name!r} selects ascending — use an "
                "entropy/density-family strategy for cost_budget"
            )
    elif scn.kind == "rare_event":
        if scn.rare_class < 0:
            raise ValueError(f"rare_class must be >= 0, got {scn.rare_class}")
    elif scn.kind == "drift":
        if scn.drift_rate <= 0.0:
            raise ValueError("drift scenario needs drift_rate > 0")
        if scn.drift_kind not in ("mean_shift", "rotation"):
            raise ValueError(
                f"unknown drift_kind {scn.drift_kind!r}; "
                "'mean_shift' or 'rotation'"
            )


def _base_key(scn: ScenarioConfig) -> jax.Array:
    return jax.random.key(np.uint32(scn.seed ^ _SALT))


def dataset_fold(name: str) -> int:
    """Stable per-dataset fold constant (crc32 of the name), so the serial
    driver and the grid derive identical per-dataset scenario draws."""
    return zlib.crc32(str(name).encode()) & 0x7FFFFFFF


def flip_mask(scn: ScenarioConfig, cell_seed: int, n: int) -> jnp.ndarray:
    """The per-experiment label-flip mask ``[n] bool``.

    Drawn ONCE per (scenario seed, experiment seed) so repeated oracle
    queries of one point are consistent — a flipped point is flipped for the
    whole experiment, like a systematically-wrong annotator. All-False when
    the scenario has no flips.
    """
    if scn.kind != "noisy_oracle" or scn.flip_prob <= 0.0:
        return jnp.zeros((n,), dtype=bool)
    key = jax.random.fold_in(_base_key(scn), int(cell_seed))
    return jax.random.uniform(key, (n,)) < scn.flip_prob


def flip_mask_block(
    scn: ScenarioConfig,
    cell_seed: int,
    n_pool: int,
    shard_index: jnp.ndarray,
    rows: int,
) -> jnp.ndarray:
    """Shard-local view of :func:`flip_mask`: the ``[rows]`` slice owned by
    the shard at data-axis index ``shard_index`` (contiguous block
    ``[shard_index * rows, (shard_index + 1) * rows)``).

    Keyed by GLOBAL row index: each shard draws the full ``[n_pool]``
    bernoulli vector locally (pure compute, ZERO collectives — the draw is a
    counter-based function of the scenario key, identical on every shard)
    and slices its own rows, so the per-shard masks concatenate to the
    single-device :func:`flip_mask` bit-for-bit at any shard count. Flips
    run once per experiment at setup, so the pool-scale local draw is a
    one-time cost, never a per-round one.
    """
    full = flip_mask(scn, cell_seed, n_pool)
    start = jnp.asarray(shard_index, jnp.int32) * rows
    return jax.lax.dynamic_slice(full, (start,), (rows,))


def abstain_draw(scn: ScenarioConfig, abstain_key, shape) -> jnp.ndarray:
    """The noisy oracle's keep-draw for a pick window: True where the oracle
    ANSWERS (probability ``1 - abstain_prob``).

    One spelling for the single-device reveal and the per-shard reveal
    (``runtime.state.reveal_masked_local``): the draw depends only on the
    replicated round key and the window shape, so every shard of a pod mesh
    computes the identical window-sized vector — the reveal scatter stays
    shard-local with no coordination. All-True for non-abstaining scenarios.
    """
    if scn.kind != "noisy_oracle" or scn.abstain_prob <= 0.0:
        return jnp.ones(shape, dtype=bool)
    return jax.random.uniform(abstain_key, shape) >= scn.abstain_prob


def apply_flips(oracle_y: jnp.ndarray, flips: jnp.ndarray, n_classes: int) -> jnp.ndarray:
    """Oracle labels with the flip mask applied (traced or host).

    Binary pools flip 0<->1; multiclass rotates to the next class — a
    deterministic wrong answer either way. With an all-False mask the
    ``where`` selects every original element, bit-identically.
    """
    if n_classes <= 2:
        return jnp.where(flips, 1 - oracle_y, oracle_y)
    return jnp.where(flips, (oracle_y + 1) % n_classes, oracle_y)


def make_costs(scn: ScenarioConfig, n: int, dataset_name: str = "") -> jnp.ndarray:
    """The per-point labeling-cost vector ``[n] float32`` in
    ``[1, 1 + cost_spread]``, keyed by (scenario seed, dataset name) so every
    seed of one dataset prices points identically (costs are a property of
    the data, not the experiment). Unit costs for non-cost scenarios.
    """
    if scn.kind != "cost_budget":
        return jnp.ones((n,), dtype=jnp.float32)
    key = jax.random.fold_in(_base_key(scn), dataset_fold(dataset_name))
    return 1.0 + scn.cost_spread * jax.random.uniform(key, (n,), dtype=jnp.float32)


def drift_apply(scn: ScenarioConfig, x: jnp.ndarray, round_: jnp.ndarray) -> jnp.ndarray:
    """The round-``round_`` drifted view of an evaluation batch (traced).

    One shared schedule implementation (``data.synthetic.drift_transform``
    — the serving drift stream uses the same formula, so the batch scenario
    and the service's synthetic traffic cannot drift apart): ``mean_shift``
    translates along a fixed unit direction drawn from the scenario seed at
    ``drift_rate`` per round; ``rotation`` rotates the first two feature
    coordinates by ``drift_rate`` radians per round. Identity for non-drift
    scenarios. ``round_`` may be a traced scalar (the scan carry's round
    counter) — the transform stays one fused affine op.
    """
    if scn.kind != "drift" or scn.drift_rate <= 0.0:
        return x
    from distributed_active_learning_tpu.data.synthetic import drift_transform

    direction = None
    if scn.drift_kind == "mean_shift":
        d = x.shape[-1]
        u = jax.random.normal(_base_key(scn), (d,), dtype=jnp.float32)
        direction = u / jnp.maximum(jnp.linalg.norm(u), 1e-6)
    return drift_transform(
        x, round_, kind=scn.drift_kind, rate=scn.drift_rate,
        direction=direction,
    )


def rare_recall(
    labeled_mask: jnp.ndarray,
    oracle_y: jnp.ndarray,
    valid_mask: jnp.ndarray,
    rare_class: int,
) -> jnp.ndarray:
    """Recall-at-budget (traced): the fraction of the pool's rare-class
    points labeled so far. The rare-event scenario's headline — at the
    budget stop this IS recall-at-budget; earlier rounds trace the curve.
    An empty rare class reports 0 rather than dividing by zero.
    """
    rare = (oracle_y == rare_class) & valid_mask
    total = jnp.sum(rare.astype(jnp.int32))
    found = jnp.sum((rare & labeled_mask).astype(jnp.int32))
    return found.astype(jnp.float32) / jnp.maximum(total, 1).astype(jnp.float32)
