"""Scenario engine: perturb the AL loop without forking it.

The reference paper exercises only the clean pool-based loop; production
labeling workloads are messier — oracles flip and abstain, labels have
costs, the interesting class is rare, and the incoming traffic drifts away
from the pool the model was fit on. This package lands those four families
as ONE engine wired into the existing drivers as config + grid axes
(``ScenarioConfig`` in config.py; ``run.py --scenario/--scenarios``;
``runtime.sweep.run_grid(scenarios=...)``), each scenario landing in the
layer it actually stresses:

- **noisy_oracle** — probabilistic reveal inside the jitted round
  (``runtime.state.reveal_masked`` grew an abstain mask; flips are a
  per-experiment mask from the scenario seed). Budget accounting counts
  REVEALED labels (the mask), never picks.
- **cost_budget** — a greedy knapsack selection kernel
  (``ops.topk.knapsack_top_k``): score-per-cost under a per-round spend
  cap, exact against a host reference.
- **rare_event** — recall-at-budget computed in-scan, riding
  ``RoundMetrics.rare_recall``.
- **drift** — the evaluation stream transforms per round index
  (``drift_apply``; generators in ``data/synthetic.py``); the serving twin
  is the bin-edge refresh in ``serving/tenants.py``.

Every scenario is OFF by default and, when off, leaves every traced program
byte-identical to the clean path — pinned by tests/test_scenarios.py.
"""

from distributed_active_learning_tpu.config import ScenarioConfig  # noqa: F401
from distributed_active_learning_tpu.scenarios.engine import (  # noqa: F401
    SCENARIO_KINDS,
    apply_flips,
    drift_apply,
    flip_mask,
    make_costs,
    rare_recall,
    scenario_from_name,
    validate_scenario,
)
