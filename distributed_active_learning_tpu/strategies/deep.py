"""Deep-AL acquisition functions over MC predictive samples.

These serve the neural configs (BASELINE.json 4-5: CIFAR CNN entropy/density,
AG-News BERT BatchBALD) — the reference itself has no neural models, so these
are capability extensions following the standard definitions:

- predictive entropy  H[E_s p]
- BALD                H[E_s p] - E_s H[p]  (mutual information I(y; w))
- BatchBALD           I(y_1..y_k; w) maximized greedily with an exact joint
                      over sampled posteriors (Kirsch et al. 2019), tracked as
                      a [S, configs] tensor while configs <= max_configs, then
                      MC-sampled (m configurations drawn from the exact joint,
                      importance-weighted joint entropies) so every later pick
                      stays joint-aware
- mean-std            mean over classes of std over posterior samples
- variation ratios    1 - max_c E_s p
- coreset             k-Center-Greedy batch diversity (Sener & Savarese 2018)
                      over pool features — the model-free diversity
                      counterpart of the uncertainty family
- BADGE               k-means++ seeding over hallucinated-label gradient
                      embeddings g_i ⊗ h_i (Ash et al. 2020), uncertainty x
                      diversity in one criterion

All are pure functions of ``probs_samples [S, n, C]`` (coreset: of the pool
features) and jit-friendly; the BatchBALD/coreset greedy loops have static
trip counts per window size.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

_EPS = 1e-12


def predictive_entropy(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """H of the posterior-mean predictive, per point [n] (nats)."""
    mean = jnp.mean(probs_samples, axis=0)
    return -jnp.sum(mean * jnp.log(mean + _EPS), axis=-1)


def expected_conditional_entropy(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """E_s H[p_s], per point [n] (nats)."""
    ent = -jnp.sum(probs_samples * jnp.log(probs_samples + _EPS), axis=-1)  # [S, n]
    return jnp.mean(ent, axis=0)


def bald_score(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """Mutual information between label and parameters, per point [n]."""
    return predictive_entropy(probs_samples) - expected_conditional_entropy(probs_samples)


def mean_std_score(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """Mean over classes of the per-class posterior std, per point [n]."""
    return jnp.mean(jnp.std(probs_samples, axis=0), axis=-1)


def variation_ratio(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """1 - max-class probability of the posterior mean, per point [n]."""
    return 1.0 - jnp.max(jnp.mean(probs_samples, axis=0), axis=-1)


def margin_score(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """Negative top-2 margin of the posterior mean, per point [n] (higher =
    smaller margin = more informative) — the multiclass companion of the
    binary ``abs(0.5 - p)`` rule the reference ranks ascending."""
    mean = jnp.mean(probs_samples, axis=0)
    top2 = jax.lax.top_k(mean, 2)[0]
    return -(top2[..., 0] - top2[..., 1])


def _joint_entropy_candidates(joint: jnp.ndarray, probs: jnp.ndarray) -> jnp.ndarray:
    """H of the joint (chosen-batch, candidate i) for every candidate.

    ``joint [S, J]``: per posterior sample, probability of each of the J class
    configurations of the already-chosen batch. ``probs [S, n, C]``. Returns
    ``[n]`` joint entropies of the extended batch.
    """
    S = joint.shape[0]
    q = jnp.einsum("sj,sic->ijc", joint, probs) / S  # [n, J, C]
    return -jnp.sum(q * jnp.log(q + _EPS), axis=(1, 2))


@functools.partial(
    jax.jit,
    static_argnames=("k", "max_configs", "candidate_pool", "mc_samples"),
)
def batchbald_select(
    probs_samples: jnp.ndarray,
    unlabeled_mask: jnp.ndarray,
    k: int,
    max_configs: int = 4096,
    candidate_pool: int = 512,
    mc_samples: int = 256,
    key: jax.Array | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy BatchBALD batch of ``k`` points — one compiled selection.

    The greedy loop is *unrolled under jit*: the joint's config count at pick
    ``t`` is the static ``C^t``, so every iteration has static shapes and the
    exact→MC switch (``C^t > max_configs``) resolves at trace time. One XLA
    launch replaces k host-driven rounds of device ops.

    Joint-entropy tracking has two regimes (Kirsch et al. 2019):

    - **exact** while the config count ``C^chosen`` stays within
      ``max_configs``: the joint rides as a ``[S, configs]`` tensor (binary
      problems: window 12 at the default cap).
    - **MC-sampled** beyond it: ``mc_samples`` batch-label configurations
      ``ŷ^m`` are drawn from the exact joint at the switch point, and the
      estimator  ``H ≈ -(1/M) Σ_m Σ_c P(ŷ^m, y_i=c)/P(ŷ^m) · log P(ŷ^m, y_i=c)``
      keeps every later pick joint-aware (the r3 kernel fell back to marginal
      BALD here, so 4-class window-50 batches were ~88% plain BALD). The
      per-sample weights ride normalized (``mean_s W = 1``) with a log-space
      offset so f32 never underflows at deep windows, and each pick extends
      every sampled config with a class drawn from its conditional.

    Memory plan: the greedy joint is evaluated only over the top
    ``candidate_pool`` unlabeled points by marginal BALD (standard practice —
    BatchBALD's own experiments subsample candidates), bounding the per-pick
    intermediate to ``candidate_pool * max(max_configs, mc_samples * C)``
    floats instead of pool-sized ones.

    ``key`` seeds the MC config draws (``None``: fixed seed — deterministic
    selection, fine for the estimator since the randomness is over
    configurations, not data).

    Returns ``(picked_idx [k], scores_at_pick [k])`` as pool-level indices.
    """
    S, n, C = probs_samples.shape
    if key is None:
        key = jax.random.key(0)
    bald = bald_score(probs_samples)

    # Candidate restriction by marginal BALD (labeled points excluded).
    m = min(candidate_pool, n)
    if m < k:
        m = min(n, k)
    _, cand = jax.lax.top_k(jnp.where(unlabeled_mask, bald, -jnp.inf), m)  # [m]
    cand_probs = probs_samples[:, cand, :]  # [S, m, C]
    cond_ent = expected_conditional_entropy(cand_probs)  # [m]
    cand_valid = unlabeled_mask[cand]  # top_k tail may hit labeled -inf entries

    joint = jnp.ones((S, 1), dtype=probs_samples.dtype)
    W = None          # [S, M] normalized sampled-config weights (MC regime)
    offs = None       # [M] log P(ŷ^m) offsets
    chosen_mask = ~cand_valid  # within-candidate excluded set
    picked = []
    scores = []
    sum_cond = jnp.asarray(0.0, dtype=probs_samples.dtype)
    exact = True

    for _ in range(k):
        if exact and joint.shape[1] * C > max_configs:
            # Trace-time handover: sample mc_samples configs from the exact
            # joint; their weights continue the joint-aware greedy.
            exact = False
            log_pm = jnp.log(jnp.mean(joint, axis=0) + _EPS)  # [J]
            key, k_cfg = jax.random.split(key)
            cfg = jax.random.categorical(k_cfg, log_pm, shape=(mc_samples,))
            W = joint[:, cfg]  # [S, M]
            pm = jnp.mean(W, axis=0)
            offs = jnp.log(pm + _EPS)
            W = W / (pm[None, :] + _EPS)
        if exact:
            h_joint = _joint_entropy_candidates(joint, cand_probs)  # [m]
        else:
            # qn[i, m, c] = P(ŷ^m, y_i=c) / P(ŷ^m)
            qn = jnp.einsum("sm,sic->imc", W, cand_probs) / S
            h_joint = -jnp.sum(
                qn * (jnp.log(qn + _EPS) + offs[None, :, None]), axis=(1, 2)
            ) / mc_samples
        score = h_joint - (sum_cond + cond_ent)
        score = jnp.where(chosen_mask, -jnp.inf, score)
        j = jnp.argmax(score)
        picked.append(cand[j])
        scores.append(score[j])
        chosen_mask = chosen_mask.at[j].set(True)
        sum_cond = sum_cond + cond_ent[j]
        p_j = cand_probs[:, j, :]  # [S, C]
        if exact:
            # extend the joint with the picked point's class axis
            joint = (joint[:, :, None] * p_j[:, None, :]).reshape(S, -1)
        else:
            # extend each sampled config with a class drawn from its
            # conditional P(y_j | ŷ^m), then renormalize into the offset.
            cls_logits = jnp.log(jnp.einsum("sm,sc->mc", W, p_j) / S + _EPS)
            key, k_cls = jax.random.split(key)
            cls = jax.random.categorical(k_cls, cls_logits, axis=-1)  # [M]
            W = W * p_j[:, cls]
            alpha = jnp.mean(W, axis=0)
            offs = offs + jnp.log(alpha + _EPS)
            W = W / (alpha[None, :] + _EPS)

    return jnp.stack(picked), jnp.stack(scores)


def coreset_min_dists(
    features: jnp.ndarray, labeled_mask: jnp.ndarray, chunk: int = 512
) -> jnp.ndarray:
    """Squared L2 distance of every pool point to its nearest labeled center
    — the k-Center-Greedy init, exposed separately so the fused neural chunk
    can reuse it as coreset's per-point score vector for RoundMetrics
    (within one jitted program XLA CSEs the duplicate against
    :func:`coreset_select`'s own init). Streams ``[chunk, n]`` Gram blocks
    via ``lax.map``; n² never materializes. With no labeled centers every
    distance degenerates to ``norms.max() + 1`` (uniform — the select's
    first pick becomes deterministic argmax)."""
    n = features.shape[0]
    x = features.reshape(n, -1).astype(jnp.float32)
    norms = jnp.sum(x * x, axis=1)  # [n]

    col_inf = jnp.where(labeled_mask, 0.0, jnp.inf)  # +inf hides unlabeled cols

    def init_chunk(args):
        xc, nc = args
        g = nc[:, None] + norms[None, :] - 2.0 * (xc @ x.T)  # [chunk, n]
        return jnp.min(g + col_inf[None, :], axis=1)

    pad = (-n) % chunk
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    np_ = jnp.pad(norms, (0, pad))
    min_dist = jax.lax.map(
        init_chunk, (xp.reshape(-1, chunk, x.shape[1]), np_.reshape(-1, chunk))
    ).reshape(-1)[:n]
    # No labeled centers at all: every point is infinitely far; fall back to
    # uniform distances so argmax degenerates to a deterministic first pick.
    return jnp.where(jnp.isfinite(min_dist), min_dist, norms.max() + 1.0)


@functools.partial(jax.jit, static_argnums=(2, 3))
def coreset_select(
    features: jnp.ndarray,
    labeled_mask: jnp.ndarray,
    k: int,
    chunk: int = 512,
    selectable_mask: jnp.ndarray | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k-Center-Greedy batch selection (Sener & Savarese 2018).

    Repeatedly picks the unlabeled point farthest (squared L2) from the
    current center set (labeled points + picks so far) — pure diversity, no
    posterior needed, so it complements the uncertainty family when MC
    estimates are unreliable (tiny labeled sets, early rounds). This variant
    runs on raw pool features (flattened), the embedding-free form.

    ``labeled_mask`` marks the center set; ``selectable_mask`` (default
    ``~labeled_mask``) marks pickable rows — pass it explicitly when some
    rows are neither (mesh-padding sentinels: zero features must not act as
    centers covering the origin, nor be picked).

    TPU shape: the O(n²) init ("distance to nearest labeled center") streams
    in ``[chunk, n]`` Gram blocks via ``lax.map`` — one MXU matmul per block,
    never materializing n² — and each of the ``k`` greedy picks is a rank-1
    distance update + masked argmax, unrolled under jit like BatchBALD.

    Returns ``(picked_idx [k], distance_at_pick [k])``.
    """
    n = features.shape[0]
    x = features.reshape(n, -1).astype(jnp.float32)
    norms = jnp.sum(x * x, axis=1)  # [n]
    min_dist = coreset_min_dists(features, labeled_mask, chunk)

    selectable = ~labeled_mask if selectable_mask is None else selectable_mask
    picked = []
    dists = []
    for _ in range(k):
        d = jnp.where(selectable, min_dist, -jnp.inf)
        j = jnp.argmax(d)
        picked.append(j)
        dists.append(d[j])
        selectable = selectable.at[j].set(False)
        d2_j = norms + norms[j] - 2.0 * (x @ x[j])
        min_dist = jnp.minimum(min_dist, d2_j)

    return jnp.stack(picked), jnp.stack(dists)


@functools.partial(jax.jit, static_argnums=(3,))
def badge_select(
    probs: jnp.ndarray,
    embeddings: jnp.ndarray,
    selectable_mask: jnp.ndarray,
    k: int,
    key: jax.Array,
) -> jnp.ndarray:
    """BADGE batch selection (Ash et al. 2020): k-means++ seeding in the
    space of hallucinated-label gradient embeddings.

    The gradient of cross-entropy w.r.t. the final-layer weights under the
    model's own predicted label is the rank-1 matrix ``g_i ⊗ h_i`` with
    ``g_i = p_i − onehot(argmax p_i)`` and ``h_i`` the penultimate features —
    its norm grows with uncertainty, its direction varies with the input, so
    D²-weighted k-means++ seeding buys uncertainty AND diversity at once.

    TPU shape: the ``[n, C·D]`` embedding is never materialized — inner
    products factorize, ``⟨g_i⊗h_i, g_j⊗h_j⟩ = ⟨g_i,g_j⟩·⟨h_i,h_j⟩``, so each
    of the ``k`` unrolled picks costs two matvecs (one [n,C], one [n,D]) and
    an elementwise D² update. The first center is drawn uniformly from the
    selectable set, then D²-categorical sampling (all draws from ``key``).

    Returns ``picked_idx [k]``.
    """
    g = probs - jax.nn.one_hot(jnp.argmax(probs, axis=-1), probs.shape[-1])  # [n, C]
    h = embeddings.reshape(embeddings.shape[0], -1).astype(jnp.float32)
    sq = jnp.sum(g * g, axis=1) * jnp.sum(h * h, axis=1)  # |g_i⊗h_i|²

    keys = jax.random.split(key, k)
    j = jax.random.categorical(keys[0], jnp.where(selectable_mask, 0.0, -jnp.inf))
    picked = [j]
    selectable = selectable_mask.at[j].set(False)
    min_d = sq + sq[j] - 2.0 * (g @ g[j]) * (h @ h[j])
    for t in range(1, k):
        w = jnp.where(selectable, jnp.maximum(min_d, 1e-12), 0.0)
        j = jax.random.categorical(keys[t], jnp.log(w))  # log 0 = -inf: masked
        picked.append(j)
        selectable = selectable.at[j].set(False)
        d2_j = sq + sq[j] - 2.0 * (g @ g[j]) * (h @ h[j])
        min_d = jnp.minimum(min_d, d2_j)

    return jnp.stack(picked)
