"""Deep-AL acquisition functions over MC predictive samples.

These serve the neural configs (BASELINE.json 4-5: CIFAR CNN entropy/density,
AG-News BERT BatchBALD) — the reference itself has no neural models, so these
are capability extensions following the standard definitions:

- predictive entropy  H[E_s p]
- BALD                H[E_s p] - E_s H[p]  (mutual information I(y; w))
- BatchBALD           I(y_1..y_k; w) maximized greedily with an exact joint
                      over sampled posteriors (Kirsch et al. 2019), tracked as
                      a [S, configs] tensor while configs <= max_configs, then
                      falling back to BALD for any remaining picks
- mean-std            mean over classes of std over posterior samples
- variation ratios    1 - max_c E_s p

All are pure functions of ``probs_samples [S, n, C]`` and jit-friendly except
the BatchBALD greedy loop, whose trip count ``k`` is static per window size.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

_EPS = 1e-12


def predictive_entropy(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """H of the posterior-mean predictive, per point [n] (nats)."""
    mean = jnp.mean(probs_samples, axis=0)
    return -jnp.sum(mean * jnp.log(mean + _EPS), axis=-1)


def expected_conditional_entropy(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """E_s H[p_s], per point [n] (nats)."""
    ent = -jnp.sum(probs_samples * jnp.log(probs_samples + _EPS), axis=-1)  # [S, n]
    return jnp.mean(ent, axis=0)


def bald_score(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """Mutual information between label and parameters, per point [n]."""
    return predictive_entropy(probs_samples) - expected_conditional_entropy(probs_samples)


def mean_std_score(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """Mean over classes of the per-class posterior std, per point [n]."""
    return jnp.mean(jnp.std(probs_samples, axis=0), axis=-1)


def variation_ratio(probs_samples: jnp.ndarray) -> jnp.ndarray:
    """1 - max-class probability of the posterior mean, per point [n]."""
    return 1.0 - jnp.max(jnp.mean(probs_samples, axis=0), axis=-1)


def _joint_entropy_candidates(joint: jnp.ndarray, probs: jnp.ndarray) -> jnp.ndarray:
    """H of the joint (chosen-batch, candidate i) for every candidate.

    ``joint [S, J]``: per posterior sample, probability of each of the J class
    configurations of the already-chosen batch. ``probs [S, n, C]``. Returns
    ``[n]`` joint entropies of the extended batch.
    """
    S = joint.shape[0]
    q = jnp.einsum("sj,sic->ijc", joint, probs) / S  # [n, J, C]
    return -jnp.sum(q * jnp.log(q + _EPS), axis=(1, 2))


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def batchbald_select(
    probs_samples: jnp.ndarray,
    unlabeled_mask: jnp.ndarray,
    k: int,
    max_configs: int = 4096,
    candidate_pool: int = 512,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy BatchBALD batch of ``k`` points — one compiled selection.

    The greedy loop is *unrolled under jit*: the joint's config count at pick
    ``t`` is the static ``C^t``, so every iteration has static shapes and the
    exact→marginal-BALD fallback branch (``C^t > max_configs``) resolves at
    trace time. One XLA launch replaces k host-driven rounds of device ops.

    Memory plan: the greedy joint is evaluated only over the top
    ``candidate_pool`` unlabeled points by marginal BALD (standard practice —
    BatchBALD's own experiments subsample candidates), bounding the per-pick
    intermediate to ``candidate_pool * max_configs`` floats instead of
    ``n_pool * max_configs``. The joint over MC posterior samples is exact
    while the config count C^chosen stays within ``max_configs`` (binary
    problems: window 12 at the default cap); further picks use marginal BALD —
    documented fallback, no silent wrong answers.

    Returns ``(picked_idx [k], scores_at_pick [k])`` as pool-level indices.
    """
    S, n, C = probs_samples.shape
    bald = bald_score(probs_samples)

    # Candidate restriction by marginal BALD (labeled points excluded).
    m = min(candidate_pool, n)
    if m < k:
        m = min(n, k)
    _, cand = jax.lax.top_k(jnp.where(unlabeled_mask, bald, -jnp.inf), m)  # [m]
    cand_probs = probs_samples[:, cand, :]  # [S, m, C]
    cond_ent = expected_conditional_entropy(cand_probs)  # [m]
    cand_bald = bald[cand]
    cand_valid = unlabeled_mask[cand]  # top_k tail may hit labeled -inf entries

    joint = jnp.ones((S, 1), dtype=probs_samples.dtype)
    chosen_mask = ~cand_valid  # within-candidate excluded set
    picked = []
    scores = []
    sum_cond = jnp.asarray(0.0, dtype=probs_samples.dtype)
    exact = True

    for _ in range(k):
        if exact and joint.shape[1] * C <= max_configs:
            h_joint = _joint_entropy_candidates(joint, cand_probs)  # [m]
            score = h_joint - (sum_cond + cond_ent)
        else:
            exact = False
            score = cand_bald
        score = jnp.where(chosen_mask, -jnp.inf, score)
        j = jnp.argmax(score)
        picked.append(cand[j])
        scores.append(score[j])
        chosen_mask = chosen_mask.at[j].set(True)
        sum_cond = sum_cond + cond_ent[j]
        if exact:
            # extend the joint with the picked point's class axis
            p_j = cand_probs[:, j, :]  # [S, C]
            joint = (joint[:, :, None] * p_j[:, None, :]).reshape(S, -1)

    return jnp.stack(picked), jnp.stack(scores)
