"""Query-strategy registry.

The reference implements strategies twice — flat per-file ``while True`` loops
(``final_thesis/random_sampling.py:61-94``, ``uncertainty_sampling.py:60-114``,
``density_weighting.py:109-179``) and an OOP hierarchy with ``selectNext()``
(``classes/active_learner.py:34-343``). Here both collapse into one registry of
pure scoring functions consumed by the jitted round function; batch ("window")
and single-point modes are the same code with ``window_size`` 10/50/100 vs 1.
"""

from distributed_active_learning_tpu.strategies.base import (
    Strategy,
    StrategyAux,
    get_strategy,
    register_strategy,
    available_strategies,
)

# Import for registration side effects.
from distributed_active_learning_tpu.strategies import core as _core  # noqa: F401
from distributed_active_learning_tpu.strategies import lal as _lal  # noqa: F401

__all__ = [
    "Strategy",
    "StrategyAux",
    "get_strategy",
    "register_strategy",
    "available_strategies",
]
