"""LAL — "Learning Active Learning" (Konyushkova et al.) strategy.

The reference's ``ActiveLearnerLAL`` (``classes/active_learner.py:240-343``)
builds 5 hand-crafted features per unlabeled point and scores them with a
pretrained random-forest *regressor* predicting expected error reduction,
selecting the argmax (``:328``). Its feature pipeline costs ~1650 s per single
query on a 1000-point pool (``classes/RESULTS.txt``), dominated by chained
``zipWithIndex``/``leftOuterJoin`` shuffles that "transpose" per-feature RDDs
into per-row vectors (``:303-314``) and 2000 sequential per-tree predict jobs.

Here the features are columns of one ``[n, 5]`` array computed in a single
fused kernel — the "transpose" is free (it's just ``stack``) — and the
regressor is a packed forest evaluated in one launch.

Feature definitions (reference lines in parens):

- f_1: mean per-tree score = positive-vote fraction (``:280``)
- f_2: SD of the per-tree Bernoulli votes, ``sqrt(p(1-p))`` (``:283``, ``getSD`` :232-236)
- f_3: proportion of positive points among the labeled set (``:286-289``) — scalar
- f_6: mean of f_2 over the pool (``:291-293``) — scalar
- f_8: number of labeled points (``:296``) — scalar

Scalars are broadcast per point (trivial on TPU; the reference paid join
shuffles for this).
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_active_learning_tpu.config import StrategyConfig
from distributed_active_learning_tpu.ops import forest_eval, scoring
from distributed_active_learning_tpu.runtime.state import PoolState
from distributed_active_learning_tpu.strategies.base import (
    Strategy,
    StrategyAux,
    register_strategy,
)


def lal_features(forest: forest_eval.Forest, state: PoolState) -> jnp.ndarray:
    """The ``[n, 5]`` LAL feature matrix (columns f_1, f_2, f_3, f_6, f_8)."""
    votes = forest_eval.votes(forest, state.x).astype(jnp.float32)
    f1 = votes / forest.n_trees
    f2 = scoring.vote_sd(votes, forest.n_trees)

    # valid_mask filters mesh-padding rows (marked labeled) out of the
    # labeled-set statistics; a no-op on unpadded pools.
    labeled = (state.labeled_mask & state.valid_mask).astype(jnp.float32)
    n_labeled = jnp.sum(labeled)
    # proportion of positive labels among labeled points (:286-289)
    f3 = jnp.sum(labeled * (state.oracle_y == 1)) / jnp.maximum(n_labeled, 1.0)
    # mean forest variance estimate over the *unlabeled* pool (:291-293 divides
    # by nUnlabeled; the training-data generator matches — avoiding train/
    # inference feature skew as labeled near-pure-leaf points would drag the
    # whole-pool mean down)
    unlabeled = 1.0 - labeled
    n_unlabeled = jnp.maximum(jnp.sum(unlabeled), 1.0)
    f6 = jnp.sum(f2 * unlabeled) / n_unlabeled
    f8 = n_labeled

    n = state.n_pool
    return jnp.stack(
        [
            f1,
            f2,
            jnp.broadcast_to(f3, (n,)),
            jnp.broadcast_to(f6, (n,)),
            jnp.broadcast_to(f8, (n,)),
        ],
        axis=1,
    )


@register_strategy("lal")
def _lal(cfg: StrategyConfig) -> Strategy:
    """Score = predicted error reduction from the LAL regressor, descending
    (``active_learner.py:319-328``). Requires ``aux.lal_forest`` — train one
    with ``models.lal_training.train_lal_regressor`` (or load reference-format
    synthesized data, ``mllib_randomforest_regression_lal_randomtree_dataset.py``).
    """

    def score(forest, state, key, aux: StrategyAux):
        del key
        from distributed_active_learning_tpu.ops.trees_multi import is_multi

        if is_multi(forest):
            raise ValueError(
                "the lal strategy is binary-only: its 5 features (positive-"
                "vote fraction, vote SD, positive-label proportion) are "
                "defined over a binary forest (active_learner.py:280-296); "
                "use uncertainty/entropy/margin on multiclass pools"
            )
        if aux.lal_forest is None:
            raise ValueError(
                "LAL strategy needs aux.lal_forest (the pretrained error-"
                "reduction regressor); see models/lal_training.py"
            )
        feats = lal_features(forest, state)
        return forest_eval.value(aux.lal_forest, feats)

    return Strategy(name="lal", score=score, higher_is_better=True)
