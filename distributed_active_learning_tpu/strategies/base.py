"""Strategy protocol: a pure scoring function plus selection direction."""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

from distributed_active_learning_tpu.config import StrategyConfig
from distributed_active_learning_tpu.ops.trees import PackedForest
from distributed_active_learning_tpu.runtime.state import PoolState


@struct.dataclass
class StrategyAux:
    """Optional per-round auxiliary inputs a strategy may need.

    A pytree (so it can cross the jit boundary as an argument).

    ``lal_forest``: the pretrained LAL regressor (``active_learner.py:319-321``).
    ``seed_mask``: the initially-labeled seed mask, for reference-exact density
    masking (``density_weighting.py:95-100``).
    """

    lal_forest: Optional[PackedForest] = None
    seed_mask: Optional[jnp.ndarray] = None


# A scoring function: (forest, state, key, aux) -> scores [n_pool].
ScoreFn = Callable[[PackedForest, PoolState, jax.Array, StrategyAux], jnp.ndarray]


@dataclasses.dataclass(frozen=True)
class Strategy:
    """A named scoring rule.

    ``higher_is_better`` decides whether selection takes the top-k (descending,
    e.g. density heuristic at ``density_weighting.py:168``) or bottom-k
    (ascending, e.g. uncertainty distance at ``uncertainty_sampling.py:106``).
    """

    name: str
    score: ScoreFn
    higher_is_better: bool = True


_REGISTRY: Dict[str, Callable[[StrategyConfig], Strategy]] = {}


def register_strategy(name: str):
    def deco(builder: Callable[[StrategyConfig], Strategy]):
        _REGISTRY[name] = builder
        return builder
    return deco


def available_strategies():
    return sorted(_REGISTRY)


def get_strategy(cfg: StrategyConfig) -> Strategy:
    if cfg.name not in _REGISTRY:
        raise KeyError(f"unknown strategy {cfg.name!r}; available: {available_strategies()}")
    return _REGISTRY[cfg.name](cfg)
