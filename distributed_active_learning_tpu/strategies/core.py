"""Core strategies: random, uncertainty, entropy, margin, density-weighted.

Each mirrors a reference strategy's scoring rule exactly (citations inline);
all are pure functions over device arrays, so one jitted round evaluates any of
them with zero host round-trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_active_learning_tpu.config import StrategyConfig
from distributed_active_learning_tpu.ops import forest_eval, scoring, similarity, trees_multi
from distributed_active_learning_tpu.runtime.state import PoolState
from distributed_active_learning_tpu.strategies.base import (
    Strategy,
    register_strategy,
)


def _vote_fraction(forest: forest_eval.Forest, state: PoolState) -> jnp.ndarray:
    """Positive-vote fraction per pool point — the probability estimate every
    reference strategy derives from the per-tree vote sum
    (``uncertainty_sampling.py:96-98``: votes from hard per-tree predictions).

    Dispatches through :mod:`ops.forest_eval`, so the MXU (GEMM) kernel is used
    whenever the round was built with ``ForestConfig.kernel="gemm"``."""
    votes = forest_eval.votes(forest, state.x)
    return votes.astype(jnp.float32) / forest.n_trees


@register_strategy("random")
def _random(cfg: StrategyConfig) -> Strategy:
    """Uniform-random selection — the control baseline.

    The reference shuffles the unlabeled index RDD by a random sort key and
    takes the window (``random_sampling.py:88-89``; ``active_learner.py:133-136``).
    A random priority per point + top-k is the same distribution.
    """

    def score(forest, state, key, aux):
        del forest, aux
        return jax.random.uniform(key, (state.n_pool,))

    return Strategy(name="random", score=score, higher_is_better=True)


@register_strategy("uncertainty")
def _uncertainty(cfg: StrategyConfig) -> Strategy:
    """Least-confidence: distance of the vote fraction from 0.5, ascending
    (``uncertainty_sampling.py:98,106``; ``active_learner.py:197,203``)."""

    def score(forest, state, key, aux):
        del key, aux
        if trees_multi.is_multi(forest):
            # Multiclass form: top-2 margin ascending (smallest margin =
            # least confident) — the C-class generalization of the binary
            # distance-from-0.5 rule.
            probs = trees_multi.proba_multi(forest, state.x)
            return trees_multi.margin_score_multi(probs)
        return scoring.uncertainty_score(_vote_fraction(forest, state))

    return Strategy(name="uncertainty", score=score, higher_is_better=False)


@register_strategy("soft_uncertainty")
def _soft_uncertainty(cfg: StrategyConfig) -> Strategy:
    """Least-confidence over the *mean leaf probability* instead of the hard
    per-tree vote fraction. The reference's hard votes
    (``uncertainty_sampling.py:96``) quantize p to n_trees+1 levels, flooding
    the top-k with ties at small forests; the soft posterior keeps the same
    ranking rule (distance from 0.5, ascending) with full resolution. A
    capability improvement beyond parity — ``uncertainty`` stays the exact
    reference formula."""

    def score(forest, state, key, aux):
        del key, aux
        if trees_multi.is_multi(forest):
            # The multiclass posterior is already soft; margin is its
            # least-confidence form.
            probs = trees_multi.proba_multi(forest, state.x)
            return trees_multi.margin_score_multi(probs)
        return scoring.uncertainty_score(forest_eval.proba(forest, state.x))

    return Strategy(name="soft_uncertainty", score=score, higher_is_better=False)


@register_strategy("entropy")
def _entropy(cfg: StrategyConfig) -> Strategy:
    """The reference's one-sided entropy ``-(1-p)·log2(1-p)``
    (``density_weighting.py:148``), descending."""

    def score(forest, state, key, aux):
        del key, aux
        if trees_multi.is_multi(forest):
            probs = trees_multi.proba_multi(forest, state.x)
            return trees_multi.entropy_multi(probs)
        return scoring.positive_entropy(_vote_fraction(forest, state))

    return Strategy(name="entropy", score=score, higher_is_better=True)


@register_strategy("full_entropy")
def _full_entropy(cfg: StrategyConfig) -> Strategy:
    """Standard binary entropy (the correct form the reference approximates)."""

    def score(forest, state, key, aux):
        del key, aux
        if trees_multi.is_multi(forest):
            probs = trees_multi.proba_multi(forest, state.x)
            return trees_multi.entropy_multi(probs)
        return scoring.full_entropy(_vote_fraction(forest, state))

    return Strategy(name="full_entropy", score=score, higher_is_better=True)


@register_strategy("margin")
def _margin(cfg: StrategyConfig) -> Strategy:
    """Top-2 margin, ascending. Standard AL companion (not in the reference)."""

    def score(forest, state, key, aux):
        del key, aux
        if trees_multi.is_multi(forest):
            probs = trees_multi.proba_multi(forest, state.x)
            return trees_multi.margin_score_multi(probs)
        return scoring.margin_score(_vote_fraction(forest, state))

    return Strategy(name="margin", score=score, higher_is_better=False)


@register_strategy("density")
def _density(cfg: StrategyConfig) -> Strategy:
    """Information density: one-sided entropy x (similarity mass ** beta),
    descending (``density_weighting.py:148-168``; beta at ``:33``).

    Similarity mass is computed in O(n·d) via the matvec identity (see
    ``ops/similarity.similarity_mass``) instead of the reference's O(n²·d)
    BlockMatrix build + n²-entry shuffle. By default mass counts the *current*
    unlabeled set; set ``options={'mass_over': 'non_seed'}`` (with
    ``aux.seed_mask``) to reproduce the reference's seeds-only exclusion
    (``density_weighting.py:95-100``).
    """
    mass_over = dict(cfg.options).get("mass_over", "unlabeled")
    beta = cfg.beta

    def score(forest, state, key, aux):
        del key
        if trees_multi.is_multi(forest):
            ent = trees_multi.entropy_multi(trees_multi.proba_multi(forest, state.x))
        else:
            ent = scoring.positive_entropy(_vote_fraction(forest, state))
        if mass_over == "non_seed" and aux.seed_mask is not None:
            count_mask = ~aux.seed_mask
        else:
            count_mask = ~state.labeled_mask
        mass = similarity.similarity_mass(state.x, count_mask)
        # mass can be slightly negative for adversarial embeddings; clamp so
        # the beta power is defined.
        mass = jnp.maximum(mass, 0.0)
        return ent * jnp.power(mass, beta)

    return Strategy(name="density", score=score, higher_is_better=True)
