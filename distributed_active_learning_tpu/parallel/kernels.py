"""Explicit shard_map kernels + the sharded AL round.

Two styles of distribution, both used:

1. **GSPMD (auto)** — :func:`make_sharded_round_fn` places the pool over the
   ``data`` axis and the forest over ``model``, then jits the same round
   function used single-device; XLA propagates shardings and inserts the
   collectives (all-gather for top-k, psum for tree reductions). This replaces
   the reference's whole shuffle graph (``uncertainty_sampling.py:62-112``).

2. **shard_map (manual)** — :func:`sharded_votes` and
   :func:`sharded_similarity_mass` spell the communication out for the two hot
   reductions, as the building blocks the kernels guide recommends when you
   need to control what rides ICI: per-shard compute + one ``psum``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_active_learning_tpu.ops.similarity import l2_normalize
from distributed_active_learning_tpu.ops.trees import PackedForest
from distributed_active_learning_tpu.parallel import mesh as mesh_lib
from distributed_active_learning_tpu.parallel.collectives import vector_accumulate
from distributed_active_learning_tpu.runtime.state import PoolState
from distributed_active_learning_tpu.strategies.base import Strategy, StrategyAux
from distributed_active_learning_tpu.utils.compat import shard_map


def sharded_votes(mesh: Mesh):
    """Per-point positive-vote counts with pool sharded over ``data`` and trees
    over ``model``: each device scores its pool block against its tree shard,
    then one psum over ``model`` completes the vote reduction — the collective
    form of ``groupByKey().mapValues(sum)`` (``uncertainty_sampling.py:96``).

    Works for every forest representation (gather ``PackedForest``, path-matrix
    ``GemmForest``, fused ``PallasForest``): all array fields carry the tree
    axis first, so one pytree of ``P(model, ...)`` specs shards any of them,
    and inside the shard_map body each device evaluates its local shard with
    the forest's own kernel — including ``pallas_call``, which sees plain
    local shapes here (no GSPMD partitioning rule needed, unlike the
    auto-sharded round).

    Returns a function ``(forest, x) -> votes [n]``.
    """
    from distributed_active_learning_tpu.ops import forest_eval

    def _local_eval_form(forest):
        """Unwrap mesh-aware pallas wrappers to their plain per-shard form.

        A :class:`~ops.trees_pallas.ShardedPallasForest` evaluates by
        shard_mapping ITSELF over its attached mesh — inside this kernel's
        shard_map body that would nest a second shard_map over already-local
        shapes (undefined axis context, and at best a second round of
        collectives). The wrapper exists to make plain ``jit`` calls shard;
        here the sharding is explicit, so evaluation must use the plain
        :class:`PallasForest` on the local tree shard.
        """
        from distributed_active_learning_tpu.ops.trees_multi import MultiForest
        from distributed_active_learning_tpu.ops.trees_pallas import (
            PallasForest,
            ShardedPallasForest,
        )

        if isinstance(forest, MultiForest):
            return MultiForest(
                planes=tuple(_local_eval_form(p) for p in forest.planes)
            )
        if isinstance(forest, ShardedPallasForest):
            return PallasForest(gf=forest.gf)
        return forest

    def votes_fn(forest, x: jnp.ndarray) -> jnp.ndarray:
        forest = _local_eval_form(forest)
        tree_specs = mesh_lib.forest_tree_specs(forest)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(tree_specs, P(mesh_lib.AXIS_DATA, None)),
            out_specs=P(mesh_lib.AXIS_DATA),
            # pallas_call declares its out_shape without a varying-mesh-axes
            # annotation; skip the vma check (the psum below states the
            # cross-axis contract explicitly).
            check_vma=False,
        )
        def kernel(f_local, x_blk):
            local = jnp.sum(forest_eval.leaves(f_local, x_blk) > 0.5, axis=1)
            return vector_accumulate(local.astype(jnp.int32), mesh_lib.AXIS_MODEL)

        with jax.named_scope("shard/votes"):
            return kernel(forest, x)

    return votes_fn


def sharded_similarity_mass(mesh: Mesh):
    """Similarity mass with the pool sharded over ``data``.

    Per-shard: normalize the local block, fold the local masked rows into a
    ``[d]`` vector; one psum over ``data`` builds the global pooled vector;
    the local matvec finishes. Total bytes over ICI per device: ``d`` floats —
    versus the reference shuffling n² similarity entries
    (``density_weighting.py:158-161``).

    Returns ``(x, count_mask) -> mass [n]``.
    """

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(mesh_lib.AXIS_DATA, None), P(mesh_lib.AXIS_DATA)),
        out_specs=P(mesh_lib.AXIS_DATA),
    )
    def mass_kernel(x_blk: jnp.ndarray, m_blk: jnp.ndarray) -> jnp.ndarray:
        xn = l2_normalize(x_blk)
        local_pooled = jnp.matmul(
            xn.T, m_blk.astype(xn.dtype), precision=lax.Precision.HIGHEST
        )
        pooled = vector_accumulate(local_pooled, mesh_lib.AXIS_DATA)
        return jnp.matmul(xn, pooled, precision=lax.Precision.HIGHEST)

    def mass(x: jnp.ndarray, count_mask: jnp.ndarray) -> jnp.ndarray:
        with jax.named_scope("shard/similarity_mass"):
            return mass_kernel(x, count_mask)

    return mass


def make_sharded_round_fn(
    strategy: Strategy,
    window_size: int,
    mesh: Mesh,
    with_metrics: bool = False,
    n_classes: int = 2,
    fused: bool = False,
    scenario=None,
):
    """The full AL round over a device mesh (GSPMD style).

    Returns ``(forest, state, aux) -> (new_state, picked, scores)`` where the
    caller is expected to have placed ``state`` via
    :func:`parallel.mesh.shard_pool_state` and ``forest`` via
    :func:`parallel.mesh.shard_forest`; jit then compiles one SPMD program over
    the mesh, keeping outputs in their input shardings. ``with_metrics``
    passes through to :func:`runtime.loop.make_round_fn`: the in-scan
    :class:`~runtime.telemetry.RoundMetrics` reductions are plain jnp ops, so
    GSPMD partitions them with the round — metrics under a mesh match the
    single-device values the same way accuracies do. ``scenario`` likewise
    rides through: the only mesh-admitted kind (``noisy_oracle``,
    runtime/loop.py's refusal gate) perturbs the round via a window-sized
    abstain draw from the replicated round key, so GSPMD partitions the
    scenario round exactly like the clean one.
    """
    from distributed_active_learning_tpu.runtime.loop import make_round_fn

    round_fn = make_round_fn(
        strategy, window_size, with_metrics=with_metrics, n_classes=n_classes,
        fused=fused, scenario=scenario,
    )

    def sharded_round(forest: PackedForest, state: PoolState, aux: StrategyAux):
        # Inputs carry NamedShardings (committed by device_put); jit compiles
        # one SPMD executable over the mesh from those placements. Guard
        # against inputs placed on a *different* mesh than the declared one.
        sh = getattr(state.x, "sharding", None)
        if hasattr(sh, "mesh") and sh.mesh.shape != mesh.shape:
            raise ValueError(
                f"state is sharded over mesh {dict(sh.mesh.shape)}, but this "
                f"round fn was built for {dict(mesh.shape)}; re-place with "
                "parallel.mesh.shard_pool_state"
            )
        return round_fn(forest, state, aux)

    return sharded_round
